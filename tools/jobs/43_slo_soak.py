# TIMEOUT: 1800
"""SLO-observatory soak (docs/monitoring.md "SLOs & burn rates"): drive
the admission-accuracy SLO through a full burn-rate alert cycle with a
real fault, per ISSUE 17.

A 3-daemon mesh serves one GLOBAL keyspace owned by a single daemon,
with the observatory sampling fast (0.25s) and the admission-accuracy
SLO's windows shrunk via the GUBER_SLO_SPECS merge override so the
whole multi-window story fits in seconds instead of hours. The
admission-accuracy SLI is the node's unreconciled admission debt —
lease outstanding + GLOBAL in-flight hits, the published
over-admission bound — as a fraction of the capacity admitted this
window. The drill:

1. steady — warm traffic flushes clean: debt 0, SLO "ok" with the full
   error budget (provably healthy, not data-less);
2. partition — fault-inject the owner's address, then pump the window
   limit through an edge. GLOBAL answers locally and queues every hit
   for the owner; the flush can't deliver, the breaker opens, and the
   debt pins near 1.0 of windowed capacity. The edge's
   admission-accuracy SLO must reach `fast_burn` within ONE evaluation
   window (the long fast window) of the first bad sample — observed
   end-to-end through /debug/slo. While still burning, the fleet
   budget view must show the edge's burn from the OWNER's
   /debug/cluster (the SLO blob riding PeersV1.DebugInfo);
3. heal — clear the fault. The stranded queue drains to the owner
   (DRAIN_OVER_LIMIT force-apply), debt falls to 0, the alert must
   clear back to "ok" and the error budget must stop burning
   (remaining stabilizes above zero — the shrunk windows are sized so
   a bounded incident never exhausts the budget).

Acceptance evidence (ISSUE 17): `fired`, `fired_within_window`,
`fleet_budget_visible`, `cleared`, `budget_stopped_burning`. Prints one
`RESULT {json}` line (ledgered + auto-gated by tools/tpu_runner.py).
"""
import sys, json, time

sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]


def run() -> dict:
    import asyncio

    import aiohttp
    import jax

    from gubernator_tpu.api.types import Behavior, RateLimitReq
    from gubernator_tpu.client import GubernatorClient
    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.service.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.utils import faults

    N_KEYS = 32
    LIMIT = 200
    DURATION_MS = 60_000  # one window outlives the whole drill
    CHUNK = 50  # pump the full limit in 4 chunks per key
    SAMPLE_S = 0.25
    EVAL_WINDOW_S = 6.0  # the long fast window: the "one window" bound
    BAD_THRESHOLD = 0.1  # admission-accuracy spec default threshold
    STEADY_S = 45.0  # clean-sample runway before the fault
    # Merge-override (service/slo.py parse_slo_specs): keep the SLI and
    # threshold, shrink the windows to soak scale. Burn fractions
    # divide by the samples PRESENT in a window, and a fresh daemon
    # only has the samples it has lived — so the steady phase banks
    # STEADY_S of clean runway and the objective is loosened to 0.8 so
    # a seconds-long incident burns hard without exhausting the budget
    # — the point is to watch fast_burn fire AND clear, not to pin the
    # state at "exhausted".
    SLO_SPECS = json.dumps([
        {
            "id": "admission-accuracy",
            "objective": 0.8,
            "fast_windows": [3.0, EVAL_WINDOW_S],
            "slow_windows": [EVAL_WINDOW_S, 18.0],
            "fast_factor": 2.0,
            "slow_factor": 2.0,
            "budget_window_s": 900.0,
        }
    ])

    def req(i: int, hits: int) -> RateLimitReq:
        return RateLimitReq(
            name="slo_soak", unique_key=f"acct:{i}",
            duration=DURATION_MS, limit=LIMIT, hits=hits,
            behavior=int(Behavior.GLOBAL),
        )

    async def main():
        behaviors = BehaviorConfig(
            circuit_failure_threshold=3,
            circuit_open_base_s=0.2, circuit_open_max_s=2.0,
            global_sync_wait_s=0.1,
        )
        c = Cluster()
        for _ in range(3):
            c.daemons.append(
                await Daemon.spawn(
                    DaemonConfig(
                        cache_size=8192,
                        behaviors=behaviors,
                        admission_ttl_s=0.5,
                        slo_sample_interval_s=SAMPLE_S,
                        slo_specs=SLO_SPECS,
                    )
                )
            )
        c.rewire()
        session = aiohttp.ClientSession()
        try:
            owner = c.find_owning_daemon("slo_soak", "acct:0")
            edge = next(d for d in c.daemons if d is not owner)
            keys = [
                i for i in range(4000)
                if c.find_owning_daemon("slo_soak", f"acct:{i}") is owner
            ][:N_KEYS]
            assert len(keys) == N_KEYS
            loop = asyncio.get_running_loop()

            async def slo_poll() -> tuple:
                # The sampler's debt-ratio denominator is the
                # TTL-cached admission scan (cached_admission never
                # scans — GL009). Production keeps that cache warm via
                # the auditor / scrape cadence; this job plays that
                # role at the same rhythm.
                await loop.run_in_executor(
                    None,
                    lambda: edge.svc.engine.admission_snapshot(
                        max_age_s=0.2
                    ),
                )
                async with session.get(
                    f"http://{edge.http_address}/debug/slo"
                ) as r:
                    blob = await r.json()
                adm = {e["id"]: e for e in blob["slos"]}[
                    "admission-accuracy"
                ]
                debt = (
                    blob["slis"]
                    .get("admission_debt_ratio", {})
                    .get("last")
                )
                return blob, adm, debt

            # -- 1. steady: warm traffic, clean flush, SLO ok ----------
            plain = GubernatorClient(edge.grpc_address)
            for i in keys:
                (resp,) = await plain.get_rate_limits(
                    [req(i, 1)], timeout=10
                )
                assert resp.error == "", resp.error
            # let the queued warm hits flush to the owner, then bank
            # STEADY_S of clean (debt 0) samples — the budget window's
            # denominator only holds the samples the daemon has lived
            await asyncio.sleep(1.0)
            _, adm, debt = await slo_poll()
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < STEADY_S:
                await asyncio.sleep(1.0)
            _, adm, debt = await slo_poll()
            steady = {
                "state": adm["state"],
                "error_budget_remaining": adm["error_budget_remaining"],
                "debt_ratio": debt,
            }

            # -- 2. partition the owner; pump; debt pins near 1 --------
            faults.INJECTOR.partition(owner.grpc_address)
            t_partition = time.perf_counter()
            served = 0
            for _ in range(LIMIT // CHUNK):
                for i in keys:
                    (resp,) = await plain.get_rate_limits(
                        [req(i, CHUNK)], timeout=10
                    )
                    assert resp.error == "", resp.error
                    served += 1
            pump_dt = time.perf_counter() - t_partition
            partition = {
                "served": served,
                "pump_checks_per_s": round(served / pump_dt, 1),
            }

            first_bad_at = fired_at = None
            fired = None
            states_seen = set()
            while time.perf_counter() - t_partition < 30.0:
                blob, adm, debt = await slo_poll()
                states_seen.add(adm["state"])
                if (
                    first_bad_at is None
                    and debt is not None
                    and debt > BAD_THRESHOLD
                ):
                    first_bad_at = time.perf_counter()
                if adm["state"] == "fast_burn":
                    fired_at = time.perf_counter()
                    fired = {
                        "state": adm["state"],
                        "burn_rates": adm["burn_rates"],
                        "error_budget_remaining": adm[
                            "error_budget_remaining"
                        ],
                        "debt_ratio": debt,
                        "s_from_partition": round(
                            fired_at - t_partition, 2
                        ),
                        "s_from_first_bad": round(
                            fired_at - (first_bad_at or t_partition), 2
                        ),
                    }
                    break
                await asyncio.sleep(SAMPLE_S)
            fired_within = bool(
                fired is not None
                and fired["s_from_first_bad"] <= EVAL_WINDOW_S + 1.0
            )

            # fleet budget view DURING the incident: the OWNER's
            # /debug/cluster must show the edge's burn through the
            # DebugInfo SLO rider (owner->edge DebugInfo is not
            # faulted — only calls TO the owner are partitioned)
            async with session.get(
                f"http://{owner.http_address}/debug/cluster"
            ) as r:
                cluster = await r.json()
            peer_blob = cluster["peers"].get(edge.grpc_address) or {}
            fleet_row = (peer_blob.get("slo") or {}).get("slos", {}).get(
                "admission-accuracy"
            )
            fleet_visible = bool(
                fleet_row is not None
                and fleet_row["state"] in ("fast_burn", "slow_burn")
                and fleet_row["error_budget_remaining"] is not None
                and fleet_row["error_budget_remaining"] < 1.0
            )

            # -- 3. heal: the debt drains, alert clears ----------------
            faults.INJECTOR.clear()
            t_heal = time.perf_counter()
            cleared = None
            while time.perf_counter() - t_heal < 45.0:
                blob, adm, debt = await slo_poll()
                states_seen.add(adm["state"])
                if adm["state"] == "ok":
                    cleared = {
                        "state": adm["state"],
                        "error_budget_remaining": adm[
                            "error_budget_remaining"
                        ],
                        "debt_ratio": debt,
                        "cleared_s": round(
                            time.perf_counter() - t_heal, 2
                        ),
                    }
                    break
                await asyncio.sleep(SAMPLE_S)
            budget_stopped = False
            if cleared is not None:
                _, adm, _ = await slo_poll()
                r1 = adm["error_budget_remaining"]
                await asyncio.sleep(3.0)
                _, adm, _ = await slo_poll()
                r2 = adm["error_budget_remaining"]
                cleared["budget_then"] = r1
                cleared["budget_after"] = r2
                # with no new bad samples the bad count is frozen, so
                # remaining can only recover (rise) — never burn down
                budget_stopped = bool(
                    r1 is not None
                    and r2 is not None
                    and r1 > 0.0
                    and r2 >= r1 - 1e-9
                )
            await plain.close()

            return {
                "bench": "slo_soak",
                "metric": (
                    "admission-SLO burn-rate alert cycle under owner "
                    f"partition ({jax.default_backend()}, 3-daemon "
                    f"mesh, {N_KEYS} GLOBAL keys) pump checks/s"
                ),
                "value": partition["pump_checks_per_s"],
                "unit": "checks/s",
                "daemons": 3,
                "keys": N_KEYS,
                "limit": LIMIT,
                "duration_ms": DURATION_MS,
                "sample_interval_s": SAMPLE_S,
                "eval_window_s": EVAL_WINDOW_S,
                "steady": steady,
                "partition": partition,
                "fired_detail": fired,
                "fleet_row": fleet_row,
                "cleared_detail": cleared,
                "states_seen": sorted(states_seen),
                "fired": fired is not None,
                "fired_within_window": fired_within,
                "fleet_budget_visible": fleet_visible,
                "cleared": cleared is not None,
                "budget_stopped_burning": budget_stopped,
                "never_exhausted": "exhausted" not in states_seen,
            }
        finally:
            faults.INJECTOR.clear()
            await session.close()
            await c.stop()

    return asyncio.run(main())


r = run()
print("RESULT " + json.dumps(r))
