# TIMEOUT: 900
import sys, json
sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]
import bench
r = bench.bench_kernel("kernel", "narrow")
print("RESULT " + json.dumps(r))
