# TIMEOUT: 1800
"""Crash soak: the standby-replication acceptance drill
(docs/robustness.md "Standby replication & crash recovery").

A 3-daemon mesh runs continuous Zipf-distributed load against keys
owned by one daemon (the victim). Mid-flight the victim is hard-killed
— its replication loops are frozen and it is partitioned, the
in-process stand-in for SIGKILL: no drain, no handover, no retire —
and the membership change promotes its standbys. The measured counter
loss across every driven key must be <= the loss bound the victim
PUBLISHED (gubernator_standby_loss_bound_hits) at the kill instant.
Afterwards the surviving pair keeps replicating: a fault-injected
standby drop (faults.OP_PEER_STANDBY) plus a deliberately corrupted
shadow must be found and repaired by anti-entropy, with a follow-up
digest exchange reporting zero mismatched regions (convergence).

Prints one `RESULT {json}` line and appends it to the benchmark ledger
(mode=crash_soak) with the auto-gate verdict as a `GATE {json}` line.
"""
import sys, json, time, random

sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]


def run() -> dict:
    import asyncio

    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.service import pb
    from gubernator_tpu.service.config import BehaviorConfig
    from gubernator_tpu.utils import faults

    NAME = "crash_soak"
    LIMIT = 10_000_000
    DURATION_MS = 600_000
    N_KEYS = 150
    LOAD_S = 4.0
    SHIP_S = 0.25

    async def main():
        c = await Cluster.start(
            3,
            behaviors=BehaviorConfig(
                standby_interval_s=SHIP_S,
                standby_promote_after_s=1.0,
                # AE runs on demand below (deterministic pass counting).
                standby_anti_entropy_interval_s=0.0,
                circuit_failure_threshold=3,
                circuit_open_base_s=0.2,
                circuit_open_max_s=1.0,
            ),
            cache_size=65536,
        )
        try:
            victim = c.find_owning_daemon(NAME, "victimkey")
            survivors = [d for d in c.daemons if d is not victim]
            driver = survivors[0]
            stub = driver.client()

            # Zipf-weighted victim-owned key set.
            keys = []
            for i in range(100_000):
                k = f"ck{i}"
                if c.find_owning_daemon(NAME, k) is victim:
                    keys.append(k)
                    if len(keys) >= N_KEYS:
                        break
            weights = [1.0 / (i + 1) ** 1.1 for i in range(len(keys))]
            rng = random.Random(42)

            async def hit(key, hits):
                msg = pb.pb.GetRateLimitsReq()
                msg.requests.append(
                    pb.pb.RateLimitReq(
                        name=NAME, unique_key=key, duration=DURATION_MS,
                        limit=LIMIT, hits=hits,
                    )
                )
                return (await stub.get_rate_limits(msg, timeout=10)).responses[0]

            # Continuous Zipf load: count a hit only when the victim
            # ACKED it (an error response consumed nothing).
            sent = dict.fromkeys(keys, 0)
            acked = 0
            t0 = time.perf_counter()
            t_end = t0 + LOAD_S
            while time.perf_counter() < t_end:
                for k in rng.choices(keys, weights=weights, k=64):
                    resp = await hit(k, 1)
                    if not resp.error:
                        sent[k] += 1
                        acked += 1
            load_rate = acked / (time.perf_counter() - t0)

            # Replication must actually be flowing before the kill.
            await asyncio.sleep(2 * SHIP_S)
            shadow_rows = sum(
                e["keys"]
                for d in survivors
                for e in d.svc.standby.summary()["shadows"].values()
            )

            # A final burst the ship loop gets no chance to ack: these
            # hits are the dirt the kill actually loses, so the bound
            # (and usually the measured loss) is nonzero — the check
            # must not pass vacuously on a quiesced owner.
            for k in rng.choices(keys, weights=weights, k=128):
                resp = await hit(k, 1)
                if not resp.error:
                    sent[k] += 1
                    acked += 1

            # --- hard kill. Freeze the victim's replication FIRST (the
            # bound stops moving), read the published bound, then cut it
            # off. No close(), no drain, no retire — the SIGKILL shape.
            sb = victim._standby
            for t in (sb._ship_task, sb._ae_task):
                if t is not None:
                    t.cancel()
            bound_at_kill = sb.loss_bound_hits()
            faults.INJECTOR.partition(victim.grpc_address)
            victim_addr = victim.grpc_address

            # Membership change (discovery notices the death): survivors
            # see the victim leave the ring unretired -> promotion.
            c.daemons.remove(victim)
            c.rewire()
            deadline = time.monotonic() + 10
            promoted = False
            while time.monotonic() < deadline:
                if all(
                    victim_addr not in d.svc.standby.summary()["shadows"]
                    for d in survivors
                ) and any(
                    d.svc.standby.summary()["promotions"] > 0
                    for d in survivors
                ):
                    promoted = True
                    break
                await asyncio.sleep(0.1)

            # --- measured loss vs the published bound. hits=0 probes
            # read each key's counter at its post-death owner.
            consumed = 0
            for k in keys:
                resp = await hit(k, 0)
                if not resp.error:
                    consumed += LIMIT - resp.remaining
            loss = acked - consumed
            loss_ok = loss <= bound_at_kill

            # --- anti-entropy: fault-injected standby drops plus a
            # corrupted shadow must be found and repaired.
            a, b = survivors
            faults.INJECTOR.add_rule(
                faults.FaultRule(
                    target=b.grpc_address, op=faults.OP_PEER_STANDBY,
                    error_rate=1.0, max_injections=4,
                )
            )
            for k in keys[:40]:
                await hit(k, 1)
            await asyncio.sleep(4 * SHIP_S)  # ships flow; 4 legs dropped
            faults.INJECTOR.clear()
            dropped_legs = int(
                sum(
                    a.svc.metrics.standby_ship_errors.labels(r).get()
                    for r in ("circuit_open", "deadline", "send_error")
                )
            )
            # Corrupt b's shadow of a (simulated restart / bit rot).
            shadow = b.svc.standby._shadow.get(a.grpc_address)
            corrupted = 0
            if shadow is not None:
                for k in list(shadow.rows)[:5]:
                    del shadow.rows[k]
                    corrupted += 1
            # Quiesce pending deltas, then: pass 1 repairs, pass 2 clean.
            await asyncio.sleep(4 * SHIP_S)
            r1 = await a.svc.standby.anti_entropy_once()
            r2 = await a.svc.standby.anti_entropy_once()
            repaired = r1["mismatched_regions"]
            converged = r2["mismatched_regions"] == 0

            ok = bool(
                promoted and loss_ok and shadow_rows > 0
                and (corrupted == 0 or repaired > 0) and converged
            )
            return {
                "bench": "crash_soak",
                "metric": f"crash soak load (cpu, {N_KEYS} zipf keys)",
                "value": round(load_rate, 1),
                "unit": "checks/s",
                "daemons": 3,
                "keys": len(keys),
                "acked_hits": acked,
                "shadow_rows_before_kill": shadow_rows,
                "bound_at_kill": bound_at_kill,
                "measured_loss": loss,
                "loss_within_bound": loss_ok,
                "promoted": promoted,
                "standby_legs_failed": dropped_legs,
                "shadow_rows_corrupted": corrupted,
                "ae_regions_repaired": repaired,
                "ae_converged": converged,
                "crash_soak_ok": ok,
            }
        finally:
            faults.INJECTOR.clear()
            await c.stop()
            if victim not in c.daemons:
                await victim.close()

    return asyncio.run(main())


r = run()
print("RESULT " + json.dumps(r))

from gubernator_tpu.utils import ledger

ledger.append(r, job="44_crash_soak", mode="crash_soak", platform="cpu")
print("GATE " + json.dumps(ledger.gate(job="44_crash_soak", mode="crash_soak")))
sys.exit(0 if r.get("crash_soak_ok") else 1)
