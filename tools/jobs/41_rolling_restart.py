# TIMEOUT: 1800
"""Rolling-restart soak (ISSUE-5 acceptance): restart a 3-daemon
cluster one node at a time UNDER LOAD and assert zero counter resets
and zero failed in-flight requests with GUBER_HANDOVER on.

Procedure per node (docs/robustness.md "Rolling restarts & handover"):
decommission signal (victim ships owned state to ring successors while
still serving) -> membership flip at survivors -> drain close ->
replacement spawn -> membership flip again. Load runs continuously
through every phase; the only tolerated slack is the in-flight window —
hits applied at the victim between its handover snapshot and the
survivors' routing flip (bounded by worker concurrency, NOT by key
count: a counter RESET would lose hundreds of hits per key and trips
the per-key bound immediately).

Prints one `RESULT {json}` line like the other jobs (picked up by
tools/tpu_runner.py / utils/ledger.py).
"""
import json
import sys

sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]

LIMIT = 10_000_000
N_KEYS = 120
WORKERS = 4
PER_KEY_TOLERANCE = 10  # in-flight window hits, not resets


def run() -> dict:
    import asyncio
    import random

    from gubernator_tpu.api.types import (
        PeerInfo,
        RateLimitReq,
        is_retryable_error,
    )
    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.service.config import DaemonConfig
    from gubernator_tpu.service.daemon import Daemon

    name = "rolling_soak"
    keys = [f"acct:{i}" for i in range(N_KEYS)]

    async def main():
        c = await Cluster.start(3, cache_size=65536)
        live = list(c.daemons)  # hammer targets (victim removed pre-close)
        applied = {k: 0 for k in keys}
        shed = 0
        failed = []
        running = True
        rng = random.Random(11)

        async def hammer(wid):
            nonlocal shed
            i = wid
            while running:
                k = keys[i % len(keys)]
                i += WORKERS
                d = live[rng.randrange(len(live))]
                try:
                    out = await d.svc.get_rate_limits(
                        [
                            RateLimitReq(
                                name=name, unique_key=k,
                                duration=600_000, limit=LIMIT, hits=1,
                            )
                        ]
                    )
                except Exception as e:  # transport-level failure
                    failed.append(str(e))
                    continue
                err = out[0].error
                if not err:
                    applied[k] += 1
                elif is_retryable_error(err):
                    shed += 1  # typed shed: never counted, safely redone
                else:
                    failed.append(err)
                await asyncio.sleep(0)

        async def push(daemons, membership):
            infos = [
                PeerInfo(
                    grpc_address=d.grpc_address, http_address=d.http_address
                )
                for d in membership
            ]
            tasks = []
            for d in daemons:
                d.set_peers(infos)
                t = d.svc.picker.handover_last
                if isinstance(t, asyncio.Task) and not t.done():
                    tasks.append(t)
            if tasks:
                await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)

        workers = [asyncio.ensure_future(hammer(w)) for w in range(WORKERS)]
        try:
            await asyncio.sleep(2.0)  # healthy-baseline load
            restarts = 0
            for i in range(len(c.daemons)):
                victim = c.daemons[i]
                survivors = [d for d in c.daemons if d is not victim]
                live[:] = survivors
                await push([victim], survivors)  # decommission: pre-ship
                await push(survivors, survivors)  # routing flips
                await victim.close()  # graceful drain
                replacement = await Daemon.spawn(
                    DaemonConfig(
                        cache_size=65536, behaviors=victim.conf.behaviors
                    )
                )
                c.daemons[i] = replacement
                await push(c.daemons, c.daemons)  # ship the new share
                live[:] = c.daemons
                restarts += 1
                await asyncio.sleep(1.0)  # steady-state load between nodes
        finally:
            running = False
            await asyncio.gather(*workers, return_exceptions=True)

        # Verification: per-key consumed vs applied.
        probe = c.daemons[0]
        worst = 0
        regressed_total = 0
        for k in keys:
            out = await probe.svc.get_rate_limits(
                [
                    RateLimitReq(
                        name=name, unique_key=k, duration=600_000,
                        limit=LIMIT, hits=0,
                    )
                ]
            )
            consumed = LIMIT - out[0].remaining
            regress = applied[k] - consumed
            if regress > 0:
                regressed_total += regress
                worst = max(worst, regress)
        total_applied = sum(applied.values())
        ok = (
            not failed
            and worst <= PER_KEY_TOLERANCE
            and total_applied > 0
        )
        result = {
            "bench": "rolling_restart_soak",
            "daemons": 3,
            "restarts": restarts,
            "keys": N_KEYS,
            "hits_applied": total_applied,
            "hits_shed_retryable": shed,
            "failed_requests": len(failed),
            "failed_sample": failed[:3],
            "regressed_hits_total": regressed_total,
            "regressed_hits_worst_key": worst,
            "per_key_tolerance": PER_KEY_TOLERANCE,
            "handover_keys_sent": int(
                sum(
                    d.svc.metrics.handover_keys_sent.labels().get()
                    for d in c.daemons
                )
            ),
            "zero_loss_ok": ok,
        }
        await c.stop()
        return result

    return asyncio.run(main())


r = run()
print("RESULT " + json.dumps(r))
sys.exit(0 if r.get("zero_loss_ok") else 1)
