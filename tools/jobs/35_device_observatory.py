# TIMEOUT: 1800
"""Device-resource observatory soak (docs/monitoring.md "Device
resources"): drive a DeviceEngine through the serving, snapshot/restore
and readthrough-inject paths, then report what the run actually cost in
device resources — per-subsystem HBM attribution + headroom from
utils/devicemem, the host<->device transfer ledger (bytes, latency and
sustained bandwidth per direction/purpose), and compile telemetry with
retrace attribution. The punchline numbers: HBM headroom after a full
warm-up, and sustainable d2h serve bandwidth (the demux readback is the
serving path's host<->device bottleneck).

Prints one `RESULT {json}` line like the other jobs (picked up by
tools/tpu_runner.py / utils/ledger.py).
"""
import sys, json

sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]


def run() -> dict:
    import time

    from gubernator_tpu.api.types import RateLimitReq
    from gubernator_tpu.runtime.engine import DeviceEngine, EngineConfig

    eng = DeviceEngine(
        EngineConfig(num_groups=1 << 12, ways=8, batch_size=256,
                     batch_wait_s=0.002)
    )

    def reqs(keys, limit=1_000_000):
        return [
            RateLimitReq(name="device_soak", unique_key=k,
                         duration=3_600_000, limit=limit, hits=1)
            for k in keys
        ]

    rounds = 40
    keys_per_round = 512
    t0 = time.monotonic()
    try:
        decided = 0
        for r in range(rounds):
            batch = reqs([f"soak{r % 8}_{i}" for i in range(keys_per_round)])
            decided += len(eng.check_batch(batch))
        # Exercise the snapshot + inject purposes so the ledger has all
        # five rows, not just serve/warmup/census.
        from gubernator_tpu.store.store import ItemSnapshot

        snap = eng.snapshot()
        now_ms = int(time.time() * 1000)
        eng.inject_snapshots([
            ItemSnapshot(key=f"inject{i}", algorithm=0, limit=1_000_000,
                         duration=3_600_000, remaining=5, stamp=now_ms,
                         expire_at=now_ms + 3_600_000)
            for i in range(64)
        ])
        eng.restore(snap)
        wall_s = time.monotonic() - t0

        mem = eng.device_memory()
        transfers = eng.metrics.transfer_snapshot()
        serve = transfers.get("d2h/serve", {})

        from gubernator_tpu.utils import compilecache

        return {
            "bench": "device_observatory",
            "decisions": decided,
            "wall_s": round(wall_s, 3),
            "memory": {
                "source": mem["source"],
                "bytes_in_use": mem["bytes_in_use"],
                "bytes_limit": mem["bytes_limit"],
                "headroom_bytes": mem["headroom_bytes"],
                "headroom_frac": round(mem["headroom_frac"], 4),
                "subsystems": mem["subsystems"],
                "unattributed_bytes": mem["unattributed_bytes"],
            },
            "transfers": transfers,
            # sustainable serve readback bandwidth over the whole soak
            "serve_d2h_bytes_per_s": round(
                serve.get("bytes", 0) / max(wall_s, 1e-9), 1
            ),
            "compile": compilecache.cache_stats(),
            "cold_compiles": eng.metrics.cold_compiles,
        }
    finally:
        eng.close()


r = run()
print("RESULT " + json.dumps(r))
