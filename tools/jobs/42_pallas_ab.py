# TIMEOUT: 3600
# Pallas-vs-XLA decide backend A/B (ISSUE 16): the same seeded Zipf
# trace through GUBER_KERNEL=xla and GUBER_KERNEL=pallas cells at
# identical geometry/layout, for both pallas-eligible layouts. On the
# TPU runner the pallas cells run the mosaic lowering (the fused
# one-HBM-pass kernel this job exists to measure); each cell's raw row
# and the pallas/xla ratio row are ledgered as they land, and the
# runner's auto-gate appends the GATE verdict for the freshest row
# (utils/ledger.gate — a pallas throughput regression fails the job's
# verdict on the next run).
import sys, json
sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]
import bench

r = None
for layout in ("fused", "narrow"):
    row = bench.bench_kernel_ab(sizes=("kernel",), layout=layout)
    r = r or row
print("RESULT " + json.dumps(r))
