# TIMEOUT: 1800
"""Paged-table capacity wall study (docs/architecture.md "Paged table"):
the same Zipf-skewed trace through (a) a flat all-resident engine — the
oracle and latency baseline — and (b) a paged engine whose logical
table is >10x its HBM-resident page budget, so the cold majority of the
keyspace lives in the host-DRAM tier and hot pages cycle through the
resident frames on demand.

Acceptance evidence (ISSUE 12): `keyspace_ratio` >= 10, `p99_ratio`
(paged p99 / all-resident p99 on the skewed serving phase) <= 2, and
`zero_loss` — after the measured phase every key's counter in the paged
engine equals the flat engine's, demote/promote churn included.

Geometry note: a single wave's distinct-page working set must fit the
page budget (PageBudgetError otherwise), so the trace is served in
8-request calls against a 12-frame budget — worst case 8 distinct
pages per wave, with 4 frames of slack for the demoter.

Prints one `RESULT {json}` line (ledgered + auto-gated by
tools/tpu_runner.py).
"""
import sys, json, time

sys.path.insert(0, "/root/repo")
for _m in [k for k in list(sys.modules) if k == "bench" or k.startswith("gubernator_tpu")]:
    del sys.modules[_m]


def run() -> dict:
    import numpy as np

    import jax

    from gubernator_tpu.api.types import RateLimitReq
    from gubernator_tpu.runtime.engine import DeviceEngine, EngineConfig

    platform = jax.devices()[0].platform
    NUM_GROUPS, WAYS = 1 << 12, 8
    PAGE_GROUPS, BUDGET = 32, 12  # 128 logical pages, 12 resident frames
    CALL = 8  # requests per check_batch call (page working set bound)
    N_KEYS = 6_000  # spans every logical page; ~2x the resident slots
    MEASURED_CALLS = 400

    keyspace_ratio = NUM_GROUPS / float(BUDGET * PAGE_GROUPS)

    def mk_engine(paged: bool) -> DeviceEngine:
        kw = dict(
            num_groups=NUM_GROUPS, ways=WAYS, batch_size=64,
            batch_wait_s=0.001,
        )
        if paged:
            kw.update(
                page_groups=PAGE_GROUPS, page_budget=BUDGET,
                page_demote_interval_s=0.5, page_free_target=2,
            )
        return DeviceEngine(EngineConfig(**kw))

    def req(i: int, hits: int = 1) -> RateLimitReq:
        return RateLimitReq(
            name="paged_soak", unique_key=f"acct:{i}",
            duration=3_600_000, limit=1_000_000, hits=hits,
        )

    # Zipf-weighted key ranks: the hot head concentrates on few pages
    # (they stay resident), the cold tail sweeps the whole keyspace.
    rng = np.random.default_rng(36)
    w = 1.0 / np.arange(1, N_KEYS + 1, dtype=np.float64) ** 1.1
    w /= w.sum()
    trace = rng.choice(N_KEYS, size=MEASURED_CALLS * CALL, p=w)

    def drive(eng: DeviceEngine) -> dict:
        # populate: every key once -> all 128 pages hold live rows
        for i in range(0, N_KEYS, CALL):
            eng.check_batch([req(k) for k in range(i, min(i + CALL, N_KEYS))])
        # measured skewed serving
        lat = []
        t0 = time.perf_counter()
        for c in range(MEASURED_CALLS):
            chunk = trace[c * CALL:(c + 1) * CALL]
            s = time.perf_counter()
            for rl in eng.check_batch([req(int(k)) for k in chunk]):
                assert rl.error == "", rl.error
            lat.append(time.perf_counter() - s)
        dt = time.perf_counter() - t0
        # zero-loss probe: every key's exact remaining
        remaining = []
        for i in range(0, N_KEYS, CALL):
            remaining.extend(
                rl.remaining
                for rl in eng.check_batch(
                    [req(k, hits=0) for k in range(i, min(i + CALL, N_KEYS))]
                )
            )
        return {
            "throughput": (MEASURED_CALLS * CALL) / dt,
            "p50_ms": float(np.percentile(lat, 50) * 1e3),
            "p99_ms": float(np.percentile(lat, 99) * 1e3),
            "remaining": remaining,
        }

    flat_eng = mk_engine(paged=False)
    try:
        flat = drive(flat_eng)
    finally:
        flat_eng.close()

    paged_eng = mk_engine(paged=True)
    try:
        paged = drive(paged_eng)
        pager = paged_eng._pager
        census = paged_eng.table_census(max_age_s=0)
        pages = dict(census["pages"])
        pages.pop("page_map", None)
        tier_live = {t: c["live"] for t, c in census["tiers"].items()}
    finally:
        paged_eng.close()

    zero_loss = paged["remaining"] == flat["remaining"]
    p99_ratio = paged["p99_ms"] / flat["p99_ms"] if flat["p99_ms"] else None
    return {
        "bench": "paged_table",
        "metric": (
            f"paged-table skewed serving ({platform}, "
            f"{keyspace_ratio:.1f}x keyspace vs HBM page budget) decisions/s"
        ),
        "value": round(paged["throughput"], 1),
        "unit": "decisions/s",
        "platform": platform,
        "geometry": {
            "num_groups": NUM_GROUPS, "ways": WAYS,
            "page_groups": PAGE_GROUPS, "page_budget": BUDGET,
            "logical_pages": NUM_GROUPS // PAGE_GROUPS,
            "keys": N_KEYS,
        },
        "keyspace_ratio": round(keyspace_ratio, 2),
        "flat": {k: round(v, 3) if isinstance(v, float) else None
                 for k, v in flat.items() if k != "remaining"},
        "paged": {k: round(v, 3) if isinstance(v, float) else None
                  for k, v in paged.items() if k != "remaining"},
        "p99_ratio": round(p99_ratio, 3) if p99_ratio else None,
        "p99_within_2x": bool(p99_ratio is not None and p99_ratio <= 2.0),
        "zero_loss": bool(zero_loss),
        "pager": {
            "demotes": pager.demotes, "promotes": pager.promotes,
            "binds": pager.binds,
        },
        "tier_live": tier_live,
        "pages": pages,
    }


r = run()
print("RESULT " + json.dumps(r))
