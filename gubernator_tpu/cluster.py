"""In-process multi-daemon cluster harness.

The reference's central test fixture boots N full daemons (real gRPC +
HTTP listeners on loopback) inside one process and wires peers statically
— no discovery backend (reference cluster/cluster.go:123-189). Same trick
here: each daemon gets its own DeviceEngine/table/registry, listeners
bind port 0, and the assembled PeerInfo list is pushed through the real
SetPeers path. Helpers locate key owners through the real hash ring
(reference cluster/cluster.go:40-110).
"""

from __future__ import annotations

import random
from typing import List, Optional, Sequence

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.service.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.service.daemon import Daemon

DATACENTER_NONE = ""


class Cluster:
    def __init__(self):
        self.daemons: List[Daemon] = []

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    async def start(
        cls,
        count: int,
        datacenters: Optional[Sequence[str]] = None,
        behaviors: Optional[BehaviorConfig] = None,
        cache_size: int = 8192,
        **daemon_conf,
    ) -> "Cluster":
        """Extra keyword args pass through to every DaemonConfig —
        e.g. ``overload=True, intake_limit=64`` arms the overload
        control plane mesh-wide (tools/jobs/45_overload_soak.py)."""
        c = cls()
        dcs = list(datacenters) if datacenters else [DATACENTER_NONE] * count
        for dc in dcs:
            conf = DaemonConfig(
                data_center=dc,
                cache_size=cache_size,
                behaviors=behaviors or BehaviorConfig(),
                **daemon_conf,
            )
            c.daemons.append(await Daemon.spawn(conf))
        c.rewire()
        return c

    def rewire(self) -> None:
        """Push the full membership to every daemon (SetPeers path)."""
        peers = [
            PeerInfo(
                grpc_address=d.grpc_address,
                http_address=d.http_address,
                data_center=d.conf.data_center,
            )
            for d in self.daemons
        ]
        for d in self.daemons:
            d.set_peers(peers)

    async def stop(self) -> None:
        for d in self.daemons:
            await d.close()
        self.daemons.clear()

    # -- lookup helpers (reference cluster/cluster.go:40-110) ----------------

    def peer_at(self, i: int) -> Daemon:
        return self.daemons[i]

    def get_random_peer(self, dc: str = DATACENTER_NONE) -> Daemon:
        options = [d for d in self.daemons if d.conf.data_center == dc]
        return random.choice(options)

    def find_owning_daemon(self, name: str, unique_key: str) -> Daemon:
        key = name + "_" + unique_key
        peer = self.daemons[0].svc.picker.get(key)
        for d in self.daemons:
            if d.grpc_address == peer.info.grpc_address:
                return d
        raise RuntimeError("owning daemon not found")

    def list_non_owning_daemons(self, name: str, unique_key: str) -> List[Daemon]:
        owner = self.find_owning_daemon(name, unique_key)
        return [d for d in self.daemons if d is not owner]

    def num_of_daemons(self) -> int:
        return len(self.daemons)
