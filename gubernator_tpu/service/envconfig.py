"""Environment-driven daemon configuration (reference config.go:270-479).

Same model as the reference: an optional `--config file` of KEY=VALUE
lines is injected into the environment first, then ~GUBER_* variables are
read with defaults (reference config.go:268-283, 633-658). Library users
skip this entirely and fill DaemonConfig directly.

Duration values accept Go-style suffixes (ns/us/ms/s/m/h) like the
reference's `500ms` / `500ns` examples in example.conf.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.service.config import (
    BehaviorConfig,
    DaemonConfig,
    EtcdConfig,
    K8sConfig,
)
from gubernator_tpu.service.tls import TlsConfig

_DUR_RE = re.compile(r"([0-9.]+)(ns|us|µs|ms|s|m|h)")
_DUR_SCALE = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}


def parse_duration_s(v: str, default: float) -> float:
    """Go-style duration string -> seconds."""
    v = v.strip()
    if not v:
        return default
    total, matched = 0.0, False
    for m in _DUR_RE.finditer(v):
        total += float(m.group(1)) * _DUR_SCALE[m.group(2)]
        matched = True
    if not matched:
        try:
            return float(v)
        except ValueError:
            return default
    return total


def load_config_file(path: str) -> None:
    """Inject KEY=VALUE lines into the environment (values already set in
    the env win, matching the reference's precedence)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, v = line.split("=", 1)
            os.environ.setdefault(k.strip(), v.strip())


def _env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _parse_census_thresholds(v: str) -> tuple:
    """GUBER_TABLE_CENSUS_THRESHOLDS: comma-separated idleness
    multipliers for the census cold-set table (e.g. "1,4,16")."""
    v = v.strip()
    if not v:
        return (1, 4, 16)
    try:
        out = tuple(int(p.strip()) for p in v.split(",") if p.strip())
    except ValueError:
        out = ()
    if not out or any(k < 1 for k in out):
        raise ValueError(
            f"'GUBER_TABLE_CENSUS_THRESHOLDS={v}' is invalid; expected "
            "comma-separated positive integers, e.g. '1,4,16'"
        )
    return out


def setup_daemon_config(config_file: Optional[str] = None) -> DaemonConfig:
    if config_file:
        load_config_file(config_file)

    behaviors = BehaviorConfig(
        batch_timeout_s=parse_duration_s(_env("GUBER_BATCH_TIMEOUT"), 0.5),
        batch_wait_s=parse_duration_s(_env("GUBER_BATCH_WAIT"), 500e-6),
        batch_limit=_env_int("GUBER_BATCH_LIMIT", 1000),
        global_timeout_s=parse_duration_s(_env("GUBER_GLOBAL_TIMEOUT"), 0.5),
        global_sync_wait_s=parse_duration_s(_env("GUBER_GLOBAL_SYNC_WAIT"), 0.1),
        global_batch_limit=_env_int("GUBER_GLOBAL_BATCH_LIMIT", 1000),
        global_peer_requests_concurrency=_env_int(
            "GUBER_GLOBAL_PEER_REQUESTS_CONCURRENCY", 100
        ),
        force_global=_env_bool("GUBER_FORCE_GLOBAL"),
        disable_batching=_env_bool("GUBER_DISABLE_BATCHING"),
        # Fault-domain knobs (docs/robustness.md)
        forward_deadline_s=parse_duration_s(_env("GUBER_FORWARD_DEADLINE"), 2.0),
        circuit_failure_threshold=_env_int("GUBER_CIRCUIT_FAILURE_THRESHOLD", 5),
        circuit_open_base_s=parse_duration_s(_env("GUBER_CIRCUIT_OPEN_BASE"), 0.5),
        circuit_open_max_s=parse_duration_s(_env("GUBER_CIRCUIT_OPEN_MAX"), 30.0),
        circuit_half_open_probes=_env_int("GUBER_CIRCUIT_HALF_OPEN_PROBES", 1),
        owner_unreachable=_env("GUBER_OWNER_UNREACHABLE", "error").lower(),
        peer_queue=_env_int("GUBER_PEER_QUEUE", 1000),
        retry_budget=_env_float("GUBER_RETRY_BUDGET", 0.1),
        global_requeue_limit=_env_int("GUBER_GLOBAL_REQUEUE_LIMIT", 10),
        global_requeue_max_keys=_env_int("GUBER_GLOBAL_REQUEUE_MAX_KEYS", 10_000),
        edge_timeout_s=parse_duration_s(_env("GUBER_EDGE_TIMEOUT"), 30.0),
        # Zero-loss elasticity (docs/robustness.md "Rolling restarts &
        # handover"): GUBER_HANDOVER=off restores the reference's lossy
        # ownership-move semantics.
        handover=_env_bool("GUBER_HANDOVER", True),
        handover_max_keys=_env_int("GUBER_HANDOVER_MAX_KEYS", 100_000),
        handover_chunk=_env_int("GUBER_HANDOVER_CHUNK", 512),
        # Consistency observatory (docs/monitoring.md "Consistency"):
        # divergence-auditor cadence and sample size; interval 0 disables.
        consistency_audit_interval_s=parse_duration_s(
            _env("GUBER_CONSISTENCY_AUDIT_INTERVAL"), 60.0
        ),
        consistency_audit_keys=_env_int("GUBER_CONSISTENCY_AUDIT_KEYS", 32),
        # Cooperative token leases (docs/architecture.md "Cooperative
        # leases"): GUBER_LEASES off keeps every path bit-exact with the
        # pre-lease daemon.
        leases=_env_bool("GUBER_LEASES"),
        lease_ttl_s=parse_duration_s(_env("GUBER_LEASE_TTL"), 2.0),
        lease_fraction=_env_float("GUBER_LEASE_FRACTION", 0.1),
        lease_low_water=_env_float("GUBER_LEASE_LOW_WATER", 0.25),
        lease_max_keys=_env_int("GUBER_LEASE_MAX_KEYS", 4096),
        lease_sweep_interval_s=parse_duration_s(
            _env("GUBER_LEASE_SWEEP_INTERVAL"), 1.0
        ),
        # Server-suggested backoff (ROADMAP item 3 first step).
        retry_after=_env_bool("GUBER_RETRY_AFTER"),
        # Crash-tolerant ownership (docs/robustness.md "Standby
        # replication & crash recovery"): GUBER_STANDBY=0 restores
        # hard-kill counter loss and is bit-exact with the pre-standby
        # daemon.
        standby=_env_bool("GUBER_STANDBY", True),
        standby_interval_s=parse_duration_s(
            _env("GUBER_STANDBY_INTERVAL"), 1.0
        ),
        standby_factor=_env_int("GUBER_STANDBY_FACTOR", 1),
        standby_promote_after_s=parse_duration_s(
            _env("GUBER_STANDBY_PROMOTE_AFTER"), 3.0
        ),
        standby_anti_entropy_interval_s=parse_duration_s(
            _env("GUBER_STANDBY_ANTI_ENTROPY_INTERVAL"), 10.0
        ),
        standby_max_keys=_env_int("GUBER_STANDBY_MAX_KEYS", 100_000),
    )
    if behaviors.standby:
        if behaviors.standby_interval_s <= 0:
            raise ValueError(
                f"'GUBER_STANDBY_INTERVAL={behaviors.standby_interval_s}' "
                "is invalid; expected a positive duration"
            )
        if behaviors.standby_factor < 1:
            raise ValueError(
                f"'GUBER_STANDBY_FACTOR={behaviors.standby_factor}' is "
                "invalid; expected a positive successor count"
            )
        if behaviors.standby_promote_after_s <= 0:
            raise ValueError(
                "'GUBER_STANDBY_PROMOTE_AFTER="
                f"{behaviors.standby_promote_after_s}' is invalid; "
                "expected a positive duration"
            )
    if not (0.0 < behaviors.lease_fraction <= 1.0):
        raise ValueError(
            f"'GUBER_LEASE_FRACTION={behaviors.lease_fraction}' is "
            "invalid; expected a fraction in (0, 1]"
        )
    if behaviors.owner_unreachable not in ("error", "local"):
        raise ValueError(
            f"'GUBER_OWNER_UNREACHABLE={behaviors.owner_unreachable}' is "
            "invalid; choices are [error, local]"
        )
    if behaviors.peer_queue < 1:
        raise ValueError(
            f"'GUBER_PEER_QUEUE={behaviors.peer_queue}' is invalid; the "
            "peer forward queue must hold at least 1 entry"
        )
    if not (0.0 <= behaviors.retry_budget <= 1.0):
        raise ValueError(
            f"'GUBER_RETRY_BUDGET={behaviors.retry_budget}' is invalid; "
            "expected a fraction in [0, 1] (0 disables retries under "
            "sustained failure)"
        )

    conf = DaemonConfig(
        instance_id=_env("GUBER_INSTANCE_ID", ""),
        grpc_listen_address=_env("GUBER_GRPC_ADDRESS", "127.0.0.1:81"),
        http_listen_address=_env("GUBER_HTTP_ADDRESS", "127.0.0.1:80"),
        status_http_listen_address=_env("GUBER_STATUS_HTTP_ADDRESS", ""),
        edge_listen_address=_env("GUBER_EDGE_LISTEN_ADDRESS", ""),
        advertise_address=_env("GUBER_ADVERTISE_ADDRESS", ""),
        data_center=_env("GUBER_DATA_CENTER", ""),
        cache_size=_env_int("GUBER_CACHE_SIZE", 50_000),
        table_layout=_env("GUBER_TABLE_LAYOUT", "fused"),
        behaviors=behaviors,
        global_mode=_env("GUBER_GLOBAL_MODE", "grpc"),
        grpc_max_conn_age_s=float(_env_int("GUBER_GRPC_MAX_CONN_AGE_SEC", 0)),
        trace_level=_env("GUBER_TRACING_LEVEL", "INFO").upper(),
        log_level=_env("GUBER_LOG_LEVEL", "info"),
        log_format=_env("GUBER_LOG_FORMAT", ""),
        debug=_env_bool("GUBER_DEBUG"),
        # Sizes the reference's goroutine pool; N/A for the device engine
        # (see DaemonConfig.worker_count).
        worker_count=_env_int("GUBER_WORKER_COUNT", 0),
        # Block startup on the width-bucket compile ladder (config.py
        # prewarm_buckets docs; ADVICE r4: these were documented but
        # never read from the environment).
        prewarm_buckets=_env_bool("GUBER_PREWARM_BUCKETS"),
        prewarm_timeout_s=parse_duration_s(_env("GUBER_PREWARM_TIMEOUT"), 600.0),
        # SIGTERM drain budget (docs/robustness.md): in-flight RPCs, the
        # engine queue, replication flushes, and the ownership handover
        # all finish inside this window before teardown.
        drain_timeout_s=parse_duration_s(_env("GUBER_DRAIN_TIMEOUT"), 5.0),
        # Continuous-batching pipeline depth (docs/architecture.md
        # "Pipelined dispatch"): 1 = serial pump, >=2 overlaps host
        # encode with device execution. Decisions are bit-exact across
        # depths.
        pipeline_depth=_env_int("GUBER_PIPELINE_DEPTH", 2),
        # Request-lifecycle observability (docs/monitoring.md): hot-key
        # sketch size, per-response stage breakdown, histogram exemplars.
        hotkeys_k=_env_int("GUBER_HOTKEYS_K", 128),
        stage_metadata=_env_bool("GUBER_STAGE_METADATA"),
        exemplars=_env_bool("GUBER_EXEMPLARS", True),
        # Table observatory (docs/monitoring.md "Table census"): census
        # scan TTL, cold-set idleness multipliers, heatmap region count.
        census_ttl_s=parse_duration_s(_env("GUBER_TABLE_CENSUS_TTL"), 5.0),
        census_thresholds=_parse_census_thresholds(
            _env("GUBER_TABLE_CENSUS_THRESHOLDS")
        ),
        census_heatmap_width=_env_int("GUBER_TABLE_CENSUS_HEATMAP", 64),
        # Admission observatory (docs/monitoring.md "Admission"):
        # admission-scan TTL and decision flight-recorder ring size.
        admission_ttl_s=parse_duration_s(_env("GUBER_ADMISSION_TTL"), 5.0),
        admission_ring=_env_int("GUBER_ADMISSION_RING", 256),
        # Paged slot table (docs/architecture.md "Paged table"): page
        # granularity in groups (0 = flat table), resident-page budget,
        # background-demoter cadence, and free-frame headroom target.
        page_groups=_env_int("GUBER_TABLE_PAGE_GROUPS", 0),
        page_budget=_env_int("GUBER_TABLE_PAGE_BUDGET", 0),
        page_demote_interval_s=parse_duration_s(
            _env("GUBER_TABLE_PAGE_DEMOTE_INTERVAL"), 2.0
        ),
        page_free_target=_env_int("GUBER_TABLE_PAGE_FREE_TARGET", 1),
        # SLO observatory + self-watchdog (docs/monitoring.md "SLOs &
        # burn rates"): SLI sampler cadence (0 = off), SLO spec
        # override JSON, heartbeat stall bound (0 = watchdog off).
        slo_sample_interval_s=parse_duration_s(
            _env("GUBER_SLO_SAMPLE_INTERVAL"), 5.0
        ),
        slo_specs=_env("GUBER_SLO_SPECS"),
        watchdog_stall_ms=_env_float("GUBER_WATCHDOG_STALL_MS", 5000.0),
        # Overload control plane (docs/robustness.md "Overload control
        # & brownout"): master switch (off = bit-exact), intake queue
        # budget, CoDel queue-wait target.
        overload=_env_bool("GUBER_OVERLOAD"),
        intake_limit=_env_int("GUBER_INTAKE_LIMIT", 8192),
        intake_target_ms=_env_float("GUBER_INTAKE_TARGET_MS", 20.0),
        # Continuous profiling (docs/monitoring.md "Device resources"):
        # sampler cadence (0 = off), per-capture trace length, and how
        # many trace dirs the rotation keeps.
        profile_interval_s=parse_duration_s(
            _env("GUBER_PROFILE_INTERVAL"), 0.0
        ),
        profile_seconds=parse_duration_s(_env("GUBER_PROFILE_SECONDS"), 0.5),
        profile_keep=_env_int("GUBER_PROFILE_KEEP", 8),
    )
    if conf.profile_keep < 1:
        raise ValueError(
            f"'GUBER_PROFILE_KEEP={conf.profile_keep}' is invalid; the "
            "rotation must keep at least 1 trace"
        )
    if conf.slo_sample_interval_s < 0:
        raise ValueError(
            f"'GUBER_SLO_SAMPLE_INTERVAL={conf.slo_sample_interval_s}' is "
            "invalid; must be >= 0 (0 disables the SLO observatory)"
        )
    if conf.watchdog_stall_ms < 0:
        raise ValueError(
            f"'GUBER_WATCHDOG_STALL_MS={conf.watchdog_stall_ms}' is "
            "invalid; must be >= 0 (0 disables the watchdog)"
        )
    if conf.slo_specs:
        # Fail a malformed GUBER_SLO_SPECS at config time, not at first
        # observatory tick (spec shape errors included).
        from gubernator_tpu.service.slo import parse_slo_specs

        try:
            parse_slo_specs(conf.slo_specs)
        except ValueError as e:
            raise ValueError(f"'GUBER_SLO_SPECS' is invalid: {e}") from None
    if conf.intake_limit < 1:
        raise ValueError(
            f"'GUBER_INTAKE_LIMIT={conf.intake_limit}' is invalid; the "
            "intake budget must admit at least 1 queued entry"
        )
    if conf.intake_target_ms <= 0:
        raise ValueError(
            f"'GUBER_INTAKE_TARGET_MS={conf.intake_target_ms}' is "
            "invalid; the CoDel target must be a positive duration"
        )
    if conf.admission_ring < 1:
        raise ValueError(
            f"'GUBER_ADMISSION_RING={conf.admission_ring}' is invalid; "
            "the decision flight recorder must hold at least 1 entry"
        )
    if conf.census_heatmap_width < 1:
        raise ValueError(
            f"'GUBER_TABLE_CENSUS_HEATMAP={conf.census_heatmap_width}' is "
            "invalid; must be >= 1 heatmap region"
        )
    if conf.pipeline_depth < 1:
        raise ValueError(
            f"'GUBER_PIPELINE_DEPTH={conf.pipeline_depth}' is invalid; "
            "must be >= 1 (1 = serial dispatch)"
        )
    if conf.page_groups < 0:
        raise ValueError(
            f"'GUBER_TABLE_PAGE_GROUPS={conf.page_groups}' is invalid; "
            "must be >= 0 (0 disables table paging)"
        )
    if conf.page_groups > 0 and conf.page_budget < 1:
        raise ValueError(
            f"'GUBER_TABLE_PAGE_BUDGET={conf.page_budget}' is invalid; "
            "must be >= 1 resident page when GUBER_TABLE_PAGE_GROUPS "
            "enables paging"
        )

    # Table layouts validate EARLY against the one registry
    # (ops/kernels.py) so a typo'd GUBER_TABLE_LAYOUT / GUBER_ICI_LAYOUT
    # fails at config time, not at first engine construction.
    from gubernator_tpu.ops.kernels import LAYOUTS

    if conf.table_layout not in LAYOUTS:
        raise ValueError(
            f"'GUBER_TABLE_LAYOUT={conf.table_layout}' is invalid; "
            f"choices are {list(LAYOUTS)}"
        )
    if conf.ici is not None and conf.ici.layout not in LAYOUTS:
        raise ValueError(
            f"'GUBER_ICI_LAYOUT={conf.ici.layout}' is invalid; "
            f"choices are {list(LAYOUTS)}"
        )

    # ICI-mode sizing (GUBER_GLOBAL_MODE=ici): the replica table must be
    # sized so live GLOBAL keys per group stay <= replica ways, or keys
    # degrade to per-replica counting (docs/architecture.md "Overflow
    # and drift bounds"). Analog of the reference's GUBER_CACHE_SIZE for
    # the collective tier.
    if conf.global_mode == "ici":
        # Always built in ici mode (not only when a GUBER_ICI_* sizing
        # var is present), or GUBER_BATCH_WAIT / GUBER_BATCH_LIMIT /
        # GUBER_GLOBAL_SYNC_WAIT would silently fall back to dataclass
        # defaults in an env-sized-by-default deployment.
        from gubernator_tpu.runtime.ici_engine import IciEngineConfig

        base = IciEngineConfig()
        conf.ici = IciEngineConfig(
            num_groups=_env_int("GUBER_ICI_NUM_GROUPS", base.num_groups),
            ways=_env_int("GUBER_ICI_WAYS", base.ways),
            num_slots=_env_int("GUBER_ICI_NUM_SLOTS", base.num_slots),
            replica_ways=_env_int(
                "GUBER_ICI_REPLICA_WAYS", base.replica_ways
            ),
            # the collective tick honors GlobalSyncWait like the gRPC
            # tier, and the micro-batch pump honors GUBER_BATCH_* (ADVICE
            # r4: these were silently reset to dataclass defaults).
            sync_wait_s=behaviors.global_sync_wait_s,
            batch_wait_s=behaviors.batch_wait_s,
            batch_limit=behaviors.batch_limit,
            layout=_env("GUBER_ICI_LAYOUT", base.layout),  # LAYOUTS-validated below
            pipeline_depth=conf.pipeline_depth,
            hotkeys_k=conf.hotkeys_k,
            stage_metadata=conf.stage_metadata,
            exemplars=conf.exemplars,
            census_ttl_s=conf.census_ttl_s,
            census_thresholds=conf.census_thresholds,
            census_heatmap_width=conf.census_heatmap_width,
            # 0 = unbounded (merge the full table every tick)
            max_sync_groups=(
                _env_int("GUBER_ICI_SYNC_GROUPS", base.max_sync_groups or 0)
                or None
            ),
            # Fingerprint-collision backstop for the capped tick: force
            # one full-table tick every N capped ticks (0 = off).
            full_tick_every=_env_int(
                "GUBER_ICI_FULL_TICK_EVERY", base.full_tick_every
            ),
            # Paged sharded tier: same GUBER_TABLE_PAGE_* knobs as the
            # single-chip engine (the unified core pages both; the page
            # map replicates across the mesh, frames shard, and each
            # shard runs its own pool + host-DRAM cold tier).
            page_groups=conf.page_groups,
            page_budget=conf.page_budget,
            page_demote_interval_s=conf.page_demote_interval_s,
            page_free_target=conf.page_free_target,
        )

    # Static peers: GUBER_STATIC_PEERS=grpc1|http1|dc1,grpc2|http2|dc2
    static = _env("GUBER_STATIC_PEERS")
    if static:
        peers: List[PeerInfo] = []
        for part in static.split(","):
            fields = part.split("|")
            peers.append(
                PeerInfo(
                    grpc_address=fields[0],
                    http_address=fields[1] if len(fields) > 1 else "",
                    data_center=fields[2] if len(fields) > 2 else "",
                )
            )
        conf.peers = peers

    conf.discovery = _env("GUBER_PEER_DISCOVERY_TYPE", "static")
    conf.dns_fqdn = _env("GUBER_DNS_FQDN", "")
    conf.dns_interval_s = parse_duration_s(_env("GUBER_DNS_POLL_INTERVAL"), 300.0)
    conf.dns_resolv_conf = _env("GUBER_RESOLV_CONF", "/etc/resolv.conf")
    # member-list / gossip (reference GUBER_MEMBERLIST_* envs)
    conf.gossip_bind = _env("GUBER_MEMBERLIST_ADDRESS", "")
    conf.gossip_advertise = _env("GUBER_MEMBERLIST_ADVERTISE_ADDRESS", "")
    known = _env("GUBER_MEMBERLIST_KNOWN_NODES", "")
    conf.gossip_seeds = [n.strip() for n in known.split(",") if n.strip()]
    conf.gossip_interval_s = parse_duration_s(
        _env("GUBER_MEMBERLIST_GOSSIP_INTERVAL"), 1.0
    )
    conf.gossip_secret = _env("GUBER_MEMBERLIST_SECRET_KEY", "")
    if conf.discovery == "member-list" and not conf.gossip_seeds:
        raise ValueError(
            "when using `member-list` for peer discovery, you MUST provide a "
            "hostname of a known host in the cluster via "
            "`GUBER_MEMBERLIST_KNOWN_NODES`"
        )

    # etcd block (reference GUBER_ETCD_*, config.go:380-404; the reference
    # also accepts the misspelled GUBER_ETCD_TLS_EABLED, config.go:701)
    if conf.discovery == "etcd" or any(
        k.startswith("GUBER_ETCD_") for k in os.environ
    ):
        endpoints = _env("GUBER_ETCD_ENDPOINTS", "localhost:2379")
        conf.etcd = EtcdConfig(
            endpoints=[e.strip() for e in endpoints.split(",") if e.strip()],
            key_prefix=_env("GUBER_ETCD_KEY_PREFIX", "/gubernator-peers"),
            advertise_address=_env(
                "GUBER_ETCD_ADVERTISE_ADDRESS", conf.advertise_address
            ),
            data_center=_env("GUBER_ETCD_DATA_CENTER", conf.data_center),
            dial_timeout_s=parse_duration_s(_env("GUBER_ETCD_DIAL_TIMEOUT"), 5.0),
            user=_env("GUBER_ETCD_USER", ""),
            password=_env("GUBER_ETCD_PASSWORD", ""),
            tls_enabled=_env_bool("GUBER_ETCD_TLS_ENABLE")
            or _env_bool("GUBER_ETCD_TLS_ENABLED")
            or _env_bool("GUBER_ETCD_TLS_EABLED"),  # reference's misspelling
            tls_ca=_env("GUBER_ETCD_TLS_CA", ""),
            tls_cert=_env("GUBER_ETCD_TLS_CERT", ""),
            tls_key=_env("GUBER_ETCD_TLS_KEY", ""),
            tls_skip_verify=_env_bool("GUBER_ETCD_TLS_SKIP_VERIFY"),
        )

    # k8s block (reference GUBER_K8S_*, config.go:405-413 + selector
    # validation :445-449)
    if conf.discovery == "k8s" or any(
        k.startswith("GUBER_K8S_") for k in os.environ
    ):
        mech = _env("GUBER_K8S_WATCH_MECHANISM", "endpoints") or "endpoints"
        if mech not in ("endpoints", "pods"):
            raise ValueError(
                "invalid value for watch mechanism `GUBER_K8S_WATCH_MECHANISM` "
                "needs to be either 'endpoints' or 'pods' (defaults to "
                "'endpoints')"
            )
        conf.k8s = K8sConfig(
            namespace=_env("GUBER_K8S_NAMESPACE", "default"),
            pod_ip=_env("GUBER_K8S_POD_IP", ""),
            pod_port=_env("GUBER_K8S_POD_PORT", ""),
            selector=_env("GUBER_K8S_ENDPOINTS_SELECTOR", ""),
            mechanism=mech,
        )
        if conf.discovery == "k8s" and not conf.k8s.selector:
            raise ValueError(
                "when using k8s for peer discovery, you MUST provide a "
                "`GUBER_K8S_ENDPOINTS_SELECTOR` to select the gubernator "
                "peers from the endpoints listing"
            )

    # Peer picker (reference config.go:421-443): GUBER_PEER_PICKER selects
    # the implementation (only replicated-hash exists). The hash defaults
    # to fnv1a-mix for distribution quality (bare FNV skews badly on
    # sequential keys); set GUBER_PEER_PICKER_HASH=fnv1 ONLY for
    # drop-in key->owner parity with a live reference cluster.
    picker = _env("GUBER_PEER_PICKER", "")
    if picker and picker != "replicated-hash":
        raise ValueError(
            f"'GUBER_PEER_PICKER={picker}' is invalid; choices are "
            "['replicated-hash', 'consistent-hash']"
        )
    conf.peer_picker_hash = _env("GUBER_PEER_PICKER_HASH", "fnv1a-mix")
    if conf.peer_picker_hash not in ("fnv1", "fnv1a", "fnv1a-mix"):
        raise ValueError(
            f"'GUBER_PEER_PICKER_HASH={conf.peer_picker_hash}' is invalid; "
            "choices are [fnv1, fnv1a, fnv1a-mix]"
        )
    conf.hash_replicas = _env_int("GUBER_REPLICATED_HASH_REPLICAS", 512)

    # Optional process/runtime collectors (reference flags.go:19-57,
    # GUBER_METRIC_FLAGS=os,golang; 'golang' maps to Python runtime/GC)
    conf.metric_flags = [
        f.strip() for f in _env("GUBER_METRIC_FLAGS").split(",") if f.strip()
    ]

    import ssl as _ssl

    # Reference getEnvMinVersion (config.go:580-597): "1.0"-"1.3", unknown
    # values fall back to the highest supported version.
    min_map = {
        "": _ssl.TLSVersion.TLSv1_3,  # reference default when unset
        "1.0": _ssl.TLSVersion.TLSv1,
        "1.1": _ssl.TLSVersion.TLSv1_1,
        "1.2": _ssl.TLSVersion.TLSv1_2,
        "1.3": _ssl.TLSVersion.TLSv1_3,
    }
    tls = TlsConfig(
        ca_file=_env("GUBER_TLS_CA"),
        ca_key_file=_env("GUBER_TLS_CA_KEY"),
        cert_file=_env("GUBER_TLS_CERT"),
        key_file=_env("GUBER_TLS_KEY"),
        auto_tls=_env_bool("GUBER_TLS_AUTO"),
        client_auth_ca_file=_env("GUBER_TLS_CLIENT_AUTH_CA_CERT"),
        client_auth_cert_file=_env("GUBER_TLS_CLIENT_AUTH_CERT"),
        client_auth_key_file=_env("GUBER_TLS_CLIENT_AUTH_KEY"),
        client_auth_server_name=_env("GUBER_TLS_CLIENT_AUTH_SERVER_NAME"),
        client_auth={
            "": "none",
            "request": "request",
            "require": "require",
            "require-and-verify": "require",
        }.get(_env("GUBER_TLS_CLIENT_AUTH"), "none"),
        insecure_skip_verify=_env_bool("GUBER_TLS_INSECURE_SKIP_VERIFY"),
        min_version=min_map.get(
            _env("GUBER_TLS_MIN_VERSION").strip(), _ssl.TLSVersion.TLSv1_3
        ),
    )
    conf.tls = (
        tls
        if (tls.ca_file or tls.cert_file or tls.auto_tls)
        else None
    )
    return conf
