"""Environment-driven daemon configuration (reference config.go:270-479).

Same model as the reference: an optional `--config file` of KEY=VALUE
lines is injected into the environment first, then ~GUBER_* variables are
read with defaults (reference config.go:268-283, 633-658). Library users
skip this entirely and fill DaemonConfig directly.

Duration values accept Go-style suffixes (ns/us/ms/s/m/h) like the
reference's `500ms` / `500ns` examples in example.conf.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.service.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.service.tls import TlsConfig

_DUR_RE = re.compile(r"([0-9.]+)(ns|us|µs|ms|s|m|h)")
_DUR_SCALE = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}


def parse_duration_s(v: str, default: float) -> float:
    """Go-style duration string -> seconds."""
    v = v.strip()
    if not v:
        return default
    total, matched = 0.0, False
    for m in _DUR_RE.finditer(v):
        total += float(m.group(1)) * _DUR_SCALE[m.group(2)]
        matched = True
    if not matched:
        try:
            return float(v)
        except ValueError:
            return default
    return total


def load_config_file(path: str) -> None:
    """Inject KEY=VALUE lines into the environment (values already set in
    the env win, matching the reference's precedence)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, v = line.split("=", 1)
            os.environ.setdefault(k.strip(), v.strip())


def _env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def setup_daemon_config(config_file: Optional[str] = None) -> DaemonConfig:
    if config_file:
        load_config_file(config_file)

    behaviors = BehaviorConfig(
        batch_timeout_s=parse_duration_s(_env("GUBER_BATCH_TIMEOUT"), 0.5),
        batch_wait_s=parse_duration_s(_env("GUBER_BATCH_WAIT"), 500e-6),
        batch_limit=_env_int("GUBER_BATCH_LIMIT", 1000),
        global_timeout_s=parse_duration_s(_env("GUBER_GLOBAL_TIMEOUT"), 0.5),
        global_sync_wait_s=parse_duration_s(_env("GUBER_GLOBAL_SYNC_WAIT"), 0.1),
        global_batch_limit=_env_int("GUBER_GLOBAL_BATCH_LIMIT", 1000),
        global_peer_requests_concurrency=_env_int(
            "GUBER_GLOBAL_PEER_REQUESTS_CONCURRENCY", 100
        ),
        force_global=_env_bool("GUBER_FORCE_GLOBAL"),
    )

    conf = DaemonConfig(
        instance_id=_env("GUBER_INSTANCE_ID", ""),
        grpc_listen_address=_env("GUBER_GRPC_ADDRESS", "127.0.0.1:81"),
        http_listen_address=_env("GUBER_HTTP_ADDRESS", "127.0.0.1:80"),
        advertise_address=_env("GUBER_ADVERTISE_ADDRESS", ""),
        data_center=_env("GUBER_DATA_CENTER", ""),
        cache_size=_env_int("GUBER_CACHE_SIZE", 50_000),
        behaviors=behaviors,
        global_mode=_env("GUBER_GLOBAL_MODE", "grpc"),
    )

    # Static peers: GUBER_STATIC_PEERS=grpc1|http1|dc1,grpc2|http2|dc2
    static = _env("GUBER_STATIC_PEERS")
    if static:
        peers: List[PeerInfo] = []
        for part in static.split(","):
            fields = part.split("|")
            peers.append(
                PeerInfo(
                    grpc_address=fields[0],
                    http_address=fields[1] if len(fields) > 1 else "",
                    data_center=fields[2] if len(fields) > 2 else "",
                )
            )
        conf.peers = peers

    conf.discovery = _env("GUBER_PEER_DISCOVERY_TYPE", "static")
    conf.dns_fqdn = _env("GUBER_DNS_FQDN", "")
    conf.dns_interval_s = parse_duration_s(_env("GUBER_DNS_POLL_INTERVAL"), 300.0)
    # member-list / gossip (reference GUBER_MEMBERLIST_* envs)
    conf.gossip_bind = _env("GUBER_MEMBERLIST_ADDRESS", "")
    known = _env("GUBER_MEMBERLIST_KNOWN_NODES", "")
    conf.gossip_seeds = [n.strip() for n in known.split(",") if n.strip()]
    conf.gossip_interval_s = parse_duration_s(
        _env("GUBER_MEMBERLIST_GOSSIP_INTERVAL"), 1.0
    )

    conf.peer_picker_hash = _env("GUBER_PEER_PICKER_HASH", "fnv1")
    conf.hash_replicas = _env_int("GUBER_REPLICATED_HASH_REPLICAS", 512)

    # Optional process/runtime collectors (reference flags.go:19-57,
    # GUBER_METRIC_FLAGS=os,golang; 'golang' maps to Python runtime/GC)
    conf.metric_flags = [
        f.strip() for f in _env("GUBER_METRIC_FLAGS").split(",") if f.strip()
    ]

    tls = TlsConfig(
        ca_file=_env("GUBER_TLS_CA"),
        ca_key_file=_env("GUBER_TLS_CA_KEY"),
        cert_file=_env("GUBER_TLS_CERT"),
        key_file=_env("GUBER_TLS_KEY"),
        auto_tls=_env_bool("GUBER_TLS_AUTO"),
        client_auth_ca_file=_env("GUBER_TLS_CLIENT_AUTH_CA_CERT"),
        client_auth={
            "": "none",
            "request": "request",
            "require": "require",
            "require-and-verify": "require",
        }.get(_env("GUBER_TLS_CLIENT_AUTH"), "none"),
        insecure_skip_verify=_env_bool("GUBER_TLS_INSECURE_SKIP_VERIFY"),
    )
    conf.tls = (
        tls
        if (tls.ca_file or tls.cert_file or tls.auto_tls)
        else None
    )
    return conf
