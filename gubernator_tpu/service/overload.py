"""Overload control plane: bounded intake, fair shedding, brownout.

The engine intake used to be an unbounded queue: a flood (or a retry
storm amplifying one) grew the queue without limit, expired-deadline
work was still executed, and one hot tenant starved everyone enqueued
behind it. This module is the self-protection layer (GUBER_OVERLOAD,
default off = bit-exact with the pre-overload daemon):

- ``IntakeGovernor`` — injected as ``engine.overload`` (the runtime
  stays service-free; the engine duck-types the seam exactly like its
  watchdog hook). ``admit()`` runs before a request is enqueued:
  already-expired deadlines (the PR 3 absolute ``deadline_ms`` wire
  metadata) are refused outright, intake past GUBER_INTAKE_LIMIT is
  shed with the typed retryable ERR_OVERLOADED + ``retry_after_ms``,
  and when the engine's own ``queue_wait`` signal sustains above
  GUBER_INTAKE_TARGET_MS a CoDel-style controller sheds
  probabilistically — weighted per tenant (tenant = rate-limit
  namespace ``req.name``) so a flooding tenant sheds first. Heavy
  hitters are attributed with the PR 7 HotKeySketch machinery. The
  pump side calls ``deadline_expired()`` at pickup so queued work
  whose caller already gave up never touches the device.

- ``RetryBudget`` — token-bucket retry budget (GUBER_RETRY_BUDGET,
  default 10%): each first attempt deposits ``ratio`` tokens, each
  retry spends one. Used by GubernatorClient and the edge relays so
  client retries can never multiply an overload by more than
  ``1 + ratio``.

- ``OverloadManager`` — the brownout ladder. A sampler thread folds
  the PR 17 SLO burn rates (``flush-latency`` fast-burn/exhausted),
  the watchdog's serving-loop stall flag, and the governor's own
  sustained-overload state into one level: normal(0) →
  shed-observability-extras(1) → degraded-local-for-replicas(2) →
  shed-low-priority-tenants(3), with escalation after a short bad
  streak and recovery hysteresis on a longer good streak. The level
  is published as the ``gubernator_overload_level`` gauge and the
  ``/debug/overload`` payload on both listeners (riding DebugInfo
  into /debug/cluster).

Shed responses are stamped with admission provenance (PATH_SHED) and
counted through the DecisionRecorder, so the admission observatory
sees shed traffic instead of losing it. Docs:
docs/robustness.md "Overload control & brownout".
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

from gubernator_tpu.api.keys import key_hash128
from gubernator_tpu.api.types import ERR_OVERLOADED, RateLimitResp
from gubernator_tpu.metrics import HotKeySketch
from gubernator_tpu.parallel.leases import RETRY_AFTER_MD_KEY
from gubernator_tpu.service.admission import PATH_SHED, stamp_decision
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.utils import lockorder
from gubernator_tpu.utils import raceguard

log = logging.getLogger("gubernator_tpu.overload")

# Absolute caller deadline, epoch ms (the PR 3 forwarding wire
# metadata — parallel/peers.py budgets forwards against the same key).
DEADLINE_MD_KEY = "deadline_ms"

# A caller deadline that expired before the request reached the device.
# Deliberately NOT retryable (no RETRYABLE_PREFIX): the caller already
# gave up, re-dispatching the same dead request only adds load.
ERR_DEADLINE_EXPIRED = (
    "DEADLINE_EXCEEDED: caller deadline expired; request not applied"
)

# Shed reason labels (gubernator_intake_shed_counter{reason=...}).
SHED_QUEUE_FULL = "queue_full"  # intake depth >= GUBER_INTAKE_LIMIT
SHED_DEADLINE = "deadline_expired"  # refused at admit or dropped at pickup
SHED_CODEL = "codel"  # standing queue above target; fair-share shed
SHED_TENANT = "tenant"  # same controller, dominant-tenant multiplier
SHED_BROWNOUT = "brownout"  # ladder level 3: heavy tenant shed outright
SHED_REASONS = (
    SHED_QUEUE_FULL,
    SHED_DEADLINE,
    SHED_CODEL,
    SHED_TENANT,
    SHED_BROWNOUT,
)

# Brownout ladder levels, least to most degraded.
LEVEL_NORMAL = 0
LEVEL_SHED_OBSERVABILITY = 1
LEVEL_DEGRADED_LOCAL = 2
LEVEL_SHED_TENANTS = 3
LEVEL_NAMES = (
    "normal",
    "shed_observability",
    "degraded_local",
    "shed_tenants",
)


def request_deadline_ms(req) -> Optional[int]:
    """The absolute epoch-ms deadline a request carries, or None."""
    md = getattr(req, "metadata", None)
    if not md:
        return None
    raw = md.get(DEADLINE_MD_KEY)
    if raw is None:
        return None
    try:
        return int(float(raw))
    except (TypeError, ValueError):
        return None


class RetryBudget:
    """Token-bucket retry budget (the classic retries-as-a-fraction-of-
    first-attempts rule). Every first attempt deposits ``ratio`` tokens
    (capped at ``burst``); every retry spends one. While the server is
    healthy the bucket sits full and retries are free; during sustained
    overload the bucket drains and retries are capped at ``ratio`` of
    the offered first-attempt load — a retry storm can amplify
    overload by at most 1 + ratio."""

    def __init__(self, ratio: float = 0.1, burst: float = 10.0):
        self.ratio = max(0.0, min(float(ratio), 1.0))
        self.burst = max(1.0, float(burst))
        self._lock = lockorder.make_lock("overload.retry_budget")
        self._tokens = self.burst  # start full: first failure may retry
        self._attempts = 0
        self._retries = 0
        self._denied = 0

    def record(self, n: int = 1) -> None:
        """Account ``n`` first attempts (refills the bucket)."""
        with self._lock:
            self._attempts += n
            self._tokens = min(self.burst, self._tokens + n * self.ratio)

    def try_spend(self, cost: float = 1.0) -> bool:
        """Spend budget for one retry; False means drop the retry."""
        with self._lock:
            if self._tokens >= cost:
                self._tokens -= cost
                self._retries += 1
                return True
            self._denied += 1
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ratio": self.ratio,
                "burst": self.burst,
                "tokens": round(self._tokens, 3),
                "attempts": self._attempts,
                "retries": self._retries,
                "denied": self._denied,
            }


raceguard.guarded_by(RetryBudget, {
    "_tokens": "overload.retry_budget",
    "_attempts": "overload.retry_budget",
    "_retries": "overload.retry_budget",
    "_denied": "overload.retry_budget",
})


class IntakeGovernor:
    """Per-engine intake admission controller.

    ``admit(req, depth)`` is called by the engine before enqueueing
    (object path: check_async / check_bulk members); it returns
    ``(shed_resp_or_None, deadline_ms_or_None)``. A non-None response
    short-circuits the request without touching the queue; a non-None
    deadline rides on the slot/future so the pump can drop it at
    pickup via ``deadline_expired()`` + ``refuse_expired()``.

    The CoDel-style controller watches the engine's queue-wait signal
    through ``observe_wait()``: per 100 ms interval it tracks the
    MINIMUM wait (the standing-queue indicator — a transient burst has
    a small min, a standing queue does not). When the interval minimum
    stays above target, shed probability ramps from a small base to
    ``p_max`` over ``ramp_s`` seconds of sustained overload, weighted
    per tenant by recent share-of-intake (EWMA over 1 s windows,
    clamped to [0.25, 4.0]) so the flooder sheds first and light
    tenants mostly pass. Ladder level 3 additionally sheds
    heavy-hitter tenants (window share >= ``heavy_share``) outright.

    Lock order: ``overload.intake`` is leaf-ish — the tenant sketch
    (``metrics.hotkeys``) and the admission recorder
    (``service.admission_ring``) are only touched OUTSIDE it."""

    def __init__(
        self,
        limit: int = 8192,
        target_ms: float = 20.0,
        *,
        metrics=None,
        recorder=None,
        interval_s: float = 0.1,
        window_s: float = 1.0,
        ramp_s: float = 1.0,
        p_base: float = 0.05,
        p_max: float = 0.9,
        heavy_share: float = 0.5,
        tenant_k: int = 128,
        rng=None,
        now=time.monotonic,
    ):
        self.limit = max(1, int(limit))
        self.target_s = max(float(target_ms), 0.001) / 1000.0
        self.metrics = metrics
        self.recorder = recorder
        self.interval_s = max(float(interval_s), 0.001)
        self.window_s = max(float(window_s), self.interval_s)
        self.ramp_s = max(float(ramp_s), 0.001)
        self.p_base = float(p_base)
        self.p_max = float(p_max)
        self.heavy_share = float(heavy_share)
        self.tenant_k = max(1, int(tenant_k))
        self._rng = rng if rng is not None else random.random
        self._now = now
        self._lock = lockorder.make_lock("overload.intake")
        # CoDel interval state.
        self._interval_min: Optional[float] = None
        self._interval_end = now() + self.interval_s
        self._over_since: Optional[float] = None
        self._wait_ewma = 0.0
        # Tenant fairness state. `_tenant_window` accumulates raw admit
        # counts for the current window; on rollover it folds into the
        # `_tenant_rates` EWMA, from which `_tenant_mult` (shed weight)
        # and `_heavy` (level-3 shed set) are rebuilt as fresh objects
        # (admit reads them racily-by-swap, never mutated in place).
        self._tenant_window: dict = {}
        self._tenant_rates: dict = {}
        self._tenant_mult: dict = {}
        self._heavy: frozenset = frozenset()
        self._window_end = now() + self.window_s
        self._level = LEVEL_NORMAL
        self._shed_counts = {r: 0 for r in SHED_REASONS}
        # Heavy-hitter attribution sketch (PR 7 machinery), fed outside
        # the intake lock; suppressed at ladder level >= 1 (it is an
        # observability extra, not a control input).
        self.tenant_sketch = HotKeySketch(
            "overload_intake_tenants",
            "per-tenant intake admits (debug-only sketch)",
            k=self.tenant_k,
        )
        self._hash_cache: dict = {}  # tenant -> (hi, lo), racily rebuilt
        self._shed_children = None
        if metrics is not None:
            self._shed_children = {
                r: metrics.intake_shed_counter.labels(r)
                for r in SHED_REASONS
            }

    # -- admission -----------------------------------------------------------

    def admit(self, req, depth: int):
        """Admission-control one request about to be enqueued. Returns
        ``(resp, deadline_ms)``: a non-None resp is the final answer
        (shed/refused, never enqueued); deadline_ms (when present and
        unexpired) must ride on the slot for pickup-time drop."""
        dl = request_deadline_ms(req)
        if dl is not None and _clock.now_ms() >= dl:
            return self.refuse_expired(req), None
        tenant = req.name or "<none>"
        now = self._now()
        reason = None
        with self._lock:
            self._maybe_roll(now)
            self._tenant_window[tenant] = (
                self._tenant_window.get(tenant, 0) + 1
            )
            level = self._level
            if depth >= self.limit:
                reason = SHED_QUEUE_FULL
            elif level >= LEVEL_SHED_TENANTS and tenant in self._heavy:
                reason = SHED_BROWNOUT
            else:
                p = self._shed_p_locked(now)
                if p > 0.0:
                    mult = self._tenant_mult.get(tenant, 1.0)
                    if self._rng() < min(self.p_max, p * mult):
                        reason = (
                            SHED_TENANT if mult > 1.5 else SHED_CODEL
                        )
            retry_ms = self._retry_after_ms_locked()
        if level < LEVEL_SHED_OBSERVABILITY:
            self.tenant_sketch.update(
                [(self._tenant_hash(tenant), 1, 0, tenant)]
            )
        if reason is None:
            return None, dl
        return self._shed(req, reason, retry_ms), dl

    def deadline_expired(self, deadline_ms: int) -> bool:
        """Pickup-time check for a slot's stored deadline."""
        return _clock.now_ms() >= deadline_ms

    def refuse_expired(self, req) -> RateLimitResp:
        """Terminal (non-retryable) refusal for an expired deadline —
        used both at admit and by the pump at pickup."""
        resp = RateLimitResp(error=ERR_DEADLINE_EXPIRED, metadata={})
        stamp_decision(resp, PATH_SHED)
        self._count_shed(SHED_DEADLINE)
        self._record(req, resp)
        return resp

    def _shed(self, req, reason: str, retry_ms: int) -> RateLimitResp:
        resp = RateLimitResp(
            error=ERR_OVERLOADED,
            metadata={RETRY_AFTER_MD_KEY: str(retry_ms)},
        )
        stamp_decision(resp, PATH_SHED)
        self._count_shed(reason)
        self._record(req, resp)
        return resp

    def _count_shed(self, reason: str) -> None:
        with self._lock:
            self._shed_counts[reason] += 1
        ch = self._shed_children
        if ch is not None:
            ch[reason].inc()

    def _record(self, req, resp) -> None:
        rec = self.recorder
        if rec is not None:
            rec.record_decision(PATH_SHED, resp, key=req.hash_key())

    def _tenant_hash(self, tenant: str):
        h = self._hash_cache.get(tenant)
        if h is None:
            if len(self._hash_cache) >= 4096:
                self._hash_cache = {}
            h = key_hash128(tenant)
            self._hash_cache[tenant] = h
        return h

    # -- queue-wait controller -----------------------------------------------

    def observe_wait(self, wait_s: float) -> None:
        """Fed by the engine pump with each dequeued entry's queue
        wait — the same signal the ``queue_wait`` histogram observes."""
        now = self._now()
        with self._lock:
            self._wait_ewma += 0.1 * (wait_s - self._wait_ewma)
            if self._interval_min is None or wait_s < self._interval_min:
                self._interval_min = wait_s
            self._maybe_roll(now)

    @raceguard.holds_lock("overload.intake")
    def _maybe_roll(self, now: float) -> None:
        """Roll the CoDel interval / fairness window clocks. Runs under
        the intake lock; both admit() and observe_wait() drive it so
        the controller can't go stale when only one side is active."""
        if now >= self._interval_end:
            # An interval with no pump observations has no standing-
            # queue evidence (idle or fully drained): treat as under
            # target — depth-based shedding still covers a stalled pump.
            if (
                self._interval_min is not None
                and self._interval_min > self.target_s
            ):
                if self._over_since is None:
                    self._over_since = now
            else:
                self._over_since = None
            self._interval_min = None
            self._interval_end = now + self.interval_s
        if now >= self._window_end:
            self._roll_window_locked()
            self._window_end = now + self.window_s

    @raceguard.holds_lock("overload.intake")
    def _roll_window_locked(self) -> None:
        counts, self._tenant_window = self._tenant_window, {}
        rates = {}
        for t, r in self._tenant_rates.items():
            nr = 0.5 * r + 0.5 * counts.pop(t, 0)
            if nr >= 0.25:
                rates[t] = nr
        for t, c in counts.items():
            rates[t] = 0.5 * c
        if len(rates) > self.tenant_k:
            keep = sorted(rates, key=rates.get, reverse=True)
            rates = {t: rates[t] for t in keep[: self.tenant_k]}
        self._tenant_rates = rates
        total = sum(rates.values())
        n = len(rates)
        if total > 0.0 and n > 1:
            self._tenant_mult = {
                t: min(4.0, max(0.25, (r / total) * n))
                for t, r in rates.items()
            }
            self._heavy = frozenset(
                t for t, r in rates.items()
                if r / total >= self.heavy_share
            )
        else:
            # A single tenant has no one to be fair against: plain
            # CoDel (mult 1.0) and no heavy set.
            self._tenant_mult = {}
            self._heavy = frozenset()

    def _shed_p_locked(self, now: float) -> float:
        if self._over_since is None:
            return 0.0
        frac = min(1.0, (now - self._over_since) / self.ramp_s)
        return min(self.p_max, self.p_base + frac * self.p_max)

    def _retry_after_ms_locked(self) -> int:
        base_ms = 2.0 * max(self._wait_ewma, self.target_s) * 1000.0
        return max(25, min(int(base_ms), 5000))

    # -- ladder / introspection ----------------------------------------------

    def set_level(self, level: int) -> None:
        with self._lock:
            self._level = max(
                LEVEL_NORMAL, min(int(level), LEVEL_SHED_TENANTS)
            )

    def overloaded(self) -> dict:
        """Controller state for the ladder: sustained standing queue."""
        now = self._now()
        with self._lock:
            self._maybe_roll(now)
            over = self._over_since
            return {
                "overloaded": over is not None,
                "sustained_s": (now - over) if over is not None else 0.0,
            }

    def snapshot(self) -> dict:
        now = self._now()
        with self._lock:
            self._maybe_roll(now)
            over = self._over_since
            snap = {
                "limit": self.limit,
                "target_ms": round(self.target_s * 1000.0, 3),
                "level": self._level,
                "overloaded": over is not None,
                "sustained_s": round(
                    (now - over) if over is not None else 0.0, 3
                ),
                "wait_ewma_ms": round(self._wait_ewma * 1000.0, 3),
                "shed_p": round(self._shed_p_locked(now), 4),
                "retry_after_ms": self._retry_after_ms_locked(),
                "shed": dict(self._shed_counts),
                "tenant_mult": {
                    t: round(m, 3)
                    for t, m in sorted(self._tenant_mult.items())
                },
                "heavy_tenants": sorted(self._heavy),
            }
        sk = self.tenant_sketch.snapshot()
        snap["hot_tenants"] = [
            {"tenant": e["key"], "admits": e["hits"]}
            for e in sk["entries"][:8]
        ]
        return snap


# Declared lock protocol (docs/robustness.md "Race sanitizer").
# `_tenant_mult` / `_heavy` are write-guarded: rebuilt as fresh objects
# under the lock, read racily-by-swap on the admit fast path.
# `_hash_cache` stays DELIBERATELY undeclared: a lost insert only costs
# one recomputed hash.
raceguard.guarded_by(IntakeGovernor, {
    "_interval_min": "overload.intake",
    "_interval_end": "overload.intake",
    "_over_since": "overload.intake",
    "_wait_ewma": "overload.intake",
    "_tenant_window": "overload.intake",
    "_tenant_rates": "overload.intake",
    "_tenant_mult": "w:overload.intake",
    "_heavy": "w:overload.intake",
    "_window_end": "overload.intake",
    "_level": "overload.intake",
    "_shed_counts": "overload.intake",
})


class OverloadManager:
    """The brownout ladder: folds SLO burn rates, the watchdog's
    serving-stall flag, and the governor's sustained-overload state
    into one published degradation level, with escalation streaks and
    recovery hysteresis. Owns the IntakeGovernor the daemon injects
    into the engine."""

    def __init__(
        self,
        svc,
        governor: IntakeGovernor,
        *,
        slo=None,
        watchdog=None,
        interval_s: float = 0.25,
        escalate_after: int = 2,
        hysteresis: int = 8,
    ):
        self.svc = svc
        self.governor = governor
        self.slo = slo
        self.watchdog = watchdog
        self.interval_s = max(float(interval_s), 0.01)
        self.escalate_after = max(1, int(escalate_after))
        self.hysteresis = max(1, int(hysteresis))
        self._level = LEVEL_NORMAL
        self._since_ms = _clock.now_ms()
        self._bad_streak = 0
        self._good_streak = 0
        self._last_signals: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._transition_children = None
        m = getattr(svc, "metrics", None)
        if m is not None:
            self._transition_children = {
                lv: m.overload_transitions.labels(str(lv))
                for lv in range(len(LEVEL_NAMES))
            }

    # -- level effects (read by server/peers/gateway) ------------------------

    @property
    def level(self) -> int:
        return self._level

    def shed_observability(self) -> bool:
        """Level >= 1: drop observability extras on the hot path."""
        return self._level >= LEVEL_SHED_OBSERVABILITY

    def degrade_forwards(self) -> bool:
        """Level >= 2: answer would-be peer forwards locally (the
        degraded-local path) instead of queueing onto a sick mesh."""
        return self._level >= LEVEL_DEGRADED_LOCAL

    # -- evaluation ----------------------------------------------------------

    def evaluate(self) -> int:
        """One ladder step: gather signals, update streaks, maybe move
        one level. Called by the sampler loop; tests call it directly."""
        sigs = {
            "slo_fast_burn": [],
            "serving_stalled": False,
            "intake_overloaded": False,
        }
        slo = self.slo if self.slo is not None else getattr(
            self.svc, "slo", None
        )
        if slo is not None:
            try:
                rows = slo.evaluate()
            except Exception:  # guberlint: allow-swallow -- a broken SLO source must not take down the ladder; the remaining signals still drive it
                rows = []
            for r in rows:
                if r.get("state") in ("fast_burn", "exhausted"):
                    sigs["slo_fast_burn"].append(r.get("id"))
        wd = self.watchdog
        if wd is not None:
            sigs["serving_stalled"] = bool(wd.serving_stalled())
        ov = self.governor.overloaded()
        sigs["intake_overloaded"] = ov["overloaded"]
        pressure = bool(
            sigs["slo_fast_burn"]
            or sigs["serving_stalled"]
            or sigs["intake_overloaded"]
        )
        if pressure:
            self._good_streak = 0
            self._bad_streak += 1
            if (
                self._bad_streak >= self.escalate_after
                and self._level < LEVEL_SHED_TENANTS
            ):
                self._set_level(self._level + 1)
                self._bad_streak = 0
        else:
            self._bad_streak = 0
            self._good_streak += 1
            if (
                self._good_streak >= self.hysteresis
                and self._level > LEVEL_NORMAL
            ):
                self._set_level(self._level - 1)
                self._good_streak = 0
        self._last_signals = sigs
        return self._level

    def _set_level(self, level: int) -> None:
        prev, self._level = self._level, level
        self._since_ms = _clock.now_ms()
        self.governor.set_level(level)
        ch = self._transition_children
        if ch is not None:
            ch[level].inc()
        lvl_log = log.warning if level > prev else log.info
        lvl_log(
            "overload ladder %s: level %d (%s) -> %d (%s)",
            "escalated" if level > prev else "recovered",
            prev, LEVEL_NAMES[prev], level, LEVEL_NAMES[level],
        )

    # -- publication ---------------------------------------------------------

    def metrics_sync(self, m) -> None:
        """Scrape-time bridge (Metrics.add_sync via V1Service)."""
        m.overload_level.set(self._level)

    def debug_info(self) -> dict:
        """/debug/overload payload (also rides DebugInfo into
        /debug/cluster)."""
        return {
            "enabled": True,
            "level": self._level,
            "level_name": LEVEL_NAMES[self._level],
            "since_ms": self._since_ms,
            "escalate_after": self.escalate_after,
            "hysteresis": self.hysteresis,
            "signals": dict(self._last_signals),
            "intake": self.governor.snapshot(),
        }

    # -- lifecycle -----------------------------------------------------------

    def _loop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.beat("overload-ladder", period_s=self.interval_s)
        while not self._stop.wait(self.interval_s):
            if self.watchdog is not None:
                self.watchdog.beat(
                    "overload-ladder", period_s=self.interval_s
                )
            try:
                self.evaluate()
            except Exception:
                # A broken signal source must not kill the ladder; the
                # watchdog beat above keeps the loop itself observable.
                log.exception("overload ladder evaluation failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gubernator-overload-ladder",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if self.watchdog is not None:
            self.watchdog.unregister("overload-ladder")


# The ladder's streak/level state is owned by the sampler thread in
# production; evaluate() is documented as directly callable from tests
# and soak jobs (without start()), so write affinity — not a lock — is
# the right pin, mirroring SloObservatory.
raceguard.guarded_by(OverloadManager, {
    "_thread": "@thread",
})
