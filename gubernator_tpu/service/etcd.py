"""etcd v3 peer discovery (reference etcd.go:42-352).

Same protocol contract as the reference's EtcdPool, on asyncio:

- register: grant a 30s-TTL lease, Put `<prefix>/<grpc_address>` =
  PeerInfo JSON bound to the lease, then stream LeaseKeepAlive; if the
  keepalive stream dies or the server reports TTL=0, re-register with a
  fresh lease after a short backoff (reference etcd.go:221-315).
- watch: Range the prefix to build the peer list, then Watch the prefix
  from that revision; any event triggers a re-Range and an OnUpdate
  callback; watch failures restart with backoff (reference
  etcd.go:109-219).
- close: delete our key and revoke the lease, best-effort (reference
  etcd.go:297-308).

The wire client is a minimal hand-rolled etcdserverpb stub
(protos/etcd.proto) speaking the real etcd gRPC API — no external etcd
client library required. Values are PeerInfo JSON with the reference's
field names; a non-JSON value is treated as a bare gRPC address
(backward-compat behavior, reference etcd.go:162-172).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Dict, List, Optional, Sequence

import grpc

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.service.config import EtcdConfig
from gubernator_tpu.service.protos import etcd_pb2 as epb

log = logging.getLogger("gubernator_tpu.etcd")

ETCD_TIMEOUT_S = 10.0
BACKOFF_S = 5.0
DEFAULT_PREFIX = "/gubernator/peers/"

_SVC_KV = "etcdserverpb.KV"
_SVC_WATCH = "etcdserverpb.Watch"
_SVC_LEASE = "etcdserverpb.Lease"
_SVC_AUTH = "etcdserverpb.Auth"


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix query: range_end = prefix with last byte + 1 (etcd's
    clientv3.GetPrefixRangeEnd)."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return b"\x00"


class EtcdClient:
    """Thin async client over the etcd v3 gRPC API subset.

    Multiple endpoints are supported by rotation: callers invoke
    next_endpoint() after persistent failures and the channel + stubs
    rebuild against the next configured member (the official client
    load-balances; rotation gives the same availability property —
    a healthy member is eventually used)."""

    def __init__(self, conf: EtcdConfig):
        self.conf = conf
        self.endpoints = list(conf.endpoints) or ["localhost:2379"]
        self._endpoint_ix = 0
        self.channel = None
        self._token: Optional[str] = None
        self._build()

    @property
    def endpoint_ix(self) -> int:
        return self._endpoint_ix

    def next_endpoint(self, observed_ix: Optional[int] = None) -> None:
        """Rotate to the next configured etcd member (failover).

        `observed_ix` is the endpoint index the caller saw fail; rotation
        is skipped when another caller already rotated away from it.
        Without this CAS the register and watch loops — which share one
        client — each rotate after failing on the same dead member and
        land straight back on it, a livelock with two endpoints."""
        if len(self.endpoints) <= 1:
            return
        if observed_ix is not None and observed_ix != self._endpoint_ix:
            return  # someone else already failed over
        old = self.channel
        self._endpoint_ix = (self._endpoint_ix + 1) % len(self.endpoints)
        self._token = None  # tokens are per-member sessions
        self._build()
        if old is not None:
            asyncio.ensure_future(old.close())
        log.info("etcd failover to %s", self.endpoints[self._endpoint_ix])

    def _build(self) -> None:
        conf = self.conf
        target = self.endpoints[self._endpoint_ix]
        options = ()
        if conf.tls_enabled:
            from gubernator_tpu.service.tls import TlsConfig, client_credentials, setup_tls

            tls = TlsConfig(
                ca_file=conf.tls_ca,
                cert_file=conf.tls_cert,
                key_file=conf.tls_key,
                insecure_skip_verify=conf.tls_skip_verify,
            )
            setup_tls(tls)
            creds = client_credentials(tls, client_cert=bool(tls.cert_pem))
            if conf.tls_skip_verify:
                options = (("grpc.ssl_target_name_override", "localhost"),)
            self.channel = grpc.aio.secure_channel(target, creds, options=options)
        else:
            self.channel = grpc.aio.insecure_channel(target)
        ch = self.channel
        self.range = ch.unary_unary(
            f"/{_SVC_KV}/Range",
            request_serializer=epb.RangeRequest.SerializeToString,
            response_deserializer=epb.RangeResponse.FromString,
        )
        self.put = ch.unary_unary(
            f"/{_SVC_KV}/Put",
            request_serializer=epb.PutRequest.SerializeToString,
            response_deserializer=epb.PutResponse.FromString,
        )
        self.delete_range = ch.unary_unary(
            f"/{_SVC_KV}/DeleteRange",
            request_serializer=epb.DeleteRangeRequest.SerializeToString,
            response_deserializer=epb.DeleteRangeResponse.FromString,
        )
        self.lease_grant = ch.unary_unary(
            f"/{_SVC_LEASE}/LeaseGrant",
            request_serializer=epb.LeaseGrantRequest.SerializeToString,
            response_deserializer=epb.LeaseGrantResponse.FromString,
        )
        self.lease_revoke = ch.unary_unary(
            f"/{_SVC_LEASE}/LeaseRevoke",
            request_serializer=epb.LeaseRevokeRequest.SerializeToString,
            response_deserializer=epb.LeaseRevokeResponse.FromString,
        )
        self.lease_keepalive = ch.stream_stream(
            f"/{_SVC_LEASE}/LeaseKeepAlive",
            request_serializer=epb.LeaseKeepAliveRequest.SerializeToString,
            response_deserializer=epb.LeaseKeepAliveResponse.FromString,
        )
        self.watch = ch.stream_stream(
            f"/{_SVC_WATCH}/Watch",
            request_serializer=epb.WatchRequest.SerializeToString,
            response_deserializer=epb.WatchResponse.FromString,
        )
        self.authenticate = ch.unary_unary(
            f"/{_SVC_AUTH}/Authenticate",
            request_serializer=epb.AuthenticateRequest.SerializeToString,
            response_deserializer=epb.AuthenticateResponse.FromString,
        )
        self._token: Optional[str] = None

    async def auth_metadata(self) -> Sequence:
        """user/password auth: Authenticate once, then send the token on
        every call (etcd's `token` metadata header)."""
        if not self.conf.user:
            return ()
        if self._token is None:
            resp = await self.authenticate(
                epb.AuthenticateRequest(
                    name=self.conf.user, password=self.conf.password
                ),
                timeout=self.conf.dial_timeout_s,
            )
            self._token = resp.token
        return (("token", self._token),)

    async def close(self) -> None:
        await self.channel.close()


class EtcdPool:
    """Peer discovery pool backed by etcd (reference EtcdPool)."""

    def __init__(
        self,
        conf: EtcdConfig,
        advertise: PeerInfo,
        on_update: Callable[[List[PeerInfo]], None],
        client: Optional[EtcdClient] = None,
    ):
        if not advertise.grpc_address:
            raise ValueError("etcd discovery requires an advertise gRPC address")
        self.conf = conf
        self.advertise = advertise
        self.on_update = on_update
        self.client = client or EtcdClient(conf)
        self.key_prefix = conf.key_prefix or DEFAULT_PREFIX
        if not self.key_prefix.endswith("/"):
            self.key_prefix += "/"
        self._key = (self.key_prefix + advertise.grpc_address).encode()
        self._value = json.dumps(
            {
                "GRPCAddress": advertise.grpc_address,
                "HTTPAddress": advertise.http_address,
                "DataCenter": conf.data_center or advertise.data_center,
            }
        ).encode()
        self._lease_id = 0
        self._running = True
        self.registrations = 0  # observability: counts (re-)registrations
        self._register_task = asyncio.ensure_future(self._register_loop())
        self._watch_task = asyncio.ensure_future(self._watch_loop())

    # -- registration + lease keepalive (reference etcd.go:221-315) ----------

    async def _register_once(self) -> None:
        md = await self.client.auth_metadata()
        lease = await self.client.lease_grant(
            epb.LeaseGrantRequest(TTL=int(self.conf.lease_ttl_s)),
            timeout=ETCD_TIMEOUT_S,
            metadata=md,
        )
        if lease.error:
            raise RuntimeError(f"lease grant: {lease.error}")
        self._lease_id = lease.ID
        await self.client.put(
            epb.PutRequest(key=self._key, value=self._value, lease=lease.ID),
            timeout=ETCD_TIMEOUT_S,
            metadata=md,
        )
        self.registrations += 1

    async def _register_loop(self) -> None:
        backoff = 0.5
        while self._running:
            ix = self.client.endpoint_ix
            try:
                await self._register_once()
                log.info(
                    "registered %s with etcd (lease %d)",
                    self.advertise.grpc_address, self._lease_id,
                )
                backoff = 0.5
                await self._keepalive_until_lost()
                if self._running:
                    log.warning("keep alive lost, attempting to re-register peer")
            except asyncio.CancelledError:
                return
            except Exception as e:
                if not self._running:
                    return
                log.warning("etcd registration failed: %s", e)
                self.client.next_endpoint(ix)
            await asyncio.sleep(min(backoff, BACKOFF_S))
            backoff *= 2

    async def _keepalive_until_lost(self) -> None:
        """Stream keepalives every TTL/3; returns when the lease is lost
        (stream error, stream end, or server-reported TTL<=0)."""
        interval = max(self.conf.lease_ttl_s / 3.0, 0.05)
        md = await self.client.auth_metadata()
        call = self.client.lease_keepalive(metadata=md)

        async def sender():
            try:
                while self._running:
                    await call.write(epb.LeaseKeepAliveRequest(ID=self._lease_id))
                    await asyncio.sleep(interval)
            # guberlint: allow-swallow -- a dead keepalive sender surfaces as a read timeout in the outer loop, which re-registers
            except Exception:
                pass

        send_task = asyncio.ensure_future(sender())
        try:
            while self._running:
                resp = await asyncio.wait_for(
                    call.read(), timeout=self.conf.lease_ttl_s + ETCD_TIMEOUT_S
                )
                if resp is grpc.aio.EOF:
                    return
                if resp.TTL <= 0:  # lease expired/revoked server-side
                    return
        except (asyncio.TimeoutError, grpc.aio.AioRpcError):
            return
        finally:
            send_task.cancel()
            try:
                call.cancel()
            # guberlint: allow-swallow -- cancel of an already-torn stream raises in some grpc versions; teardown is the goal
            except Exception:
                pass

    # -- watch + peer collection (reference etcd.go:109-219) -----------------

    async def _collect_peers(self) -> int:
        md = await self.client.auth_metadata()
        prefix = self.key_prefix.encode()
        resp = await self.client.range(
            epb.RangeRequest(key=prefix, range_end=prefix_range_end(prefix)),
            timeout=ETCD_TIMEOUT_S,
            metadata=md,
        )
        peers: Dict[str, PeerInfo] = {}
        for kv in resp.kvs:
            p = self._unmarshal(kv.value)
            peers[p.grpc_address] = p
        out = []
        for p in peers.values():
            if p.grpc_address == self.advertise.grpc_address:
                p = PeerInfo(
                    grpc_address=p.grpc_address,
                    http_address=p.http_address,
                    data_center=p.data_center,
                    is_owner=True,
                )
            out.append(p)
        self.on_update(out)
        return resp.header.revision

    def _unmarshal(self, value: bytes) -> PeerInfo:
        try:
            d = json.loads(value)
            return PeerInfo(
                grpc_address=d.get("GRPCAddress", ""),
                http_address=d.get("HTTPAddress", ""),
                data_center=d.get("DataCenter", ""),
            )
        except (json.JSONDecodeError, UnicodeDecodeError, AttributeError):
            # Backward compat: a bare address value (reference
            # etcd.go:162-172)
            return PeerInfo(grpc_address=value.decode(errors="replace"))

    async def _watch_loop(self) -> None:
        while self._running:
            ix = self.client.endpoint_ix
            try:
                revision = await self._collect_peers()
                md = await self.client.auth_metadata()
                call = self.client.watch(metadata=md)
                prefix = self.key_prefix.encode()
                await call.write(
                    epb.WatchRequest(
                        create_request=epb.WatchCreateRequest(
                            key=prefix,
                            range_end=prefix_range_end(prefix),
                            start_revision=revision + 1,
                        )
                    )
                )
                try:
                    while self._running:
                        resp = await call.read()
                        if resp is grpc.aio.EOF or resp.canceled:
                            break
                        if resp.events:
                            await self._collect_peers()
                finally:
                    try:
                        call.cancel()
                    # guberlint: allow-swallow -- cancel of an already-torn stream raises in some grpc versions; teardown is the goal
                    except Exception:
                        pass
            except asyncio.CancelledError:
                return
            except Exception as e:
                if not self._running:
                    return
                log.warning("etcd watch failed, restarting: %s", e)
                self.client.next_endpoint(ix)
            if self._running:
                await asyncio.sleep(0.5)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Synchronous close (PoolInterface contract); schedules the
        deregistration on the running loop."""
        if not self._running:
            return
        self._running = False
        self._register_task.cancel()
        self._watch_task.cancel()
        asyncio.ensure_future(self._deregister())

    async def aclose(self) -> None:
        if self._running:
            self._running = False
            self._register_task.cancel()
            self._watch_task.cancel()
        await self._deregister()

    async def _deregister(self) -> None:
        try:
            md = await self.client.auth_metadata()
            await self.client.delete_range(
                epb.DeleteRangeRequest(key=self._key),
                timeout=ETCD_TIMEOUT_S,
                metadata=md,
            )
            if self._lease_id:
                await self.client.lease_revoke(
                    epb.LeaseRevokeRequest(ID=self._lease_id),
                    timeout=ETCD_TIMEOUT_S,
                    metadata=md,
                )
        except Exception as e:
            log.warning("during etcd deregistration: %s", e)
        finally:
            await self.client.close()
