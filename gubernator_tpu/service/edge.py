"""Disaggregated serving edge: framed RPC between edge processes and
the device daemon.

TPU-native scale-out of the serving tier (SURVEY.md §2.3 sharding row;
docs/benchmarks.md round-2/3 edge analysis): the chip — and the one
process owning its HBM slot table — is the scarce resource, while gRPC
/ HTTP2 / TLS termination and the native wire parse are horizontally
scalable host work. N `gubernator-tpu-edge` processes terminate client
gRPC and relay each call over a length-prefixed stream (unix socket or
TCP, usually loopback) to the device daemon, which serves it through
the SAME core as its own gRPC listener
(grpc_service.serve_get_rate_limits_bytes: columnar fast path,
mixed-ownership splitting, object-path fallback) minus the gRPC server
cost. The reference scales by adding whole nodes to the peer mesh
(reference README.md:129-139); this splits a node into a device tier
and an edge tier instead — the edge speaks the identical V1 wire API,
so reference clients cannot tell the difference.

Frame format (little-endian):
    request:  u32 frame_len | u8 method | u64 call_id | payload
    response: u32 frame_len | u8 status | u64 call_id | payload
methods: 1 = V1/GetRateLimits (payload = GetRateLimitsReq bytes)
         2 = V1/HealthCheck   (payload ignored)
         3 = V1/Lease         (payload = lease request bytes, pb.py codec)
status:  0 = ok    (payload = response message bytes)
         1 = error (payload = u8 code_len | grpc-code-name | utf-8 message)
Responses are matched by call_id and may arrive out of order (the
listener serves frames concurrently; a slow mixed-ownership call does
not head-of-line-block a columnar one on the same connection).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Optional, Tuple

log = logging.getLogger("gubernator_tpu.edge")

METHOD_GET_RATE_LIMITS = 1
METHOD_HEALTH_CHECK = 2
METHOD_LEASE = 3

_HDR = struct.Struct("<IBQ")  # frame_len (of method..payload) | method | call_id
MAX_FRAME = 8 << 20  # a 1000-item batch is ~100KB; 8MB is generous


class EdgeError(Exception):
    """Transported whole-call failure (grpc code name + message)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _pack(method_or_status: int, call_id: int, payload: bytes) -> bytes:
    return _HDR.pack(9 + len(payload), method_or_status, call_id) + payload


async def _read_frame(reader) -> Optional[Tuple[int, int, bytes]]:
    """Returns (method_or_status, call_id, payload) or None on EOF."""
    try:
        hdr = await reader.readexactly(4)
        (flen,) = struct.unpack("<I", hdr)
        if flen < 9 or flen > MAX_FRAME:
            raise ValueError(f"bad frame length {flen}")
        body = await reader.readexactly(flen)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None  # peer died mid-frame: same as EOF
    tag, call_id = struct.unpack("<BQ", body[:9])
    return tag, call_id, body[9:]


def _split_address(address: str) -> Tuple[bool, str, int]:
    """(is_unix, path_or_host, port). unix:///path, /path, or host:port."""
    if address.startswith("unix://"):
        return True, address[len("unix://"):], 0
    if address.startswith("/"):
        return True, address, 0
    host, port = address.rsplit(":", 1)
    return False, host.strip("[]"), int(port)


# ---- device-daemon side ----------------------------------------------------


class EdgeListener:
    """Accepts edge-process connections inside the device daemon and
    serves frames through the daemon's V1 core."""

    def __init__(self, svc, address: str):
        self.svc = svc
        self.address = address
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()

    async def start(self) -> None:
        is_unix, host, port = _split_address(self.address)
        if is_unix:
            # asyncio never removes the socket file; a stale one from a
            # previous daemon (clean exit or crash) would EADDRINUSE
            import contextlib
            import os

            with contextlib.suppress(OSError):
                os.unlink(host)
            self._server = await asyncio.start_unix_server(self._conn, path=host)
        else:
            self._server = await asyncio.start_server(self._conn, host, port)
        log.info("edge listener on %s", self.address)

    @property
    def bound_address(self) -> str:
        if self.address.startswith(("unix://", "/")):
            return self.address
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        return f"{host}:{port}"

    async def _conn(self, reader, writer) -> None:
        wlock = asyncio.Lock()  # frame writes must not interleave
        tasks = set()
        self._writers.add(writer)
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                t = asyncio.ensure_future(self._serve(frame, writer, wlock))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        except (ValueError, ConnectionResetError) as e:
            log.warning("edge connection dropped: %s", e)
        finally:
            for t in tasks:
                t.cancel()
            self._writers.discard(writer)
            writer.close()

    async def _serve(self, frame, writer, wlock) -> None:
        from gubernator_tpu.service import pb
        from gubernator_tpu.service.grpc_service import (
            serve_get_rate_limits_bytes,
            serve_lease_bytes,
        )
        from gubernator_tpu.service.server import ApiError

        from gubernator_tpu.service.grpc_service import _instrumented

        method, call_id, payload = frame
        try:
            # Same instrumentation labels as the gRPC listener: in an
            # all-edge deployment the daemon's request count/duration
            # metrics must still see the traffic.
            if method == METHOD_GET_RATE_LIMITS:
                async with _instrumented(
                    self.svc.metrics, "/pb.gubernator.V1/GetRateLimits"
                ):
                    out = await serve_get_rate_limits_bytes(self.svc, payload)
            elif method == METHOD_LEASE:
                async with _instrumented(
                    self.svc.metrics, "/pb.gubernator.V1/Lease"
                ):
                    out = await serve_lease_bytes(self.svc, payload, None)
            elif method == METHOD_HEALTH_CHECK:
                async with _instrumented(
                    self.svc.metrics, "/pb.gubernator.V1/HealthCheck"
                ):
                    out = pb.health_to_pb(
                        await self.svc.health_check()
                    ).SerializeToString()
            else:
                raise ApiError(f"unknown edge method {method}", grpc_code="INTERNAL")
            resp = _pack(0, call_id, out)
        except ApiError as e:
            code = e.grpc_code.encode()
            resp = _pack(
                1, call_id, bytes([len(code)]) + code + str(e).encode()
            )
        except asyncio.CancelledError:
            raise
        # guberlint: allow-swallow -- the failure is serialized back to the edge client as an INTERNAL error frame
        except Exception as e:
            msg = f"edge serve failure: {e}".encode()
            resp = _pack(1, call_id, bytes([8]) + b"INTERNAL" + msg)
        try:
            async with wlock:
                writer.write(resp)
                await writer.drain()
        except (ConnectionResetError, RuntimeError):
            pass  # edge went away; its client sees the broken channel

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        # server.close() only stops ACCEPTING; close live connections so
        # edges see EOF now (and so 3.12's wait_closed — which waits for
        # connection handlers — can finish)
        for w in list(self._writers):
            w.close()
        if self._server is not None:
            await self._server.wait_closed()


# ---- edge-process side -----------------------------------------------------


class EdgeClient:
    """Multiplexed client: N connections to the device daemon, calls
    matched to responses by call_id. Reconnects lazily on failure.

    `timeout_s` is the default per-call deadline (sourced from
    BehaviorConfig.edge_timeout_s / GUBER_EDGE_TIMEOUT by the edge
    entry point; it was a hard-coded 30.0). `timeout_counter` is any
    .inc()-able — timed-out calls bump it so edge-tier stalls are
    observable at the edge's /metrics.

    With `retries` > 0 (knob GUBER_EDGE_RETRIES at the edge entry
    point) UNAVAILABLE transport legs are re-sent under a token-bucket
    RetryBudget (service/overload.py, knob GUBER_RETRY_BUDGET): each
    first attempt deposits `retry_budget` tokens and each retry spends
    one, so an edge fleet's retry storm can amplify daemon load by at
    most 1 + retry_budget. `retries=0` (the constructor default) is
    the historical single-shot relay, bit-exact."""

    def __init__(
        self,
        address: str,
        connections: int = 2,
        timeout_s: float = 30.0,
        timeout_counter=None,
        retries: int = 0,
        retry_budget: float = 0.1,
    ):
        self.address = address
        self.timeout_s = timeout_s
        self.timeout_counter = timeout_counter
        self.retries = max(0, int(retries))
        self.retry_budget = None
        if self.retries > 0:
            from gubernator_tpu.service.overload import RetryBudget

            self.retry_budget = RetryBudget(ratio=retry_budget)
        self._n = max(1, connections)
        self._conns: list = [None] * self._n
        self._locks = [asyncio.Lock() for _ in range(self._n)]
        self._rr = itertools.count()
        self._ids = itertools.count(1)
        self._pending: dict = {}

    async def _connect(self, i: int):
        is_unix, host, port = _split_address(self.address)
        if is_unix:
            reader, writer = await asyncio.open_unix_connection(host)
        else:
            reader, writer = await asyncio.open_connection(host, port)
        conn = {"reader": reader, "writer": writer, "wlock": asyncio.Lock()}
        conn["pump"] = asyncio.ensure_future(self._pump(conn))
        self._conns[i] = conn
        return conn

    async def _pump(self, conn) -> None:
        try:
            while True:
                frame = await _read_frame(conn["reader"])
                if frame is None:
                    break
                status, call_id, payload = frame
                fut = self._pending.pop(call_id, None)
                if fut is not None and not fut.done():
                    fut.set_result((status, payload))
        except Exception as e:
            log.warning("edge upstream read failed: %s", e)
        finally:
            conn["dead"] = True
            # fail whatever was in flight on this connection
            for call_id in list(conn.get("calls", ())):
                fut = self._pending.pop(call_id, None)
                if fut is not None and not fut.done():
                    fut.set_exception(
                        EdgeError("UNAVAILABLE", "device daemon connection lost")
                    )

    async def call(
        self, method: int, payload: bytes, timeout: Optional[float] = None
    ) -> bytes:
        """One framed call, with budgeted UNAVAILABLE retries. Only
        transport-level UNAVAILABLE legs (daemon unreachable, pipe lost)
        re-send; DEADLINE_EXCEEDED and typed daemon errors propagate
        immediately — the daemon may already have applied the work."""
        budget = self.retry_budget
        if budget is not None:
            budget.record(1.0)
        attempt = 0
        while True:
            try:
                return await self._call_once(method, payload, timeout)
            except EdgeError as e:
                if (
                    e.code != "UNAVAILABLE"
                    or attempt >= self.retries
                    or budget is None
                    or not budget.try_spend()
                ):
                    raise
                attempt += 1
                await asyncio.sleep(min(0.025 * (2 ** attempt), 1.0))

    async def _call_once(
        self, method: int, payload: bytes, timeout: Optional[float] = None
    ) -> bytes:
        from gubernator_tpu.utils import faults

        if timeout is None:
            timeout = self.timeout_s
        if faults.active():
            try:
                await faults.inject(faults.EDGE_TARGET, faults.OP_EDGE_CALL)
            except faults.FaultInjected as e:
                raise EdgeError("UNAVAILABLE", str(e))
        i = next(self._rr) % self._n
        async with self._locks[i]:
            conn = self._conns[i]
            if conn is None or conn.get("dead"):
                try:
                    conn = await self._connect(i)
                except OSError as e:
                    raise EdgeError("UNAVAILABLE", f"device daemon unreachable: {e}")
        call_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[call_id] = fut
        conn.setdefault("calls", set()).add(call_id)
        try:
            # Re-check AFTER registration: a pump that died in the gap
            # has already snapshotted conn["calls"] without this id, so
            # nobody would ever fail the future.
            if conn.get("dead"):
                raise EdgeError("UNAVAILABLE", "device daemon connection lost")
            async with conn["wlock"]:
                conn["writer"].write(_pack(method, call_id, payload))
                await conn["writer"].drain()
            status, resp = await asyncio.wait_for(fut, timeout)
        except EdgeError:
            raise
        except (OSError, ConnectionResetError) as e:
            conn["dead"] = True
            raise EdgeError("UNAVAILABLE", f"device daemon connection lost: {e}")
        except asyncio.TimeoutError:
            if self.timeout_counter is not None:
                self.timeout_counter.inc()
            raise EdgeError("DEADLINE_EXCEEDED", "device daemon call timed out")
        finally:
            # no-op on the happy path (the pump pops before resolving);
            # guarantees no leak on timeout/cancellation/errors
            self._pending.pop(call_id, None)
            conn.get("calls", set()).discard(call_id)
        if status == 0:
            return resp
        code_len = resp[0]
        code = resp[1 : 1 + code_len].decode("ascii", errors="replace")
        raise EdgeError(code, resp[1 + code_len :].decode("utf-8", errors="replace"))

    async def close(self) -> None:
        for conn in self._conns:
            if conn is not None:
                conn["pump"].cancel()
                conn["writer"].close()
        self._conns = [None] * self._n


class EdgeLeases:
    """Edge-tier lease holder: a LeaseCache plus the maintenance driver
    that reconciles it with the device daemon over METHOD_LEASE frames.

    Wired into EdgeV1Servicer / build_edge_app when GUBER_LEASES is on
    at the edge process; None (the default) keeps the edge a pure byte
    relay — bit-exact with today's wire behavior. Maintenance is lazy:
    each served call checks cache.due() and fires at most one
    background Lease RPC (renew at the low-water mark, returns for
    retired slices, grants for newly-wanted keys) — the cache's
    `inflight` flag is the only serialization needed because the edge
    process is single-loop. Maintenance frames ride EdgeClient.call,
    so when the edge runs with retries they share its RetryBudget —
    a flapping daemon pipe cannot turn lease upkeep into a retry
    storm."""

    def __init__(self, client: EdgeClient, cache, holder: str = "edge",
                 local_counter=None, recorder=None):
        self.client = client
        self.cache = cache
        self.holder = holder
        self.local_counter = local_counter
        # DecisionRecorder (service/admission.py): edge-answered debits
        # count under path=lease like holder-side daemon answers do.
        self.recorder = recorder
        self._tasks: set = set()

    def try_serve(self, req):
        resp = self.cache.try_serve(req)
        if resp is not None:
            if self.local_counter is not None:
                self.local_counter.inc()
            if self.recorder is not None:
                from gubernator_tpu.parallel.leases import (
                    LEASE_STALENESS_MD_KEY,
                )
                from gubernator_tpu.service.admission import PATH_LEASE

                self.recorder.record_decision(
                    PATH_LEASE,
                    resp,
                    key=req.hash_key(),
                    staleness_ms=int(
                        resp.metadata.get(LEASE_STALENESS_MD_KEY, 0)
                    ),
                )
        return resp

    def kick(self) -> None:
        if not self.cache.due():
            return
        t = asyncio.ensure_future(self.maintain())
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def maintain(self) -> None:
        from gubernator_tpu.service import pb

        grants, returns = self.cache.collect()
        if not grants and not returns:
            self.cache.inflight = False
            return
        try:
            raw = await self.client.call(
                METHOD_LEASE,
                pb.lease_req_to_bytes(grants, returns, holder=self.holder),
            )
            g_res, _r_res, _md = pb.lease_resp_from_bytes(raw)
        except (EdgeError, ValueError, TypeError) as e:
            log.debug("edge lease maintenance failed: %s", e)
            self.cache.abort()
            return
        self.cache.apply(grants, g_res)

    async def close(self) -> None:
        """Best-effort final return of every held slice so the owner
        reclaims tokens as `returned` instead of waiting for expiry."""
        # A renewal in flight re-installs an entry on apply(); let it
        # land first so the final return covers every live slice.
        for t in list(self._tasks):
            try:
                await asyncio.wait_for(t, timeout=2.0)
            except (asyncio.TimeoutError, EdgeError):
                pass
        self.cache.drain_for_close()
        try:
            await asyncio.wait_for(self.maintain(), timeout=2.0)
        except (asyncio.TimeoutError, EdgeError):
            pass


async def _redispatch_sheds(
    client: EdgeClient, req_msg, raw_resp: bytes
) -> bytes:
    """One budgeted re-dispatch of per-item typed retryable errors (the
    daemon's overload governor refused those items without applying
    them — api.types.is_retryable_error), paced by the server's
    retry_after_ms response metadata. Active only when the EdgeClient
    has a RetryBudget (GUBER_EDGE_RETRIES > 0); the gate below is a
    bytes scan, so a shed-free response costs no protobuf parse."""
    from gubernator_tpu.api.types import RETRYABLE_PREFIX, is_retryable_error
    from gubernator_tpu.service import pb

    budget = client.retry_budget
    if budget is None or RETRYABLE_PREFIX.encode() not in raw_resp:
        return raw_resp
    try:
        resp = pb.pb.GetRateLimitsResp.FromString(raw_resp)
    except Exception:  # guberlint: allow-swallow -- a response we cannot parse relays verbatim; the client sees exactly what the daemon sent
        return raw_resp
    retry = [
        (i, m)
        for i, m in enumerate(resp.responses)
        if i < len(req_msg.requests) and is_retryable_error(m.error)
    ]
    if not retry or not budget.try_spend():
        return raw_resp
    delay = 0.05
    for _, m in retry:
        try:
            delay = max(delay, int(m.metadata.get("retry_after_ms", 0)) / 1000.0)
        except (TypeError, ValueError):
            pass
    await asyncio.sleep(min(delay, 5.0))
    sub = pb.pb.GetRateLimitsReq()
    for i, _ in retry:
        sub.requests.append(req_msg.requests[i])
    try:
        sub_resp = pb.pb.GetRateLimitsResp.FromString(
            await client.call(METHOD_GET_RATE_LIMITS, sub.SerializeToString())
        )
    except (EdgeError, ValueError):
        return raw_resp  # keep the original typed sheds; they are retryable
    for (i, _), m in zip(retry, sub_resp.responses):
        resp.responses[i].CopyFrom(m)
    return resp.SerializeToString()


async def serve_edge_get_rate_limits(
    client: EdgeClient, raw: bytes, leases: Optional[EdgeLeases] = None
) -> bytes:
    """GetRateLimits over the framed upstream, optionally through the
    edge lease cache: leased items are answered locally (zero frames to
    the daemon), only the misses are forwarded, and the responses are
    spliced back in request order. With `leases` None and no retry
    budget this is exactly the old one-line byte relay; with a budget
    (GUBER_EDGE_RETRIES) per-item overload sheds get one budgeted,
    retry_after_ms-paced re-dispatch before reaching the client."""
    if leases is None and client.retry_budget is None:
        return await client.call(METHOD_GET_RATE_LIMITS, raw)
    from gubernator_tpu.service import pb

    try:
        msg = pb.pb.GetRateLimitsReq.FromString(raw)
    except Exception:  # guberlint: allow-swallow -- unparseable payload relays verbatim so the daemon produces the same error a lease-less edge would
        return await client.call(METHOD_GET_RATE_LIMITS, raw)
    if leases is None:
        return await _redispatch_sheds(
            client, msg, await client.call(METHOD_GET_RATE_LIMITS, raw)
        )
    local = {}
    miss: list = []
    for i, m in enumerate(msg.requests):
        resp = leases.try_serve(pb.req_from_pb(m))
        if resp is not None:
            local[i] = resp
        else:
            miss.append(i)
    leases.kick()
    if not local:
        return await _redispatch_sheds(
            client, msg, await client.call(METHOD_GET_RATE_LIMITS, raw)
        )
    fwd_resps = []
    if miss:
        sub = pb.pb.GetRateLimitsReq()
        for i in miss:
            sub.requests.append(msg.requests[i])
        fwd_raw = await client.call(
            METHOD_GET_RATE_LIMITS, sub.SerializeToString()
        )
        fwd_resps = list(
            pb.pb.GetRateLimitsResp.FromString(fwd_raw).responses
        )
    out = pb.pb.GetRateLimitsResp()
    from gubernator_tpu.api.types import RateLimitResp

    fwd_it = iter(fwd_resps)
    for i in range(len(msg.requests)):
        if i in local:
            out.responses.append(pb.resp_to_pb(local[i]))
        else:
            nxt = next(fwd_it, None)
            if nxt is None:  # daemon returned fewer rows than sent
                out.responses.append(
                    pb.resp_to_pb(RateLimitResp(error="missing response"))
                )
            else:
                out.responses.append(nxt)
    return await _redispatch_sheds(client, msg, out.SerializeToString())


class EdgeV1Servicer:
    """grpc.aio servicer for the edge process: relays raw bytes.

    With `leases` (an EdgeLeases), GetRateLimits serves leased items
    from the local slice cache and relays only the misses."""

    def __init__(self, client: EdgeClient, leases: Optional[EdgeLeases] = None):
        self.client = client
        self.leases = leases

    async def GetRateLimits(self, request_bytes, context):
        import grpc

        try:
            return await serve_edge_get_rate_limits(
                self.client, request_bytes, self.leases
            )
        except EdgeError as e:
            await context.abort(
                getattr(grpc.StatusCode, e.code, grpc.StatusCode.INTERNAL), str(e)
            )

    async def HealthCheck(self, request_bytes, context):
        import grpc

        try:
            return await self.client.call(METHOD_HEALTH_CHECK, b"")
        except EdgeError as e:
            await context.abort(
                getattr(grpc.StatusCode, e.code, grpc.StatusCode.INTERNAL), str(e)
            )

    async def Lease(self, request_bytes, context):
        """Relay client-SDK Lease calls: holders behind an edge lease
        from the daemon exactly as holders dialing it directly."""
        import grpc

        try:
            return await self.client.call(METHOD_LEASE, request_bytes)
        except EdgeError as e:
            await context.abort(
                getattr(grpc.StatusCode, e.code, grpc.StatusCode.INTERNAL), str(e)
            )


_EDGE_HTTP_CODES = {
    "INVALID_ARGUMENT": 400,
    "OUT_OF_RANGE": 400,
    "UNAVAILABLE": 503,
    "DEADLINE_EXCEEDED": 504,
}
_EDGE_JSON_CODES = {  # gRPC status numbers for the JSON error body
    "INVALID_ARGUMENT": 3,
    "DEADLINE_EXCEEDED": 4,
    "OUT_OF_RANGE": 11,
    "INTERNAL": 13,
    "UNAVAILABLE": 14,
}


def build_edge_app(client: EdgeClient, metrics=None, leases=None):
    """aiohttp app mirroring the daemon's HTTP/JSON gateway
    (service/gateway.py) over the framed upstream — the edge presents
    the daemon's full client-facing surface (gRPC + JSON + /healthz).
    With `metrics` (a gubernator_tpu.metrics.Metrics), the edge also
    serves its own /metrics — edge-local series like
    gubernator_edge_call_timeouts live here, not on the daemon. With
    `leases` (an EdgeLeases) the JSON path shares the gRPC path's
    local lease serving."""
    from aiohttp import web

    from gubernator_tpu.service import pb
    from gubernator_tpu.service.gateway import read_json_requests

    app = web.Application()

    def _edge_err(e: EdgeError) -> web.Response:
        return web.json_response(
            {"code": _EDGE_JSON_CODES.get(e.code, 13), "message": str(e)},
            status=_EDGE_HTTP_CODES.get(e.code, 500),
        )

    async def get_rate_limits(request: web.Request) -> web.Response:
        reqs, err = await read_json_requests(request)
        if err is not None:
            return err
        msg = pb.pb.GetRateLimitsReq()
        for r in reqs:
            msg.requests.append(pb.req_to_pb(r))
        try:
            raw = await serve_edge_get_rate_limits(
                client, msg.SerializeToString(), leases
            )
        except EdgeError as e:
            return _edge_err(e)
        out = pb.pb.GetRateLimitsResp.FromString(raw)
        return web.json_response(
            {
                "responses": [
                    pb.resp_to_json(pb.resp_from_pb(m)) for m in out.responses
                ]
            }
        )

    async def _health():
        raw = await client.call(METHOD_HEALTH_CHECK, b"")
        return pb.pb.HealthCheckResp.FromString(raw)

    async def health_check(request: web.Request) -> web.Response:
        try:
            h = await _health()
        except EdgeError as e:
            return _edge_err(e)
        # same body shape as the daemon gateway (pb.health_to_json):
        # message omitted when empty
        body = {"status": h.status, "peer_count": h.peer_count}
        if h.message:
            body["message"] = h.message
        return web.json_response(body)

    async def healthz(request: web.Request) -> web.Response:
        try:
            h = await _health()
        except EdgeError:
            return web.Response(text="unreachable", status=503)
        return web.Response(
            text=h.status, status=200 if h.status == "healthy" else 503
        )

    app.router.add_post("/v1/GetRateLimits", get_rate_limits)
    app.router.add_get("/v1/HealthCheck", health_check)
    app.router.add_get("/healthz", healthz)
    if metrics is not None:

        async def metrics_route(request: web.Request) -> web.Response:
            return web.Response(
                body=metrics.render(), content_type="text/plain", charset="utf-8"
            )

        app.router.add_get("/metrics", metrics_route)
    return app


def edge_v1_handler(servicer) -> "grpc.GenericRpcHandler":  # noqa: F821
    """V1 service handler with identity (de)serializers on BOTH methods
    — the edge never parses messages, it relays bytes."""
    import grpc

    return grpc.method_handlers_generic_handler(
        "pb.gubernator.V1",
        {
            "GetRateLimits": grpc.unary_unary_rpc_method_handler(
                servicer.GetRateLimits,
                request_deserializer=None,
                response_serializer=None,
            ),
            "HealthCheck": grpc.unary_unary_rpc_method_handler(
                servicer.HealthCheck,
                request_deserializer=None,
                response_serializer=None,
            ),
            "Lease": grpc.unary_unary_rpc_method_handler(
                servicer.Lease,
                request_deserializer=None,
                response_serializer=None,
            ),
        },
    )
