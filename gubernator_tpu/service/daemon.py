"""Daemon: composition root (reference daemon.go:73-366).

Builds the device engine, core service, gRPC server (V1 + PeersV1), and
the HTTP gateway; exposes SetPeers for discovery backends and a client
helper for tests. One process can host many daemons (each with its own
engine/table/registry) — the in-process cluster fixture depends on that,
like the reference's cluster harness (cluster/cluster.go:151-189).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Sequence

import grpc
from aiohttp import web

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.runtime.engine import DeviceEngine
from gubernator_tpu.service import rpc
from gubernator_tpu.service.config import DaemonConfig
from gubernator_tpu.service.gateway import build_app
from gubernator_tpu.service.grpc_service import PeersV1Servicer, V1Servicer
from gubernator_tpu.service.server import V1Service
from gubernator_tpu.utils import net

log = logging.getLogger("gubernator.daemon")


class Daemon:
    def __init__(self, conf: DaemonConfig):
        self.conf = conf
        self.engine: Optional[DeviceEngine] = None
        self.svc: Optional[V1Service] = None
        self.grpc_server: Optional[grpc.aio.Server] = None
        self.http_runner: Optional[web.AppRunner] = None
        self.grpc_address = ""
        self.http_address = ""
        self.status_runner = None
        self.status_address = ""
        self._channel: Optional[grpc.aio.Channel] = None
        # Lifecycle: serving -> draining -> stopped (docs/robustness.md)
        self.state = "serving"

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    async def spawn(cls, conf: DaemonConfig) -> "Daemon":
        d = cls(conf)
        await d.start()
        return d

    async def start(self) -> None:
        # NOTE: trace level is process-global (like the env var that sets
        # it); the CLI entry point applies conf.trace_level. A library
        # Daemon must not clobber other in-process daemons' tracing.
        conf = self.conf
        # Chaos-testing fault rules (GUBER_FAULTS); no-op when unset.
        from gubernator_tpu.utils import faults

        faults.configure_from_env()
        if conf.global_mode == "ici":
            from gubernator_tpu.runtime.ici_engine import IciEngine, IciEngineConfig

            self.engine = IciEngine(conf.ici or IciEngineConfig())
        else:
            self.engine = DeviceEngine(conf.engine_config())

        # Persistence plugins (reference gubernator.go:138-148)
        if conf.store is not None:
            from gubernator_tpu.store import attach_store

            attach_store(self.engine, conf.store)
        if conf.loader is not None:
            from gubernator_tpu.store import load_engine

            load_engine(self.engine, conf.loader)

        # Optionally block startup until the kernel bucket ladder is
        # warm, so the very first NO_BATCHING request already gets a
        # width-sized kernel (GUBER_PREWARM_BUCKETS; cheap on restart
        # under the persistent compile cache — see utils/compilecache).
        if conf.prewarm_buckets and hasattr(self.engine, "wait_warm"):
            t0 = time.monotonic()
            done = await asyncio.get_running_loop().run_in_executor(
                None, self.engine.wait_warm, conf.prewarm_timeout_s
            )
            log.info(
                "bucket prewarm %s in %.1fs",
                "complete" if done else "TIMED OUT (serving anyway)",
                time.monotonic() - t0,
            )

        metrics = Metrics()
        from gubernator_tpu.metrics import wire_engine_telemetry

        # Scalar bridge + device-tier histogram exposition (flush
        # latency/width/waves, queue wait, ICI tick series, occupancy
        # gauges — docs/monitoring.md).
        wire_engine_telemetry(metrics, self.engine)

        # Optional OS/runtime collectors (reference daemon.go:276-287)
        flags = getattr(conf, "metric_flags", [])
        if "os" in flags:
            from prometheus_client import ProcessCollector

            ProcessCollector(registry=metrics.registry)
        if "golang" in flags:  # runtime collectors; Python GC here
            from prometheus_client import GCCollector, PlatformCollector

            PlatformCollector(registry=metrics.registry)
            GCCollector(registry=metrics.registry)

        self.svc = V1Service(
            self.engine,
            metrics=metrics,
            force_global=conf.behaviors.force_global,
            # knob: GUBER_ADMISSION_RING (decision flight recorder)
            admission_ring=getattr(conf, "admission_ring", 256),
        )
        # Server-suggested backoff (GUBER_RETRY_AFTER): OVER_LIMIT
        # responses carry retry_after_ms; off keeps responses bit-exact.
        self.svc.retry_after = conf.behaviors.retry_after
        # Columnar serving edge. A Store no longer disables it:
        # check_columns runs the same per-wave probe -> read-through ->
        # decide -> write-behind sequence as the object path (and records
        # key strings). A Loader-only daemon keeps the object path so the
        # key-string dictionary stays complete for snapshots without the
        # columnar path paying O(n) string decodes. GLOBAL (including
        # force_global) is served columnar too (fastpath.try_serve ORs
        # the flag in and queues the replication legs).
        self.svc.fast_edge = conf.loader is None or conf.store is not None

        # gRPC server hosting both services (reference daemon.go:139-167)
        # with the reference's hardening: 1MB receive cap (daemon.go:122)
        # and optional max-connection-age rotation (daemon.go:128-133).
        opts = [("grpc.max_receive_message_length", 1024 * 1024)]
        if conf.grpc_max_conn_age_s > 0:
            age_ms = int(conf.grpc_max_conn_age_s * 1000)
            opts += [
                ("grpc.max_connection_age_ms", age_ms),
                ("grpc.max_connection_age_grace_ms", age_ms),
            ]
        self.grpc_server = grpc.aio.server(options=opts)
        self.grpc_server.add_generic_rpc_handlers(
            (rpc.v1_handler(V1Servicer(self.svc)), rpc.peers_handler(PeersV1Servicer(self.svc)))
        )
        host = conf.grpc_listen_address.rsplit(":", 1)[0]
        if conf.tls is not None:
            from gubernator_tpu.service.tls import server_credentials, setup_tls

            setup_tls(conf.tls, hosts=[host if host not in ("0.0.0.0", "::") else "localhost", "127.0.0.1"])
            port = self.grpc_server.add_secure_port(
                conf.grpc_listen_address, server_credentials(conf.tls)
            )
        else:
            port = self.grpc_server.add_insecure_port(conf.grpc_listen_address)
        self.grpc_address = f"{host}:{port}"
        await self.grpc_server.start()

        # Local identity must be known before peers are set
        advertise = conf.advertise_address or self.grpc_address

        # HTTP gateway + metrics (reference daemon.go:251-299); serves TLS
        # with the same certs as the gRPC listener when configured.
        self.http_runner = None
        self.http_address = ""
        if conf.http_listen_address:
            app = build_app(self.svc)
            self.http_runner = web.AppRunner(app)
            await self.http_runner.setup()
            # ":80" binds all interfaces (every family) Go-style; ""
            # disables the listener entirely (GUBER_HTTP_ADDRESS= in the
            # environment previously crashed spawn with an unpack error).
            hhost, hport = net.parse_listen_address(conf.http_listen_address)
            ssl_ctx = None
            if conf.tls is not None:
                from gubernator_tpu.service.tls import http_ssl_context

                ssl_ctx = http_ssl_context(conf.tls)
            site = web.TCPSite(
                self.http_runner, hhost, int(hport), ssl_context=ssl_ctx
            )
            await site.start()
            actual = site._server.sockets[0].getsockname()
            # Recorded address must be dialable: wildcard/all-interfaces
            # binds expand to a concrete interface IP (ADVICE r5).
            self.http_address = net.recorded_address(hhost, actual[1])

        # Optional health-only listener that never requests a client cert
        # (reference daemon.go:305-333): lets load balancers probe
        # /v1/HealthCheck on an mTLS deployment without presenting certs.
        self.status_runner = None
        self.status_address = ""
        if conf.status_http_listen_address:
            from gubernator_tpu.service.gateway import build_status_app

            status_app = build_status_app(self.svc)
            self.status_runner = web.AppRunner(status_app)
            await self.status_runner.setup()
            shost, sport = net.parse_listen_address(
                conf.status_http_listen_address
            )
            status_ssl = None
            if conf.tls is not None:
                from gubernator_tpu.service.tls import http_ssl_context

                status_ssl = http_ssl_context(conf.tls, no_client_auth=True)
            ssite = web.TCPSite(
                self.status_runner, shost, sport, ssl_context=status_ssl
            )
            await ssite.start()
            sactual = ssite._server.sockets[0].getsockname()
            self.status_address = net.recorded_address(shost, sactual[1])

        # Edge-tier listener: gubernator-tpu-edge processes relay client
        # calls here over framed RPC (service/edge.py) — same serving
        # core as the gRPC listener, minus the gRPC server cost.
        self.edge_listener = None
        if conf.edge_listen_address:
            from gubernator_tpu.service.edge import EdgeListener

            self.edge_listener = EdgeListener(self.svc, conf.edge_listen_address)
            await self.edge_listener.start()

        self.svc.local_info = PeerInfo(
            grpc_address=advertise,
            http_address=self.http_address,
            data_center=conf.data_center,
            is_owner=True,
        )

        # Peer mesh (hash ring + forwarder + global manager) is attached by
        # wire_peers(); a daemon with no peers serves everything locally.
        from gubernator_tpu.parallel.peers import wire_peers

        wire_peers(self, global_mode=conf.global_mode)

        # Cooperative token leases (docs/architecture.md "Cooperative
        # leases"): owner-side authority + expiry sweep, only under
        # GUBER_LEASES — the None default keeps every path bit-exact.
        self._lease_mgr = None
        if conf.behaviors.leases:
            from gubernator_tpu.parallel.leases import LeaseManager

            self._lease_mgr = LeaseManager(
                self.svc,
                ttl_s=conf.behaviors.lease_ttl_s,
                fraction=conf.behaviors.lease_fraction,
                max_leases=conf.behaviors.lease_max_keys,
                sweep_interval_s=conf.behaviors.lease_sweep_interval_s,
            )
            self.svc.lease_mgr = self._lease_mgr
            self._lease_mgr.start()

        # Crash-tolerant ownership (docs/robustness.md "Standby
        # replication & crash recovery"): every owner shadows its
        # counter state to its ring successors; standbys promote on
        # owner death. Only under GUBER_STANDBY — the None default (and
        # the engine's None dirty registry) keeps every path bit-exact
        # with the pre-standby daemon.
        self._standby = None
        if conf.behaviors.standby:
            from gubernator_tpu.parallel.standby import ReplicationManager

            self.engine.enable_dirty_tracking()
            self._standby = ReplicationManager(
                self.svc,
                conf.behaviors,
                local_addr=advertise,
                mesh=self.svc.picker,
            )
            self.svc.standby = self._standby
            self.svc.picker.standby = self._standby
            self._standby.start()

        # Background divergence auditor (consistency observatory,
        # docs/monitoring.md "Consistency"): samples broadcast keys and
        # verifies one replica's view per pass. Off when the audit
        # interval is 0 or the daemon has no GLOBAL manager to audit.
        self._auditor = None
        if self.svc.global_mgr is not None:
            from gubernator_tpu.parallel.auditor import ConsistencyAuditor

            self._auditor = ConsistencyAuditor(self.svc, conf.behaviors)
            self.svc.auditor = self._auditor
            self._auditor.start()

        # Continuous profiler (docs/monitoring.md "Device resources"):
        # off unless GUBER_PROFILE_INTERVAL > 0. Shares the one-capture-
        # at-a-time guard with /debug/profile; trace dirs rotate, so an
        # unattended soak holds profile_keep traces, not thousands.
        self._profiler = None
        if float(getattr(conf, "profile_interval_s", 0.0)) > 0:
            from gubernator_tpu.service.profiler import ContinuousProfiler

            self._profiler = ContinuousProfiler(
                conf.profile_interval_s,
                seconds=conf.profile_seconds,
                keep=conf.profile_keep,
            )
            self.svc.profiler = self._profiler
            self._profiler.start()

        # Self-watchdog + SLO observatory (docs/monitoring.md "SLOs &
        # burn rates"): every long-lived loop (engine pump, completion
        # thread, ICI sync, auditor, demoter, lease sweep, profiler,
        # SLO sampler) heartbeats the watchdog; the observatory samples
        # already-cached SLIs into bounded rings and evaluates
        # multi-window burn rates. GUBER_SLO_SAMPLE_INTERVAL=0 turns
        # both off (the watchdog without a sampler would flag stalls
        # nobody exports).
        self._watchdog = None
        self._slo = None
        if conf.slo_sample_interval_s > 0:
            from gubernator_tpu.runtime.watchdog import Watchdog
            from gubernator_tpu.service.slo import (
                SloObservatory,
                parse_slo_specs,
            )

            self._watchdog = Watchdog(stall_ms=conf.watchdog_stall_ms)
            # Injected attribute, checked per-iteration by the engine
            # loops — the engine threads started before the daemon
            # built the watchdog, and None keeps the engine usable
            # standalone (tests, tools) with zero overhead.
            self.engine.watchdog = self._watchdog
            self.svc.watchdog = self._watchdog
            if self._auditor is not None:
                self._auditor.watchdog = self._watchdog
            if self._lease_mgr is not None:
                self._lease_mgr.watchdog = self._watchdog
            if self._profiler is not None:
                self._profiler.watchdog = self._watchdog
            if self._standby is not None:
                self._standby.watchdog = self._watchdog
            self._slo = SloObservatory(
                self.svc,
                interval_s=conf.slo_sample_interval_s,
                specs=parse_slo_specs(conf.slo_specs),
                watchdog=self._watchdog,
            )
            self.svc.slo = self._slo
            self._watchdog.start()
            self._slo.start()

        # Overload control plane (docs/robustness.md "Overload control
        # & brownout"): the intake governor is injected into the engine
        # (deadline-aware bounded intake + CoDel tenant-fair shedding)
        # and the brownout ladder folds the SLO burn rates + watchdog
        # stall flags into a published degradation level. Off (default)
        # wires nothing — intake and forwarding stay bit-exact.
        self._overload = None
        if conf.overload:
            from gubernator_tpu.service.overload import (
                IntakeGovernor,
                OverloadManager,
            )

            governor = IntakeGovernor(
                limit=conf.intake_limit,
                target_ms=conf.intake_target_ms,
                metrics=self.svc.metrics,
                recorder=self.svc.recorder,
            )
            self._overload = OverloadManager(
                self.svc,
                governor,
                slo=self._slo,
                watchdog=self._watchdog,
            )
            self.svc.overload = self._overload
            # Injected attribute, checked per-call by intake and
            # per-pickup by the pump (same seam model as the watchdog).
            self.engine.overload = governor
            self._overload.start()

        # Discovery pool pushes membership through set_peers
        # (reference daemon.go:208-243). Unknown/unavailable backends fail
        # fast rather than silently serving as a cluster of one.
        from gubernator_tpu.service.discovery import DnsPool, StaticPool

        self._pool = None
        if conf.discovery == "dns":
            if not conf.dns_fqdn:
                raise ValueError("dns discovery requires GUBER_DNS_FQDN")
            self._pool = DnsPool(
                conf.dns_fqdn,
                self.set_peers,
                interval_s=conf.dns_interval_s,
                own_address=advertise,
                resolv_conf=conf.dns_resolv_conf,
            )
        elif conf.discovery == "static":
            if conf.peers:
                self._pool = StaticPool(conf.peers, self.set_peers)
        elif conf.discovery == "member-list":
            from gubernator_tpu.service.discovery import GossipPool

            self._pool = GossipPool(
                bind=conf.gossip_bind or "127.0.0.1:0",
                info=self.svc.local_info,
                on_update=self.set_peers,
                seeds=conf.gossip_seeds,
                interval_s=conf.gossip_interval_s,
                advertise=conf.gossip_advertise,
                secret=conf.gossip_secret,
            )
            await self._pool.started()  # resolve the ephemeral bind
        elif conf.discovery == "etcd":
            from gubernator_tpu.service.config import EtcdConfig
            from gubernator_tpu.service.etcd import EtcdPool

            econf = conf.etcd or EtcdConfig()
            if not econf.advertise_address:
                econf.advertise_address = advertise
            self._pool = EtcdPool(
                econf,
                PeerInfo(
                    grpc_address=econf.advertise_address,
                    http_address=self.http_address,
                    data_center=conf.data_center,
                ),
                self.set_peers,
            )
        elif conf.discovery == "k8s":
            from gubernator_tpu.service.config import K8sConfig
            from gubernator_tpu.service.k8s import K8sPool

            self._pool = K8sPool(conf.k8s or K8sConfig(), self.set_peers)
        else:
            raise ValueError(f"unknown peer discovery type: {conf.discovery!r}")

        # Readiness gate (reference WaitForConnect, daemon.go:451-488):
        # confirm every listener actually accepts connections before
        # declaring the daemon started.
        await self.wait_for_connect()

    async def wait_for_connect(self, timeout_s: float = 10.0) -> None:
        """Dial each listener until it accepts a TCP connection
        (reference daemon.go:451-488)."""
        addrs = [a for a in (self.grpc_address, self.http_address) if a]
        if self.status_address:
            addrs.append(self.status_address)
        deadline = asyncio.get_running_loop().time() + timeout_s
        for addr in addrs:
            host, port = addr.rsplit(":", 1)
            # Bracketed IPv6 hosts ("[::]:81" -> "[::]") must be unwrapped
            # before the wildcard check, or the dial below targets the
            # literal string "[::]" and times out.
            host = host.strip("[]")
            if host in ("0.0.0.0", "::"):
                host = "127.0.0.1"
            while True:
                try:
                    _, writer = await asyncio.open_connection(host, int(port))
                    writer.close()
                    break
                except OSError:
                    if asyncio.get_running_loop().time() > deadline:
                        raise TimeoutError(
                            f"listener {addr} not accepting connections "
                            f"after {timeout_s}s"
                        )
                    await asyncio.sleep(0.05)

    async def close(self) -> None:
        """Graceful drain, then teardown (docs/robustness.md "Rolling
        restarts & handover"). SIGTERM lands here via cmd/daemon.py; the
        sequence flips the node lossless instead of dropping in-flight
        traffic and resetting limits:

        1. DRAINING state: /readyz and HealthCheck report `draining`
           (orchestrators stop routing without killing the pod), and
           discovery deregisters so no new ownership lands here.
        2. Intake stops: the gRPC/edge listeners quit accepting new
           RPCs but in-flight calls get the drain budget to finish
           (the engine pump is still alive to serve them).
        3. Replication flush: queued GLOBAL hit-updates/broadcasts and
           MULTI_REGION legs ship now instead of dying with the loop.
        4. Ownership handover: every owned key's counter state ships to
           its ring successor over TransferSnapshots.
        5. Engine drain: the pump finishes its queue; only stragglers
           past GUBER_DRAIN_TIMEOUT fail, with the typed retryable
           status (api.types.ERR_ENGINE_DRAINING).
        6. Loader.save runs AFTER the engine drained, so the checkpoint
           includes every applied hit; then teardown."""
        if self.state == "stopped":
            return
        drain_s = max(float(getattr(self.conf, "drain_timeout_s", 5.0)), 0.0)
        self.state = "draining"
        if self.svc is not None:
            self.svc.draining = True
        # Auditor first: an audit RPC racing the drain would read peers
        # that are mid-handover and report phantom divergence.
        if getattr(self, "_auditor", None) is not None:
            await self._auditor.close()
        if getattr(self, "_profiler", None) is not None:
            self._profiler.stop()
        # Ladder before the SLO sampler it reads, then sampler +
        # watchdog before the loops they observe: a loop stopping
        # during drain must not be flagged as a stall. The engine keeps
        # its governor through drain — queued entries whose deadline
        # lapses mid-drain are still dropped at pickup.
        if getattr(self, "_overload", None) is not None:
            self._overload.stop()
        if getattr(self, "_slo", None) is not None:
            self._slo.stop()
        if getattr(self, "_watchdog", None) is not None:
            self._watchdog.stop()
        if getattr(self, "_pool", None) is not None:
            self._pool.close()
        # Standby before the listener stops AND before drain_handover:
        # the retire legs need peers' transports up, and retiring the
        # shadows first guarantees the standby and the handover never
        # both replay the same rows at a successor (docs/robustness.md
        # "Standby replication & crash recovery").
        if getattr(self, "_standby", None) is not None:
            await self._standby.close()
        # preStop settle (the k8s preStop-sleep analog): calls already on
        # the wire get dispatched to handlers before the listener stops
        # accepting — without it, transport-queued RPCs die CANCELLED at
        # stop() no matter how long the grace is.
        await asyncio.sleep(min(0.05, drain_s))
        if self.grpc_server is not None:
            # Stops new RPCs immediately; in-flight handlers get up to
            # the drain budget (the engine below them is still serving).
            await self.grpc_server.stop(grace=drain_s)
        if getattr(self, "edge_listener", None) is not None:
            await self.edge_listener.close()
        if self.svc is not None and self.svc.global_mgr is not None:
            await self.svc.global_mgr.drain()
        if self.svc is not None and getattr(self.svc, "region_mgr", None) is not None:
            await self.svc.region_mgr.drain()
        if self.svc is not None and hasattr(self.svc.forwarder, "drain_handover"):
            await self.svc.forwarder.drain_handover()
        if self.svc is not None and self.svc.global_mgr is not None:
            await self.svc.global_mgr.close()
        if self.svc is not None and getattr(self.svc, "region_mgr", None) is not None:
            await self.svc.region_mgr.close()
        # After drain_handover: the handover ships outstanding lease
        # records to ring successors, so the manager must outlive it.
        if getattr(self, "_lease_mgr", None) is not None:
            await self._lease_mgr.close()
        if self.engine is not None:
            # Engine close blocks for its own drain pass; keep the event
            # loop responsive (other in-process daemons share it).
            await asyncio.get_running_loop().run_in_executor(
                None, self.engine.close
            )
        # Checkpoint AFTER the engine drained (reference workerPool.Store
        # at shutdown, gubernator.go:151-178) so the snapshot includes
        # every hit the drain just applied.
        if self.conf.loader is not None and self.engine is not None:
            from gubernator_tpu.store import save_engine

            save_engine(self.engine, self.conf.loader)
        if self.svc is not None and self.svc.forwarder is not None:
            await self.svc.forwarder.close()
        if self._channel is not None:
            # Grace lets client-side RPCs that already have responses in
            # flight deliver them instead of dying CANCELLED.
            await self._channel.close(grace=drain_s)
            self._channel = None
        if self.http_runner is not None:
            await self.http_runner.cleanup()
        if getattr(self, "status_runner", None) is not None:
            await self.status_runner.cleanup()
        self.state = "stopped"

    # -- peers ---------------------------------------------------------------

    def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        """Discovery callback (reference daemon.go:208-243 -> SetPeers)."""
        local = self.svc.local_info
        normalized: List[PeerInfo] = []
        for p in peers:
            # Self-detection: advertise-address equality, or a discovery
            # backend that already marked this entry as us (DnsPool).
            is_self = p.is_owner or p.grpc_address == local.grpc_address
            normalized.append(
                PeerInfo(
                    grpc_address=p.grpc_address,
                    http_address=p.http_address,
                    data_center=p.data_center,
                    is_owner=is_self,
                )
            )
        self.svc.set_peers(normalized)

    def peer_info(self) -> PeerInfo:
        return self.svc.local_info

    # -- client helper (reference daemon.go:433-447) -------------------------

    def client(self) -> rpc.V1Stub:
        if self._channel is None:
            if self.conf.tls is not None:
                from gubernator_tpu.service.tls import client_credentials

                target = self.grpc_address.replace("0.0.0.0", "localhost")
                self._channel = grpc.aio.secure_channel(
                    target,
                    client_credentials(self.conf.tls, client_cert=True),
                    options=(("grpc.ssl_target_name_override", "localhost"),),
                )
            else:
                self._channel = grpc.aio.insecure_channel(self.grpc_address)
        return rpc.V1Stub(self._channel)

    async def must_client(self) -> rpc.V1Stub:
        return self.client()
