"""grpc.aio servicers bridging the wire to V1Service."""

from __future__ import annotations

import time

import grpc

from gubernator_tpu.service import pb
from gubernator_tpu.service.server import ApiError, V1Service

_GRPC_CODES = {
    "OUT_OF_RANGE": grpc.StatusCode.OUT_OF_RANGE,
    "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
    "INTERNAL": grpc.StatusCode.INTERNAL,
}


class V1Servicer:
    def __init__(self, svc: V1Service):
        self.svc = svc

    async def GetRateLimits(self, request, context):
        m = self.svc.metrics
        t0 = time.perf_counter()
        try:
            reqs = [pb.req_from_pb(r) for r in request.requests]
            try:
                out = await self.svc.get_rate_limits(reqs)
            except ApiError as e:
                m.grpc_request_counts.labels("/pb.gubernator.V1/GetRateLimits", "failed").inc()
                await context.abort(
                    _GRPC_CODES.get(e.grpc_code, grpc.StatusCode.INTERNAL), str(e)
                )
            resp = pb.pb.GetRateLimitsResp()
            for r in out:
                resp.responses.append(pb.resp_to_pb(r))
            m.grpc_request_counts.labels("/pb.gubernator.V1/GetRateLimits", "success").inc()
            return resp
        finally:
            m.grpc_request_duration.labels("/pb.gubernator.V1/GetRateLimits").observe(
                time.perf_counter() - t0
            )

    async def HealthCheck(self, request, context):
        h = await self.svc.health_check()
        self.svc.metrics.grpc_request_counts.labels(
            "/pb.gubernator.V1/HealthCheck", "success"
        ).inc()
        return pb.health_to_pb(h)


class PeersV1Servicer:
    def __init__(self, svc: V1Service):
        self.svc = svc

    async def GetPeerRateLimits(self, request, context):
        try:
            reqs = [pb.req_from_pb(r) for r in request.requests]
            out = await self.svc.get_peer_rate_limits(reqs)
        except ApiError as e:
            await context.abort(
                _GRPC_CODES.get(e.grpc_code, grpc.StatusCode.INTERNAL), str(e)
            )
        resp = pb.peers_pb.GetPeerRateLimitsResp()
        for r in out:
            resp.rate_limits.append(pb.resp_to_pb(r))
        return resp

    async def UpdatePeerGlobals(self, request, context):
        await self.svc.update_peer_globals(
            [pb.global_from_pb(g) for g in request.globals]
        )
        return pb.peers_pb.UpdatePeerGlobalsResp()
