"""grpc.aio servicers bridging the wire to V1Service."""

from __future__ import annotations

import asyncio
import contextlib
import time

import grpc

from gubernator_tpu.service import pb
from gubernator_tpu.service.server import ApiError, V1Service

_GRPC_CODES = {
    "OUT_OF_RANGE": grpc.StatusCode.OUT_OF_RANGE,
    "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
    "INTERNAL": grpc.StatusCode.INTERNAL,
}


@contextlib.asynccontextmanager
async def _instrumented(metrics, method: str):
    """Per-RPC duration + success/failed counters (the reference's
    GRPCStatsHandler role, grpc_stats.go:41-131). Counts every outcome:
    any exception — ApiError-driven aborts included — is 'failed'."""
    t0 = time.perf_counter()
    try:
        yield
        metrics.grpc_request_counts.labels(method, "success").inc()
    except BaseException:
        metrics.grpc_request_counts.labels(method, "failed").inc()
        raise
    finally:
        metrics.grpc_request_duration.labels(method).observe(time.perf_counter() - t0)


async def _abort(context, e: ApiError):
    await context.abort(_GRPC_CODES.get(e.grpc_code, grpc.StatusCode.INTERNAL), str(e))


async def serve_get_rate_limits_bytes(svc: V1Service, request_bytes) -> bytes:
    """The V1/GetRateLimits serving core over raw wire bytes, shared by
    the gRPC servicer and the edge-tier listener (service/edge.py) so
    both transports have identical semantics. Raises ApiError for
    whole-call failures (the caller maps it to its transport's status)."""
    from gubernator_tpu.service import fastpath

    if fastpath.enabled(svc):
        # Executor keeps the event loop responsive while the
        # kernel runs (the C parse and the jitted decide release
        # the GIL, so calls genuinely overlap).
        res = await asyncio.get_running_loop().run_in_executor(
            None, fastpath.try_serve, svc, request_bytes, False
        )
        if isinstance(res, bytes):
            return res
        if res is not None:  # mixed ownership: forward the rest
            _, n, local_pos, local_out, nl_reqs, md = res
            # Local hits are already committed — a forwarding
            # failure must degrade the REMOTE items to per-item
            # errors, never fail the RPC (a client retry would
            # double-charge every local key).
            from gubernator_tpu.api.types import RateLimitResp

            try:
                nl_resps = await svc.get_rate_limits(nl_reqs)
            except Exception as e:
                nl_resps = [RateLimitResp(error=str(e)) for _ in nl_reqs]
            return fastpath.merge_mixed(n, local_pos, local_out, nl_resps, md)
    try:
        request = pb.pb.GetRateLimitsReq.FromString(request_bytes)
    except Exception:
        raise ApiError("malformed request", grpc_code="INVALID_ARGUMENT")
    reqs = [pb.req_from_pb(r) for r in request.requests]
    out = await svc.get_rate_limits(reqs)
    resp = pb.pb.GetRateLimitsResp()
    for r in out:
        resp.responses.append(pb.resp_to_pb(r))
    return resp.SerializeToString()


async def serve_lease_bytes(svc: V1Service, request_bytes, context) -> bytes:
    """Shared Lease serving core (V1 + PeersV1 + the edge framed
    listener): decode, route through V1Service.lease, encode."""
    from gubernator_tpu.utils import tracing

    try:
        grants, returns, holder, md = pb.lease_req_from_bytes(request_bytes)
    except (ValueError, TypeError):
        if context is not None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "malformed lease request"
            )
        raise ApiError("malformed lease request")
    ctx = tracing.propagate_extract(md)
    with tracing.attached(ctx):
        with tracing.span(
            "V1Instance.Lease", level="DEBUG",
            grants=len(grants), returns=len(returns),
        ):
            g_res, r_res = await svc.lease(
                grants, returns, holder=holder,
                no_forward=md.get("no_forward") == "1",
            )
    return pb.lease_resp_to_bytes(g_res, r_res)


class V1Servicer:
    """GetRateLimits runs in BYTES mode (identity deserializer): the
    columnar fast path serves eligible calls without building a single
    per-item Python object; everything else parses and takes the object
    path with identical semantics (service/fastpath.py)."""

    def __init__(self, svc: V1Service):
        self.svc = svc

    async def GetRateLimits(self, request_bytes, context):
        async with _instrumented(self.svc.metrics, "/pb.gubernator.V1/GetRateLimits"):
            try:
                return await serve_get_rate_limits_bytes(self.svc, request_bytes)
            except ApiError as e:
                await _abort(context, e)

    async def HealthCheck(self, request, context):
        async with _instrumented(self.svc.metrics, "/pb.gubernator.V1/HealthCheck"):
            return pb.health_to_pb(await self.svc.health_check())

    async def Lease(self, request_bytes, context):
        """Cooperative token leases (docs/architecture.md): grant/renew/
        return quota slices. The service routes each row to the owning
        daemon — local grants hit the LeaseManager, remote ones forward
        over PeersV1/Lease."""
        async with _instrumented(self.svc.metrics, "/pb.gubernator.V1/Lease"):
            return await serve_lease_bytes(self.svc, request_bytes, context)


class PeersV1Servicer:
    def __init__(self, svc: V1Service):
        self.svc = svc
        from gubernator_tpu.service import fastpath

        self._fast = fastpath

    async def GetPeerRateLimits(self, request_bytes, context):
        async with _instrumented(
            self.svc.metrics, "/pb.gubernator.PeersV1/GetPeerRateLimits"
        ):
            # Forwarded batches are owned by construction — the owner-side
            # hot path (SURVEY.md §3.2) skips the ring check. The response
            # field (rate_limits = 1) shares its wire shape with
            # GetRateLimitsResp.responses, so the same native builder
            # serves both.
            if self._fast.enabled(self.svc):
                raw = await asyncio.get_running_loop().run_in_executor(
                    None, self._fast.try_serve, self.svc, request_bytes, True
                )
                if isinstance(raw, bytes):  # peer calls are never "mixed"
                    return raw
            try:
                request = pb.peers_pb.GetPeerRateLimitsReq.FromString(
                    request_bytes
                )
            except Exception:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "malformed request"
                )
            reqs = [pb.req_from_pb(r) for r in request.requests]
            try:
                out = await self.svc.get_peer_rate_limits(reqs)
            except ApiError as e:
                await _abort(context, e)
            resp = pb.peers_pb.GetPeerRateLimitsResp()
            for r in out:
                resp.rate_limits.append(pb.resp_to_pb(r))
            return resp.SerializeToString()

    async def UpdatePeerGlobals(self, request, context):
        async with _instrumented(
            self.svc.metrics, "/pb.gubernator.PeersV1/UpdatePeerGlobals"
        ):
            await self.svc.update_peer_globals(
                [pb.global_from_pb(g) for g in request.globals]
            )
            return pb.peers_pb.UpdatePeerGlobalsResp()

    async def TransferSnapshots(self, request_bytes, context):
        """Ownership handover receiver (docs/robustness.md): merge the
        sender's counter state last-writer-wins on stamp. The chunk's
        optional metadata carries the sender's trace context, so the
        receive + merge lands under the sender's handover trace."""
        from gubernator_tpu.utils import tracing

        async with _instrumented(
            self.svc.metrics, "/pb.gubernator.PeersV1/TransferSnapshots"
        ):
            # Standby envelope (v=2, parallel/standby.py) rides the same
            # RPC: route it to the shadow store when this node runs a
            # ReplicationManager; reject it INVALID_ARGUMENT otherwise —
            # the SAME rejection class a pre-standby build produces, so
            # skewed senders fall back to v=1 full images either way.
            try:
                parsed = pb.maybe_standby_from_bytes(request_bytes)
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            if parsed is not None:
                sb = getattr(self.svc, "standby", None)
                if sb is None:
                    await context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "standby replication not enabled on this node",
                    )
                loop = asyncio.get_running_loop()
                accepted, stale, extra = await loop.run_in_executor(
                    None, sb.receive, parsed
                )
                return pb.transfer_resp_to_bytes(accepted, stale, extra)
            try:
                snaps, md, leases = pb.snapshots_full_from_bytes(
                    request_bytes
                )
            except (ValueError, TypeError):
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "malformed snapshot transfer",
                )
            ctx = tracing.propagate_extract(md)
            with tracing.attached(ctx):
                with tracing.span(
                    "PeersV1.TransferSnapshots", level="DEBUG",
                    keys=len(snaps),
                ):
                    accepted, stale = await self.svc.transfer_snapshots(
                        snaps, leases=leases
                    )
            return pb.transfer_resp_to_bytes(accepted, stale)

    async def DebugInfo(self, request_bytes, context):
        """Consistency observatory: serve this node's debug blob — LOCAL
        state only, so the /debug/cluster fan-out cannot recurse. With
        `keys`, includes those keys' counter snapshots (the divergence
        auditor's replica-view fetch)."""
        from gubernator_tpu.utils import tracing

        async with _instrumented(
            self.svc.metrics, "/pb.gubernator.PeersV1/DebugInfo"
        ):
            try:
                keys, md = pb.debug_req_from_bytes(request_bytes)
            except (ValueError, TypeError):
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "malformed debug info request",
                )
            ctx = tracing.propagate_extract(md)
            with tracing.attached(ctx):
                with tracing.span(
                    "PeersV1.DebugInfo", level="DEBUG", keys=len(keys)
                ):
                    # Engine readbacks + table snapshot off the loop.
                    info = await asyncio.get_running_loop().run_in_executor(
                        None, self.svc.local_debug_info, keys or None
                    )
            return pb.debug_resp_to_bytes(info)

    async def Lease(self, request_bytes, context):
        """Daemon-to-owner forwarded lease traffic: same payload and
        serving core as V1/Lease (the service refuses to re-forward a
        peer-forwarded request — `no_forward` rides the payload md — so
        disagreeing ring views cannot loop)."""
        async with _instrumented(
            self.svc.metrics, "/pb.gubernator.PeersV1/Lease"
        ):
            return await serve_lease_bytes(self.svc, request_bytes, context)
