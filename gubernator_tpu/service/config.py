"""Daemon configuration (reference config.go:73-252 analog).

Library users fill these dataclasses directly; the CLI/env layer
(`gubernator_tpu.service.envconfig`) populates them from GUBER_* env vars
the way the reference's SetupDaemonConfig does (config.go:270-479).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.runtime.engine import EngineConfig


@dataclasses.dataclass
class BehaviorConfig:
    """Batching / GLOBAL tuning knobs (reference config.go:49-70,126-134)."""

    batch_timeout_s: float = 0.5
    batch_wait_s: float = 500e-6
    batch_limit: int = 1000

    global_timeout_s: float = 0.5
    global_sync_wait_s: float = 0.1
    global_batch_limit: int = 1000
    global_peer_requests_concurrency: int = 100

    force_global: bool = False
    # Forward every peer request as its own RPC instead of micro-batching
    # (reference Behaviors.DisableBatching / GUBER_DISABLE_BATCHING,
    # peer_client.go:128-133).
    disable_batching: bool = False

    # -- fault-domain knobs (docs/robustness.md; no reference analog: the
    # reference retries a dead owner 5x back-to-back with no backoff) ----

    # Per-call deadline budget for the forwarding path: retries share
    # this budget instead of multiplying per-leg timeouts. Propagated to
    # the owning peer via request metadata ("deadline_ms", absolute epoch
    # ms) so a re-forwarded item honors the original caller's remaining
    # time.
    forward_deadline_s: float = 2.0

    # GUBER_PEER_QUEUE: bound on each peer's forward batch queue (was a
    # hardcoded 1000). A full queue sheds with the typed retryable
    # overload error instead of blocking producers; size it to
    # batch_limit x the number of batches you are willing to buffer
    # toward one slow peer.
    peer_queue: int = 1000

    # GUBER_RETRY_BUDGET: token-bucket retry budget for the client and
    # edge relays (service/overload.py RetryBudget) — each first attempt
    # deposits this fraction of a token, each retry spends one, so
    # retries can never multiply offered load by more than 1 + budget.
    # 0 disables retries entirely under sustained failure.
    retry_budget: float = 0.1

    # Per-peer circuit breaker (utils/breaker.py): trip after this many
    # consecutive transport failures, hold open for an exponential
    # backoff (base doubling per consecutive trip, capped, ±10% jitter),
    # then admit `circuit_half_open_probes` trial calls.
    circuit_failure_threshold: int = 5
    circuit_open_base_s: float = 0.5
    circuit_open_max_s: float = 30.0
    circuit_half_open_probes: int = 1

    # What the forwarding path does when the owner's circuit is open
    # (GUBER_OWNER_UNREACHABLE): "error" fails fast; "local" answers
    # from local engine state (eventual-consistency caveats in
    # docs/robustness.md) and queues the hits for reconciliation with
    # the owner once its circuit closes.
    owner_unreachable: str = "error"

    # GLOBAL hit-update redelivery: a failed flush leg is merged back
    # into the hit queue instead of dropped. Each key survives at most
    # `global_requeue_limit` failed *send attempts* (circuit-open skips
    # do not age a key — no send was attempted), and at most
    # `global_requeue_max_keys` keys are held for redelivery; past
    # either cap, hits drop with the gubernator_global_send_dropped
    # counter.
    global_requeue_limit: int = 10
    global_requeue_max_keys: int = 10_000

    # Edge-tier frame-call timeout (GUBER_EDGE_TIMEOUT): was a
    # hard-coded 30.0 in EdgeClient.call.
    edge_timeout_s: float = 30.0

    # -- zero-loss elasticity (docs/robustness.md "Rolling restarts &
    # handover"; no reference analog: the reference accepts counter
    # loss whenever ownership moves) --------------------------------------

    # GUBER_HANDOVER: when the ring changes (or this node drains), ship
    # counter state for keys this node no longer owns to their new
    # owners over TransferSnapshots; receivers merge last-writer-wins on
    # stamp. Off restores the reference's lossy elasticity semantics.
    handover: bool = True
    # GUBER_HANDOVER_MAX_KEYS: cap on keys gathered per handover pass;
    # beyond it keys drop (counted in gubernator_handover_keys_dropped).
    handover_max_keys: int = 100_000
    # GUBER_HANDOVER_CHUNK: keys per TransferSnapshots RPC leg.
    handover_chunk: int = 512

    # -- consistency observatory (docs/monitoring.md "Consistency"; no
    # reference analog: the reference takes GLOBAL reconvergence on
    # faith) --------------------------------------------------------------

    # GUBER_CONSISTENCY_AUDIT_INTERVAL: cadence of the background
    # divergence auditor (samples owned GLOBAL keys, fetches one
    # replica's view over PeersV1.DebugInfo, classifies lag/lost/
    # conflict). 0 disables the auditor.
    consistency_audit_interval_s: float = 60.0
    # GUBER_CONSISTENCY_AUDIT_KEYS: max owned keys sampled per pass.
    consistency_audit_keys: int = 32

    # -- cooperative token leases (docs/architecture.md "Cooperative
    # leases"; no reference analog: every reference check costs an RPC) --

    # GUBER_LEASES: master switch. Off (default) keeps every path
    # bit-exact with the pre-lease daemon — no LeaseManager is wired, no
    # probe/carve checks run, snapshot chunks carry no lease rows.
    leases: bool = False
    # GUBER_LEASE_TTL: owner-side lease lifetime; the advertised holder
    # ttl is this minus the worst observed peer clock skew, and never
    # reaches past the bucket window's reset_time.
    lease_ttl_s: float = 2.0
    # GUBER_LEASE_FRACTION: max slice per grant as a fraction of the
    # key's limit — bounds one holder's share of the budget (and with
    # it the worst-case over-admission per holder per ttl).
    lease_fraction: float = 0.1
    # GUBER_LEASE_LOW_WATER: holders renew when the local slice falls
    # below this fraction of its granted size.
    lease_low_water: float = 0.25
    # GUBER_LEASE_MAX_KEYS: cap on outstanding lease records per owner
    # (grants reject past it) and on distinct leased keys per holder
    # cache.
    lease_max_keys: int = 4096
    # GUBER_LEASE_SWEEP_INTERVAL: cadence of the owner-side expiry sweep
    # that reclaims lapsed slices (conservation's `expired` term).
    lease_sweep_interval_s: float = 1.0

    # GUBER_RETRY_AFTER: server-suggested backoff — OVER_LIMIT responses
    # (leased and unleased) carry retry_after_ms derived from
    # reset_time. Off (default) keeps responses bit-exact with today;
    # on trades the columnar fast edge for the richer responses (only
    # the object path attaches metadata, service/fastpath.py).
    retry_after: bool = False

    # -- crash-tolerant ownership (docs/robustness.md "Standby
    # replication & crash recovery"; no reference analog: the reference
    # loses every counter an owner holds when the owner dies hard) --------

    # GUBER_STANDBY: owners continuously ship incremental snapshot
    # deltas of their dirtied keys to their ring successor(s); on owner
    # death the standby promotes the shadowed rows. Off restores
    # hard-kill counter loss (planned ring changes stay lossless via
    # handover) and keeps every serving path bit-exact with the
    # pre-standby daemon.
    standby: bool = True
    # GUBER_STANDBY_INTERVAL: delta ship cadence. The published loss
    # bound is "hits dirtied since the last acked ship", so this is the
    # durability/traffic tradeoff knob.
    standby_interval_s: float = 1.0
    # GUBER_STANDBY_FACTOR: distinct ring successors each key's state
    # is shadowed to (replication factor minus the owner itself).
    standby_factor: int = 1
    # GUBER_STANDBY_PROMOTE_AFTER: a standby promotes a dead owner's
    # shadow once that owner's circuit has been continuously open this
    # long (removal from the ring promotes immediately).
    standby_promote_after_s: float = 3.0
    # GUBER_STANDBY_ANTI_ENTROPY_INTERVAL: cadence of the per-region
    # digest exchange that re-ships mismatched regions (repairs deltas
    # lost to drops/partitions). 0 disables anti-entropy repair.
    standby_anti_entropy_interval_s: float = 10.0
    # GUBER_STANDBY_MAX_KEYS: cap on dirty keys gathered per ship pass
    # and on shadow rows held per upstream owner; beyond it the oldest
    # dirt stays pending (the loss bound keeps counting it).
    standby_max_keys: int = 100_000


@dataclasses.dataclass
class EtcdConfig:
    """etcd discovery settings (reference EtcdPoolConfig + GUBER_ETCD_*
    env block, config.go:380-404, etcd.go:42-80)."""

    endpoints: List[str] = dataclasses.field(
        default_factory=lambda: ["localhost:2379"]
    )
    key_prefix: str = "/gubernator-peers"
    advertise_address: str = ""
    data_center: str = ""
    dial_timeout_s: float = 5.0
    user: str = ""
    password: str = ""
    # TLS toward etcd (reference setupEtcdTLS, config.go:680-715)
    tls_enabled: bool = False
    tls_ca: str = ""
    tls_cert: str = ""
    tls_key: str = ""
    tls_skip_verify: bool = False
    # lease TTL driving registration keepalive (reference etcd.go:37)
    lease_ttl_s: float = 30.0


@dataclasses.dataclass
class K8sConfig:
    """Kubernetes discovery settings (reference K8sPoolConfig + GUBER_K8S_*
    env block, kubernetes.go:24-33, config.go:405-413)."""

    namespace: str = "default"
    pod_ip: str = ""
    pod_port: str = ""
    selector: str = ""  # label selector for the peer Endpoints/Pods
    mechanism: str = "endpoints"  # endpoints | pods
    api_server: str = ""  # default: in-cluster env/service account
    token_file: str = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    ca_file: str = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


@dataclasses.dataclass
class DaemonConfig:
    grpc_listen_address: str = "127.0.0.1:0"
    http_listen_address: str = "127.0.0.1:0"
    advertise_address: str = ""  # defaults to the bound gRPC address
    data_center: str = ""

    # Counter capacity: total slots = cache_size rounded up to groups*ways
    # (reference default 50k items, config.go:139-140)
    cache_size: int = 50_000

    # Device table layout for the single-chip engine (GUBER_TABLE_LAYOUT;
    # ops/kernels.py LAYOUTS). All layouts are oracle-exact and Loader
    # snapshots are portable across them; "narrow" halves the probe DMA
    # at large tables (ops/narrow.py). Ignored when `engine` is set
    # explicitly; the ici tier has its own knob (IciEngineConfig.layout /
    # GUBER_ICI_LAYOUT).
    table_layout: str = "fused"

    behaviors: BehaviorConfig = dataclasses.field(default_factory=BehaviorConfig)
    engine: Optional[EngineConfig] = None

    # Static peer list (the in-process cluster fixture and tests use this;
    # discovery pools feed the same set_peers path)
    peers: List[PeerInfo] = dataclasses.field(default_factory=list)

    # GLOBAL sync transport: "grpc" (cross-host, reference-compatible) or
    # "ici" (multi-device collective mode: the daemon serves a whole
    # device mesh as one process; see runtime/ici_engine.py)
    global_mode: str = "grpc"
    ici: Optional[object] = None  # runtime.ici_engine.IciEngineConfig

    # Discovery backend: static | dns | etcd | k8s | member-list
    discovery: str = "static"
    dns_fqdn: str = ""
    dns_interval_s: float = 300.0
    dns_resolv_conf: str = "/etc/resolv.conf"  # reference GUBER_RESOLV_CONF
    # member-list (gossip) backend (reference memberlist.go knobs)
    gossip_bind: str = ""  # UDP host:port; port 0 = ephemeral
    gossip_advertise: str = ""  # reference GUBER_MEMBERLIST_ADVERTISE_ADDRESS
    gossip_seeds: List[str] = dataclasses.field(default_factory=list)
    gossip_interval_s: float = 1.0
    # Shared HMAC key authenticating gossip datagrams (memberlist
    # SecretKey analog; authenticates, does not encrypt). "" = off.
    gossip_secret: str = ""
    # etcd / k8s discovery blocks (populated by the matching env vars)
    etcd: Optional[EtcdConfig] = None
    k8s: Optional[K8sConfig] = None

    # gRPC server hardening (reference daemon.go:120-133): receive cap is
    # always 1MB like the reference; conn-age rotation is opt-in.
    grpc_max_conn_age_s: float = 0.0  # GUBER_GRPC_MAX_CONN_AGE_SEC; 0 = off

    # Separate health-only listener that never requests a client cert
    # (reference HTTPStatusListenAddress / GUBER_STATUS_HTTP_ADDRESS,
    # daemon.go:305-333). Only meaningful with TLS+mTLS configured.
    status_http_listen_address: str = ""

    # Edge-tier listener (GUBER_EDGE_LISTEN_ADDRESS): framed-RPC address
    # (unix:///path or host:port) where gubernator-tpu-edge processes
    # relay client calls (service/edge.py). Empty = disabled. No
    # reference analog — the edge tier is the TPU-native scale-out of
    # the serving path (the chip-owning process is singular; gRPC
    # termination scales horizontally).
    edge_listen_address: str = ""

    # Span verbosity: ERROR | INFO | DEBUG (reference GUBER_TRACING_LEVEL,
    # config.go:717-752 — INFO drops noisy per-peer/healthcheck spans).
    trace_level: str = "INFO"

    # Log settings (reference GUBER_LOG_LEVEL / GUBER_LOG_FORMAT /
    # GUBER_DEBUG; applied by the CLI entry point).
    log_level: str = "info"
    log_format: str = ""  # "json" or "" (text)
    debug: bool = False

    # Reference GUBER_WORKER_COUNT sizes its goroutine WorkerPool
    # (workers.go:125-147). The TPU engine has no worker shards — the
    # kernel replaces them — so this knob is accepted and recorded but
    # intentionally has no effect (documented N/A).
    worker_count: int = 0

    # Peer picker tuning (reference config.go:421-443). Default
    # fnv1a-mix (fnv1a + murmur fmix64 finalizer) for distribution
    # quality — bare FNV skews badly on sequential keys; "fnv1" is the
    # reference-compat opt-in for drop-in key->owner ring parity.
    peer_picker_hash: str = "fnv1a-mix"
    hash_replicas: int = 512

    # Optional TLS (service.tls.TlsConfig); None = plaintext
    tls: Optional[object] = None

    # Optional OS/runtime Prometheus collectors: ["os", "golang"]
    # (reference flags.go:19-57; 'golang' maps to the Python runtime)
    metric_flags: List[str] = dataclasses.field(default_factory=list)

    # Optional persistence plugins (gubernator_tpu.store protocols):
    # loader restores at startup / saves at close (reference
    # gubernator.go:138-148, 151-178); store enables read-through +
    # write-behind on the engine.
    loader: Optional[object] = None
    store: Optional[object] = None

    # Instance identity for logs/debugging (reference GUBER_INSTANCE_ID)
    instance_id: str = ""

    # Block startup until the kernel width-bucket ladder is compiled so
    # the first NO_BATCHING request gets a width-sized kernel instead of
    # a batch_size-wide dispatch (GUBER_PREWARM_BUCKETS; VERDICT r3 item
    # 7). Off by default: the serving path never JIT-compiles either
    # way, and warm restarts make this near-instant under the
    # persistent compile cache.
    prewarm_buckets: bool = False
    prewarm_timeout_s: float = 600.0

    # Graceful-drain budget (GUBER_DRAIN_TIMEOUT): bounds how long a
    # SIGTERM/close() waits for in-flight RPCs and the engine queue to
    # finish before stragglers fail with the typed retryable status.
    # Also feeds EngineConfig.drain_timeout_s for the pump's own drain
    # pass (docs/robustness.md "Rolling restarts & handover").
    drain_timeout_s: float = 5.0

    # Continuous-batching pipeline depth (GUBER_PIPELINE_DEPTH): max
    # engine flushes in flight at once — host encode of the next flush
    # overlaps device execution of the previous (docs/architecture.md
    # "Pipelined dispatch"). 1 = the serial pump (bit-exact decisions
    # either way); feeds EngineConfig/IciEngineConfig.pipeline_depth.
    pipeline_depth: int = 2

    # Request-lifecycle observability (docs/monitoring.md "Tracing the
    # pipeline" / "Hot keys"): GUBER_HOTKEYS_K bounds the top-K hot-key
    # sketch (0 = off); GUBER_STAGE_METADATA returns a per-response
    # stage_breakdown_us metadata entry (off: zero per-item cost);
    # GUBER_EXEMPLARS attaches flush-trace exemplars to the latency
    # histograms under OpenMetrics negotiation.
    hotkeys_k: int = 128
    stage_metadata: bool = False
    exemplars: bool = True

    # Table observatory (docs/monitoring.md "Table census"):
    # GUBER_TABLE_CENSUS_TTL caches the device census scan for this many
    # seconds (scrapes within the window reuse it — zero device work);
    # GUBER_TABLE_CENSUS_THRESHOLDS sets the cold-set idleness
    # multipliers (a slot is "cold at kx" when idle > k x its own
    # duration); GUBER_TABLE_CENSUS_HEATMAP sets how many group regions
    # the occupancy heatmap aggregates into (the future page axis).
    census_ttl_s: float = 5.0
    census_thresholds: tuple = (1, 4, 16)
    census_heatmap_width: int = 64

    # Admission observatory (docs/monitoring.md "Admission"):
    # GUBER_ADMISSION_TTL caches the device admission scan (ground-truth
    # admitted-vs-limit accounting) for this many seconds — scrapes of
    # /metrics and /debug/admission within the window reuse it, zero
    # device work; GUBER_ADMISSION_RING bounds the decision
    # flight-recorder ring (last N answers with path, status, key hash,
    # staleness, trace id).
    admission_ttl_s: float = 5.0
    admission_ring: int = 256

    # Paged slot table (docs/architecture.md "Paged table"):
    # GUBER_TABLE_PAGE_GROUPS > 0 carves the table into pages of that
    # many contiguous groups behind a device-resident indirection map,
    # keeping only GUBER_TABLE_PAGE_BUDGET pages in HBM (cold pages
    # demote to a host-DRAM tier). GUBER_TABLE_PAGE_DEMOTE_INTERVAL
    # paces the background demoter (0 = demand demotes only);
    # GUBER_TABLE_PAGE_FREE_TARGET is the free-frame headroom it keeps.
    # Default off: the flat table is bit-exact and has zero translation
    # overhead when the keyspace fits HBM.
    page_groups: int = 0
    page_budget: int = 0
    page_demote_interval_s: float = 2.0
    page_free_target: int = 1

    # SLO observatory + self-watchdog (docs/monitoring.md "SLOs & burn
    # rates"): GUBER_SLO_SAMPLE_INTERVAL paces the background SLI
    # sampler that feeds the time-series rings (0 = observatory off);
    # GUBER_SLO_SPECS overrides/extends the built-in SLO spec set
    # (JSON list, see service/slo.py); GUBER_WATCHDOG_STALL_MS is the
    # heartbeat-age bound past which a background loop is flagged
    # stalled (0 = watchdog off).
    slo_sample_interval_s: float = 5.0
    slo_specs: str = ""
    watchdog_stall_ms: float = 5000.0

    # -- overload control plane (docs/robustness.md "Overload control &
    # brownout"; service/overload.py) ------------------------------------

    # GUBER_OVERLOAD: master switch. Off (default) keeps intake,
    # forwarding, and every response bit-exact with the pre-overload
    # daemon — no governor is injected, the intake queue stays
    # effectively unbounded.
    overload: bool = False
    # GUBER_INTAKE_LIMIT: engine intake queue budget; past it, intake
    # resolves the typed retryable ERR_OVERLOADED (with retry_after_ms)
    # instead of queueing toward a timeout.
    intake_limit: int = 8192
    # GUBER_INTAKE_TARGET_MS: CoDel target for the intake queue-wait
    # signal — when the per-interval MINIMUM wait sustains above this,
    # the governor sheds probabilistically with per-tenant weighting.
    intake_target_ms: float = 20.0

    # Continuous profiling (docs/monitoring.md "Device resources"):
    # GUBER_PROFILE_INTERVAL > 0 starts a background sampler that takes
    # a GUBER_PROFILE_SECONDS-long jax.profiler capture each interval,
    # keeping the newest GUBER_PROFILE_KEEP trace dirs on disk
    # (service/profiler.py). Default off — captures cost real device
    # time and trace bytes; an explicit operator opt-in.
    profile_interval_s: float = 0.0
    profile_seconds: float = 0.5
    profile_keep: int = 8

    def engine_config(self) -> EngineConfig:
        if self.engine is not None:
            return self.engine
        from gubernator_tpu.ops.kernels import LAYOUTS

        if self.table_layout not in LAYOUTS:
            raise ValueError(
                f"table_layout={self.table_layout!r} is invalid; choices "
                f"are {list(LAYOUTS)}"
            )
        ways = 8
        groups = 1
        while groups * ways < self.cache_size:
            groups <<= 1
        return EngineConfig(
            num_groups=groups,
            ways=ways,
            batch_wait_s=self.behaviors.batch_wait_s,
            batch_limit=self.behaviors.batch_limit,
            # Daemons serve the columnar edge; sized kernel buckets
            # compile in the background at boot.
            fast_buckets=True,
            layout=self.table_layout,
            hotkeys_k=self.hotkeys_k,
            stage_metadata=self.stage_metadata,
            exemplars=self.exemplars,
            drain_timeout_s=self.drain_timeout_s,
            pipeline_depth=self.pipeline_depth,
            census_ttl_s=self.census_ttl_s,
            census_thresholds=self.census_thresholds,
            census_heatmap_width=self.census_heatmap_width,
            admission_ttl_s=self.admission_ttl_s,
            page_groups=self.page_groups,
            page_budget=self.page_budget,
            page_demote_interval_s=self.page_demote_interval_s,
            page_free_target=self.page_free_target,
            # Handover and standby replication need routable
            # (string-keyed) snapshots even on the store-less columnar
            # edge; with both off, skip the decode.
            record_columnar_keys=self.behaviors.handover
            or self.behaviors.standby,
        )
