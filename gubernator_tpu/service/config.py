"""Daemon configuration (reference config.go:73-252 analog).

Library users fill these dataclasses directly; the CLI/env layer
(`gubernator_tpu.service.envconfig`) populates them from GUBER_* env vars
the way the reference's SetupDaemonConfig does (config.go:270-479).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.runtime.engine import EngineConfig


@dataclasses.dataclass
class BehaviorConfig:
    """Batching / GLOBAL tuning knobs (reference config.go:49-70,126-134)."""

    batch_timeout_s: float = 0.5
    batch_wait_s: float = 500e-6
    batch_limit: int = 1000

    global_timeout_s: float = 0.5
    global_sync_wait_s: float = 0.1
    global_batch_limit: int = 1000
    global_peer_requests_concurrency: int = 100

    force_global: bool = False


@dataclasses.dataclass
class DaemonConfig:
    grpc_listen_address: str = "127.0.0.1:0"
    http_listen_address: str = "127.0.0.1:0"
    advertise_address: str = ""  # defaults to the bound gRPC address
    data_center: str = ""

    # Counter capacity: total slots = cache_size rounded up to groups*ways
    # (reference default 50k items, config.go:139-140)
    cache_size: int = 50_000

    behaviors: BehaviorConfig = dataclasses.field(default_factory=BehaviorConfig)
    engine: Optional[EngineConfig] = None

    # Static peer list (the in-process cluster fixture and tests use this;
    # discovery pools feed the same set_peers path)
    peers: List[PeerInfo] = dataclasses.field(default_factory=list)

    # GLOBAL sync transport: "grpc" (cross-host, reference-compatible) or
    # "ici" (multi-device collective mode: the daemon serves a whole
    # device mesh as one process; see runtime/ici_engine.py)
    global_mode: str = "grpc"
    ici: Optional[object] = None  # runtime.ici_engine.IciEngineConfig

    # Discovery backend: static | dns | etcd | k8s | member-list
    discovery: str = "static"
    dns_fqdn: str = ""
    dns_interval_s: float = 300.0
    # member-list (gossip) backend (reference memberlist.go knobs)
    gossip_bind: str = ""  # UDP host:port; port 0 = ephemeral
    gossip_seeds: List[str] = dataclasses.field(default_factory=list)
    gossip_interval_s: float = 1.0

    # Peer picker tuning (reference config.go:421-443)
    peer_picker_hash: str = "fnv1"
    hash_replicas: int = 512

    # Optional TLS (service.tls.TlsConfig); None = plaintext
    tls: Optional[object] = None

    # Optional OS/runtime Prometheus collectors: ["os", "golang"]
    # (reference flags.go:19-57; 'golang' maps to the Python runtime)
    metric_flags: List[str] = dataclasses.field(default_factory=list)

    # Optional persistence plugins (gubernator_tpu.store protocols):
    # loader restores at startup / saves at close (reference
    # gubernator.go:138-148, 151-178); store enables read-through +
    # write-behind on the engine.
    loader: Optional[object] = None
    store: Optional[object] = None

    # Instance identity for logs/debugging (reference GUBER_INSTANCE_ID)
    instance_id: str = ""

    def engine_config(self) -> EngineConfig:
        if self.engine is not None:
            return self.engine
        ways = 8
        groups = 1
        while groups * ways < self.cache_size:
            groups <<= 1
        return EngineConfig(
            num_groups=groups,
            ways=ways,
            batch_wait_s=self.behaviors.batch_wait_s,
            batch_limit=self.behaviors.batch_limit,
        )
