"""Kubernetes peer discovery (reference kubernetes.go:35-247).

Informer-equivalent built on the Kubernetes HTTP API with aiohttp — no
client-go analog required:

- list Endpoints (default) or Pods in a namespace filtered by a label
  selector, then open a `?watch=1` stream from the returned
  resourceVersion; every event updates an object store and rebuilds the
  full peer list (the reference's SharedIndexInformer re-lists its store
  on every add/update/delete, kubernetes.go:174-247).
- Endpoints mode: one peer per subset address at `<ip>:<pod_port>`
  (kubernetes.go:218-245). Pods mode: one peer per pod with all
  containers ready+running (kubernetes.go:188-216).
- Self-detection: address IP == conf.pod_ip marks IsOwner.
- Watch failures (410 Gone, network errors, stream end) re-list and
  re-watch with backoff — the informer's resync behavior.

In-cluster credentials come from the standard service-account mount
(token + CA) and KUBERNETES_SERVICE_HOST/PORT; both are overridable for
tests/off-cluster runs (reference kubernetesconfig.go in-cluster vs
local kubeconfig split).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import ssl
from typing import Callable, Dict, List, Optional

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.service.config import K8sConfig

log = logging.getLogger("gubernator_tpu.k8s")

BACKOFF_S = 5.0


async def _iter_lines(stream):
    """Yield newline-delimited chunks without aiohttp's per-line 64KB
    readline cap — a single watch event for a large Endpoints object can
    exceed it."""
    buf = b""
    while True:
        chunk = await stream.readany()
        if not chunk:
            if buf.strip():
                yield buf
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            line = line.strip()
            if line:
                yield line


class K8sPool:
    def __init__(
        self,
        conf: K8sConfig,
        on_update: Callable[[List[PeerInfo]], None],
    ):
        if not conf.selector:
            raise ValueError(
                "k8s discovery requires a label selector "
                "(GUBER_K8S_ENDPOINTS_SELECTOR)"
            )
        if conf.mechanism not in ("endpoints", "pods"):
            raise ValueError(f"invalid k8s watch mechanism {conf.mechanism!r}")
        self.conf = conf
        self.on_update = on_update
        self._objects: Dict[str, dict] = {}  # name -> API object
        self._running = True
        self._session = None
        self._task = asyncio.ensure_future(self._run())

    # -- API plumbing ---------------------------------------------------------

    def _base_url(self) -> str:
        if self.conf.api_server:
            return self.conf.api_server.rstrip("/")
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return f"https://{host}:{port}"

    def _headers(self) -> dict:
        try:
            with open(self.conf.token_file) as f:
                return {"Authorization": f"Bearer {f.read().strip()}"}
        except OSError:
            return {}

    def _ssl(self):
        if not self._base_url().startswith("https"):
            return None
        try:
            ctx = ssl.create_default_context(cafile=self.conf.ca_file)
        except OSError:
            ctx = ssl.create_default_context()
        return ctx

    def _resource(self) -> str:
        return "endpoints" if self.conf.mechanism == "endpoints" else "pods"

    def _path(self) -> str:
        return (
            f"/api/v1/namespaces/{self.conf.namespace}/{self._resource()}"
        )

    async def _ensure_session(self):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    # -- list + watch loop ----------------------------------------------------

    async def _run(self) -> None:
        while self._running:
            try:
                rv = await self._list()
                await self._watch(rv)
            except asyncio.CancelledError:
                return
            except Exception as e:
                if not self._running:
                    return
                log.warning("k8s watch failed, re-listing: %s", e)
            if self._running:
                await asyncio.sleep(min(BACKOFF_S, 1.0))

    async def _list(self) -> str:
        session = await self._ensure_session()
        url = self._base_url() + self._path()
        async with session.get(
            url,
            params={"labelSelector": self.conf.selector},
            headers=self._headers(),
            ssl=self._ssl(),
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
        self._objects = {
            o["metadata"]["name"]: o for o in body.get("items", [])
        }
        self._rebuild()
        return body.get("metadata", {}).get("resourceVersion", "0")

    async def _watch(self, resource_version: str) -> None:
        import aiohttp

        session = await self._ensure_session()
        url = self._base_url() + self._path()
        async with session.get(
            url,
            params={
                "labelSelector": self.conf.selector,
                "watch": "1",
                "resourceVersion": resource_version,
                # Standard k8s watch bound: the server closes the stream
                # after this long, forcing a clean re-list/re-watch even
                # through half-open connections.
                "timeoutSeconds": "300",
            },
            headers=self._headers(),
            ssl=self._ssl(),
            # sock_read bounds a silent half-open connection (total stays
            # None — the watch is long-lived by design).
            timeout=aiohttp.ClientTimeout(total=None, sock_read=330),
        ) as resp:
            resp.raise_for_status()
            async for line in _iter_lines(resp.content):
                if not self._running:
                    return
                ev = json.loads(line)
                typ = ev.get("type")
                obj = ev.get("object", {})
                if typ == "ERROR":
                    # e.g. 410 Gone — resourceVersion too old; re-list
                    raise RuntimeError(f"watch error event: {obj}")
                name = obj.get("metadata", {}).get("name")
                if not name:
                    continue
                if typ == "DELETED":
                    self._objects.pop(name, None)
                else:  # ADDED | MODIFIED
                    self._objects[name] = obj
                self._rebuild()

    # -- peer extraction (kubernetes.go:188-245) ------------------------------

    def _rebuild(self) -> None:
        peers: List[PeerInfo] = []
        if self.conf.mechanism == "endpoints":
            for obj in self._objects.values():
                for subset in obj.get("subsets") or []:
                    for addr in subset.get("addresses") or []:
                        ip = addr.get("ip", "")
                        if not ip:
                            continue
                        peers.append(
                            PeerInfo(
                                grpc_address=f"{ip}:{self.conf.pod_port}",
                                is_owner=ip == self.conf.pod_ip,
                            )
                        )
        else:
            for obj in self._objects.values():
                status = obj.get("status", {})
                ip = status.get("podIP", "")
                if not ip:
                    continue
                # Running is `state.running: {}` (possibly empty) — check
                # presence, not truthiness (reference kubernetes.go:202:
                # `status.State.Running == nil`).
                ready = all(
                    cs.get("ready")
                    and (cs.get("state") or {}).get("running") is not None
                    for cs in status.get("containerStatuses") or [{}]
                )
                if not ready:
                    continue
                peers.append(
                    PeerInfo(
                        grpc_address=f"{ip}:{self.conf.pod_port}",
                        is_owner=ip == self.conf.pod_ip,
                    )
                )
        self.on_update(peers)

    # -- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        if not self._running:
            return
        self._running = False
        self._task.cancel()
        if self._session is not None:
            asyncio.ensure_future(self._session.close())

    async def aclose(self) -> None:
        self._running = False
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass  # the cancel above; expected teardown
        except Exception as e:
            # The watch task died on its own before the cancel — that
            # failure was about to vanish with the pool.
            log.warning("k8s watch task died before close: %s", e)
        if self._session is not None:
            await self._session.close()
            self._session = None
