"""Fleet SLO observatory: declarative specs + multi-window burn rates.

PRs 2/7/9/10/11/14 built a wide observation vector of point-in-time
SLIs — enforcement-error ratio, propagation lag, flush/device-sync
histograms, breaker states, census churn, lease outstanding — but
nothing tracked them over TIME: no error budgets, no burn rates, no
notion of "this has been bad for 5 minutes AND the last hour". This
module closes that gap with the standard multi-window multi-burn-rate
construction (Google SRE workbook ch. 5):

  - a background sampler pushes each SLI into a bounded time-series
    ring (utils/timeseries.py) every GUBER_SLO_SAMPLE_INTERVAL,
    reading ONLY already-cached snapshots and host counters — a
    sampler tick does zero device work (GL009; the engine's
    cached_census()/cached_admission() accessors exist for exactly
    this), so the observatory is free at any cadence;
  - each SloSpec maps its ring to a bad-event fraction (comparator +
    threshold against the raw SLI value) and an OBJECTIVE; burn rate
    over a window = bad fraction / (1 - objective), so 1.0 means
    "burning exactly at budget";
  - the alert state machine fires `fast_burn` when BOTH fast windows
    (default 5m and 1h) exceed the fast factor (14.4 — budget gone in
    ~10h at that pace), `slow_burn` when both slow windows (30m / 6h)
    exceed 6.0, and `exhausted` when the budget window's remaining
    budget hits 0. Two windows per alert is what makes this page-able:
    the short window gives fast detection, the long window keeps a
    single bad scrape from paging anyone.

Everything exports three ways: the gubernator_slo_* metric families
(scrape bridge), the /debug/slo route on both listeners (gateway), and
a compact blob riding DebugInfo so /debug/cluster shows the fleet-wide
error-budget view. The self-watchdog (runtime/watchdog.py) feeds the
availability SLI: a stalled SERVING loop (pump / completion thread)
zeroes `serving_ok`, so a wedged daemon burns its availability budget
instead of silently flatlining.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from dataclasses import dataclass
from typing import Optional

from gubernator_tpu.utils import raceguard
from gubernator_tpu.utils.timeseries import RingSet

log = logging.getLogger(__name__)

# Alert states, least to most severe; exported as the numeric value of
# gubernator_slo_alert_state.
STATES = ("ok", "slow_burn", "fast_burn", "exhausted")

_COMPARATORS = ("gt", "ge", "lt", "le")


@dataclass(frozen=True)
class SloSpec:
    """One declarative SLO: which SLI ring, what counts as a bad
    sample, the objective, and the evaluation windows. Frozen so specs
    can be shared between the observatory, /debug/slo, and tests."""

    id: str
    sli: str  # ring name in the observatory's RingSet
    objective: float  # e.g. 0.999 -> error budget 0.001
    threshold: float = 0.0
    comparator: str = "gt"  # sample is BAD when <value> <cmp> <threshold>
    fast_windows: tuple = (300.0, 3600.0)  # 5m / 1h
    slow_windows: tuple = (1800.0, 21600.0)  # 30m / 6h
    fast_factor: float = 14.4
    slow_factor: float = 6.0
    budget_window_s: float = 21600.0  # budget accounted over 6h
    description: str = ""

    def validate(self) -> None:
        if not self.id or not self.sli:
            raise ValueError("SLO spec needs non-empty 'id' and 'sli'")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.id!r}: objective must be in (0, 1), got "
                f"{self.objective}"
            )
        if self.comparator not in _COMPARATORS:
            raise ValueError(
                f"SLO {self.id!r}: comparator must be one of "
                f"{_COMPARATORS}, got {self.comparator!r}"
            )
        for name, pair in (
            ("fast_windows", self.fast_windows),
            ("slow_windows", self.slow_windows),
        ):
            if len(pair) != 2 or not all(
                isinstance(w, (int, float)) and w > 0 for w in pair
            ):
                raise ValueError(
                    f"SLO {self.id!r}: {name} must be two positive "
                    f"durations, got {pair!r}"
                )
        if self.budget_window_s <= 0:
            raise ValueError(
                f"SLO {self.id!r}: budget_window_s must be > 0"
            )

    def is_bad(self, value: float) -> bool:
        if self.comparator == "gt":
            return value > self.threshold
        if self.comparator == "ge":
            return value >= self.threshold
        if self.comparator == "lt":
            return value < self.threshold
        return value <= self.threshold


def default_specs() -> tuple:
    """The built-in SLO catalog. Every id here must have a matching row
    in docs/monitoring.md's alert table (guberlint GL015 pins both
    directions)."""
    return (
        SloSpec(
            id="availability",
            sli="serving_ok",
            objective=0.999,
            threshold=1.0,
            comparator="lt",
            description="Serving loops alive: the watchdog saw the pump "
            "and completion heartbeats within their stall deadline.",
        ),
        SloSpec(
            id="admission-accuracy",
            sli="admission_debt_ratio",
            objective=0.999,
            threshold=0.1,
            comparator="gt",
            description="Unreconciled admission debt — lease outstanding "
            "+ GLOBAL in-flight hits, the published over-admission bound "
            "(/debug/admission `bound`) — stays under 10% of the "
            "capacity admitted this window. A partitioned owner strands "
            "debt at the edges and burns this SLO until the heal drains "
            "it.",
        ),
        SloSpec(
            id="enforcement-fidelity",
            sli="false_over_limit_keys",
            objective=0.999,
            threshold=0.0,
            comparator="gt",
            description="No sampled key is refused at a current replica "
            "while the owner still has budget (auditor false-OVER_LIMIT).",
        ),
        SloSpec(
            id="flush-latency",
            sli="flush_p99_s",
            objective=0.99,
            threshold=0.1,
            comparator="gt",
            description="Engine flush p99 stays under 100ms.",
        ),
        SloSpec(
            id="propagation-freshness",
            sli="propagation_lag_p99_s",
            objective=0.99,
            threshold=5.0,
            comparator="gt",
            description="GLOBAL propagation lag p99 stays under 5s "
            "(origin stamp to replica apply).",
        ),
        SloSpec(
            id="durability",
            sli="standby_loss_bound_hits",
            objective=0.999,
            threshold=1000.0,
            comparator="gt",
            description="The published hard-kill loss bound — hits "
            "dirtied since the last acked standby delta ship "
            "(/debug/standby `loss_bound_hits`) — stays under 1000. A "
            "dead or partitioned successor stops acks, the bound grows "
            "with traffic, and this SLO burns until the standby leg "
            "heals or promotes.",
        ),
        SloSpec(
            id="shard-balance",
            sli="shard_imbalance_ratio",
            objective=0.99,
            threshold=1.5,
            comparator="gt",
            description="Mesh shard skew (max/mean across decisions, "
            "occupancy, resident frames) stays under 1.5x.",
        ),
    )


_SPEC_FIELDS = {f.name for f in SloSpec.__dataclass_fields__.values()}


def parse_slo_specs(text: str) -> tuple:
    """GUBER_SLO_SPECS: a JSON list of spec dicts. An entry whose id
    matches a built-in OVERRIDES it field-by-field (unset fields keep
    the built-in's values — so shrinking just the windows for a soak
    doesn't mean restating the whole spec); a new id appends. Raises
    ValueError on malformed JSON or spec shape (envconfig fails the
    daemon at config time, not at first tick)."""
    if not text or not text.strip():
        return default_specs()
    try:
        raw = json.loads(text)
    except ValueError as e:
        raise ValueError(f"not valid JSON ({e})") from None
    if not isinstance(raw, list):
        raise ValueError("must be a JSON LIST of spec objects")
    base = {s.id: s for s in default_specs()}
    order = list(base)
    for i, entry in enumerate(raw):
        if not isinstance(entry, dict) or "id" not in entry:
            raise ValueError(f"entry {i} must be an object with an 'id'")
        unknown = set(entry) - _SPEC_FIELDS
        if unknown:
            raise ValueError(
                f"entry {entry['id']!r} has unknown fields {sorted(unknown)}"
            )
        for k in ("fast_windows", "slow_windows"):
            if k in entry:
                entry[k] = tuple(float(w) for w in entry[k])
        sid = entry["id"]
        if sid in base:
            merged = {**base[sid].__dict__, **entry}
            base[sid] = SloSpec(**merged)
        else:
            if "sli" not in entry or "objective" not in entry:
                raise ValueError(
                    f"new SLO {sid!r} needs at least 'sli' and 'objective'"
                )
            base[sid] = SloSpec(**entry)
            order.append(sid)
    specs = tuple(base[sid] for sid in order)
    for s in specs:
        s.validate()
    return specs


def _window_label(seconds: float) -> str:
    """Stable human window label for the burn-rate gauge ('5m', '1h');
    falls back to '<n>s' for non-round overrides."""
    s = float(seconds)
    if s % 3600 == 0:
        return f"{int(s // 3600)}h"
    if s % 60 == 0:
        return f"{int(s // 60)}m"
    return f"{s:g}s"


class SloObservatory:
    """Sampler thread + burn-rate evaluator for one daemon.

    The sampler reads ONLY cached snapshots and host-side counters
    (the sources list below documents each one's zero-device-work
    justification); evaluation is pure ring arithmetic. Both are safe
    from any thread at any cadence."""

    def __init__(
        self,
        svc,
        interval_s: float = 5.0,
        specs: Optional[tuple] = None,
        watchdog=None,
    ):
        self.svc = svc
        self.interval_s = max(float(interval_s), 0.1)
        self.specs = tuple(specs) if specs is not None else default_specs()
        self.watchdog = watchdog
        # Ring capacity: cover the largest window any spec evaluates at
        # this cadence, bounded so a 1ms soak interval can't balloon.
        horizon = max(
            [self.interval_s]
            + [
                max(max(s.fast_windows), max(s.slow_windows),
                    s.budget_window_s)
                for s in self.specs
            ]
        )
        cap = int(min(max(math.ceil(horizon / self.interval_s) + 8, 720),
                      8640))
        self.rings = RingSet(cap)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticks = 0

    # -- sampling (zero device work) ----------------------------------------

    def sample_once(self, now: Optional[float] = None) -> None:
        """One sampling pass. Sources, and why each does no device
        work: cached_admission()/cached_census() return the TTL cache
        or None (never scan); histogram summaries and breaker/lease/
        auditor summaries are host dict walks; the pager's move
        counters and the watchdog table are plain attributes. An SLI
        whose source is absent this tick simply pushes nothing — its
        windows read as empty, which the evaluator reports as
        data-less rather than healthy."""
        now = time.monotonic() if now is None else now
        svc = self.svc
        push = self.rings.push

        # Availability: the watchdog's view of the serving loops. This
        # is the SLI a wedged completion thread burns.
        wd = self.watchdog
        if wd is not None:
            push("serving_ok", 0.0 if wd.serving_stalled() else 1.0, now)

        eng = getattr(svc, "engine", None)
        limit_hits = None
        if eng is not None:
            if hasattr(eng, "cached_admission"):
                adm = eng.cached_admission()
                if adm is not None:
                    push(
                        "admission_excess_ratio",
                        float(adm.get("excess_ratio", 0.0)),
                        now,
                    )
                    limit_hits = float(adm.get("limit_hits", 0) or 0)
            em = getattr(eng, "metrics", None)
            if em is not None:
                fd = getattr(em, "flush_duration", None)
                if fd is not None:
                    push("flush_p99_s", float(fd.summary()["p99"]), now)
                ds = getattr(em, "device_sync", None)
                if ds is not None:
                    push(
                        "device_sync_p99_s",
                        float(ds.summary()["p99"]),
                        now,
                    )
            if hasattr(eng, "shard_stats"):
                ss = eng.shard_stats()
                if ss is not None and ss.get("imbalance_ratio") is not None:
                    push(
                        "shard_imbalance_ratio",
                        float(ss["imbalance_ratio"]),
                        now,
                    )
            pager = getattr(eng, "_pager", None)
            if pager is not None:
                # Cumulative move counters; rate() turns them into the
                # paging-churn series /debug/slo reports.
                push("page_demotes", float(pager.demotes), now)
                push("page_promotes", float(pager.promotes), now)

        m = getattr(svc, "metrics", None)
        if m is not None and hasattr(m, "global_propagation_lag"):
            lag = m.global_propagation_lag.summary()
            if lag.get("count"):
                push(
                    "propagation_lag_p99_s", float(lag["p99"]), now
                )

        auditor = getattr(svc, "auditor", None)
        if auditor is not None:
            s = auditor.summary()
            adm = s.get("admission") or {}
            if "false_over_limit_keys" in adm:
                push(
                    "false_over_limit_keys",
                    float(adm["false_over_limit_keys"]),
                    now,
                )
            push(
                "divergence_total",
                float(sum((s.get("divergence") or {}).values())),
                now,
            )
            push(
                "max_staleness_ms", float(s.get("max_staleness_ms", 0)), now
            )

        lm = getattr(svc, "lease_mgr", None)
        if lm is not None:
            push(
                "lease_outstanding_hits", float(lm.outstanding_hits()), now
            )

        # Durability: the standby loss bound (pending unacked hits +
        # undrained engine dirt — host dict sum under the dirty lock,
        # zero device work).
        sb = getattr(svc, "standby", None)
        if sb is not None:
            push(
                "standby_loss_bound_hits", float(sb.loss_bound_hits()), now
            )

        # Admission debt: the node's published over-admission bound
        # (lease outstanding + GLOBAL in-flight hits, /debug/admission
        # `bound`) as a fraction of the capacity the TTL-cached
        # admission scan saw admitted this window. This is the
        # admission-accuracy SLI: a partitioned owner strands the
        # GLOBAL hit queue at the edges, the ratio pins near 1, and
        # the SLO fast-burns until the heal drains the debt.
        gm = getattr(svc, "global_mgr", None)
        debt = 0.0
        have_debt = False
        if lm is not None:
            debt += float(lm.outstanding_hits())
            have_debt = True
        if gm is not None and hasattr(gm, "inflight_hits"):
            debt += float(gm.inflight_hits())
            have_debt = True
        if have_debt and limit_hits:
            push("admission_debt_ratio", debt / limit_hits, now)

        fwd = getattr(svc, "forwarder", None)
        if fwd is not None and hasattr(fwd, "breaker_summary"):
            summary = fwd.breaker_summary()
            if summary:
                open_n = sum(1 for st in summary.values() if st != "closed")
                push(
                    "breaker_open_fraction", open_n / len(summary), now
                )

        self._ticks += 1

    # -- evaluation ----------------------------------------------------------

    def evaluate_spec(
        self, spec: SloSpec, now: Optional[float] = None
    ) -> dict:
        """Burn rates + alert state for one spec, from its ring."""
        now = time.monotonic() if now is None else now
        ring = self.rings.get(spec.sli)
        budget = 1.0 - spec.objective

        def burn(window_s: float) -> Optional[float]:
            if ring is None:
                return None
            frac = ring.bad_fraction(spec.is_bad, window_s, now)
            return None if frac is None else frac / budget

        windows = {}
        for w in (*spec.fast_windows, *spec.slow_windows,
                  spec.budget_window_s):
            lbl = _window_label(w)
            if lbl not in windows:
                b = burn(w)
                windows[lbl] = None if b is None else round(b, 4)

        budget_burn = burn(spec.budget_window_s)
        remaining = (
            None
            if budget_burn is None
            else round(max(1.0 - budget_burn, 0.0), 4)
        )

        def pair_fires(pair, factor) -> bool:
            burns = [burn(w) for w in pair]
            return all(b is not None and b > factor for b in burns)

        if remaining is not None and remaining <= 0.0:
            state = "exhausted"
        elif pair_fires(spec.fast_windows, spec.fast_factor):
            state = "fast_burn"
        elif pair_fires(spec.slow_windows, spec.slow_factor):
            state = "slow_burn"
        else:
            state = "ok"
        return {
            "id": spec.id,
            "sli": spec.sli,
            "objective": spec.objective,
            "state": state,
            "state_value": STATES.index(state),
            "burn_rates": windows,
            "error_budget_remaining": remaining,
            "samples": 0 if ring is None else len(ring),
            "last": None if ring is None else (
                None if ring.last() is None else round(ring.last()[1], 6)
            ),
        }

    def evaluate(self, now: Optional[float] = None) -> list:
        now = time.monotonic() if now is None else now
        return [self.evaluate_spec(s, now) for s in self.specs]

    # -- exports -------------------------------------------------------------

    def debug_info(self) -> dict:
        """/debug/slo payload; the compact `fleet` block also rides
        DebugInfo so /debug/cluster aggregates error budgets."""
        evals = self.evaluate()
        out = {
            "v": 1,
            "sample_interval_s": self.interval_s,
            "ticks": self._ticks,
            "slos": evals,
            "slis": self.rings.snapshot(window_s=300.0),
        }
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.snapshot()
        remaining = [
            (e["error_budget_remaining"], e["id"])
            for e in evals
            if e["error_budget_remaining"] is not None
        ]
        worst = min(remaining) if remaining else None
        out["budget"] = {
            "min_remaining": None if worst is None else worst[0],
            "worst_slo": None if worst is None else worst[1],
            "alerting": sorted(
                e["id"] for e in evals if e["state"] != "ok"
            ),
        }
        return out

    def fleet_info(self) -> dict:
        """The DebugInfo rider: per-SLO state + budget, no ring dumps
        (wire weight the fleet view doesn't need)."""
        evals = self.evaluate()
        info = {
            "slos": {
                e["id"]: {
                    "state": e["state"],
                    "error_budget_remaining": e["error_budget_remaining"],
                }
                for e in evals
            },
        }
        if self.watchdog is not None:
            info["serving_stalled"] = self.watchdog.serving_stalled()
            info["stalled_loops"] = self.watchdog.stalled_loops()
        return info

    def metrics_sync(self, m) -> None:
        """Scrape bridge (Metrics.add_sync): publish burn rates, budget
        remaining, alert state, and the watchdog's per-loop stall flags.
        Pure ring/dict arithmetic — zero device work on scrape."""
        for e in self.evaluate():
            for lbl, b in e["burn_rates"].items():
                if b is not None:
                    m.slo_burn_rate.labels(e["id"], lbl).set(b)
            if e["error_budget_remaining"] is not None:
                m.slo_error_budget_remaining.labels(e["id"]).set(
                    e["error_budget_remaining"]
                )
            m.slo_alert_state.labels(e["id"]).set(e["state_value"])
        wd = self.watchdog
        if wd is not None:
            snap = wd.snapshot()
            for name, row in snap["loops"].items():
                m.thread_stalled.labels(name).set(
                    1 if row["stalled"] else 0
                )

    # -- lifecycle -----------------------------------------------------------

    def _loop(self) -> None:
        # First beat up front: the loop must appear in the watchdog
        # table the moment it starts, not one interval later.
        if self.watchdog is not None:
            self.watchdog.beat("slo-sampler", period_s=self.interval_s)
        while not self._stop.wait(self.interval_s):
            if self.watchdog is not None:
                self.watchdog.beat("slo-sampler", period_s=self.interval_s)
            try:
                self.sample_once()
            except Exception:
                # A broken source must not kill the sampler — the SLI
                # it feeds goes data-less, which /debug/slo surfaces.
                log.exception("SLO sampling pass failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gubernator-slo-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if self.watchdog is not None:
            self.watchdog.unregister("slo-sampler")


# Declared lock protocol (docs/robustness.md "Race sanitizer"). The
# Sampler-thread handle rebinds are single-threaded by contract (daemon
# startup/shutdown); concurrent start()/stop() would leak or double-
# start the loop, so write affinity is worth pinning. _ticks stays
# DELIBERATELY undeclared: the loop owns it in production, but
# sample_once() is documented as directly callable from tests and soak
# jobs while the loop runs — a second `+= 1` writer the monitoring
# counter tolerates (a lost increment skews nothing), and /debug/slo
# reads the int racily by design.
raceguard.guarded_by(SloObservatory, {
    "_thread": "@thread",
})
