"""HTTP/JSON gateway + /metrics + /healthz.

Mirrors the reference's grpc-gateway mux (reference daemon.go:251-299):
POST /v1/GetRateLimits and GET /v1/HealthCheck speak snake_case JSON
(pinned by the reference's TestGRPCGateway), /metrics serves Prometheus
text, /healthz is the liveness probe.
"""

from __future__ import annotations

import json

from aiohttp import web

from gubernator_tpu.service import pb
from gubernator_tpu.service.server import ApiError, V1Service


async def read_json_requests(request: web.Request):
    """Parse + validate a /v1/GetRateLimits JSON body.

    Returns (reqs, None) or (None, error_response). Shared by the
    daemon gateway and the edge gateway (service/edge.py) so the two
    HTTP fronts cannot diverge on the wire contract."""
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return None, web.json_response(
            {"code": 3, "message": f"invalid JSON: {e}"}, status=400
        )
    if not isinstance(body, dict):
        return None, web.json_response(
            {"code": 3, "message": "request body must be a JSON object"},
            status=400,
        )
    items = body.get("requests") or []
    if not isinstance(items, list) or not all(
        isinstance(d, dict) for d in items
    ):
        return None, web.json_response(
            {"code": 3, "message": "'requests' must be a list of objects"},
            status=400,
        )
    try:
        return [pb.req_from_json(d) for d in items], None
    except (TypeError, ValueError) as e:
        return None, web.json_response(
            {"code": 3, "message": f"invalid request: {e}"}, status=400
        )


def build_app(svc: V1Service) -> web.Application:
    app = web.Application()

    async def get_rate_limits(request: web.Request) -> web.Response:
        reqs, err = await read_json_requests(request)
        if err is not None:
            return err
        try:
            out = await svc.get_rate_limits(reqs)
        except ApiError as e:
            return web.json_response({"code": 11, "message": str(e)}, status=e.http_code)
        return web.json_response({"responses": [pb.resp_to_json(r) for r in out]})

    async def health_check(request: web.Request) -> web.Response:
        h = await svc.health_check()
        return web.json_response(pb.health_to_json(h))

    async def healthz(request: web.Request) -> web.Response:
        h = await svc.health_check()
        return web.Response(
            text=h.status, status=200 if h.status == "healthy" else 503
        )

    async def metrics(request: web.Request) -> web.Response:
        return web.Response(
            body=svc.metrics.render(), content_type="text/plain", charset="utf-8"
        )

    app.router.add_post("/v1/GetRateLimits", get_rate_limits)
    app.router.add_get("/v1/HealthCheck", health_check)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    return app


def build_status_app(svc: V1Service) -> web.Application:
    """Health-only app for the no-mTLS status listener (reference
    daemon.go:305-333 serves ONLY /v1/HealthCheck there)."""
    app = web.Application()

    async def health_check(request: web.Request) -> web.Response:
        h = await svc.health_check()
        return web.json_response(pb.health_to_json(h))

    app.router.add_get("/v1/HealthCheck", health_check)
    return app
