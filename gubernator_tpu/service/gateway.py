"""HTTP/JSON gateway + /metrics + /healthz + /debug.

Mirrors the reference's grpc-gateway mux (reference daemon.go:251-299):
POST /v1/GetRateLimits and GET /v1/HealthCheck speak snake_case JSON
(pinned by the reference's TestGRPCGateway), /metrics serves Prometheus
text, /healthz is the liveness probe.

Device-tier debug surface (docs/monitoring.md; no reference analog):

- GET /debug/engine — the engine's flight recorder (last K flush
  records), histogram summaries, counters, and table occupancy as JSON.
- GET /debug/hotkeys — top-K hot-key attribution (the space-saving
  sketch: estimated hits, error bound, over-limit counts per key).
- GET /debug/profile?seconds=N — on-demand jax.profiler capture to a
  temp dir (one capture at a time process-wide; 503 when busy or when
  the profiler is unavailable). Works on CPU too — the XLA profiler is
  backend-agnostic.
- GET /debug/slo — the SLO observatory: per-SLO multi-window burn
  rates, alert states, remaining error budgets, and the self-watchdog's
  per-loop heartbeat table (docs/monitoring.md "SLOs & burn rates").

Both are served by the main gateway AND the status listener
(daemon.go:305-333 analog), so an mTLS deployment can reach them
without client certs.
"""

from __future__ import annotations

import asyncio
import json

from aiohttp import web

from gubernator_tpu.service import pb
from gubernator_tpu.service import profiler as _profiler
from gubernator_tpu.service.server import ApiError, V1Service

# jax.profiler state is process-global: exactly one capture at a time,
# regardless of how many daemons/listeners share the process. The guard
# and the bounded/rotating capture itself live in service/profiler.py
# (shared with the continuous sampler); these aliases keep the
# historical gateway names importable.
_PROFILE_GUARD = _profiler.PROFILE_GUARD
_PROFILE_MAX_SECONDS = _profiler.PROFILE_MAX_SECONDS


def add_debug_routes(app: web.Application, svc: V1Service) -> None:
    async def debug_engine(request: web.Request) -> web.Response:
        # debug_snapshot takes the engine lock for an occupancy readback;
        # keep it off the event loop.
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.engine.debug_snapshot
        )
        return web.json_response(snap)

    async def debug_profile(request: web.Request) -> web.Response:
        try:
            seconds = float(request.query.get("seconds", "1"))
        except ValueError:
            return web.json_response(
                {"error": "seconds must be a number"}, status=400
            )
        seconds = min(max(seconds, 0.05), _PROFILE_MAX_SECONDS)
        if not _PROFILE_GUARD.acquire(blocking=False):
            # Captures are short and serialized; tell pollers when to
            # come back instead of having them hammer the 503.
            return web.json_response(
                {"error": "a profile capture is already running"},
                status=503,
                headers={"Retry-After": str(int(seconds) or 1)},
            )
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, _profiler.capture, seconds
            )
        except Exception as e:
            return web.json_response(
                {"error": f"profiler unavailable: {e}"}, status=503
            )
        finally:
            _PROFILE_GUARD.release()
        return web.json_response(out)

    async def debug_device(request: web.Request) -> web.Response:
        """Device-resource observatory (docs/monitoring.md "Device
        resources"): per-subsystem HBM attribution + headroom, the
        host<->device transfer ledger, and compile telemetry with
        retrace attribution. Pure host-side reads (one allocator stats
        query, histogram summaries, bounded ring copies) — no device
        program runs (GL009); executor only for the engine attribute
        reads."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.device_debug_info
        )
        return web.json_response(snap)

    async def debug_hotkeys(request: web.Request) -> web.Response:
        # Sketch snapshot + census residency join: the join gathers the
        # tracked keys' slot rows under the engine lock — executor, not
        # event loop.
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.engine.hotkeys_snapshot
        )
        return web.json_response(snap)

    async def debug_table(request: web.Request) -> web.Response:
        """Full table-census snapshot (docs/monitoring.md "Table
        census"): per-tier age/idle histograms, the group-region
        occupancy heatmap, waste + cold-set summaries, and the churn
        ledger. TTL-cached in the engine — scraping this endpoint never
        triggers device work beyond one census per TTL interval; the
        cache read still briefly takes engine locks, so executor."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.engine.table_census
        )
        return web.json_response(snap)

    async def debug_leases(request: web.Request) -> web.Response:
        """Owner-side lease ledger (docs/architecture.md "Cooperative
        leases"): record/key counts, granted/returned/expired/credited
        hit flows, the outstanding over-admission bound, revocation
        state, and the top outstanding keys. Pure host-side dict reads;
        {"enabled": false} when GUBER_LEASES is off."""
        if svc.lease_mgr is None:
            return web.json_response({"enabled": False})
        return web.json_response(
            {"enabled": True, **svc.lease_mgr.summary()}
        )

    async def debug_admission(request: web.Request) -> web.Response:
        """Admission observatory (docs/monitoring.md "Admission"): the
        engine's TTL-cached ground-truth window accounting (admitted vs
        configured limit over the resident table), decision counts by
        serving path, the node's over-admission bound (outstanding lease
        hits + un-relayed GLOBAL hits), and the decision flight-recorder
        ring. TTL-cached engine snapshot + host dict copies — scraping
        this endpoint never compiles or dispatches device work beyond
        one scan per TTL interval; the cache read takes engine locks,
        so executor."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.admission_debug_info
        )
        return web.json_response(snap)

    async def debug_slo(request: web.Request) -> web.Response:
        """SLO observatory (docs/monitoring.md "SLOs & burn rates"):
        per-SLO multi-window burn rates, alert states (ok / slow_burn /
        fast_burn / exhausted), remaining error budgets, the sampled
        SLI time-series summaries, and the self-watchdog's per-loop
        heartbeat table. Pure host-side ring arithmetic over values the
        background sampler already cached — scraping this endpoint does
        zero device work; the ring reads take per-ring locks, so
        executor. {"enabled": false} when the observatory isn't wired."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.slo_debug_info
        )
        return web.json_response(snap)

    async def debug_standby(request: web.Request) -> web.Response:
        """Crash-tolerance observatory (docs/robustness.md "Standby
        replication & crash recovery"): the published hard-kill loss
        bound, pending (unacked) ledger size, shadow inventory by
        source owner, promotion history, and legacy (v1-fallback)
        peers. Host-side dict copies plus one dirty-registry read under
        its own lock — zero device work (GL009); executor because the
        loss bound briefly takes that lock. {"enabled": false} when
        GUBER_STANDBY is off."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.standby_debug_info
        )
        return web.json_response(snap)

    async def debug_overload(request: web.Request) -> web.Response:
        """Overload control plane (docs/robustness.md "Overload
        control & brownout"): the brownout ladder level + the signals
        driving it, and the intake governor's controller state — shed
        counts by reason, CoDel standing-queue state, per-tenant shed
        weights and heavy-hitter attribution. Host-side dict copies
        under the governor's own lock — zero device work (GL009);
        executor for the lock. {"enabled": false} when GUBER_OVERLOAD
        is off."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, svc.overload_debug_info
        )
        return web.json_response(snap)

    async def debug_cluster(request: web.Request) -> web.Response:
        """Cluster-wide debug view (docs/monitoring.md "Consistency"):
        this node's local_debug_info plus a breaker-gated, shared-deadline
        fan-out of PeersV1.DebugInfo to every live peer — the whole mesh's
        health, breakers, occupancy, hot keys, and consistency gauges
        from any single node. Skipped (circuit open) and failed peers
        appear as {"error": ...} rows, never as a whole-call failure."""
        loop = asyncio.get_running_loop()
        local = await loop.run_in_executor(None, svc.local_debug_info)
        out = {"local": local, "peers": {}}
        peers = []
        if svc.picker is not None:
            peers = [p for p in svc.picker.peers() if not p.info.is_owner]
        if peers:
            budget_s = 2.0
            if svc.forwarder is not None:
                budget_s = float(
                    getattr(svc.forwarder.behaviors, "forward_deadline_s", 2.0)
                )
            deadline = loop.time() + budget_s

            async def fetch(peer):
                addr = peer.info.grpc_address
                if not peer.breaker.allow():
                    return addr, {"error": "circuit open"}
                remaining = deadline - loop.time()
                if remaining <= 0:
                    return addr, {"error": "deadline exceeded"}
                try:
                    return addr, await peer.debug_info(timeout=remaining)
                except Exception as e:  # guberlint: allow-swallow -- failure becomes this peer's {"error": ...} row; the peer leg already counted it
                    return addr, {"error": str(e)}

            for addr, blob in await asyncio.gather(*(fetch(p) for p in peers)):
                out["peers"][addr] = blob
        return web.json_response(out)

    app.router.add_get("/debug/engine", debug_engine)
    app.router.add_get("/debug/hotkeys", debug_hotkeys)
    app.router.add_get("/debug/table", debug_table)
    app.router.add_get("/debug/device", debug_device)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_get("/debug/leases", debug_leases)
    app.router.add_get("/debug/admission", debug_admission)
    app.router.add_get("/debug/slo", debug_slo)
    app.router.add_get("/debug/standby", debug_standby)
    app.router.add_get("/debug/overload", debug_overload)
    app.router.add_get("/debug/cluster", debug_cluster)


def add_probe_routes(app: web.Application, svc: V1Service) -> None:
    """/livez + /readyz (docs/robustness.md). /healthz keeps the
    reference's TTL'd-error semantics for back-compat, but it conflates
    liveness with mesh health: one flapping peer 503s the node for the
    full 5-minute error TTL, so a restart-on-liveness orchestrator
    would bounce a healthy process. The split:

    - /livez: process liveness only — 200 while the event loop serves.
    - /readyz: breaker-derived readiness — 200 "ready" (all circuits
      closed), 200 "degraded" (some open; surviving keys still serve),
      503 "unready" (every peer circuit open), 503 "draining" (graceful
      shutdown: stop routing, don't kill — the body distinguishes it
      from "unready" so orchestrators and cmd/healthcheck.py can tell
      a leaving node from a partitioned one). Flips degraded -> ready
      without a restart the moment a returning peer's circuit closes.
    """

    async def livez(request: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def readyz(request: web.Request) -> web.Response:
        r = svc.readiness()
        return web.json_response(
            r, status=503 if r["status"] in ("unready", "draining") else 200
        )

    app.router.add_get("/livez", livez)
    app.router.add_get("/readyz", readyz)


async def read_json_requests(request: web.Request):
    """Parse + validate a /v1/GetRateLimits JSON body.

    Returns (reqs, None) or (None, error_response). Shared by the
    daemon gateway and the edge gateway (service/edge.py) so the two
    HTTP fronts cannot diverge on the wire contract."""
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return None, web.json_response(
            {"code": 3, "message": f"invalid JSON: {e}"}, status=400
        )
    if not isinstance(body, dict):
        return None, web.json_response(
            {"code": 3, "message": "request body must be a JSON object"},
            status=400,
        )
    items = body.get("requests") or []
    if not isinstance(items, list) or not all(
        isinstance(d, dict) for d in items
    ):
        return None, web.json_response(
            {"code": 3, "message": "'requests' must be a list of objects"},
            status=400,
        )
    try:
        return [pb.req_from_json(d) for d in items], None
    except (TypeError, ValueError) as e:
        return None, web.json_response(
            {"code": 3, "message": f"invalid request: {e}"}, status=400
        )


def build_app(svc: V1Service) -> web.Application:
    app = web.Application()

    async def get_rate_limits(request: web.Request) -> web.Response:
        reqs, err = await read_json_requests(request)
        if err is not None:
            return err
        try:
            out = await svc.get_rate_limits(reqs)
        except ApiError as e:
            return web.json_response({"code": 11, "message": str(e)}, status=e.http_code)
        return web.json_response({"responses": [pb.resp_to_json(r) for r in out]})

    async def health_check(request: web.Request) -> web.Response:
        h = await svc.health_check()
        return web.json_response(pb.health_to_json(h))

    async def healthz(request: web.Request) -> web.Response:
        h = await svc.health_check()
        return web.Response(
            text=h.status, status=200 if h.status == "healthy" else 503
        )

    async def metrics(request: web.Request) -> web.Response:
        # OpenMetrics content negotiation: exemplars (trace ids on
        # histogram buckets) render ONLY when the scraper asks for
        # application/openmetrics-text; plain scrapes stay byte-stable.
        body, ctype = svc.metrics.render_negotiated(
            request.headers.get("Accept", "")
        )
        return web.Response(body=body, headers={"Content-Type": ctype})

    app.router.add_post("/v1/GetRateLimits", get_rate_limits)
    app.router.add_get("/v1/HealthCheck", health_check)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    add_probe_routes(app, svc)
    add_debug_routes(app, svc)
    return app


def build_status_app(svc: V1Service) -> web.Application:
    """Health + debug app for the no-mTLS status listener (reference
    daemon.go:305-333 serves /v1/HealthCheck there; the device-tier
    debug surface rides the same listener so operators can reach the
    flight recorder and profiler without client certs)."""
    app = web.Application()

    async def health_check(request: web.Request) -> web.Response:
        h = await svc.health_check()
        return web.json_response(pb.health_to_json(h))

    app.router.add_get("/v1/HealthCheck", health_check)
    add_probe_routes(app, svc)
    add_debug_routes(app, svc)
    return app
