"""Decision provenance: which path answered, how stale it was.

After PR 13 a single rate-limit check can be answered by any of six
paths with very different staleness properties. This module is the
provenance half of the admission observatory (docs/monitoring.md
"Admission"): one canonical path enum, a metadata stamping helper
every answer-constructing site in service/ must call (enforced by
guberlint GL012), and a bounded flight recorder that joins decisions
with the tracing spans (trace_id) for /debug/admission.

The split of responsibilities:

- `stamp_decision(resp, path, staleness_ms)` — response METADATA, only
  attached when the caller passes a metadata dict to write into
  (servers gate it on GUBER_STAGE_METADATA, the lease cache always
  stamps — its answers are stale by construction and the bound is the
  honesty contract of client-side enforcement);
- `DecisionRecorder.record_decision / record_columnar` — the
  `gubernator_admission_decisions{path,status}` counters, the
  `gubernator_over_limit_counter{path}` children, and the ring. Always
  on: counters are O(1) dict bumps, the ring is bounded.

Everything here is host-side stdlib + numpy — never any device work
(the recorder sits on serving paths AND scrape paths).
"""

from __future__ import annotations

import collections
from typing import Optional

from gubernator_tpu.api.keys import key_hash128
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.utils import lockorder
from gubernator_tpu.utils import raceguard
from gubernator_tpu.utils import tracing

# The provenance enum. Every answer a client can receive names exactly
# one of these as the path that produced it:
PATH_OWNER = "owner"  # this node owns the key; local engine decided
PATH_REPLICA = "replica"  # GLOBAL non-owner answered from replicated state
PATH_DEGRADED_LOCAL = "degraded_local"  # owner circuit open; local answer
PATH_LEASE = "lease"  # holder-side zero-RPC debit from a leased slice
PATH_FASTPATH = "fastpath"  # columnar edge fastpath (owner-local decide)
PATH_FORWARDED = "forwarded"  # answered by the owner over peer forwarding
PATH_SHED = "shed"  # overload governor refused it (never reached a table)

PATHS = (
    PATH_OWNER,
    PATH_REPLICA,
    PATH_DEGRADED_LOCAL,
    PATH_LEASE,
    PATH_FASTPATH,
    PATH_FORWARDED,
    PATH_SHED,
)

# Response-metadata keys (GUBER_STAGE_METADATA surface, service/pb.py
# carries metadata verbatim on the wire).
DECISION_PATH_MD_KEY = "decision_path"
DECISION_STALENESS_MD_KEY = "decision_staleness_ms"

_STATUS_LABELS = ("under_limit", "over_limit")


def status_label(resp) -> str:
    """Counter label for a response: under_limit | over_limit | error."""
    if getattr(resp, "error", ""):
        return "error"
    s = int(getattr(resp, "status", 0))
    return _STATUS_LABELS[1] if s == 1 else _STATUS_LABELS[0]


def stamp_decision(resp, path: str, staleness_ms: Optional[int] = None):
    """Stamp provenance metadata on a response (in place) and return it.
    `staleness_ms` is the answer's staleness bound: 0 for authoritative
    owner answers, the broadcast age for replica answers, the grant age
    for lease debits, unknown (omitted) when the caller cannot bound
    it."""
    md = resp.metadata
    if md is None:
        return resp
    md[DECISION_PATH_MD_KEY] = path
    if staleness_ms is not None:
        md[DECISION_STALENESS_MD_KEY] = str(max(0, int(staleness_ms)))
    return resp


class DecisionRecorder:
    """Decision counters + bounded flight recorder.

    Counters are pre-resolved per (path, status) pair so the object
    hot path pays one dict lookup and one locked add per response. The
    ring holds the last `ring_size` decisions as plain dicts (key hash
    pair, path, status, remaining, staleness_ms, trace_id, ts_ms) —
    joinable with the tracing spans via trace_id and with the engine
    flight recorder via the key hash pair."""

    def __init__(self, metrics, ring_size: int = 256):
        self.metrics = metrics
        self.ring: collections.deque = collections.deque(
            maxlen=max(int(ring_size), 1)
        )
        self._lock = lockorder.make_lock("service.admission_ring")
        self._children: dict = {}
        self._over_children: dict = {}
        self._counts: dict = {}  # (path, status) -> int, for snapshot()

    # -- counting ------------------------------------------------------------

    def _child(self, path: str, label: str):
        # Cache get and insert both run under the lock (two racing
        # threads used to each create a child and inc their own, with
        # one cached — splitting counts across counter objects). The
        # labels() call itself stays OUTSIDE: it takes the metrics
        # registry lock, which must never nest under ours.
        with self._lock:
            c = self._children.get((path, label))
        if c is None:
            c = self.metrics.admission_decisions.labels(path, label)
            with self._lock:
                c = self._children.setdefault((path, label), c)
        return c

    def _over_child(self, path: str):
        with self._lock:
            c = self._over_children.get(path)
        if c is None:
            c = self.metrics.over_limit_counter.labels(path)
            with self._lock:
                c = self._over_children.setdefault(path, c)
        return c

    def _count(self, path: str, label: str, n: int = 1) -> None:
        self._child(path, label).inc(n)
        if label == "over_limit":
            self._over_child(path).inc(n)
        with self._lock:
            self._counts[(path, label)] = (
                self._counts.get((path, label), 0) + n
            )

    # -- recording -----------------------------------------------------------

    def record_decision(
        self,
        path: str,
        resp,
        *,
        key: Optional[str] = None,
        key_hi: int = 0,
        key_lo: int = 0,
        staleness_ms: int = 0,
    ) -> None:
        """Count one object-path decision and append it to the ring."""
        label = status_label(resp)
        self._count(path, label)
        if key is not None:
            key_hi, key_lo = key_hash128(key)
        entry = {
            "key_hi": int(key_hi),
            "key_lo": int(key_lo),
            "path": path,
            "status": label,
            "remaining": int(getattr(resp, "remaining", 0)),
            "staleness_ms": max(0, int(staleness_ms)),
            "trace_id": tracing.trace_id_of(tracing.current_span()),
            "ts_ms": _clock.now_ms(),
        }
        with self._lock:
            self.ring.append(entry)

    def record_columnar(
        self,
        path: str,
        statuses,
        remaining,
        mask=None,
        staleness_ms: int = 0,
        sample_key=None,
    ) -> None:
        """Vectorized recording for the columnar fastpath: numpy sums
        feed the counters (no per-item Python), and ONE sample row per
        call (the last served lane) feeds the ring — bounded cost at
        any batch width. `sample_key(idx) -> hash_key string` is only
        invoked for that single sampled lane, so callers never pay a
        per-item key materialization."""
        import numpy as np

        statuses = np.asarray(statuses)
        if mask is None:
            mask = np.ones(statuses.shape, dtype=bool)
        else:
            mask = np.asarray(mask, dtype=bool)
        n = int(mask.sum())
        if n == 0:
            return
        over = int(((statuses == 1) & mask).sum())
        if over:
            self._count(path, "over_limit", over)
        if n - over:
            self._count(path, "under_limit", n - over)
        idx = int(np.flatnonzero(mask)[-1])
        key_hi = key_lo = 0
        if sample_key is not None:
            try:
                key_hi, key_lo = key_hash128(sample_key(idx))
            except Exception:  # guberlint: allow-swallow -- the ring sample is best-effort observability; the counters above already landed
                pass
        entry = {
            "key_hi": int(key_hi),
            "key_lo": int(key_lo),
            "path": path,
            "status": (
                "over_limit" if int(statuses[idx]) == 1 else "under_limit"
            ),
            "remaining": int(np.asarray(remaining)[idx]),
            "staleness_ms": max(0, int(staleness_ms)),
            "trace_id": tracing.trace_id_of(tracing.current_span()),
            "ts_ms": _clock.now_ms(),
        }
        with self._lock:
            self.ring.append(entry)

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        """/debug/admission payload: per-(path, status) totals plus the
        ring, newest last. Pure host-side copies."""
        with self._lock:
            counts = {
                f"{path}:{label}": n
                for (path, label), n in sorted(self._counts.items())
            }
            ring = list(self.ring)
        return {
            "decisions": counts,
            "ring_size": self.ring.maxlen,
            "ring": ring,
        }


# Declared lock protocol (docs/robustness.md "Race sanitizer"). `ring`
# is write-guarded only: the deque attribute is never rebound after
# __init__ and maxlen is read racily by snapshot(); the append/copy
# interior operations run under the lock above.
raceguard.guarded_by(DecisionRecorder, {
    "_children": "service.admission_ring",
    "_over_children": "service.admission_ring",
    "_counts": "service.admission_ring",
    "ring": "w:service.admission_ring",
})
