"""TLS for the gRPC and HTTP listeners (reference tls.go:46-443).

Capabilities mirrored from the reference:
- load CA / server cert / key from files or PEM blobs,
- AutoTLS: generate a self-signed CA + server certificate on the fly,
- client-auth (mTLS) modes, and client-side configs for dialing peers.

Implementation uses the `cryptography` package for generation and
ssl/grpc credentials for serving.
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import ssl
from typing import List, Optional, Tuple

import grpc


@dataclasses.dataclass
class TlsConfig:
    ca_file: str = ""
    ca_key_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    ca_pem: bytes = b""
    ca_key_pem: bytes = b""
    cert_pem: bytes = b""
    key_pem: bytes = b""
    auto_tls: bool = False
    # 'none' | 'request' | 'require' (reference client-auth modes)
    client_auth: str = "none"
    client_auth_ca_file: str = ""
    client_auth_ca_pem: bytes = b""
    # Dedicated client-side identity for dialing mTLS peers (reference
    # ClientAuthCertFile/ClientAuthKeyFile/ClientAuthServerName,
    # tls.go:70-90); falls back to the server cert pair when unset.
    client_auth_cert_file: str = ""
    client_auth_key_file: str = ""
    client_auth_cert_pem: bytes = b""
    client_auth_key_pem: bytes = b""
    client_auth_server_name: str = ""
    insecure_skip_verify: bool = False
    min_version: int = ssl.TLSVersion.TLSv1_2


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def generate_self_signed(
    hosts: List[str],
) -> Tuple[bytes, bytes, bytes, bytes]:
    """AutoTLS: returns (ca_pem, ca_key_pem, cert_pem, key_pem)
    (reference tls.go self-signed generation)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)

    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "gubernator-tpu AutoTLS CA")]
    )
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    sans = []
    for h in hosts:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hosts[0] if hosts else "localhost")])
        )
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    pem = serialization.Encoding.PEM
    pk8 = serialization.PrivateFormat.TraditionalOpenSSL
    noenc = serialization.NoEncryption()
    return (
        ca_cert.public_bytes(pem),
        ca_key.private_bytes(pem, pk8, noenc),
        cert.public_bytes(pem),
        key.private_bytes(pem, pk8, noenc),
    )


def setup_tls(conf: TlsConfig, hosts: Optional[List[str]] = None) -> TlsConfig:
    """Resolve files/AutoTLS into in-memory PEM blobs
    (reference SetupTLS flow)."""
    if conf.ca_file:
        conf.ca_pem = _read(conf.ca_file)
    if conf.ca_key_file:
        conf.ca_key_pem = _read(conf.ca_key_file)
    if conf.cert_file:
        conf.cert_pem = _read(conf.cert_file)
    if conf.key_file:
        conf.key_pem = _read(conf.key_file)
    if conf.client_auth_ca_file:
        conf.client_auth_ca_pem = _read(conf.client_auth_ca_file)
    if conf.client_auth_cert_file:
        conf.client_auth_cert_pem = _read(conf.client_auth_cert_file)
    if conf.client_auth_key_file:
        conf.client_auth_key_pem = _read(conf.client_auth_key_file)
    if bool(conf.client_auth_cert_pem) != bool(conf.client_auth_key_pem):
        # Half a dialing identity would silently pair with the server's
        # key/cert and fail every mTLS handshake with an opaque SSL error.
        raise ValueError(
            "GUBER_TLS_CLIENT_AUTH_CERT and GUBER_TLS_CLIENT_AUTH_KEY must "
            "be set together"
        )
    if conf.auto_tls and not conf.cert_pem:
        ca, ca_key, cert, key = generate_self_signed(hosts or ["localhost", "127.0.0.1"])
        if not conf.ca_pem:
            conf.ca_pem = ca
            conf.ca_key_pem = ca_key
        conf.cert_pem = cert
        conf.key_pem = key
    return conf


def server_credentials(conf: TlsConfig) -> grpc.ServerCredentials:
    require = conf.client_auth == "require"
    # Client certs verify against a dedicated client-auth CA when set
    # (reference GUBER_TLS_CLIENT_AUTH_CA_CERT), else the server CA.
    client_ca = conf.client_auth_ca_pem or conf.ca_pem
    return grpc.ssl_server_credentials(
        [(conf.key_pem, conf.cert_pem)],
        root_certificates=client_ca if conf.client_auth != "none" else None,
        require_client_auth=require,
    )


def client_credentials(
    conf: TlsConfig, client_cert: bool = False
) -> grpc.ChannelCredentials:
    # A dedicated client-auth identity wins over reusing the server pair
    # (reference tls.go:70-90).
    key = conf.client_auth_key_pem or conf.key_pem
    chain = conf.client_auth_cert_pem or conf.cert_pem
    return grpc.ssl_channel_credentials(
        root_certificates=conf.ca_pem or None,
        private_key=key if client_cert else None,
        certificate_chain=chain if client_cert else None,
    )


def client_channel_options(conf: TlsConfig, host: str = "") -> tuple:
    """Channel options for dialing with this config.

    insecure_skip_verify note: grpc-python cannot disable chain
    validation; the supported relaxation is overriding the expected
    server name (covers the common self-signed/SAN-mismatch case). The
    chain must still anchor at ca_pem or the system roots.
    """
    if conf.client_auth_server_name:
        return (("grpc.ssl_target_name_override", conf.client_auth_server_name),)
    if conf.insecure_skip_verify:
        return (("grpc.ssl_target_name_override", "localhost"),)
    return ()


def http_ssl_context(conf: TlsConfig, no_client_auth: bool = False) -> ssl.SSLContext:
    """Server-side context for the aiohttp gateway listener.

    no_client_auth builds the status-listener variant that never requests
    a client certificate (reference daemon.go:316 ClientAuth=NoClientCert)."""
    import tempfile

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = conf.min_version
    with tempfile.NamedTemporaryFile(suffix=".pem") as cf, tempfile.NamedTemporaryFile(
        suffix=".pem"
    ) as kf:
        cf.write(conf.cert_pem)
        cf.flush()
        kf.write(conf.key_pem)
        kf.flush()
        ctx.load_cert_chain(cf.name, kf.name)
    if conf.client_auth != "none" and not no_client_auth:
        # Mirror server_credentials: a dedicated client-auth CA takes
        # precedence over the serving CA, and 'request' maps to OPTIONAL
        # (reference tls.go client-auth modes).
        client_ca = conf.client_auth_ca_pem or conf.ca_pem
        if client_ca:
            ctx.verify_mode = (
                ssl.CERT_REQUIRED
                if conf.client_auth == "require"
                else ssl.CERT_OPTIONAL
            )
            ctx.load_verify_locations(cadata=client_ca.decode())
        elif conf.client_auth == "require":
            raise ValueError(
                "client_auth='require' needs a CA: set client_auth_ca_file/"
                "client_auth_ca_pem or ca_file/ca_pem"
            )
        # 'request' with no CA configured: nothing to verify against —
        # serve without client-cert verification (tolerated config).
    return ctx
