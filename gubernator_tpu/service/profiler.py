"""Bounded jax.profiler capture + continuous background profiling.

Two consumers share this module (and its one-at-a-time guard —
jax.profiler state is process-global, so exactly one capture may run
at a time regardless of how many daemons/listeners share the process):

- /debug/profile (service/gateway.py): on-demand captures. Earlier
  revisions mkdtemp'd a fresh directory per capture and never deleted
  it — a debug-poller leaked a trace dir per request. Captures now
  land under ONE rotating parent (capture-<ns> children, newest
  `keep` retained).
- ContinuousProfiler: the opt-in sampler (GUBER_PROFILE_INTERVAL >
  0): a daemon thread that wakes on the configured cadence, takes a
  short capture, and relies on the same rotation bound — a week of
  unattended soak holds `keep` traces, not 10k. It acquires the guard
  non-blocking: an operator's /debug/profile always wins, the sampler
  just skips that cycle.

Trace directories are plain jax.profiler trace dumps (TensorBoard /
xprof readable); capture() reports the path, file count, and byte
footprint so the debug JSON tells the operator where to point the
viewer and how much disk the trace took.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import time

from gubernator_tpu.utils import lockorder

log = logging.getLogger("gubernator_tpu.profiler")

# Keep the historical lock name: the guard moved here from gateway.py
# and the lockorder graph keys by name.
PROFILE_GUARD = lockorder.make_lock("gateway.profile_guard")
PROFILE_MAX_SECONDS = 30.0
DEFAULT_KEEP = 8


def trace_root() -> str:
    """Parent directory all captures rotate under."""
    return os.path.join(tempfile.gettempdir(), "gubernator_profiles")


def _dir_stats(path: str) -> tuple:
    files = 0
    nbytes = 0
    for r, _, fs in os.walk(path):
        for f in fs:
            files += 1
            try:
                nbytes += os.path.getsize(os.path.join(r, f))
            except OSError:
                pass
    return files, nbytes


def rotate(keep: int, root: str | None = None) -> int:
    """Delete all but the newest `keep` capture dirs. Returns how many
    were removed. Never raises (a half-deleted trace dir is fine)."""
    root = root or trace_root()
    try:
        entries = sorted(
            e for e in os.listdir(root) if e.startswith("capture-")
        )
    except OSError:
        return 0
    removed = 0
    for name in entries[: max(len(entries) - max(keep, 1), 0)]:
        shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        removed += 1
    return removed


def capture(
    seconds: float, keep: int = DEFAULT_KEEP, root: str | None = None
) -> dict:
    """Blocking profiler capture (callers run it in an executor or the
    sampler thread) into a fresh dir under the rotating parent.
    Caller must hold PROFILE_GUARD."""
    import jax

    root = root or trace_root()
    os.makedirs(root, exist_ok=True)
    # Monotonic-clock suffix: unique per process without a tempfile
    # handle the rotation would then have to special-case.
    trace_dir = os.path.join(root, f"capture-{time.time_ns():020d}")
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
    files, nbytes = _dir_stats(trace_dir)
    rotated = rotate(keep, root)
    return {
        "trace_dir": trace_dir,
        "seconds": seconds,
        "files": files,
        "bytes": nbytes,
        "rotated_out": rotated,
        "keep": keep,
    }


class ContinuousProfiler:
    """Background sampler: one short capture every `interval_s`,
    bounded on disk by `keep`. Off unless interval_s > 0 (the
    GUBER_PROFILE_INTERVAL default is off — captures cost real device
    time and trace bytes, an explicit operator opt-in)."""

    def __init__(
        self,
        interval_s: float,
        seconds: float = 0.5,
        keep: int = DEFAULT_KEEP,
        root: str | None = None,
    ):
        self.interval_s = float(interval_s)
        self.seconds = min(max(float(seconds), 0.05), PROFILE_MAX_SECONDS)
        self.keep = max(int(keep), 1)
        self.root = root or trace_root()
        self.captures = 0
        self.skipped = 0
        self.errors = 0
        self.last = None  # most recent capture() result
        self._stop = threading.Event()
        self._thread = None
        # Self-watchdog heartbeat seam, injected by the daemon (None
        # keeps the sampler usable standalone in tests).
        self.watchdog = None

    def start(self) -> bool:
        if self.interval_s <= 0 or self._thread is not None:
            return False
        self._thread = threading.Thread(
            target=self._loop, name="gubernator-profiler", daemon=True
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # A cycle is at most seconds + rotation; don't hang close().
            t.join(timeout=self.seconds + 5.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            wd = self.watchdog
            if wd is not None:
                # A capture blocks for up to `seconds`; fold it into the
                # deadline so a slow trace isn't flagged as a stall.
                wd.beat(
                    "profiler", period_s=self.interval_s + self.seconds
                )
            # Non-blocking: an in-flight /debug/profile capture wins and
            # this cycle is skipped, never queued behind it.
            if not PROFILE_GUARD.acquire(blocking=False):
                self.skipped += 1
                continue
            try:
                self.last = capture(self.seconds, self.keep, self.root)
                self.captures += 1
            except Exception:
                self.errors += 1
                if self.errors in (1, 10) or self.errors % 100 == 0:
                    log.exception(
                        "continuous profile capture failed (%d total)",
                        self.errors,
                    )
            finally:
                PROFILE_GUARD.release()

    def stats(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "seconds": self.seconds,
            "keep": self.keep,
            "captures": self.captures,
            "skipped": self.skipped,
            "errors": self.errors,
            "last": self.last,
        }
