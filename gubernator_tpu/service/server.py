"""Core rate-limit service: validation, ownership routing, execution.

The transport-agnostic heart of the daemon (the reference's V1Instance,
gubernator.go:45-773): gRPC servicers and the HTTP gateway both call into
this class. Owner-path items go to the local DeviceEngine in one batch;
non-owner items are forwarded to the owning peer (micro-batched by
PeerForwarder) or, for GLOBAL, answered from the local replica and
reconciled asynchronously.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from gubernator_tpu.api.types import (
    Behavior,
    HealthCheckResp,
    MAX_BATCH_SIZE,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
    Status,
    UpdatePeerGlobal,
    has_behavior,
)
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.parallel.global_sync import ORIGIN_MD_KEY
from gubernator_tpu.parallel.leases import (
    LEASE_REVOKE_MD_KEY,
    RETRY_AFTER_MD_KEY,
)
from gubernator_tpu.runtime.engine import DeviceEngine
from gubernator_tpu.service.admission import (
    DecisionRecorder,
    PATH_FORWARDED,
    PATH_OWNER,
    PATH_REPLICA,
    stamp_decision,
)
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.utils import tracing

# Bound on the replica-staleness map (key -> last owner-broadcast wall ms).
# LRU eviction: staleness metadata is best-effort observability, so the
# oldest-touched keys fall out first rather than growing without bound.
_STALENESS_MAP_MAX = 8192


class ApiError(Exception):
    """Whole-call failure, mapped to gRPC OUT_OF_RANGE / HTTP 400 etc."""

    def __init__(self, message: str, grpc_code: str = "INVALID_ARGUMENT", http_code: int = 400):
        super().__init__(message)
        self.grpc_code = grpc_code
        self.http_code = http_code


class V1Service:
    def __init__(
        self,
        engine: DeviceEngine,
        metrics: Optional[Metrics] = None,
        local_info: Optional[PeerInfo] = None,
        force_global: bool = False,
        now_fn=_clock.now_ms,
        admission_ring: int = 256,
    ):
        self.engine = engine
        self.metrics = metrics or Metrics()
        self.local_info = local_info or PeerInfo(is_owner=True)
        self.force_global = force_global
        self.now_fn = now_fn
        # Peer mesh seams, wired by the daemon (tasks: peers, global)
        self.picker = None  # PeerPicker; None => every key is local
        self.forwarder = None  # PeerForwarder for non-owner items
        self.global_mgr = None  # GlobalManager for GLOBAL behavior
        self.region_mgr = None  # RegionManager for MULTI_REGION behavior
        # Graceful-drain state (docs/robustness.md): flipped by
        # Daemon.close() before teardown starts. /readyz and HealthCheck
        # report it so orchestrators stop routing without killing the
        # pod early; the node keeps serving while it drains.
        self.draining = False
        self._peers_lock = asyncio.Lock()
        # Consistency observatory seams (docs/monitoring.md "Consistency"):
        # last owner-broadcast arrival per GLOBAL key (feeds the
        # global_staleness_ms response metadata under GUBER_STAGE_METADATA)
        # and the background divergence auditor, wired by the daemon.
        self._global_last_update: "OrderedDict[str, int]" = OrderedDict()
        self.auditor = None  # ConsistencyAuditor; None when not wired
        self.profiler = None  # ContinuousProfiler; None when not wired
        # Cooperative token leases (docs/architecture.md "Cooperative
        # leases"): the owner-side authority, wired by the daemon when
        # GUBER_LEASES is on. None (default) keeps every path bit-exact
        # with the pre-lease daemon.
        self.lease_mgr = None
        # Server-suggested backoff (GUBER_RETRY_AFTER): OVER_LIMIT
        # responses carry retry_after_ms derived from reset_time.
        self.retry_after = False
        # Replica-noted lease revocations (key -> owner-clock ms until
        # which grants are refused), learned from the LEASE_REVOKE_MD_KEY
        # riding owner broadcasts. Bounded LRU like the staleness map.
        self._lease_revoked: "OrderedDict[str, int]" = OrderedDict()
        # pre-resolved metric children (labels() lookups are hot-loop cost)
        m = self.metrics
        self._m_local = m.getratelimit_counter.labels("local")
        self._m_global = m.getratelimit_counter.labels("global")
        self._m_forward = m.getratelimit_counter.labels("forward")
        # Admission observatory (docs/monitoring.md "Admission"): every
        # answer this node produces is counted by serving path and logged
        # in the bounded flight recorder; the scrape-time bridge publishes
        # the node's measured over-admission ratio from the engine's
        # TTL-cached admission scan.
        self.recorder = DecisionRecorder(self.metrics, ring_size=admission_ring)
        self.metrics.add_sync(self._admission_sync)
        # SLO observatory + self-watchdog seams (docs/monitoring.md
        # "SLOs & burn rates"), wired by the daemon. The sync bridge is
        # registered unconditionally and no-ops until wired.
        self.slo = None  # SloObservatory
        self.watchdog = None  # Watchdog
        self.metrics.add_sync(self._slo_sync)
        # Overload control plane seam (service/overload.py), wired by
        # the daemon under GUBER_OVERLOAD. None (default) keeps intake
        # and forwarding bit-exact with the pre-overload daemon; the
        # sync bridge is registered unconditionally and no-ops unwired.
        self.overload = None  # OverloadManager
        self.metrics.add_sync(self._overload_sync)
        # Crash-tolerant ownership seam (parallel/standby.py), wired by
        # the daemon under GUBER_STANDBY. None (default) keeps every
        # path — including TransferSnapshots payload handling — bit-exact
        # with the pre-standby daemon.
        self.standby = None  # ReplicationManager

    # ---- V1.GetRateLimits (reference gubernator.go:183-309) ----------------

    async def get_rate_limits(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        m = self.metrics
        if len(reqs) > MAX_BATCH_SIZE:
            m.check_error_counter.labels("Request too large").inc()
            raise ApiError(
                f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'",
                grpc_code="OUT_OF_RANGE",
            )
        m.concurrent_checks.inc()
        t0 = time.perf_counter()
        try:
            # Request span: the engine links the flush span that serves
            # each batch back to this span (and vice versa) across the
            # batch boundary — see runtime/engine.py _start_flush_span
            # and docs/monitoring.md "Tracing the pipeline".
            with tracing.span(
                "V1Instance.GetRateLimits", level="INFO", items=len(reqs)
            ):
                return await self._get_rate_limits(reqs)
        finally:
            m.concurrent_checks.dec()
            m.func_duration.labels("V1Instance.GetRateLimits").observe(
                time.perf_counter() - t0
            )

    async def _get_rate_limits(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        m = self.metrics
        now = self.now_fn()
        n = len(reqs)
        responses: List[Optional[RateLimitResp]] = [None] * n
        local_items: List[tuple] = []  # (idx, req) -> bulk engine submit
        global_items: List[tuple] = []  # (idx, req, owner_info) -> bulk
        forward_tasks = []

        from gubernator_tpu.api.types import validate_request

        GLOBAL = int(Behavior.GLOBAL)  # plain-int flag tests in the hot loop
        for i, req in enumerate(reqs):
            err = validate_request(req)
            if err is not None:
                m.check_error_counter.labels("Invalid request").inc()
                responses[i] = RateLimitResp(error=err)
                continue
            if req.created_at is None or req.created_at == 0:
                req.created_at = now
            if self.force_global:
                req.behavior |= GLOBAL

            key = req.hash_key()
            try:
                peer = self._get_peer(key)
            except Exception as e:
                m.check_error_counter.labels("Error in GetPeer").inc()
                responses[i] = RateLimitResp(
                    error=f"Error in GetPeer, looking up peer that owns rate limit '{key}': {e}"
                )
                continue

            if peer.info.is_owner:
                self._m_local.inc()
                local_items.append((i, req))
            elif req.behavior & GLOBAL:
                self._m_global.inc()
                global_items.append((i, req, peer.info))
            else:
                self._m_forward.inc()
                forward_tasks.append(
                    (i, asyncio.ensure_future(self._forward(peer, req)))
                )

        # GLOBAL non-owner items: ONE bulk submission against the local
        # replica (reference answers each from the local cache,
        # gubernator.go:395-421 — per-item dispatch would force one engine
        # flush per item via NO_BATCHING). Both bulks are SUBMITTED before
        # either is awaited so the pump can coalesce them into one flush.
        global_fut = None
        if global_items:
            import dataclasses

            strip = not getattr(self.engine, "routes_global_internally", False)
            bulk_reqs = []
            for _, req, _owner in global_items:
                r2 = dataclasses.replace(req, metadata=dict(req.metadata))
                r2.behavior = req.behavior | Behavior.NO_BATCHING
                if strip:
                    r2.behavior &= ~Behavior.GLOBAL
                bulk_reqs.append(r2)
            global_fut = self.engine.check_bulk(bulk_reqs)

        local_fut = None
        if local_items:
            local_fut = self.engine.check_bulk([r for _, r in local_items])

        stage_md = bool(getattr(self.engine.cfg, "stage_metadata", False))
        if global_fut is not None:
            try:
                results = await asyncio.wrap_future(global_fut)
                for (i, req, owner), resp in zip(global_items, results):
                    if self.global_mgr is not None:
                        self.global_mgr.queue_hit(req)
                    # Merge, don't replace: the engine may have attached
                    # stage_breakdown_us (GUBER_STAGE_METADATA) already.
                    resp.metadata["owner"] = owner.grpc_address
                    self._attach_retry_after(resp, now)
                    # Replica-staleness bound: age of the last owner
                    # broadcast applied locally for this key. Absent
                    # until the first broadcast lands (a fresh replica
                    # has no bound to honestly report).
                    ts = self._global_last_update.get(req.hash_key())
                    stale = max(0, now - ts) if ts is not None else None
                    if stage_md:
                        if stale is not None:
                            resp.metadata["global_staleness_ms"] = str(stale)
                        stamp_decision(resp, PATH_REPLICA, stale)
                    self.recorder.record_decision(
                        PATH_REPLICA,
                        resp,
                        key=req.hash_key(),
                        staleness_ms=stale or 0,
                    )
                    responses[i] = resp
            except Exception as e:
                for i, _, _ in global_items:
                    responses[i] = RateLimitResp(error=str(e))

        if local_fut is not None:
            try:
                results = await asyncio.wrap_future(local_fut)
                for (i, req), resp in zip(local_items, results):
                    responses[i] = resp
                    if resp.error:
                        self.recorder.record_decision(
                            PATH_OWNER, resp, key=req.hash_key()
                        )
                        continue
                    self._attach_retry_after(resp, now)
                    # Owner answers are authoritative: staleness bound 0.
                    if stage_md:
                        stamp_decision(resp, PATH_OWNER, 0)
                    self.recorder.record_decision(
                        PATH_OWNER, resp, key=req.hash_key()
                    )
                    # Replication legs queue only AFTER a successful local
                    # apply (reference gubernator.go:603-606 order) — a
                    # failed apply must not push hits it never counted.
                    if self.global_mgr is not None and (req.behavior & GLOBAL):
                        self.global_mgr.queue_update(req)
                    if self.region_mgr is not None and (
                        req.behavior & int(Behavior.MULTI_REGION)
                    ):
                        # In-region owner applied a MULTI_REGION item:
                        # queue the cross-region leg (delta toward the
                        # home region, or authoritative broadcast from it).
                        self.region_mgr.observe(req)
            except Exception as e:
                for i, _ in local_items:
                    responses[i] = RateLimitResp(error=str(e))

        for i, task in forward_tasks:
            try:
                resp = await task
            except Exception as e:
                m.check_error_counter.labels("Error in asyncRequests").inc()
                resp = RateLimitResp(error=str(e))
            else:
                # The degraded-local fallback stamps its own provenance
                # (peers.py _owner_unreachable + its recorder hook) —
                # don't overwrite it or double-count here. The "degraded"
                # marker is unconditional there, unlike the stage_md-gated
                # path stamp, so it discriminates at every knob setting.
                degraded = bool(resp.metadata) and "degraded" in resp.metadata
                if not degraded:
                    if stage_md and not resp.error:
                        # Answered by the owner's engine: authoritative.
                        stamp_decision(resp, PATH_FORWARDED, 0)
                    self.recorder.record_decision(
                        PATH_FORWARDED, resp, key=reqs[i].hash_key()
                    )
            responses[i] = resp
        return [r if r is not None else RateLimitResp(error="internal: no response") for r in responses]

    def _get_peer(self, key: str):
        """Hash-ring lookup (reference gubernator.go:714-725); a standalone
        daemon (no peers configured) owns every key."""
        if self.picker is None or not self.picker.peers():
            return _LocalPeer(self.local_info)
        return self.picker.get(key)

    async def _forward(self, peer, req: RateLimitReq) -> RateLimitResp:
        if self.forwarder is None:
            raise RuntimeError("no peer forwarder configured")
        return await self.forwarder.forward(peer, req)

    def _attach_retry_after(self, resp: RateLimitResp, now: int) -> None:
        """Server-suggested backoff (GUBER_RETRY_AFTER, default off):
        OVER_LIMIT answers carry the ms until the window refills. Gated
        so the off state stays bit-exact with today's responses."""
        if (
            self.retry_after
            and resp.status == Status.OVER_LIMIT
            and not resp.error
        ):
            resp.metadata.setdefault(
                RETRY_AFTER_MD_KEY, str(max(0, resp.reset_time - now))
            )

    # ---- V1/PeersV1.Lease (cooperative token leases) -----------------------

    def _lease_reject(self, g: dict, error: str, retry_after_ms: int = 0) -> dict:
        return {
            "ok": 0, "lease_id": "", "slice": 0, "ttl_ms": 0,
            "expiry_ms": 0, "limit": int(g.get("limit", 0)), "remaining": 0,
            "reset_time": 0, "retry_after_ms": retry_after_ms, "error": error,
        }

    async def lease(
        self,
        grants: List[dict],
        returns: List[dict],
        holder: str = "",
        no_forward: bool = False,
    ) -> tuple:
        """Route one Lease RPC: rows for keys this daemon owns go to the
        local LeaseManager; the rest forward to their owners over
        PeersV1/Lease (one hop — `no_forward` stops ring-view
        disagreements from looping). Returns (grant_results,
        return_results), positional with the inputs."""
        now = self.now_fn()
        g_res: List[Optional[dict]] = [None] * len(grants)
        r_res: List[Optional[dict]] = [
            {"lease_id": str(r.get("lease_id", "")), "status": "unknown"}
            for r in returns
        ]
        local_g: List[int] = []
        local_r: List[int] = []
        remote: Dict[str, tuple] = {}  # addr -> (peer, g_idx, r_idx)

        def _route(key: str):
            try:
                return self._get_peer(key), None
            except Exception as e:  # guberlint: allow-swallow -- ring empty / picker failure becomes a per-row UNAVAILABLE reject, not a dropped error
                return None, str(e)

        for i, g in enumerate(grants):
            key = str(g.get("name", "")) + "_" + str(g.get("unique_key", ""))
            until = self._lease_revoked.get(key)
            if until is not None and until > now:
                g_res[i] = self._lease_reject(g, "revoked", until - now)
                continue
            peer, err = _route(key)
            if peer is None:
                g_res[i] = self._lease_reject(g, f"UNAVAILABLE: {err}")
            elif peer.info.is_owner:
                local_g.append(i)
            elif no_forward:
                g_res[i] = self._lease_reject(g, "UNAVAILABLE: not owner")
            else:
                addr = peer.info.grpc_address
                ent = remote.setdefault(addr, (peer, [], []))
                ent[1].append(i)
        for i, r in enumerate(returns):
            key = str(r.get("name", "")) + "_" + str(r.get("unique_key", ""))
            peer, err = _route(key)
            if peer is None:
                continue  # stays "unknown"; the holder drops its copy
            if peer.info.is_owner:
                local_r.append(i)
            elif not no_forward:
                addr = peer.info.grpc_address
                ent = remote.setdefault(addr, (peer, [], []))
                ent[2].append(i)

        if local_g or local_r:
            if self.lease_mgr is None:
                for i in local_g:
                    g_res[i] = self._lease_reject(grants[i], "leases disabled")
            else:
                gr, rr = await self.lease_mgr.handle(
                    [grants[i] for i in local_g],
                    [returns[i] for i in local_r],
                    holder=holder,
                )
                for i, res in zip(local_g, gr):
                    g_res[i] = res
                for i, res in zip(local_r, rr):
                    r_res[i] = res

        if remote:
            from gubernator_tpu.service import pb as _pb

            async def _one(peer, g_idx, r_idx):
                md = tracing.propagate_inject({"no_forward": "1"})
                payload = _pb.lease_req_to_bytes(
                    [grants[i] for i in g_idx],
                    [returns[i] for i in r_idx],
                    holder=holder, metadata=md,
                )
                raw = await peer.lease(payload)
                return _pb.lease_resp_from_bytes(raw)

            ents = list(remote.values())
            outs = await asyncio.gather(
                *(_one(p, gi, ri) for p, gi, ri in ents),
                return_exceptions=True,
            )
            for (peer, g_idx, r_idx), out in zip(ents, outs):
                if isinstance(out, BaseException):
                    for i in g_idx:
                        g_res[i] = self._lease_reject(
                            grants[i], f"UNAVAILABLE: {out}"
                        )
                    continue  # returns stay "unknown"
                gr, rr, _md = out
                for i, res in zip(g_idx, gr):
                    g_res[i] = res
                for i, res in zip(r_idx, rr):
                    r_res[i] = res

        for i, g in enumerate(grants):
            if g_res[i] is None:
                g_res[i] = self._lease_reject(g, "internal: no response")
        return g_res, r_res

    def _note_lease_revoked(self, key: str, until_ms: int) -> None:
        """Record a revocation learned from an owner broadcast (LRU,
        bounded like the staleness map; event-loop only)."""
        mp = self._lease_revoked
        mp[key] = max(mp.get(key, 0), until_ms)
        mp.move_to_end(key)
        while len(mp) > _STALENESS_MAP_MAX:
            mp.popitem(last=False)

    # ---- PeersV1.GetPeerRateLimits (reference gubernator.go:462-539) -------

    async def get_peer_rate_limits(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        if len(reqs) > MAX_BATCH_SIZE:
            self.metrics.check_error_counter.labels("Request too large").inc()
            raise ApiError(
                f"'PeerRequest.rate_limits' list too large; max size is '{MAX_BATCH_SIZE}'",
                grpc_code="OUT_OF_RANGE",
            )
        from gubernator_tpu.utils import tracing

        has_global = False
        for req in reqs:
            # Extract the forwarding peer's trace context from the item's
            # metadata (reference gubernator.go:503-504).
            ctx = tracing.propagate_extract(req.metadata)
            if ctx is not None:
                with tracing.attached(ctx):
                    # Per-peer span: DEBUG-level, dropped at the default
                    # INFO trace level (reference config.go:736-752).
                    with tracing.span(
                        "V1Instance.getLocalRateLimit",
                        level="DEBUG",
                        key=req.hash_key(),
                    ):
                        pass
            if has_behavior(req.behavior, Behavior.GLOBAL):
                # Owner handling a relayed GLOBAL hit always drains
                # (reference gubernator.go:510-512) and queues a broadcast.
                req.behavior |= Behavior.DRAIN_OVER_LIMIT
                has_global = True
            if req.created_at is None or req.created_at == 0:
                req.created_at = self.now_fn()
        t_apply = time.perf_counter()
        try:
            results = await asyncio.wrap_future(self.engine.check_bulk(list(reqs)))
        except Exception as e:
            return [RateLimitResp(error=str(e)) for _ in reqs]
        if has_global:
            # owner_apply leg: relayed-hit batch arrival to engine apply
            # done — the owner's contribution to propagation lag.
            self.metrics.global_sync_leg_duration.labels("owner_apply").observe(
                time.perf_counter() - t_apply
            )
        now = self.now_fn()
        for req, resp in zip(reqs, results):
            if resp.error:
                continue
            self._attach_retry_after(resp, now)
            # Replication legs queue only AFTER a successful apply — a
            # failed apply must not push hits it never counted.
            if self.global_mgr is not None and has_behavior(req.behavior, Behavior.GLOBAL):
                self.global_mgr.queue_update(req)
            if self.region_mgr is not None and has_behavior(
                req.behavior, Behavior.MULTI_REGION
            ):
                # Both in-region forwards and cross-region deltas land
                # here; the same rule covers both — the applying node is
                # the in-region owner, so it queues the cross-region leg.
                self.region_mgr.observe(req)
        return results

    # ---- PeersV1.UpdatePeerGlobals (reference gubernator.go:425-459) -------

    async def update_peer_globals(self, globals_: Sequence[UpdatePeerGlobal]) -> None:
        m = self.metrics
        now_ms = self.now_fn()
        trace_id = tracing.trace_id_of(tracing.current_span())
        for g in globals_:
            md = getattr(g.status, "metadata", None)
            revoke = md.pop(LEASE_REVOKE_MD_KEY, None) if md else None
            if revoke is not None:
                # Revocation riding the broadcast leg: refuse new grants
                # for this key here too, so a holder renewing through a
                # replica is turned away without an extra owner hop.
                try:
                    self._note_lease_revoked(g.key, int(revoke))
                except ValueError:
                    pass
            origin = md.pop(ORIGIN_MD_KEY, None) if md else None
            if origin is not None:
                # Close the end-to-end loop: origin stamp (sampled at the
                # hit's first enqueue) to this replica applying the owner's
                # broadcast. Cross-node wall clocks — read alongside
                # gubernator_peer_clock_skew_ms; clamp at 0 so a skewed
                # clock can't underflow the histogram.
                try:
                    lag_s = max(0.0, (now_ms - int(origin)) / 1000.0)
                except ValueError:
                    pass
                else:
                    m.global_propagation_lag.observe(lag_s, trace_id=trace_id)
            self._note_global_update(g.key, now_ms)
        loop = asyncio.get_running_loop()
        t0 = time.perf_counter()
        await loop.run_in_executor(None, self.engine.inject_globals, globals_)
        m.global_sync_leg_duration.labels("replica_inject").observe(
            time.perf_counter() - t0
        )

    def _note_global_update(self, key: str, now_ms: int) -> None:
        """Record an owner-broadcast arrival for the staleness map (LRU,
        bounded at _STALENESS_MAP_MAX; event-loop only, no lock needed)."""
        mp = self._global_last_update
        mp[key] = now_ms
        mp.move_to_end(key)
        while len(mp) > _STALENESS_MAP_MAX:
            mp.popitem(last=False)

    # ---- PeersV1.TransferSnapshots (ownership handover) --------------------

    async def transfer_snapshots(self, snaps, leases=None) -> tuple:
        """Receiver half of ring-change/drain handover: merge incoming
        counter state last-writer-wins on stamp (docs/robustness.md
        "Rolling restarts & handover"). `leases` carries the sender's
        outstanding lease records for the re-homed keys (same LWW
        discipline, keyed on lease id) so holders keep serving through
        the handover without re-granting. Returns (accepted, stale)."""
        from gubernator_tpu.store.store import merge_snapshots_lww

        loop = asyncio.get_running_loop()
        accepted, stale = await loop.run_in_executor(
            None, merge_snapshots_lww, self.engine, list(snaps)
        )
        m = self.metrics
        if accepted:
            m.handover_keys_received.inc(accepted)
        if stale:
            m.handover_keys_dropped.labels("stale").inc(stale)
        if leases and self.lease_mgr is not None:
            self.lease_mgr.adopt(leases)
        return accepted, stale

    # ---- V1.HealthCheck (reference gubernator.go:542-586) ------------------

    async def health_check(self) -> HealthCheckResp:
        errors: List[str] = []
        peer_count = 0
        open_circuits: List[str] = []
        if self.picker is not None:
            peer_count = len(self.picker.peers())
            if hasattr(self.picker, "region_peers"):
                peer_count += len(self.picker.region_peers())
            if self.forwarder is not None:
                errors = self.forwarder.recent_errors()
                if hasattr(self.forwarder, "breaker_summary"):
                    open_circuits = sorted(
                        a
                        for a, s in self.forwarder.breaker_summary().items()
                        if s != "closed"
                    )
        if self.draining:
            # Drain state outranks the error log: the node is leaving on
            # purpose; orchestrators should stop routing, not restart it
            # (cmd/healthcheck.py exits 2 on this status).
            return HealthCheckResp(
                status="draining",
                message="graceful drain in progress; stop routing",
                peer_count=peer_count,
            )
        if errors:
            msg = "; ".join(errors[:3])
            if open_circuits:
                # Breaker summary rides the reference-shaped message so
                # probes see WHICH fault domain is dark, not just that
                # errors happened in the last 5 minutes.
                msg = f"circuits open: {', '.join(open_circuits)}; {msg}"
            return HealthCheckResp(
                status="unhealthy", message=msg, peer_count=peer_count
            )
        return HealthCheckResp(status="healthy", peer_count=peer_count)

    def readiness(self) -> dict:
        """Readiness for the /readyz probe (docs/robustness.md): unlike
        the TTL'd error log feeding health_check — where one flapping
        peer marks the node unhealthy for a full 5 minutes — readiness
        derives from CURRENT breaker state, so it flips back the moment
        a dead peer's circuit closes.

        ready    — every peer circuit closed (or no mesh at all)
        degraded — some circuits open; keys owned by surviving peers
                   still serve within SLO
        unready  — every remote peer's circuit is open (the node cannot
                   reach any fault domain but its own)
        draining — graceful shutdown in progress: stop routing here, but
                   do NOT kill the pod — queued work is finishing and
                   owned keys are handing off to ring successors
        """
        summary = {}
        if self.forwarder is not None and hasattr(self.forwarder, "breaker_summary"):
            summary = self.forwarder.breaker_summary()
        open_circuits = sorted(a for a, s in summary.items() if s == "open")
        if self.draining:
            status = "draining"
        elif summary and len(open_circuits) == len(summary):
            status = "unready"
        elif open_circuits:
            status = "degraded"
        else:
            status = "ready"
        return {
            "status": status,
            "peers": len(summary),
            "open_circuits": open_circuits,
        }

    # ---- consistency observatory (docs/monitoring.md "Consistency") --------

    def local_debug_info(self, keys: Optional[Sequence[str]] = None) -> dict:
        """One node's slice of the cluster debug view: health, breaker
        states, occupancy, hot keys, and consistency gauges in a single
        JSON-able blob. Served locally under /debug/cluster (gateway) and
        remotely over PeersV1.DebugInfo — always LOCAL state only, so the
        fan-out cannot recurse. With `keys`, also returns those keys'
        counter snapshots (the divergence auditor's replica-view fetch).
        Runs engine readbacks; call from an executor on hot paths."""
        m = self.metrics
        info: dict = {
            "v": 1,
            "now_ms": self.now_fn(),
            "address": self.local_info.grpc_address,
            "readiness": self.readiness(),
        }
        if self.forwarder is not None and hasattr(self.forwarder, "breaker_summary"):
            info["breakers"] = self.forwarder.breaker_summary()
        if hasattr(self.engine, "occupancy_stats"):
            info["occupancy"] = self.engine.occupancy_stats()
        if hasattr(self.engine, "table_census"):
            # Full census rides the free-form DebugInfo dict, so
            # /debug/cluster aggregates a fleet-wide table observatory
            # with no wire-format bump (docs/monitoring.md).
            info["table_census"] = self.engine.table_census()
        if hasattr(self.engine, "hotkeys_snapshot"):
            info["hotkeys"] = self.engine.hotkeys_snapshot()
        # Device-resource blob rides the free-form DebugInfo dict too,
        # so /debug/cluster shows fleet-wide HBM headroom and transfer
        # bandwidth with no wire-format bump (docs/monitoring.md
        # "Device resources").
        info["device"] = self.device_debug_info()
        # Admission blob rides DebugInfo as well (sans flight-recorder
        # ring — 256 rows per node is wire weight the fleet view doesn't
        # need; /debug/admission serves the ring locally): the auditor's
        # admission pass reads each node's measured window accounting and
        # over-admission bound from here.
        info["admission"] = self.admission_debug_info(include_ring=False)
        consistency: dict = {
            "propagation_lag": m.global_propagation_lag.summary(),
            "staleness_keys_tracked": len(self._global_last_update),
        }
        if self.auditor is not None:
            consistency.update(self.auditor.summary())
        info["consistency"] = consistency
        if self.lease_mgr is not None:
            # Lease ledger rides the free-form DebugInfo dict like the
            # census — /debug/cluster aggregates fleet-wide outstanding
            # slices (the over-admission bound) with no wire bump.
            info["leases"] = self.lease_mgr.summary()
        if self.slo is not None:
            # Compact SLO blob (per-SLO alert state + remaining error
            # budget, no ring dumps) rides DebugInfo so /debug/cluster
            # shows the fleet-wide budget view (docs/monitoring.md
            # "SLOs & burn rates").
            info["slo"] = self.slo.fleet_info()
        if self.standby is not None:
            # Standby summary (loss bound, shadow inventory, promotions)
            # rides DebugInfo like the census, so /debug/cluster shows
            # the fleet-wide durability picture with no wire bump.
            info["standby"] = self.standby.summary()
        if self.overload is not None:
            # Brownout ladder blob (level, signals, intake governor
            # state) rides DebugInfo so /debug/cluster shows which
            # nodes are degraded and why (docs/robustness.md "Overload
            # control & brownout").
            info["overload"] = self.overload.debug_info()
        if keys:
            from gubernator_tpu.store.store import snapshots_from_engine

            wanted = set(keys)
            info["snapshots"] = [
                dataclasses.asdict(s)
                for s in snapshots_from_engine(self.engine)
                if s.key in wanted
            ]
            # Per-key broadcast-arrival stamps: the transport-level
            # replica view the auditor compares against the owner's
            # broadcast ledger (algorithm-agnostic, unlike raw counter
            # state — leaky injects re-stamp updated_at on arrival).
            info["global_updates"] = {
                k: self._global_last_update[k]
                for k in keys
                if k in self._global_last_update
            }
        return info

    def admission_debug_info(self, include_ring: bool = True) -> dict:
        """/debug/admission payload (docs/monitoring.md "Admission"):
        the engine's TTL-cached ground-truth window accounting, the
        decision counters by path, the over-admission BOUND this node
        contributes (outstanding lease hits + queued GLOBAL hits not yet
        relayed), and — locally only — the decision flight recorder.
        Scrape-safe: the engine snapshot is TTL-cached (GL009), the rest
        is host-side dict copies."""
        blob: dict = {"v": 1}
        if hasattr(self.engine, "admission_snapshot"):
            blob["window"] = self.engine.admission_snapshot()
        rec = self.recorder.snapshot()
        blob["decisions"] = rec["decisions"]
        blob["ring_size"] = rec["ring_size"]
        if include_ring:
            blob["ring"] = rec["ring"]
        # The over-admission bound: hits this node has admitted (or will
        # admit) that the owners' tables have not yet absorbed. During a
        # partition the fleet's measured excess must stay within the sum
        # of these across nodes; after heal both legs drain to 0.
        bound: dict = {}
        if self.lease_mgr is not None:
            bound["lease_outstanding_hits"] = int(
                self.lease_mgr.outstanding_hits()
            )
        if self.global_mgr is not None and hasattr(
            self.global_mgr, "inflight_hits"
        ):
            bound["global_inflight_hits"] = int(
                self.global_mgr.inflight_hits()
            )
        bound["total_hits"] = sum(bound.values())
        blob["bound"] = bound
        return blob

    def standby_debug_info(self) -> dict:
        """/debug/standby payload (docs/robustness.md "Standby
        replication & crash recovery"): the published loss bound, the
        pending (unacked) ledger, shadow inventory by source owner, and
        promotion history. Host-side dict copies only — the loss bound
        reads the engine's dirty registry under its own lock, never the
        device (GL009)."""
        if self.standby is None:
            return {"enabled": False}
        return self.standby.summary()

    def slo_debug_info(self) -> dict:
        """/debug/slo payload (docs/monitoring.md "SLOs & burn rates"):
        per-SLO burn rates over every evaluation window, alert states,
        remaining error budgets, the sampled SLI ring summaries, and
        the watchdog's per-loop heartbeat table. Pure ring arithmetic
        over already-sampled values — zero device work (GL009)."""
        if self.slo is None:
            return {"enabled": False}
        return {"enabled": True, **self.slo.debug_info()}

    def overload_debug_info(self) -> dict:
        """/debug/overload payload (docs/robustness.md "Overload
        control & brownout"): the brownout ladder level + driving
        signals and the intake governor's controller state (shed
        counts by reason, tenant weights, heavy-hitter attribution).
        Host-side dict copies only — zero device work (GL009)."""
        if self.overload is None:
            return {"enabled": False}
        return self.overload.debug_info()

    def _overload_sync(self, _metrics=None) -> None:
        """Scrape-time bridge for gubernator_overload_level. No-op
        until the daemon wires the overload manager."""
        if self.overload is None:
            return
        try:
            self.overload.metrics_sync(self.metrics)
        except Exception:  # guberlint: allow-swallow -- scrape bridge: a failed ladder read must not poison /metrics
            return

    def _slo_sync(self, _metrics=None) -> None:
        """Scrape-time bridge for the SLO families (burn rate, budget
        remaining, alert state) and gubernator_thread_stalled. No-op
        until the daemon wires the observatory."""
        if self.slo is None:
            return
        try:
            self.slo.metrics_sync(self.metrics)
        except Exception:  # guberlint: allow-swallow -- scrape bridge: a failed evaluation must not poison /metrics
            return

    def _admission_sync(self, _metrics=None) -> None:
        """Scrape-time bridge: publish this node's measured over-admission
        ratio (excess hits / configured limit over active windows, from
        the engine's TTL-cached admission scan). Single writer for
        gubernator_admission_excess_ratio — the auditor's fleet-max lives
        in a separate gauge (admission_audit_max_excess_ratio).
        Metrics.sync() passes the Metrics instance to every callback;
        this bound method already closes over self.metrics."""
        if not hasattr(self.engine, "admission_snapshot"):
            return
        try:
            snap = self.engine.admission_snapshot()
        except Exception:  # guberlint: allow-swallow -- scrape bridge: a failed scan must not poison /metrics
            return
        self.metrics.admission_excess_ratio.set(
            float(snap.get("excess_ratio", 0.0))
        )

    def device_debug_info(self) -> dict:
        """/debug/device payload (docs/monitoring.md "Device
        resources"): per-subsystem HBM attribution + headroom, the
        host<->device transfer ledger, compile telemetry with retrace
        attribution, and profiler capture stats. Host-side reads only —
        allocator stats, histogram summaries, bounded ring copies — so
        scraping it never dispatches device work (GL009)."""
        from gubernator_tpu.runtime import telemetry as _rt
        from gubernator_tpu.utils import compilecache

        info: dict = {"v": 1}
        if hasattr(self.engine, "device_memory"):
            info["memory"] = self.engine.device_memory()
        em = getattr(self.engine, "metrics", None)
        if em is not None and hasattr(em, "transfer_snapshot"):
            info["transfers"] = em.transfer_snapshot()
        info["compile"] = compilecache.cache_stats()
        info["retraces"] = _rt.compile_attribution()
        prof = getattr(self, "profiler", None)
        if prof is not None and hasattr(prof, "stats"):
            info["profiler"] = prof.stats()
        return info

    # ---- peer membership (reference gubernator.go:616-711) -----------------

    def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        """Swap in a new peer set; wired fully by the daemon/peers layer."""
        if self.picker is not None:
            self.picker.set_peers(peers, self.local_info)


class _LocalPeer:
    """Self-peer shim for daemons running without a mesh."""

    def __init__(self, info: PeerInfo):
        self.info = PeerInfo(
            grpc_address=info.grpc_address,
            http_address=info.http_address,
            data_center=info.data_center,
            is_owner=True,
        )
