"""Hand-written gRPC service glue (grpc_tools codegen unavailable).

Defines the V1 and PeersV1 services (reference gubernator.proto:27-44,
peers.proto:28-34) as generic handlers over the protoc-generated message
classes, plus async client stubs. Method paths match the reference's
generated stubs exactly, so reference Go/Python clients interoperate.
"""

from __future__ import annotations

import grpc

from gubernator_tpu.service import pb

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


def v1_handler(servicer) -> grpc.GenericRpcHandler:
    """servicer: async methods GetRateLimits(req, ctx), HealthCheck(req, ctx)."""
    return grpc.method_handlers_generic_handler(
        V1_SERVICE,
        {
            # BYTES mode: identity (de)serializers — the servicer parses
            # via the native columnar path or protobuf itself
            # (service/fastpath.py).
            "GetRateLimits": grpc.unary_unary_rpc_method_handler(
                servicer.GetRateLimits,
                request_deserializer=None,
                response_serializer=None,
            ),
            "HealthCheck": grpc.unary_unary_rpc_method_handler(
                servicer.HealthCheck,
                request_deserializer=pb.pb.HealthCheckReq.FromString,
                response_serializer=pb.pb.HealthCheckResp.SerializeToString,
            ),
            # Cooperative token leases (docs/architecture.md): BYTES mode
            # with a hand-encoded versioned payload
            # (pb.lease_req_to_bytes / pb.lease_resp_to_bytes). Runs at
            # renew cadence — the whole point is that checks don't RPC.
            "Lease": grpc.unary_unary_rpc_method_handler(
                servicer.Lease,
                request_deserializer=None,
                response_serializer=None,
            ),
        },
    )


def peers_handler(servicer) -> grpc.GenericRpcHandler:
    """servicer: async GetPeerRateLimits(req, ctx), UpdatePeerGlobals(req,
    ctx), TransferSnapshots(req, ctx)."""
    return grpc.method_handlers_generic_handler(
        PEERS_SERVICE,
        {
            # BYTES mode (see v1_handler note).
            "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
                servicer.GetPeerRateLimits,
                request_deserializer=None,
                response_serializer=None,
            ),
            "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
                servicer.UpdatePeerGlobals,
                request_deserializer=pb.peers_pb.UpdatePeerGlobalsReq.FromString,
                response_serializer=pb.peers_pb.UpdatePeerGlobalsResp.SerializeToString,
            ),
            # Ownership handover (docs/robustness.md): BYTES mode with a
            # hand-encoded payload (pb.snapshots_to_bytes) — no protoc in
            # this image, and the RPC runs at membership-change cadence.
            "TransferSnapshots": grpc.unary_unary_rpc_method_handler(
                servicer.TransferSnapshots,
                request_deserializer=None,
                response_serializer=None,
            ),
            # Consistency observatory (docs/monitoring.md): one node's
            # debug blob for /debug/cluster fan-out and the divergence
            # auditor's replica-view fetch. BYTES mode, hand-encoded
            # payload (pb.debug_req_to_bytes / pb.debug_resp_to_bytes).
            "DebugInfo": grpc.unary_unary_rpc_method_handler(
                servicer.DebugInfo,
                request_deserializer=None,
                response_serializer=None,
            ),
            # Cooperative token leases: daemon-to-owner forwarding leg of
            # the same BYTES-mode payload as V1.Lease.
            "Lease": grpc.unary_unary_rpc_method_handler(
                servicer.Lease,
                request_deserializer=None,
                response_serializer=None,
            ),
        },
    )


class V1Stub:
    """Async client for the public V1 service."""

    def __init__(self, channel: grpc.aio.Channel):
        self.get_rate_limits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.pb.GetRateLimitsResp.FromString,
        )
        self.health_check = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.pb.HealthCheckResp.FromString,
        )
        # BYTES mode both ways (payload is pb.lease_req_to_bytes output).
        self.lease = channel.unary_unary(
            f"/{V1_SERVICE}/Lease",
            request_serializer=None,
            response_deserializer=None,
        )


class PeersV1Stub:
    """Async client for the peer-to-peer service."""

    def __init__(self, channel: grpc.aio.Channel):
        self.get_peer_rate_limits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=pb.peers_pb.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=pb.peers_pb.GetPeerRateLimitsResp.FromString,
        )
        self.update_peer_globals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=pb.peers_pb.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=pb.peers_pb.UpdatePeerGlobalsResp.FromString,
        )
        # BYTES mode both ways (payload is pb.snapshots_to_bytes output).
        self.transfer_snapshots = channel.unary_unary(
            f"/{PEERS_SERVICE}/TransferSnapshots",
            request_serializer=None,
            response_deserializer=None,
        )
        # BYTES mode both ways (payload is pb.debug_req_to_bytes output).
        self.debug_info = channel.unary_unary(
            f"/{PEERS_SERVICE}/DebugInfo",
            request_serializer=None,
            response_deserializer=None,
        )
        # BYTES mode both ways (payload is pb.lease_req_to_bytes output).
        self.lease = channel.unary_unary(
            f"/{PEERS_SERVICE}/Lease",
            request_serializer=None,
            response_deserializer=None,
        )
