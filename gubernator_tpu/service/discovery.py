"""Peer discovery pools.

The reference ships four backends (etcd lease+watch, kubernetes informer,
SWIM gossip via memberlist, DNS polling — reference etcd.go,
kubernetes.go, memberlist.go, dns.go), each of which pushes a full
PeerInfo list through one callback into SetPeers (reference
daemon.go:208-243). Same shape here:

- StaticPool: fixed peer list (tests, config-driven clusters).
- DnsPool: polls A/AAAA records via the stdlib resolver on an interval;
  each address becomes a peer at fixed ports (reference dns.go:130-218).
- EtcdPool / K8sPool / MemberListPool: gated — their client libraries
  are not in this image; constructing one raises a clear error naming
  the missing dependency. The watch/lease/gossip protocols are
  documented seams for when the dependency is available.

The JAX device mesh is static per process, so discovery governs the
*host* layer only; a mesh reconfiguration is a restart/resharding event
(SURVEY.md §2.3 membership row).
"""

from __future__ import annotations

import asyncio
import socket
from typing import Callable, List, Optional, Sequence

from gubernator_tpu.api.types import PeerInfo

OnUpdate = Callable[[List[PeerInfo]], None]


class StaticPool:
    """Immediately pushes a fixed peer list (the cluster fixture's path)."""

    def __init__(self, peers: Sequence[PeerInfo], on_update: OnUpdate):
        self._peers = list(peers)
        on_update(self._peers)

    def close(self) -> None:
        pass


class DnsPool:
    """Resolves an FQDN on an interval; every address becomes a peer
    (reference dns.go:130-218; fixed-port convention dns.go:187-195)."""

    def __init__(
        self,
        fqdn: str,
        on_update: OnUpdate,
        grpc_port: int = 81,
        http_port: int = 80,
        interval_s: float = 300.0,
        own_address: str = "",
        resolver=None,
    ):
        self.fqdn = fqdn
        self.on_update = on_update
        self.grpc_port = grpc_port
        self.http_port = http_port
        self.interval_s = interval_s
        self.own_address = own_address
        self._resolver = resolver or self._system_resolve
        self._task: Optional[asyncio.Task] = None
        self._running = True
        self._task = asyncio.ensure_future(self._poll())

    @staticmethod
    def _system_resolve(fqdn: str) -> List[str]:
        infos = socket.getaddrinfo(fqdn, None, proto=socket.IPPROTO_TCP)
        return sorted({i[4][0] for i in infos})

    async def _poll(self) -> None:
        loop = asyncio.get_running_loop()
        while self._running:
            try:
                ips = await loop.run_in_executor(None, self._resolver, self.fqdn)
                peers = [
                    PeerInfo(
                        grpc_address=f"{ip}:{self.grpc_port}",
                        http_address=f"{ip}:{self.http_port}",
                        # self-detection by advertise-address equality
                        # (reference dns.go self marking)
                        is_owner=f"{ip}:{self.grpc_port}" == self.own_address,
                    )
                    for ip in ips
                ]
                if peers:
                    self.on_update(peers)
            except Exception:
                pass  # transient resolver failures: keep the old peer set
            await asyncio.sleep(self.interval_s)

    def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()


def _gated(name: str, dep: str):
    class _Gated:
        def __init__(self, *a, **kw):
            raise RuntimeError(
                f"{name} discovery requires the '{dep}' package, which is "
                f"not available in this environment. Use 'static' or 'dns' "
                f"discovery, or install {dep}."
            )

    _Gated.__name__ = name
    return _Gated


# Gated backends (reference etcd.go:42-352, kubernetes.go:35-247,
# memberlist.go:38-299): same OnUpdate contract once their deps exist.
EtcdPool = _gated("EtcdPool", "etcd3")
K8sPool = _gated("K8sPool", "kubernetes")
MemberListPool = _gated("MemberListPool", "memberlist/SWIM")

POOLS = {
    "static": StaticPool,
    "dns": DnsPool,
    "etcd": EtcdPool,
    "k8s": K8sPool,
    "member-list": MemberListPool,
}
