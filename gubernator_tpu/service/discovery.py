"""Peer discovery pools.

The reference ships four backends (etcd lease+watch, kubernetes informer,
SWIM gossip via memberlist, DNS polling — reference etcd.go,
kubernetes.go, memberlist.go, dns.go), each of which pushes a full
PeerInfo list through one callback into SetPeers (reference
daemon.go:208-243). Same shape here:

- StaticPool: fixed peer list (tests, config-driven clusters).
- DnsPool: polls A/AAAA records via the stdlib resolver on an interval;
  each address becomes a peer at fixed ports (reference dns.go:130-218).
- GossipPool ("member-list"): dependency-free UDP gossip membership —
  the memberlist-style backend implemented on stdlib asyncio.
- EtcdPool (service/etcd.py): lease registration + keepalive +
  re-register-on-loss + prefix watch over a hand-rolled etcd v3 gRPC
  client (reference etcd.go:42-352).
- K8sPool (service/k8s.py): informer-equivalent HTTP list+watch of
  Endpoints/Pods with readiness filtering (reference kubernetes.go:35-247).

The JAX device mesh is static per process, so discovery governs the
*host* layer only; a mesh reconfiguration is a restart/resharding event
(SURVEY.md §2.3 membership row).
"""

from __future__ import annotations

import asyncio
import logging
import socket
from typing import Callable, List, Optional, Sequence

from gubernator_tpu.api.types import PeerInfo

log = logging.getLogger("gubernator.discovery")

OnUpdate = Callable[[List[PeerInfo]], None]


class StaticPool:
    """Immediately pushes a fixed peer list (the cluster fixture's path)."""

    def __init__(self, peers: Sequence[PeerInfo], on_update: OnUpdate):
        self._peers = list(peers)
        on_update(self._peers)

    def close(self) -> None:
        pass


def _query_nameserver(
    ns: str, fqdn: str, qtype: int, timeout: float = 2.0, port: int = 53
) -> List[str]:
    """One A (1) or AAAA (28) query against a specific nameserver over
    UDP, stdlib-only (the reference uses miekg/dns to honor a custom
    resolv.conf, dns.go:39-127)."""
    import random
    import struct

    txid = random.randint(0, 0xFFFF)
    header = struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 0)  # RD=1
    qname = b"".join(
        bytes([len(p)]) + p.encode() for p in fqdn.rstrip(".").split(".")
    ) + b"\x00"
    pkt = header + qname + struct.pack(">HH", qtype, 1)  # IN
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(pkt, (ns, port))
        data, _ = s.recvfrom(4096)
    if len(data) < 12 or struct.unpack(">H", data[:2])[0] != txid:
        return []
    _, _, qd, an, _, _ = struct.unpack(">HHHHHH", data[:12])

    def skip_name(off: int) -> int:
        # A name is a run of labels ending with either a null byte or a
        # compression pointer; labels and a trailing pointer can MIX
        # (RFC 1035 §4.1.4), so check for the pointer at every label.
        while True:
            b = data[off]
            if b & 0xC0 == 0xC0:
                return off + 2
            if b == 0:
                return off + 1
            off += b + 1

    off = 12
    for _ in range(qd):  # skip questions
        off = skip_name(off) + 4
    out = []
    for _ in range(an):
        off = skip_name(off)
        rtype, _, _, rdlen = struct.unpack(">HHIH", data[off : off + 10])
        off += 10
        rdata = data[off : off + rdlen]
        off += rdlen
        if rtype == qtype == 1 and rdlen == 4:
            out.append(socket.inet_ntop(socket.AF_INET, rdata))
        elif rtype == qtype == 28 and rdlen == 16:
            out.append(socket.inet_ntop(socket.AF_INET6, rdata))
    return out


def resolve_with_resolv_conf(fqdn: str, resolv_conf: str) -> List[str]:
    """Resolve A+AAAA records using the nameservers listed in a specific
    resolv.conf file (reference GUBER_RESOLV_CONF, dns.go:60-87)."""
    nameservers = []
    with open(resolv_conf) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2 and parts[0] == "nameserver":
                nameservers.append(parts[1])
    import struct

    for ns in nameservers:
        ips: List[str] = []
        for qtype in (1, 28):
            try:
                ips.extend(_query_nameserver(ns, fqdn, qtype))
            except (OSError, struct.error, IndexError):
                # Unreachable nameserver or a malformed/truncated answer:
                # try the next nameserver rather than erroring the poll.
                continue
        if ips:
            return sorted(set(ips))
    return []


class DnsPool:
    """Resolves an FQDN on an interval; every address becomes a peer
    (reference dns.go:130-218; fixed-port convention dns.go:187-195)."""

    def __init__(
        self,
        fqdn: str,
        on_update: OnUpdate,
        grpc_port: int = 81,
        http_port: int = 80,
        interval_s: float = 300.0,
        own_address: str = "",
        resolver=None,
        resolv_conf: str = "",
    ):
        self.fqdn = fqdn
        self.on_update = on_update
        self.grpc_port = grpc_port
        self.http_port = http_port
        self.interval_s = interval_s
        self.own_address = own_address
        if resolver is not None:
            self._resolver = resolver
        elif resolv_conf and resolv_conf != "/etc/resolv.conf":
            # Custom resolv.conf: query its nameservers directly (the
            # system resolver already honors the default path).
            self._resolver = lambda f: resolve_with_resolv_conf(f, resolv_conf)
        else:
            self._resolver = self._system_resolve
        self._task: Optional[asyncio.Task] = None
        self._running = True
        self._task = asyncio.ensure_future(self._poll())

    @staticmethod
    def _system_resolve(fqdn: str) -> List[str]:
        infos = socket.getaddrinfo(fqdn, None, proto=socket.IPPROTO_TCP)
        return sorted({i[4][0] for i in infos})

    async def _poll(self) -> None:
        loop = asyncio.get_running_loop()
        failing = False
        while self._running:
            try:
                ips = await loop.run_in_executor(None, self._resolver, self.fqdn)
                peers = [
                    PeerInfo(
                        grpc_address=f"{ip}:{self.grpc_port}",
                        http_address=f"{ip}:{self.http_port}",
                        # self-detection by advertise-address equality
                        # (reference dns.go self marking)
                        is_owner=f"{ip}:{self.grpc_port}" == self.own_address,
                    )
                    for ip in ips
                ]
                if peers:
                    self.on_update(peers)
                failing = False
            except Exception as e:
                # Keep the old peer set, but never silently: one warning
                # per outage (not per poll — a dead resolver at a 300s
                # interval must not fill the log), cleared on recovery.
                if not failing:
                    log.warning(
                        "dns peer poll for %s failed (keeping previous "
                        "peer set): %s", self.fqdn, e,
                    )
                    failing = True
            await asyncio.sleep(self.interval_s)

    def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()


class GossipPool:
    """Zero-dependency SWIM gossip membership (the memberlist-style
    backend, reference memberlist.go:38-299, reimagined on stdlib
    asyncio UDP).

    TRUST MODEL: by default datagrams are unauthenticated JSON — deploy
    only on trusted LANs / private VPCs (the reference's memberlist
    default is the same unless its encryption key is set). On a hostile
    network an attacker can forge `from` fields to refresh a dead peer's
    liveness or clear its tombstone, and forged suspect/dead gossip can
    evict a live peer until it refutes. Set `secret` (all nodes must
    share it — the memberlist-SecretKey analog) to authenticate every
    datagram with HMAC-SHA256: sends are prefixed with a 16-byte tag
    over a signed wall-clock timestamp + payload, and receives that are
    unauthenticated OR outside the replay window (`replay_window_s`,
    default a handful of gossip intervals) are dropped before parsing —
    a captured datagram cannot be replayed later to refresh a dead
    peer's liveness or resurrect stale suspicion. Authenticated nodes
    need loosely synchronized clocks (NTP-grade skew is far inside the
    window). Note HMAC authenticates but does NOT encrypt (memberlist's
    SecretKey also encrypts); membership views are still readable on the
    wire. Use the etcd/k8s/DNS backends where the network is not trusted
    at all.

    Each node carries its own PeerInfo in its gossip state and
    periodically sends its full membership view (JSON datagram) to a few
    random peers plus the configured seed nodes; receivers merge views
    and refresh liveness. On top of that anti-entropy layer, the SWIM
    failure-detector runs (reference memberlist.go:160-233 event
    semantics):

    - Every interval, ONE member (round-robin) is pinged; a missing ack
      triggers an indirect round — `indirect_probes` random members are
      asked to ping the target on our behalf (acks return directly).
    - A member failing both rounds is marked SUSPECT and the suspicion
      gossips with the view. A suspect refutes by bumping its own
      incarnation number and gossiping alive; suspicion at an older
      incarnation is discarded.
    - A member suspect for `suspicion_intervals` rounds is declared dead:
      removed from the membership (SetPeers fires) and tombstoned so
      stale third-party views cannot resurrect it at an old incarnation.
      A datagram from the address itself always proves life and clears
      the tombstone (fast rejoin after restart).

    Detection is therefore O(probe interval), not O(freshness window);
    the `expire_intervals` freshness sweep remains as a backstop for
    peers that were never probed (e.g. learned moments ago).
    """

    def __init__(
        self,
        bind: str,  # "host:port" UDP listen address (wildcards/port 0 ok)
        info: PeerInfo,  # advertised service addresses
        on_update: OnUpdate,
        seeds: Sequence[str] = (),  # known gossip addresses
        interval_s: float = 1.0,
        expire_intervals: int = 5,
        fanout: int = 3,
        advertise: str = "",  # reachable gossip identity; derived if empty
        suspicion_intervals: int = 3,
        indirect_probes: int = 3,
        tombstone_intervals: int = 10,
        secret: "str | bytes" = b"",  # shared HMAC key; b"" = unauthenticated
        replay_window_s: float = 0.0,  # 0 = derive from the gossip interval
    ):
        import json as _json
        import random as _random

        self._json = _json
        self._random = _random
        self._secret = secret.encode() if isinstance(secret, str) else secret
        # Authenticated datagrams older (or newer) than this are dropped
        # as replays; sized in gossip intervals so slower cadences keep
        # proportional tolerance, floored at 10s for clock skew.
        self.replay_window_s = replay_window_s or max(10.0, 10 * interval_s)
        self.bind = bind
        self.advertise = advertise
        self.info = info
        self.on_update = on_update
        self.seeds = [s for s in seeds if s]
        self.interval_s = interval_s
        self.expire_s = interval_s * expire_intervals
        self.fanout = fanout
        self.suspicion_s = interval_s * suspicion_intervals
        self.indirect_probes = indirect_probes
        self.tombstone_s = interval_s * tombstone_intervals
        # gossip_addr -> {"info": PeerInfo, "seen": monotonic,
        #                 "state": "alive"|"suspect", "inc": int,
        #                 "since": monotonic (state transition time)}
        self._peers = {}
        self._inc = 0  # own incarnation (bumped to refute suspicion)
        self._tombs = {}  # addr -> {"inc": int, "until": monotonic}
        self._seq = 0
        self._acked = set()
        self._probe = None  # (addr, seq, "direct"|"indirect")
        self._probe_ring = []
        self._last_pushed = None
        self._transport = None
        self._task = None
        self._running = True
        self._started = asyncio.ensure_future(self._start())

    async def _start(self) -> None:
        import time as _time

        from gubernator_tpu.utils.net import resolve_host_ip

        loop = asyncio.get_running_loop()
        host, port = self.bind.rsplit(":", 1)

        pool = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                pool._receive(data)

        self._transport, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=(host, int(port))
        )
        if not self._running:  # closed before the bind completed
            self._transport.close()
            return
        # Gossip identity must be REACHABLE: actual bound port, wildcard
        # host expanded to a real interface IP (the reference memberlist's
        # separate advertise address).
        actual = self._transport.get_extra_info("sockname")
        self.bind = f"{host}:{actual[1]}"
        if not self.advertise:
            self.advertise = resolve_host_ip(self.bind)
        self.seeds = [s for s in self.seeds if s != self.advertise]
        self._peers[self.advertise] = {
            "info": self.info, "seen": _time.monotonic(),
            "state": "alive", "inc": self._inc, "since": _time.monotonic(),
        }
        self._push()
        self._task = asyncio.ensure_future(self._loop())

    def _encode(self) -> bytes:
        import time as _time

        now = _time.monotonic()
        peers = {
            addr: {
                "grpc": st["info"].grpc_address,
                "http": st["info"].http_address,
                "dc": st["info"].data_center,
                # freshness: how long ago this node heard from the peer,
                # so receivers get accurate indirect liveness (prevents
                # membership flapping in clusters larger than the fanout)
                "age": round(now - st["seen"], 3),
                "state": st["state"],
                "inc": st["inc"],
            }
            for addr, st in self._peers.items()
        }
        # dead members gossip as tombstones until they age out, so the
        # death propagates faster than everyone independently probing.
        # The death's age travels with it: receivers seed their tombstone
        # with the REMAINING ttl, so re-gossip can never extend a
        # tombstone past its original death + tombstone_s and the
        # cluster-wide set provably drains (no mutual resurrection).
        for addr, tomb in self._tombs.items():
            if addr not in peers:
                peers[addr] = {
                    "state": "dead", "inc": tomb["inc"],
                    "age": round(now - tomb["died"], 3),
                }
        return self._json.dumps({"from": self.advertise, "peers": peers}).encode()

    _TAG_LEN = 16  # truncated HMAC-SHA256, memberlist-style overhead
    _TS_LEN = 8  # big-endian wall-clock ms INSIDE the signed bytes

    def _sign(self, payload: bytes) -> bytes:
        import hmac as _hmac
        import time as _time

        # The timestamp is covered by the tag: an attacker without the
        # key can neither forge a fresh one nor refresh a captured
        # datagram's — replays age out of the window.
        ts = int(_time.time() * 1000).to_bytes(self._TS_LEN, "big")
        tag = _hmac.new(self._secret, ts + payload, "sha256").digest()
        return tag[: self._TAG_LEN] + ts + payload

    def _authenticate(self, data: bytes) -> "bytes | None":
        """Strip + verify tag and freshness; None = drop (forged,
        unauthenticated, or replayed outside the window)."""
        import hmac as _hmac
        import time as _time

        if len(data) <= self._TAG_LEN + self._TS_LEN:
            return None
        tag, signed = data[: self._TAG_LEN], data[self._TAG_LEN:]
        want = _hmac.new(self._secret, signed, "sha256").digest()
        if not _hmac.compare_digest(tag, want[: self._TAG_LEN]):
            return None
        ts = int.from_bytes(signed[: self._TS_LEN], "big")
        if abs(_time.time() * 1000 - ts) > self.replay_window_s * 1000:
            return None  # stale capture (or hopeless clock skew): drop
        return signed[self._TS_LEN:]

    def _sendto(self, payload: bytes, addr: str) -> None:
        try:
            if self._secret:
                payload = self._sign(payload)
            host, port = addr.rsplit(":", 1)
            self._transport.sendto(payload, (host, int(port)))
        # guberlint: allow-swallow -- best-effort UDP gossip send: a down peer is routine and surfaces via its own liveness timeout
        except Exception:
            pass

    def _gossip_out(self) -> None:
        """Send the current view to fanout random members + seeds."""
        targets = set(self.seeds)
        others = [a for a in self._peers if a != self.advertise]
        if others:
            targets.update(
                self._random.sample(others, min(self.fanout, len(others)))
            )
        payload = self._encode()
        for t in targets:
            self._sendto(payload, t)

    def _receive(self, data: bytes) -> None:
        import time as _time

        try:
            if self._secret:
                data = self._authenticate(data)
                if data is None:
                    return  # forged or unauthenticated: drop pre-parse
            msg = self._json.loads(data)
            if not isinstance(msg, dict):
                return
            now = _time.monotonic()
            sender = msg.get("from")

            t = msg.get("t")
            if t is not None:
                self._receive_probe(t, msg, now)
                return

            changed = False
            peers = msg.get("peers")
            if not isinstance(peers, dict):
                return
            if isinstance(sender, str) and sender in self._tombs:
                # a datagram FROM the address itself is proof of life:
                # clear the tombstone so the rejoin merges below
                del self._tombs[sender]
            for addr, p in peers.items():
                if not isinstance(p, dict):
                    continue
                state = str(p.get("state", "alive"))
                if state not in ("alive", "suspect", "dead"):
                    # unknown states (version skew, hostile input) must
                    # not park a peer outside the detector's state machine
                    continue
                pinc = int(p.get("inc", 0) or 0)
                if addr == self.advertise:
                    # refutation (memberlist.go:214-233): someone believes
                    # we are suspect/dead — outlive that incarnation and
                    # gossip alive immediately
                    if state in ("suspect", "dead") and pinc >= self._inc:
                        self._inc = pinc + 1
                        me = self._peers.get(self.advertise)
                        if me is not None:
                            me["inc"] = self._inc
                        self._gossip_out()
                    continue
                if state == "dead":
                    tomb = self._tombs.get(addr)
                    if addr == sender or (
                        tomb is not None and tomb["inc"] >= pinc
                    ):
                        continue
                    died = now - float(p.get("age", 0) or 0)
                    until = died + self.tombstone_s
                    if until <= now:
                        continue  # the death already aged out everywhere
                    st = self._peers.get(addr)
                    if st is not None and pinc >= st["inc"]:
                        del self._peers[addr]
                        self._tombs[addr] = {
                            "inc": pinc, "until": until, "died": died
                        }
                        changed = True
                    elif st is None:
                        self._tombs[addr] = {
                            "inc": pinc, "until": until, "died": died
                        }
                    continue
                tomb = self._tombs.get(addr)
                if tomb is not None:
                    if addr != sender and pinc <= tomb["inc"]:
                        continue  # stale resurrection at an old incarnation
                    del self._tombs[addr]
                age = float(p.get("age", 0) or 0)
                # indirect liveness: the sender saw this peer `age` ago;
                # one transit interval of slack
                seen = now - age - self.interval_s
                if addr == sender:
                    seen = now
                info = PeerInfo(
                    grpc_address=str(p.get("grpc", "")),
                    http_address=str(p.get("http", "")),
                    data_center=str(p.get("dc", "")),
                )
                st = self._peers.get(addr)
                if st is None:
                    self._peers[addr] = {
                        "info": info, "seen": seen,
                        "state": state if state == "suspect" else "alive",
                        "inc": pinc, "since": now,
                    }
                    changed = True
                else:
                    st["seen"] = max(st["seen"], seen)
                    if pinc > st["inc"]:
                        # higher incarnation overrides state outright
                        st["inc"] = pinc
                        if st["state"] != state:
                            st["state"] = state
                            st["since"] = now
                    elif (
                        pinc == st["inc"]
                        and state == "suspect"
                        and st["state"] == "alive"
                    ):
                        st["state"] = "suspect"
                        st["since"] = now
                    if st["info"] != info:
                        # peer restarted with new service addresses
                        st["info"] = info
                        changed = True
            if changed:
                self._push()
        # guberlint: allow-swallow -- malformed/hostile datagrams must never escape OR spam logs (unauthenticated UDP is attacker-controlled input)
        except Exception:
            return

    def _receive_probe(self, t: str, msg: dict, now: float) -> None:
        """SWIM probe traffic: ping / ping-req / ack."""
        sender = msg.get("from")
        if not isinstance(sender, str) or not sender:
            return
        # any probe datagram FROM an address proves that address is alive:
        # clear its tombstone (fast rejoin) and refresh liveness
        self._tombs.pop(sender, None)
        st = self._peers.get(sender)
        if st is not None:
            st["seen"] = now
        if t == "ping":
            # reply to the probe origin (direct probes set reply_to=from;
            # an indirect probe carries the ORIGIN so the ack proves
            # liveness where it matters)
            reply_to = str(msg.get("reply_to") or sender)
            ack = self._json.dumps(
                {"t": "ack", "from": self.advertise,
                 "seq": msg.get("seq"), "inc": self._inc}
            ).encode()
            self._sendto(ack, reply_to)
        elif t == "ping-req":
            target = msg.get("target")
            if isinstance(target, str) and target:
                ping = self._json.dumps(
                    {"t": "ping", "from": self.advertise,
                     "seq": msg.get("seq"), "reply_to": sender}
                ).encode()
                self._sendto(ping, target)
        elif t == "ack":
            if st is not None and st["state"] == "suspect":
                # direct proof of life refutes local suspicion
                st["state"] = "alive"
                st["since"] = now
            if self._probe is not None and self._probe[0] == sender:
                # explicit None check: seq 0 is a legitimate value (the
                # suspect re-probe uses it), `or`-style coercion is not
                seq = msg.get("seq")
                if isinstance(seq, int):
                    self._acked.add(seq)

    async def _loop(self) -> None:
        import math as _math
        import time as _time

        while self._running:
            await asyncio.sleep(self.interval_s)
            now = _time.monotonic()
            changed = False

            # --- SWIM failure detector ---------------------------------
            # resolve last round's probe
            if self._probe is not None:
                addr, seq, stage = self._probe
                st = self._peers.get(addr)
                if seq in self._acked or st is None:
                    self._probe = None
                elif stage == "direct":
                    # no direct ack: ask indirect_probes members to ping
                    # the target on our behalf (memberlist.go:160-187)
                    proxies = [
                        a for a in self._peers
                        if a not in (self.advertise, addr)
                    ]
                    req = self._json.dumps(
                        {"t": "ping-req", "from": self.advertise,
                         "seq": seq, "target": addr}
                    ).encode()
                    for p in self._random.sample(
                        proxies, min(self.indirect_probes, len(proxies))
                    ):
                        self._sendto(req, p)
                    self._probe = (addr, seq, "indirect")
                else:
                    # direct AND indirect rounds failed: suspect
                    if st["state"] == "alive":
                        st["state"] = "suspect"
                        st["since"] = now
                    self._probe = None
            # suspicion timeout -> dead (+ tombstone against stale views).
            # The timeout scales with log(cluster size) — refutation has
            # to travel via fanout gossip, which takes more rounds in a
            # larger cluster (memberlist's suspicionMult * log(n) rule).
            n_members = len(self._peers)
            suspicion_s = self.suspicion_s * max(
                1.0, _math.log10(max(n_members, 1)) + 1.0
            )
            for a, st in list(self._peers.items()):
                if a == self.advertise:
                    continue
                if (
                    st["state"] == "suspect"
                    and now - st["since"] > suspicion_s
                ):
                    del self._peers[a]
                    self._tombs[a] = {
                        "inc": st["inc"], "until": now + self.tombstone_s,
                        "died": now,
                    }
                    changed = True
                elif st["state"] == "suspect":
                    # a live suspect must get every chance to prove
                    # itself before the timeout: dedicated re-probe each
                    # round (the round-robin ring would take ~n rounds to
                    # come back to it) — prevents flapping under one lost
                    # probe round
                    ping = self._json.dumps(
                        {"t": "ping", "from": self.advertise, "seq": 0}
                    ).encode()
                    self._sendto(ping, a)
            # freshness backstop + tombstone gc
            expired = [
                a
                for a, st in self._peers.items()
                if a != self.advertise and now - st["seen"] > self.expire_s
            ]
            for a in expired:
                del self._peers[a]
                changed = True
            for a in [a for a, tb in self._tombs.items() if now > tb["until"]]:
                del self._tombs[a]
            if changed:
                self._push()
            # launch a new probe (round-robin over the membership)
            if self._probe is None:
                self._acked.clear()
                self._probe_ring = [
                    a for a in self._probe_ring if a in self._peers
                ]
                if not self._probe_ring:
                    ring = [a for a in self._peers if a != self.advertise]
                    self._random.shuffle(ring)
                    self._probe_ring = ring
                if self._probe_ring:
                    addr = self._probe_ring.pop()
                    self._seq += 1
                    ping = self._json.dumps(
                        {"t": "ping", "from": self.advertise, "seq": self._seq}
                    ).encode()
                    self._sendto(ping, addr)
                    self._probe = (addr, self._seq, "direct")

            # --- anti-entropy view gossip ------------------------------
            self._gossip_out()

    def _push(self) -> None:
        members = sorted(
            (st["info"] for st in self._peers.values()),
            key=lambda p: p.grpc_address,
        )
        snapshot = [(p.grpc_address, p.http_address, p.data_center) for p in members]
        if snapshot != self._last_pushed:
            self._last_pushed = snapshot
            self.on_update(list(members))

    async def started(self) -> "GossipPool":
        """Await the UDP endpoint bind (resolves the ephemeral port)."""
        await self._started
        return self

    def members(self) -> List[PeerInfo]:
        return [st["info"] for st in self._peers.values()]

    def close(self) -> None:
        self._running = False
        self._started.cancel()
        if self._task is not None:
            self._task.cancel()
        if self._transport is not None:
            self._transport.close()


# Real etcd/k8s backends live in their own modules (service/etcd.py with
# a hand-rolled etcdserverpb wire client; service/k8s.py on the HTTP
# list+watch API) — re-exported here for discoverability. Constructor
# signatures are per-backend (each takes its own config block), so the
# daemon selects backends explicitly; DISCOVERY_TYPES is the valid-name
# registry.
from gubernator_tpu.service.etcd import EtcdPool  # noqa: E402
from gubernator_tpu.service.k8s import K8sPool  # noqa: E402

DISCOVERY_TYPES = ("static", "dns", "member-list", "etcd", "k8s")
