"""Columnar serving edge: bytes -> columns -> kernel -> bytes.

The object path (protobuf message -> dataclass -> pump -> demux) costs
~10-20µs of Python per request item; this path serves an entire
GetRateLimits/GetPeerRateLimits call with no per-item Python at all
(native wire parse, vectorized wave assembly, one jitted decide per
wave, native response build). It is an OPTIMIZATION, not a semantic
fork: every batch it cannot serve byte-identically falls back to the
object path (equivalence is fuzz-tested in tests/test_fastpath.py).

Fallback triggers:
- native library unavailable, malformed/empty/oversized batch;
- any item carrying metadata (trace context) or failing validation
  (those need per-item error strings);
- DURATION_IS_GREGORIAN items on a peer call or an all-Gregorian batch
  (V1 mixed batches keep the columnar lanes and splice the Gregorian
  items through the object path, like GLOBAL's round-5 lane split);
- a key this node does not own (peer forwarding), checked with the
  vectorized ring mask — GetPeerRateLimits skips this check because
  forwarded items are owned by construction;
- engine not eligible (wave/lane overflow); a daemon with a Loader but
  no Store keeps the object path so the key-string dictionary stays
  complete for snapshots without columnar string-decode overhead.

A Store does NOT fall back: check_columns runs the object path's exact
per-wave sequence (probe -> read-through -> decide -> write-behind,
reference algorithms.go:45-51, 149-153) with request objects built only
for actual miss lanes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gubernator_tpu import wire
from gubernator_tpu.api.types import Behavior
from gubernator_tpu.parallel import hash_ring

MAX_BATCH_SIZE = 1000


def _committed_error():
    from gubernator_tpu.runtime.engine import TableCommittedError

    return TableCommittedError

# Gregorian durations need host-side calendar math the columnar decide
# doesn't carry — those ITEMS are pinned to the object path (via the
# mixed splice on V1 calls; whole-batch fallback on peer calls).
_SLOW_BEHAVIOR = int(Behavior.DURATION_IS_GREGORIAN)
_GLOBAL = int(Behavior.GLOBAL)
_DRAIN = int(Behavior.DRAIN_OVER_LIMIT)
_MULTI_REGION = int(Behavior.MULTI_REGION)
_RESET = int(Behavior.RESET_REMAINING)

_RING_VARIANT = {
    hash_ring.fnv1_64: "fnv1",
    hash_ring.fnv1a_64: "fnv1a",
    hash_ring.fnv1a_mix_64: "fnv1a-mix",
}


import os


def _disabled() -> bool:
    # Read per call, NOT at import: the daemon's --config file is
    # injected into os.environ after this module may already have been
    # imported (guberlint GL004).
    return os.environ.get("GUBER_DISABLE_FAST_EDGE", "") in ("1", "true")


def enabled(svc) -> bool:
    """Static eligibility for this service instance."""
    return (
        not _disabled()
        and getattr(svc, "fast_edge", False)
        and wire.available()
        and hasattr(svc.engine, "check_columns")
        # GUBER_STAGE_METADATA promises per-response diagnostics
        # (stage_breakdown_us, global_staleness_ms) that only the object
        # path attaches — a diagnostics mode, so it trades the fast edge
        # for the richer responses rather than silently dropping them.
        and not getattr(
            getattr(svc.engine, "cfg", None), "stage_metadata", False
        )
        # GUBER_RETRY_AFTER promises retry_after_ms on OVER_LIMIT
        # responses, which only the object path attaches — same
        # trade as stage_metadata above.
        and not getattr(svc, "retry_after", False)
    )


def try_serve(svc, data: bytes, peer_call: bool):
    """Serve one call's raw request bytes columnar-fast.

    Returns:
    - bytes — the complete response (all items served columnar);
    - ("mixed", n, local_pos, local_arrays, nonlocal_reqs, md) — locally
      served items (owned, plus ALL GLOBAL items) already DECIDED
      columnar; the async caller forwards `nonlocal_reqs` through the
      object path and splices with merge_mixed() (V1 only; peer calls
      are all-local by construction). `md` carries the GLOBAL non-owner
      owner-metadata spans, or None;
    - None — fall back to the object path entirely.

    GLOBAL items: V1 calls are answered from the local table whether
    owned or not (reference gubernator.go:395-421), with the
    replication legs queued through the GlobalManager after the decide
    commits — queue_update for owned items, queue_hit plus
    metadata={"owner": ...} for non-owned. Peer relays apply drain
    semantics at the owner (DRAIN_OVER_LIMIT forced) and queue the
    broadcast. Engines that route GLOBAL internally (ici mode) receive
    the flag unstripped and decide through their replica tier; items
    carrying trace metadata keep the object path.
    """
    cols = wire.parse_requests(data)
    if cols is None or cols.n == 0 or cols.n > MAX_BATCH_SIZE:
        return None
    if cols.slow.any():
        return None
    # DURATION_IS_GREGORIAN needs host-side calendar math the columnar
    # decide doesn't carry — but those ITEMS ride the mixed return's
    # object-path lane (the same split GLOBAL lanes got in round 5)
    # instead of demoting the whole batch. Peer calls cannot return
    # "mixed", and an all-Gregorian batch has no columnar work left.
    greg = (cols.behavior & _SLOW_BEHAVIOR) != 0
    has_greg = bool(greg.any())
    if has_greg and (peer_call or bool(greg.all())):
        return None
    if not peer_call and getattr(svc, "force_global", False):
        # GUBER_FORCE_GLOBAL: every V1 item becomes GLOBAL (the same OR
        # the object path applies per item, server.py).
        cols.behavior = cols.behavior | np.int64(_GLOBAL)
    g_mask = (cols.behavior & _GLOBAL) != 0
    has_global = bool(g_mask.any())
    # ici-mode engines route GLOBAL internally (replica tier): the
    # GLOBAL bit must reach the engine unstripped; the daemon-level
    # replication legs + owner metadata are identical.
    strip_global = not getattr(svc.engine, "routes_global_internally", False)
    if peer_call and has_global:
        # Owner applying relayed GLOBAL hits always drains (reference
        # gubernator.go:510-512) and queues a broadcast; items with
        # trace metadata took the object path already (cols.slow).
        cols.behavior = np.where(
            g_mask, cols.behavior | np.int64(_DRAIN), cols.behavior
        )
    # Validation needs per-item error strings -> object path.
    key_lens = np.diff(cols.key_offsets)
    if np.any(cols.name_lens == 0) or np.any(
        key_lens - cols.name_lens - 1 == 0
    ):
        return None
    local = None
    g_owned = g_mask  # standalone daemon: owner of everything
    owner_addrs = None
    ring_mask = None
    if not peer_call:
        picker = svc.picker
        if picker is not None and picker.peers():
            variant = _RING_VARIANT.get(getattr(picker, "hash_fn", None))
            if variant is None:
                return None
            ring_h = wire.fnv1_batch(cols.key_data, cols.key_offsets, variant)
            mask = np.asarray(picker.local_mask(ring_h), dtype=bool)
            ring_mask = mask
            if has_global:
                # GLOBAL items are answered from the LOCAL table whether
                # owned or not (reference gubernator.go:395-421); only
                # non-GLOBAL peer-owned items forward.
                if not hasattr(picker, "owner_spans"):
                    return None
                g_owned = g_mask & mask
                owner_addrs = (picker, ring_h)  # spans built post-decide
                serve = mask | g_mask
            else:
                serve = mask
            if not serve.all():
                local = serve
    if has_greg:
        # Gregorian lanes leave the columnar set and come back spliced
        # through merge_mixed, decided by the object path.
        base = local if local is not None else np.ones(cols.n, dtype=bool)
        local = base & ~greg
    # MULTI_REGION: the in-region owner's apply queues the cross-region
    # leg (server.py observe call sites). V1 owned items qualify (the
    # non-owned forward and observe at their in-region owner); peer-call
    # applies are owner applies by definition. Reqs are built BEFORE the
    # GLOBAL strip so combined-flag items replicate with both bits.
    mr_mask = (cols.behavior & _MULTI_REGION) != 0
    mr_queue = []
    if bool(mr_mask.any()) and svc.region_mgr is not None:
        mr_owned = mr_mask if ring_mask is None else (mr_mask & ring_mask)
        if has_greg:
            # Gregorian lanes decide through svc.get_rate_limits, which
            # observes its own cross-region leg (server.py) — queueing
            # here too would double-replicate.
            mr_owned = mr_owned & ~greg
        q = mr_owned & (
            (cols.hits != 0) | ((cols.behavior & _RESET) != 0)
        )
        mr_queue = [
            _req_from_columns(cols, int(i)) for i in np.nonzero(q)[0]
        ]

    now = None
    if has_global or mr_queue:
        # One timestamp for BOTH the local decide and the replicated
        # legs — the object path stamps created_at before the engine
        # call and replicates that same value (server.py); a later
        # re-stamp could land the owner's apply in the next window.
        now = svc.engine.now_fn()
        for req in mr_queue:
            if req.created_at is None:
                req.created_at = now
    if has_global:
        # Queue the replication legs ONLY for items the decide applies
        # (built from the pre-strip behavior; zero-hit items queue
        # nothing, matching GlobalManager's own gate). Objects are built
        # up front so a failed construction falls back BEFORE any table
        # commit.
        # Gregorian GLOBAL lanes replicate through the object path they
        # decide on (svc.get_rate_limits queues their legs) — queueing
        # them here too would double-count the hit at the owner.
        g_queue = [
            (bool(g_owned[i]), _req_from_columns(cols, int(i)))
            for i in np.nonzero(g_mask & ~greg & (cols.hits != 0))[0]
        ]
        for _, req in g_queue:
            if req.created_at is None:
                req.created_at = now
        # The standard engine expects GLOBAL stripped (the daemon's
        # global manager owns replication) — same conditional strip the
        # object path does (server.py). Gregorian lanes keep the bit:
        # they never reach the columnar engine, and their object-path
        # request must still carry it.
        if strip_global:
            stripped = cols.behavior & ~np.int64(_GLOBAL)
            cols.behavior = (
                np.where(greg, cols.behavior, stripped) if has_greg else stripped
            )

    def queue_legs():
        # try_serve runs on the serving executor; the managers' queues
        # are loop-affine — hop each batch over in one callback.
        if has_global and svc.global_mgr is not None and g_queue:
            svc.global_mgr.queue_from_thread(g_queue)
        if mr_queue:
            svc.region_mgr.observe_from_thread(mr_queue)

    def count_metrics(served_mask):
        # Label parity with the object path: owned GLOBAL items count
        # as "local" (server.py checks is_owner before the GLOBAL
        # branch); only non-owner GLOBAL answers count as "global".
        n_glob = (
            int((g_mask & ~g_owned & served_mask).sum()) if has_global else 0
        )
        m = getattr(svc, "_m_global", None)
        if n_glob and m is not None:
            m.inc(n_glob)
        m = getattr(svc, "_m_local", None)
        if m is not None:
            m.inc(int(served_mask.sum()) - n_glob)

    def record_provenance(out, positions):
        # Decision provenance (docs/monitoring.md "Admission"), with the
        # same replica/local split as the labels above: GLOBAL non-owner
        # lanes answered from the local table are path=replica, the rest
        # path=fastpath. Peer-call batches are NOT recorded — the object
        # path counts forwarded answers at the forwarding node only, and
        # the columnar edge must match it decision-for-decision. Staleness
        # bounds stay 0: the per-key bound lives in the object path's
        # metadata, and GUBER_STAGE_METADATA disables this edge entirely.
        rec = getattr(svc, "recorder", None)
        if rec is None or peer_call:
            return
        status, _limit, remaining, _reset = out

        def sample_key(j):
            return _req_from_columns(cols, int(positions[j])).hash_key()

        rest = None
        if has_global:
            rep = (g_mask & ~g_owned)[positions]
            if bool(rep.any()):
                rec.record_columnar(
                    "replica", status, remaining,
                    mask=rep, sample_key=sample_key,
                )
                rest = ~rep
        rec.record_columnar(
            "fastpath", status, remaining,
            mask=rest, sample_key=sample_key,
        )

    def owner_spans(positions):
        """(owner_data, owner_offsets) for build_responses_md: non-owned
        GLOBAL items report their authoritative owner; everything else
        gets an empty span (no metadata). Fully vectorized in the ring."""
        pick, rh = owner_addrs
        need = (g_mask & ~g_owned)[positions]
        return pick.owner_spans(rh[positions], need)

    if local is None:
        # NOTE: a failure BEFORE the table commits falls back safely;
        # a failure AFTER waves committed to a surviving table raises
        # TableCommittedError, which must propagate (a silent fallback
        # would re-apply every committed hit).
        try:
            out = svc.engine.check_columns(cols, now=now)
        except _committed_error():
            raise
        # guberlint: allow-swallow -- fallback to the object path IS the handling (byte-equivalence fuzzed); TableCommittedError re-raised above
        except Exception:
            return None
        if out is None:
            return None
        count_metrics(np.ones(cols.n, dtype=bool))
        record_provenance(out, np.arange(cols.n))
        if has_global or mr_queue:
            queue_legs()
        if has_global and owner_addrs is not None and bool(
            (g_mask & ~g_owned).any()
        ):
            odata, ooffs = owner_spans(np.arange(cols.n))
            return wire.build_responses_md(*out, odata, ooffs)
        return wire.build_responses(*out)
    if not local.any():
        return None  # nothing local to decide: pure forwarding batch
    # Mixed ownership: decide the local subset columnar now (with the
    # identity hashes computed once over the full batch); hand the
    # peer-owned subset back as objects for the forwarding path. The
    # request objects build BEFORE the decide so a construction failure
    # cannot strand already-committed hits.
    from gubernator_tpu import native as _native

    local_pos = np.nonzero(local)[0]
    nonlocal_pos = np.nonzero(~local)[0]
    nonlocal_reqs = [_req_from_columns(cols, int(i)) for i in nonlocal_pos]
    hashes = _native.hash128_batch_raw(
        cols.key_data.tobytes(), cols.key_offsets,
        svc.engine.cfg.num_groups,
    )
    try:
        out = svc.engine.check_columns(
            cols, now=now, select=local_pos, hashes=hashes
        )
    except _committed_error():
        raise
    # guberlint: allow-swallow -- fallback to the object path IS the handling (byte-equivalence fuzzed); TableCommittedError re-raised above
    except Exception:
        return None
    if out is None:
        return None
    count_metrics(local)
    record_provenance(out, local_pos)
    md = None
    if has_global or mr_queue:
        queue_legs()
    if has_global and owner_addrs is not None and bool(
        (g_mask & ~g_owned).any()
    ):
        md = owner_spans(local_pos)
    return ("mixed", cols.n, local_pos, out, nonlocal_reqs, md)


def _req_from_columns(cols, i: int):
    """RateLimitReq object for one (peer-owned) lane — the forwarding
    path needs objects; only the non-local fraction pays this cost."""
    return wire.req_from_columns(cols, i)


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def merge_mixed(n: int, local_pos, local_out, nonlocal_resps, md=None) -> bytes:
    """Splice columnar-decided local items with forwarded object-path
    responses, preserving request order. Repeated message items frame
    independently, so native-built runs and protobuf-serialized items
    concatenate into one valid GetRateLimitsResp. `md` (owner_data,
    owner_offsets aligned with local_out order) adds the GLOBAL
    non-owner metadata={"owner": ...} entries."""
    from gubernator_tpu.service import pb

    status, limit, remaining, reset_time = local_out
    local_set = set(int(i) for i in local_pos)
    chunks = []
    li = 0  # pointer into local arrays
    ni = 0  # pointer into nonlocal responses

    def flush_run(count):
        nonlocal li
        if count:
            s = slice(li - count, li)
            if md is not None:
                odata, ooffs = md
                sub = ooffs[li - count: li + 1]
                chunks.append(
                    wire.build_responses_md(
                        status[s], limit[s], remaining[s], reset_time[s],
                        odata[int(sub[0]): int(sub[-1])],
                        (sub - sub[0]).astype("int64"),
                    )
                )
                return
            chunks.append(
                wire.build_responses(
                    status[s], limit[s], remaining[s], reset_time[s]
                )
            )

    run = 0
    for i in range(n):
        if i in local_set:
            li += 1
            run += 1
        else:
            flush_run(run)
            run = 0
            body = pb.resp_to_pb(nonlocal_resps[ni]).SerializeToString()
            ni += 1
            chunks.append(b"\x0a" + _varint(len(body)) + body)
    flush_run(run)
    return b"".join(chunks)
