"""Columnar serving edge: bytes -> columns -> kernel -> bytes.

The object path (protobuf message -> dataclass -> pump -> demux) costs
~10-20µs of Python per request item; this path serves an entire
GetRateLimits/GetPeerRateLimits call with no per-item Python at all
(native wire parse, vectorized wave assembly, one jitted decide per
wave, native response build). It is an OPTIMIZATION, not a semantic
fork: every batch it cannot serve byte-identically falls back to the
object path (equivalence is fuzz-tested in tests/test_fastpath.py).

Fallback triggers:
- native library unavailable, malformed/empty/oversized batch;
- any item carrying metadata (trace context), GLOBAL or
  DURATION_IS_GREGORIAN behaviors, or failing validation (those need
  per-item error strings);
- a key this node does not own (peer forwarding), checked with the
  vectorized ring mask — GetPeerRateLimits skips this check because
  forwarded items are owned by construction;
- engine not eligible (Store attached, wave/lane overflow) — also a
  daemon with a Loader keeps the object path so the key-string
  dictionary stays complete for snapshots.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gubernator_tpu import wire
from gubernator_tpu.api.types import Behavior
from gubernator_tpu.parallel import hash_ring

MAX_BATCH_SIZE = 1000

_SLOW_BEHAVIOR = int(Behavior.GLOBAL) | int(Behavior.DURATION_IS_GREGORIAN)

_RING_VARIANT = {
    hash_ring.fnv1_64: "fnv1",
    hash_ring.fnv1a_64: "fnv1a",
}


import os

_DISABLED = os.environ.get("GUBER_DISABLE_FAST_EDGE", "") in ("1", "true")


def enabled(svc) -> bool:
    """Static eligibility for this service instance."""
    return (
        not _DISABLED
        and getattr(svc, "fast_edge", False)
        and wire.available()
        and hasattr(svc.engine, "check_columns")
    )


def try_serve(svc, data: bytes, peer_call: bool) -> Optional[bytes]:
    """Serve one call's raw request bytes columnar-fast, or None to fall
    back to the object path."""
    cols = wire.parse_requests(data)
    if cols is None or cols.n == 0 or cols.n > MAX_BATCH_SIZE:
        return None
    if cols.slow.any():
        return None
    if np.any((cols.behavior & _SLOW_BEHAVIOR) != 0):
        return None
    # Validation needs per-item error strings -> object path.
    key_lens = np.diff(cols.key_offsets)
    if np.any(cols.name_lens == 0) or np.any(
        key_lens - cols.name_lens - 1 == 0
    ):
        return None
    if not peer_call:
        picker = svc.picker
        if picker is not None and picker.peers():
            variant = _RING_VARIANT.get(getattr(picker, "hash_fn", None))
            if variant is None:
                return None
            hashes = wire.fnv1_batch(cols.key_data, cols.key_offsets, variant)
            if not picker.local_mask(hashes).all():
                return None  # at least one key is peer-owned
    try:
        out = svc.engine.check_columns(cols)
    except Exception:
        # Engine failure: fall back so the object path produces its
        # per-item error contract instead of an opaque RPC failure.
        return None
    if out is None:
        return None
    status, limit, remaining, reset_time = out
    m = getattr(svc, "_m_local", None)
    if m is not None:
        m.inc(cols.n)
    return wire.build_responses(status, limit, remaining, reset_time)
