"""Columnar serving edge: bytes -> columns -> kernel -> bytes.

The object path (protobuf message -> dataclass -> pump -> demux) costs
~10-20µs of Python per request item; this path serves an entire
GetRateLimits/GetPeerRateLimits call with no per-item Python at all
(native wire parse, vectorized wave assembly, one jitted decide per
wave, native response build). It is an OPTIMIZATION, not a semantic
fork: every batch it cannot serve byte-identically falls back to the
object path (equivalence is fuzz-tested in tests/test_fastpath.py).

Fallback triggers:
- native library unavailable, malformed/empty/oversized batch;
- any item carrying metadata (trace context), GLOBAL or
  DURATION_IS_GREGORIAN behaviors, or failing validation (those need
  per-item error strings);
- a key this node does not own (peer forwarding), checked with the
  vectorized ring mask — GetPeerRateLimits skips this check because
  forwarded items are owned by construction;
- engine not eligible (wave/lane overflow); a daemon with a Loader but
  no Store keeps the object path so the key-string dictionary stays
  complete for snapshots without columnar string-decode overhead.

A Store does NOT fall back: check_columns runs the object path's exact
per-wave sequence (probe -> read-through -> decide -> write-behind,
reference algorithms.go:45-51, 149-153) with request objects built only
for actual miss lanes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gubernator_tpu import wire
from gubernator_tpu.api.types import Behavior
from gubernator_tpu.parallel import hash_ring

MAX_BATCH_SIZE = 1000


def _committed_error():
    from gubernator_tpu.runtime.engine import TableCommittedError

    return TableCommittedError

_SLOW_BEHAVIOR = (
    int(Behavior.GLOBAL)
    | int(Behavior.DURATION_IS_GREGORIAN)
    # MULTI_REGION items need the object path's region_mgr.observe hook
    # (cross-region delta/broadcast queueing).
    | int(Behavior.MULTI_REGION)
)

_RING_VARIANT = {
    hash_ring.fnv1_64: "fnv1",
    hash_ring.fnv1a_64: "fnv1a",
    hash_ring.fnv1a_mix_64: "fnv1a-mix",
}


import os

_DISABLED = os.environ.get("GUBER_DISABLE_FAST_EDGE", "") in ("1", "true")


def enabled(svc) -> bool:
    """Static eligibility for this service instance."""
    return (
        not _DISABLED
        and getattr(svc, "fast_edge", False)
        and wire.available()
        and hasattr(svc.engine, "check_columns")
    )


def try_serve(svc, data: bytes, peer_call: bool):
    """Serve one call's raw request bytes columnar-fast.

    Returns:
    - bytes — the complete response (all items served columnar);
    - ("mixed", n, local_pos, local_arrays, nonlocal_reqs) — locally
      owned items already DECIDED columnar; the async caller forwards
      `nonlocal_reqs` through the object path and splices with
      merge_mixed() (V1 only; peer calls are all-local by construction);
    - None — fall back to the object path entirely.
    """
    cols = wire.parse_requests(data)
    if cols is None or cols.n == 0 or cols.n > MAX_BATCH_SIZE:
        return None
    if cols.slow.any():
        return None
    if np.any((cols.behavior & _SLOW_BEHAVIOR) != 0):
        return None
    # Validation needs per-item error strings -> object path.
    key_lens = np.diff(cols.key_offsets)
    if np.any(cols.name_lens == 0) or np.any(
        key_lens - cols.name_lens - 1 == 0
    ):
        return None
    local = None
    if not peer_call:
        picker = svc.picker
        if picker is not None and picker.peers():
            variant = _RING_VARIANT.get(getattr(picker, "hash_fn", None))
            if variant is None:
                return None
            ring_h = wire.fnv1_batch(cols.key_data, cols.key_offsets, variant)
            mask = picker.local_mask(ring_h)
            if not mask.all():
                local = np.asarray(mask, dtype=bool)
    if local is None:
        # NOTE: a failure BEFORE the table commits falls back safely;
        # a failure AFTER waves committed to a surviving table raises
        # TableCommittedError, which must propagate (a silent fallback
        # would re-apply every committed hit).
        try:
            out = svc.engine.check_columns(cols)
        except _committed_error():
            raise
        except Exception:
            return None
        if out is None:
            return None
        m = getattr(svc, "_m_local", None)
        if m is not None:
            m.inc(cols.n)
        return wire.build_responses(*out)
    if not local.any():
        return None  # nothing local to decide: pure forwarding batch
    # Mixed ownership: decide the local subset columnar now (with the
    # identity hashes computed once over the full batch); hand the
    # peer-owned subset back as objects for the forwarding path. The
    # request objects build BEFORE the decide so a construction failure
    # cannot strand already-committed hits.
    from gubernator_tpu import native as _native

    local_pos = np.nonzero(local)[0]
    nonlocal_pos = np.nonzero(~local)[0]
    nonlocal_reqs = [_req_from_columns(cols, int(i)) for i in nonlocal_pos]
    hashes = _native.hash128_batch_raw(
        cols.key_data.tobytes(), cols.key_offsets,
        svc.engine.cfg.num_groups,
    )
    try:
        out = svc.engine.check_columns(cols, select=local_pos, hashes=hashes)
    except _committed_error():
        raise
    except Exception:
        return None
    if out is None:
        return None
    m = getattr(svc, "_m_local", None)
    if m is not None:
        m.inc(len(local_pos))
    return ("mixed", cols.n, local_pos, out, nonlocal_reqs)


def _req_from_columns(cols, i: int):
    """RateLimitReq object for one (peer-owned) lane — the forwarding
    path needs objects; only the non-local fraction pays this cost."""
    return wire.req_from_columns(cols, i)


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def merge_mixed(n: int, local_pos, local_out, nonlocal_resps) -> bytes:
    """Splice columnar-decided local items with forwarded object-path
    responses, preserving request order. Repeated message items frame
    independently, so native-built runs and protobuf-serialized items
    concatenate into one valid GetRateLimitsResp."""
    from gubernator_tpu.service import pb

    status, limit, remaining, reset_time = local_out
    local_set = set(int(i) for i in local_pos)
    chunks = []
    li = 0  # pointer into local arrays
    ni = 0  # pointer into nonlocal responses

    def flush_run(count):
        nonlocal li
        if count:
            s = slice(li - count, li)
            chunks.append(
                wire.build_responses(
                    status[s], limit[s], remaining[s], reset_time[s]
                )
            )

    run = 0
    for i in range(n):
        if i in local_set:
            li += 1
            run += 1
        else:
            flush_run(run)
            run = 0
            body = pb.resp_to_pb(nonlocal_resps[ni]).SerializeToString()
            ni += 1
            chunks.append(b"\x0a" + _varint(len(body)) + body)
    flush_run(run)
    return b"".join(chunks)
