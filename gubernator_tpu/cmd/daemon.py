"""Daemon entry point: `python -m gubernator_tpu.cmd.daemon [--config f]`
(reference cmd/gubernator/main.go:41-100)."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def main() -> None:
    import json
    import os

    parser = argparse.ArgumentParser(description="gubernator-tpu daemon")
    parser.add_argument("--config", default=None, help="KEY=VALUE config file")
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args()

    # GUBER_LOG_LEVEL / GUBER_LOG_FORMAT=json (reference config.go:286-310)
    level_name = os.environ.get("GUBER_LOG_LEVEL", "").upper()
    level = (
        logging.DEBUG
        if args.debug
        else getattr(logging, level_name, logging.INFO)
    )
    if os.environ.get("GUBER_LOG_FORMAT", "").lower() == "json":

        class _Json(logging.Formatter):
            def format(self, record):
                return json.dumps(
                    {
                        "ts": self.formatTime(record),
                        "level": record.levelname.lower(),
                        "logger": record.name,
                        "msg": record.getMessage(),
                    }
                )

        handler = logging.StreamHandler()
        handler.setFormatter(_Json())
        logging.basicConfig(level=level, handlers=[handler])
    else:
        logging.basicConfig(
            level=level, format="%(asctime)s %(levelname)s %(name)s %(message)s"
        )

    from gubernator_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.envconfig import setup_daemon_config

    conf = setup_daemon_config(args.config)

    async def run() -> None:
        d = await Daemon.spawn(conf)
        logging.info(
            "gubernator-tpu listening: grpc=%s http=%s", d.grpc_address, d.http_address
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        logging.info("shutting down")
        await d.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
