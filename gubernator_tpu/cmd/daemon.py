"""Daemon entry point: `python -m gubernator_tpu.cmd.daemon [--config f]`
(reference cmd/gubernator/main.go:41-100)."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def main() -> None:
    import json
    import os

    parser = argparse.ArgumentParser(description="gubernator-tpu daemon")
    parser.add_argument("--config", default=None, help="KEY=VALUE config file")
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args()

    from gubernator_tpu.utils.compilecache import enable_compile_cache
    from gubernator_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()
    # Persistent XLA cache: a restarted daemon deserializes its decide
    # kernels instead of recompiling (~123s cold on TPU) — serving within
    # seconds of exec, like the reference's Go daemon.
    enable_compile_cache()

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.envconfig import setup_daemon_config

    # Config FIRST so --config file keys (injected into the env) are seen
    # by the log settings too (reference config.go:268-310 order).
    conf = setup_daemon_config(args.config)

    # GUBER_LOG_LEVEL / GUBER_LOG_FORMAT=json / GUBER_DEBUG or --debug
    # (reference config.go:286-310)
    level = (
        logging.DEBUG
        if args.debug or conf.debug
        else getattr(logging, conf.log_level.upper(), logging.INFO)
    )
    if conf.log_format.lower() == "json":

        class _Json(logging.Formatter):
            def format(self, record):
                return json.dumps(
                    {
                        "ts": self.formatTime(record),
                        "level": record.levelname.lower(),
                        "logger": record.name,
                        "msg": record.getMessage(),
                    }
                )

        handler = logging.StreamHandler()
        handler.setFormatter(_Json())
        logging.basicConfig(level=level, handlers=[handler])
    else:
        logging.basicConfig(
            level=level, format="%(asctime)s %(levelname)s %(name)s %(message)s"
        )

    # Span verbosity is process-global, so only the CLI entry point sets
    # it (GUBER_TRACING_LEVEL; reference config.go:717-752).
    from gubernator_tpu.utils import tracing

    tracing.set_trace_level(conf.trace_level)

    async def run() -> None:
        d = await Daemon.spawn(conf)
        logging.info(
            "gubernator-tpu listening: grpc=%s http=%s", d.grpc_address, d.http_address
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        # Graceful drain, not teardown (docs/robustness.md): /readyz
        # flips to `draining`, in-flight RPCs and the engine queue finish
        # inside GUBER_DRAIN_TIMEOUT, replication queues flush, owned
        # keys hand off to ring successors, THEN the listeners die.
        logging.info(
            "signal received: draining (budget %.1fs) — queues flush and "
            "owned keys hand off before teardown",
            getattr(conf, "drain_timeout_s", 5.0),
        )
        await d.close()
        logging.info("drain complete; daemon stopped")

    asyncio.run(run())


if __name__ == "__main__":
    main()
