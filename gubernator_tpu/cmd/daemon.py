"""Daemon entry point: `python -m gubernator_tpu.cmd.daemon [--config f]`
(reference cmd/gubernator/main.go:41-100)."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def main() -> None:
    parser = argparse.ArgumentParser(description="gubernator-tpu daemon")
    parser.add_argument("--config", default=None, help="KEY=VALUE config file")
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.DEBUG if args.debug else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    from gubernator_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()

    from gubernator_tpu.service.daemon import Daemon
    from gubernator_tpu.service.envconfig import setup_daemon_config

    conf = setup_daemon_config(args.config)

    async def run() -> None:
        d = await Daemon.spawn(conf)
        logging.info(
            "gubernator-tpu listening: grpc=%s http=%s", d.grpc_address, d.http_address
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        logging.info("shutting down")
        await d.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
