"""Container healthcheck probe: `python -m gubernator_tpu.cmd.healthcheck`
(reference cmd/healthcheck/main.go): GET /v1/HealthCheck, exit 0 iff
healthy.

Exit codes:
    0  healthy
    1  unhealthy / unreachable — orchestrators may restart the pod
    2  draining — graceful shutdown in progress: stop routing, do NOT
       kill early (queued work is finishing and owned keys are handing
       off to ring successors; docs/robustness.md)

Address resolution (first match wins):
    --url                              explicit probe URL
    GUBER_STATUS_HTTP_ADDRESS          the no-mTLS status listener exists
    (alias GUBER_STATUS_LISTEN_ADDRESS) precisely for probes — an mTLS
                                       deployment's main gateway would
                                       reject a certless probe
    GUBER_HTTP_ADDRESS                 main HTTP gateway
    127.0.0.1:80                       reference default
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request


def default_url() -> str:
    addr = (
        os.environ.get("GUBER_STATUS_HTTP_ADDRESS")
        or os.environ.get("GUBER_STATUS_LISTEN_ADDRESS")
        or os.environ.get("GUBER_HTTP_ADDRESS")
        or "127.0.0.1:80"
    )
    return f"http://{addr}/v1/HealthCheck"


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--url", default=default_url())
    p.add_argument(
        "--timeout",
        type=float,
        default=5.0,
        help="probe timeout in seconds (default 5)",
    )
    args = p.parse_args(argv)
    try:
        with urllib.request.urlopen(args.url, timeout=args.timeout) as resp:
            body = json.loads(resp.read())
    except Exception as e:
        print(f"unhealthy: {e}", file=sys.stderr)
        return 1
    status = body.get("status")
    if status == "draining":
        # Distinct from unhealthy: the node is leaving on purpose.
        # Stop routing; don't kill the pod before the drain finishes.
        print(f"draining: {body}", file=sys.stderr)
        return 2
    if status != "healthy":
        print(f"unhealthy: {body}", file=sys.stderr)
        return 1
    print("healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
