"""Container healthcheck probe: `python -m gubernator_tpu.cmd.healthcheck`
(reference cmd/healthcheck/main.go): GET /v1/HealthCheck, exit 0 iff
healthy."""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--url",
        default=f"http://{os.environ.get('GUBER_HTTP_ADDRESS', '127.0.0.1:80')}/v1/HealthCheck",
    )
    args = p.parse_args()
    try:
        with urllib.request.urlopen(args.url, timeout=5) as resp:
            body = json.loads(resp.read())
    except Exception as e:
        print(f"unhealthy: {e}", file=sys.stderr)
        return 1
    if body.get("status") != "healthy":
        print(f"unhealthy: {body}", file=sys.stderr)
        return 1
    print("healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
