"""Edge-tier entry point: `python -m gubernator_tpu.cmd.edge`.

A lightweight, jax-free process that terminates client gRPC and relays
every call over framed RPC to a device daemon (service/edge.py). Run N
of these per device daemon to scale the serving tier horizontally — the
wire API is identical to the daemon's own gRPC listener, so clients and
load balancers cannot tell edge from daemon.

Env:
    GUBER_GRPC_ADDRESS        gRPC listen address (default 127.0.0.1:81)
    GUBER_HTTP_ADDRESS        HTTP/JSON listen address ("" = disabled)
    GUBER_EDGE_UPSTREAM       device daemon's GUBER_EDGE_LISTEN_ADDRESS
                              (unix:///path or host:port; required)
    GUBER_EDGE_CONNECTIONS    upstream connections (default 2)
    GUBER_EDGE_RETRIES        budgeted upstream retries per call
                              (default 2; 0 = pure single-shot relay)
    GUBER_RETRY_BUDGET        retry-budget refill ratio (default 0.1)
    GUBER_LEASES              serve leased keys locally (zero upstream
                              frames on the hot path); the daemon must
                              also run with GUBER_LEASES=true
    GUBER_LEASE_LOW_WATER     renew when a slice falls below this
                              fraction (default 0.25)
    GUBER_LEASE_MAX_KEYS      max cached lease entries (default 4096)
    GUBER_LOG_LEVEL
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from gubernator_tpu.utils.net import parse_listen_address


def main() -> None:
    parser = argparse.ArgumentParser(description="gubernator-tpu edge")
    parser.add_argument("--debug", action="store_true")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.DEBUG if args.debug else getattr(
            logging, os.environ.get("GUBER_LOG_LEVEL", "info").upper(),
            logging.INFO,
        ),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    upstream = os.environ.get("GUBER_EDGE_UPSTREAM", "")
    if not upstream:
        raise SystemExit(
            "GUBER_EDGE_UPSTREAM must point at a device daemon's "
            "GUBER_EDGE_LISTEN_ADDRESS"
        )
    listen = os.environ.get("GUBER_GRPC_ADDRESS", "127.0.0.1:81")
    http_listen = os.environ.get("GUBER_HTTP_ADDRESS", "")
    if http_listen:
        # An empty host (":8080") binds all interfaces, Go-style
        # (ADVICE r4: rejecting it was a behavior regression).
        try:
            hhost, hport = parse_listen_address(http_listen)
        except ValueError:
            hport = 0
        if hport == 0:
            raise SystemExit(
                "GUBER_HTTP_ADDRESS must be [host]:port with an explicit "
                f"port (edges are load-balancer targets), got {http_listen!r}"
            )
    n_conns = int(os.environ.get("GUBER_EDGE_CONNECTIONS", "2"))

    async def run() -> None:
        import grpc

        from gubernator_tpu.metrics import Metrics
        from gubernator_tpu.service.edge import (
            EdgeClient,
            EdgeLeases,
            EdgeV1Servicer,
            build_edge_app,
            edge_v1_handler,
        )
        from gubernator_tpu.service.envconfig import parse_duration_s
        from gubernator_tpu.utils import faults

        faults.configure_from_env()
        metrics = Metrics()
        client = EdgeClient(
            upstream,
            connections=n_conns,
            timeout_s=parse_duration_s(
                os.environ.get("GUBER_EDGE_TIMEOUT", ""), 30.0
            ),
            timeout_counter=metrics.edge_call_timeouts,
            # knob: GUBER_EDGE_RETRIES — budgeted UNAVAILABLE retries +
            # one shed re-dispatch per call; 0 restores the pure relay.
            retries=int(os.environ.get("GUBER_EDGE_RETRIES", "") or 2),
            # knob: GUBER_RETRY_BUDGET (same ratio the daemon and the
            # client SDK use — docs/robustness.md retry-budget math)
            retry_budget=float(
                os.environ.get("GUBER_RETRY_BUDGET", "") or 0.1
            ),
        )
        leases = None
        # knob: GUBER_LEASES (same switch as the daemon's — an edge only
        # holds leases when the upstream daemon grants them)
        if os.environ.get("GUBER_LEASES", "").strip().lower() in (
            "1", "true", "yes", "on",
        ):
            from gubernator_tpu.parallel.leases import LeaseCache
            from gubernator_tpu.service.admission import DecisionRecorder

            leases = EdgeLeases(
                client,
                LeaseCache(
                    # knob: GUBER_LEASE_LOW_WATER
                    low_water=float(
                        os.environ.get("GUBER_LEASE_LOW_WATER", "") or 0.25
                    ),
                    # knob: GUBER_LEASE_MAX_KEYS
                    max_keys=int(
                        os.environ.get("GUBER_LEASE_MAX_KEYS", "") or 4096
                    ),
                ),
                holder=f"edge:{listen}",
                local_counter=metrics.lease_local_answers,
                # knob: GUBER_ADMISSION_RING (decision flight recorder)
                recorder=DecisionRecorder(
                    metrics,
                    ring_size=int(
                        os.environ.get("GUBER_ADMISSION_RING", "") or 256
                    ),
                ),
            )
        server = grpc.aio.server()
        server.add_generic_rpc_handlers(
            (edge_v1_handler(EdgeV1Servicer(client, leases=leases)),)
        )
        port = server.add_insecure_port(listen)
        await server.start()
        http_runner = None
        if http_listen:
            from aiohttp import web

            http_runner = web.AppRunner(
                build_edge_app(client, metrics=metrics, leases=leases)
            )
            await http_runner.setup()
            site = web.TCPSite(http_runner, hhost, hport)
            await site.start()
            from gubernator_tpu.utils.net import recorded_address

            logging.info(
                "edge http listening on %s", recorded_address(hhost, hport)
            )
        logging.info(
            "gubernator-tpu edge listening on %s -> upstream %s",
            listen.rsplit(":", 1)[0] + f":{port}", upstream,
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        logging.info("edge shutting down")
        await server.stop(grace=0.5)
        if leases is not None:
            await leases.close()  # return held slices before the pipe dies
        if http_runner is not None:
            await http_runner.cleanup()
        await client.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
