"""Load-generating client CLI: `python -m gubernator_tpu.cmd.cli`
(reference cmd/gubernator-cli/main.go)."""

from __future__ import annotations

import argparse
import asyncio
import random
import string
import time


def main() -> None:
    p = argparse.ArgumentParser(description="gubernator-tpu client CLI")
    p.add_argument("address", help="daemon gRPC address host:port")
    p.add_argument("--rate", type=int, default=100, help="requests/s")
    p.add_argument("--duration", type=float, default=5.0, help="seconds")
    p.add_argument("--concurrency", type=int, default=10)
    p.add_argument("--keys", type=int, default=100, help="unique key count")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--algorithm", type=int, default=0, choices=(0, 1))
    p.add_argument("--behavior", type=int, default=0)
    args = p.parse_args()

    import grpc

    from gubernator_tpu.service import pb
    from gubernator_tpu.service.rpc import V1Stub

    name = "cli_" + "".join(random.choices(string.ascii_lowercase, k=6))

    async def run() -> None:
        channel = grpc.aio.insecure_channel(args.address)
        stub = V1Stub(channel)
        stats = {"ok": 0, "over": 0, "err": 0}
        deadline = time.monotonic() + args.duration
        interval = args.concurrency / max(args.rate, 1)

        async def worker():
            while time.monotonic() < deadline:
                msg = pb.pb.GetRateLimitsReq()
                msg.requests.append(
                    pb.pb.RateLimitReq(
                        name=name,
                        unique_key=f"key:{random.randrange(args.keys)}",
                        algorithm=args.algorithm,
                        behavior=args.behavior,
                        duration=10_000,
                        limit=args.limit,
                        hits=1,
                    )
                )
                try:
                    resp = await stub.get_rate_limits(msg, timeout=5)
                    r = resp.responses[0]
                    if r.error:
                        stats["err"] += 1
                    elif r.status == 1:
                        stats["over"] += 1
                    else:
                        stats["ok"] += 1
                except Exception:
                    stats["err"] += 1
                await asyncio.sleep(interval)

        t0 = time.monotonic()
        await asyncio.gather(*(worker() for _ in range(args.concurrency)))
        dt = time.monotonic() - t0
        total = sum(stats.values())
        print(
            f"{total} requests in {dt:.2f}s ({total / dt:.0f}/s): "
            f"{stats['ok']} under, {stats['over']} over, {stats['err']} errors"
        )
        await channel.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
