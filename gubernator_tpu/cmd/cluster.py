"""Local cluster binary: `python -m gubernator_tpu.cmd.cluster -n 4`
(reference cmd/gubernator-cluster/main.go — used by cross-language client
smoke tests, reference python/tests/test_client.py:25-37)."""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys


def main() -> None:
    p = argparse.ArgumentParser(description="in-process gubernator-tpu cluster")
    p.add_argument("-n", "--nodes", type=int, default=4)
    p.add_argument("--cache-size", type=int, default=8192)
    args = p.parse_args()

    from gubernator_tpu.utils.compilecache import enable_compile_cache
    from gubernator_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()
    enable_compile_cache()

    from gubernator_tpu.cluster import Cluster

    async def run() -> None:
        c = await Cluster.start(args.nodes, cache_size=args.cache_size)
        info = [
            {"grpc": d.grpc_address, "http": d.http_address} for d in c.daemons
        ]
        # One ready line on stdout for parent processes to parse.
        print("READY " + json.dumps(info), flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await c.stop()

    asyncio.run(run())


if __name__ == "__main__":
    sys.exit(main())
