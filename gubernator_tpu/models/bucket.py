"""Bucket state records and the exact integer arithmetic of the spec.

The reference keeps per-key mutable state in two structs
(reference store.go:29-43): `TokenBucketItem{Status, Limit, Duration,
Remaining, CreatedAt}` and `LeakyBucketItem{Limit, Duration, Remaining
float64, UpdatedAt, Burst}`.

Design decision (TPU-first): the leaky bucket's fractional `Remaining` is
kept in **Q44.20 fixed point** (int64, scale 2^20 ≈ 1e-6 token resolution)
instead of float64. TPUs have no native f64, and fixed point makes the
device kernel, the host oracle, and every replica bit-identical — a feature
for a distributed system that the reference's float64 math does not have.
All observable semantics (truncation to whole tokens, leak-accrual
threshold, burst clamping) match the reference's float64 behavior except
within 2^-20 of a token boundary.

`leak_fixed` is the one nontrivial op: floor(elapsed * limit * SCALE /
rate_num) computed without 128-bit intermediates, so the identical sequence
of int64 ops runs inside the XLA kernel (ops/decide.py) and in this pure
Python spec. Its exactness (vs bignum) is unit-tested in
tests/test_fixedpoint.py over the validated input domain.
"""

from __future__ import annotations

from dataclasses import dataclass

from gubernator_tpu.api.types import Status

# Fixed-point scale for leaky-bucket fractional remaining.
FIXED_SHIFT = 20
FIXED_ONE = 1 << FIXED_SHIFT

# Validated input domain (enforced host-side in batch assembly). Within
# these bounds every intermediate in leak_fixed fits in int64.
MAX_ELAPSED_MS = 1 << 42  # ~139 years
MAX_DURATION_MS = 1 << 42
MAX_COUNT = (1 << 31) - 1  # limit, burst, |hits|


@dataclass
class TokenBucketState:
    """Mutable token-bucket counter (reference store.go:36-43)."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    duration: int = 0
    remaining: int = 0
    created_at: int = 0


@dataclass
class LeakyBucketState:
    """Mutable leaky-bucket counter (reference store.go:29-34).

    `remaining_s` is Q44.20 fixed point (whole tokens = remaining_s >>
    FIXED_SHIFT, matching the reference's int64(b.Remaining) truncation).
    """

    limit: int = 0
    duration: int = 0
    remaining_s: int = 0
    updated_at: int = 0
    burst: int = 0


def leak_fixed(elapsed: int, limit: int, rate_num: int, burst: int) -> int:
    """Fixed-point leak accrual: min(floor(elapsed*limit*2^20 / rate_num),
    (burst+1) << 20), for elapsed >= 0.

    The reference computes `leak = float64(elapsed) / rate` with
    `rate = rate_num / limit` (reference algorithms.go:336, 360-362). The
    result is saturated just above `burst` because the caller clamps
    remaining to burst immediately after accrual (algorithms.go:369-371),
    so any leak >= burst+1 tokens is observationally equivalent.

    Every intermediate fits int64 when elapsed <= 2^42, rate_num <= 2^42,
    limit <= 2^31, burst <= 2^31 — the same ops run under jit in the
    device kernel. Division is by-parts (16-bit split of `limit`) to avoid
    the 128-bit product elapsed*limit*2^20.
    """
    if elapsed <= 0:
        return 0
    limit_g = max(limit, 1)
    rate_num = max(rate_num, 1)  # duration 0 => immediate full refill
    cap_t = burst + 1

    e_c = min(elapsed, MAX_ELAPSED_MS)
    a = e_c // rate_num  # whole rate-periods elapsed
    e = e_c % rate_num  # partial period, < rate_num

    # Whole-period token credit a*limit, saturated at cap_t.
    a_lim = cap_t // limit_g + 1
    a_c = min(a, a_lim)
    whole = a_c * limit  # <= cap_t + 2*limit, fits easily
    saturated = (a > a_lim) | (whole >= cap_t)

    # Partial-period credit: floor(e*limit / rate_num) tokens + fixed frac.
    hi = limit >> 16
    lo = limit & 0xFFFF
    p1 = e * hi
    q1, r1 = divmod(p1, rate_num)
    q2, r2 = divmod(r1 << 16, rate_num)
    p2 = e * lo
    q3, r3 = divmod(r2 + p2, rate_num)
    tok = (q1 << 16) + q2 + q3  # == e*limit // rate_num exactly
    frac_s = (r3 << FIXED_SHIFT) // rate_num

    cap_s = cap_t << FIXED_SHIFT
    if saturated:
        return cap_s
    leak_s = ((whole + tok) << FIXED_SHIFT) + frac_s
    return min(leak_s, cap_s)


def rate_int(rate_num: int, limit: int) -> int:
    """int64(rate) where rate = rate_num/limit (reference
    algorithms.go:336, 377). Guarded against limit==0 (the reference
    produces +Inf there; tests never exercise it)."""
    return rate_num // max(limit, 1)
