"""Reference oracle: the behavioral spec of the decide step.

A dict-backed, sequential, pure-Python engine implementing the exact
observable semantics of the reference's hot path (reference
algorithms.go:37-493, cache.go:43-57, gubernator.go:183-309). It exists to

1. pin the semantics with transcribed golden tests (tests/test_oracle_*),
2. serve as the fuzz target the vectorized TPU kernel must match bit-for-bit,
3. document every branch the kernel has to reproduce as masked vector ops.

Branch order is deliberately identical to the reference, including its
quirks (sticky token-bucket Status, the stale-response path when a duration
change renews an expired item, over-limit rejections not consuming hits,
new-item rate computed from the raw duration field under Gregorian).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    has_behavior,
    validate_request,
    MAX_BATCH_SIZE,
)
from gubernator_tpu.models.bucket import (
    FIXED_SHIFT,
    LeakyBucketState,
    TokenBucketState,
    leak_fixed,
    rate_int,
)
from gubernator_tpu.utils import gregorian as greg


def _i64(x: int) -> int:
    """Wrap to int64 like Go's arithmetic (and the kernel's): the spec is
    bug-for-bug at adversarial extremes where products overflow."""
    x &= (1 << 64) - 1
    return x - (1 << 64) if x >= (1 << 63) else x


@dataclass
class CacheEntry:
    """Host-side mirror of the reference CacheItem (reference cache.go:29-41)."""

    algorithm: int
    key: str
    value: object
    expire_at: int = 0
    invalid_at: int = 0

    def is_expired(self, now: int) -> bool:
        # reference cache.go:43-57
        if self.invalid_at != 0 and self.invalid_at < now:
            return True
        return self.expire_at < now


class OracleEngine:
    """Sequential in-memory rate limiter with exact reference semantics."""

    def __init__(self, store=None):
        self.cache: Dict[str, CacheEntry] = {}
        self.store = store  # optional Store plugin (read-through/write-behind)

    # -- public API ---------------------------------------------------------

    def get_rate_limits(
        self, reqs: List[RateLimitReq], now_ms: int, is_owner: bool = True
    ) -> List[RateLimitResp]:
        if len(reqs) > MAX_BATCH_SIZE:
            raise ValueError(
                f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'"
            )
        out = []
        for r in reqs:
            err = validate_request(r)
            if err is not None:
                out.append(RateLimitResp(error=err))
                continue
            out.append(self.decide(r, now_ms, is_owner))
        return out

    def decide(
        self, r: RateLimitReq, now_ms: int, is_owner: bool = True
    ) -> RateLimitResp:
        if r.created_at is None:
            r.created_at = now_ms
        if r.algorithm == Algorithm.LEAKY_BUCKET:
            return self._leaky_bucket(r, now_ms, is_owner)
        return self._token_bucket(r, now_ms, is_owner)

    # -- cache access with lazy expiry --------------------------------------

    def _get(self, r: RateLimitReq, now_ms: int) -> Optional[CacheEntry]:
        key = r.hash_key()
        item = self.cache.get(key)
        if item is not None and item.is_expired(now_ms):
            # lazy removal on read (reference lrucache.go:111-128)
            del self.cache[key]
            item = None
        if item is None and self.store is not None:
            # read-through on cache miss (reference algorithms.go:45-51)
            item = self.store.get(r)
            if item is not None:
                self.cache[item.key] = item
        return item

    def _remove(self, key: str) -> None:
        self.cache.pop(key, None)
        if self.store is not None:
            self.store.remove(key)

    def _on_change(self, r: RateLimitReq, item: CacheEntry, is_owner: bool) -> None:
        # write-behind (reference algorithms.go:149-153, 252-254, 488-490)
        if self.store is not None and is_owner:
            self.store.on_change(r, item)

    # -- token bucket (reference algorithms.go:37-257) -----------------------

    def _token_bucket(
        self, r: RateLimitReq, now_ms: int, is_owner: bool
    ) -> RateLimitResp:
        key = r.hash_key()
        item = self._get(r, now_ms)

        if item is not None:
            if has_behavior(r.behavior, Behavior.RESET_REMAINING):
                # reference algorithms.go:78-90
                self._remove(key)
                return RateLimitResp(
                    status=Status.UNDER_LIMIT,
                    limit=r.limit,
                    remaining=r.limit,
                    reset_time=0,
                )
            if item.algorithm != Algorithm.TOKEN_BUCKET:
                # algorithm switch resets state (reference algorithms.go:91-103)
                self._remove(key)
                return self._token_bucket_new_item(r, now_ms, is_owner)

            t: TokenBucketState = item.value

            # Limit hot-change: credit/debit the difference
            # (reference algorithms.go:105-113).
            if t.limit != r.limit:
                t.remaining += r.limit - t.limit
                if t.remaining < 0:
                    t.remaining = 0
                t.limit = r.limit

            rl = RateLimitResp(
                status=t.status,
                limit=r.limit,
                remaining=t.remaining,
                reset_time=item.expire_at,
            )

            # Duration hot-change, possibly renewing an expired-by-new-rules
            # item (reference algorithms.go:122-147). Note the reference does
            # NOT refresh rl.remaining after a renewal — preserved here.
            if t.duration != r.duration:
                expire = t.created_at + r.duration
                if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
                    expire = greg.gregorian_expiration(now_ms, r.duration)
                created_at = r.created_at
                if expire <= created_at:
                    expire = created_at + r.duration
                    t.created_at = created_at
                    t.remaining = t.limit
                item.expire_at = expire
                t.duration = r.duration
                rl.reset_time = expire

            self._on_change(r, item, is_owner)

            # Status/config read only (reference algorithms.go:157-159).
            if r.hits == 0:
                return rl

            # Already at the limit (reference algorithms.go:162-170).
            # Sticky: stored status flips to OVER_LIMIT.
            if rl.remaining == 0 and r.hits > 0:
                rl.status = Status.OVER_LIMIT
                t.status = Status.OVER_LIMIT
                return rl

            # Exact drain (reference algorithms.go:173-178).
            if t.remaining == r.hits:
                t.remaining = 0
                rl.remaining = 0
                return rl

            # Over the limit: reject WITHOUT consuming, unless
            # DRAIN_OVER_LIMIT (reference algorithms.go:182-194).
            if r.hits > t.remaining:
                rl.status = Status.OVER_LIMIT
                if has_behavior(r.behavior, Behavior.DRAIN_OVER_LIMIT):
                    t.remaining = 0
                    rl.remaining = 0
                return rl

            t.remaining -= r.hits
            rl.remaining = t.remaining
            return rl

        return self._token_bucket_new_item(r, now_ms, is_owner)

    def _token_bucket_new_item(
        self, r: RateLimitReq, now_ms: int, is_owner: bool
    ) -> RateLimitResp:
        # reference algorithms.go:206-257
        created_at = r.created_at
        expire = created_at + r.duration
        t = TokenBucketState(
            status=Status.UNDER_LIMIT,
            limit=r.limit,
            duration=r.duration,
            remaining=r.limit - r.hits,
            created_at=created_at,
        )
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            expire = greg.gregorian_expiration(now_ms, r.duration)

        rl = RateLimitResp(
            status=Status.UNDER_LIMIT,
            limit=r.limit,
            remaining=t.remaining,
            reset_time=expire,
        )

        # First request already over the limit: do not consume; note the
        # stored status stays UNDER_LIMIT (reference algorithms.go:240-248).
        if r.hits > r.limit:
            rl.status = Status.OVER_LIMIT
            rl.remaining = r.limit
            t.remaining = r.limit

        item = CacheEntry(
            algorithm=Algorithm.TOKEN_BUCKET, key=r.hash_key(), value=t, expire_at=expire
        )
        self.cache[item.key] = item
        self._on_change(r, item, is_owner)
        return rl

    # -- leaky bucket (reference algorithms.go:260-493) -----------------------

    def _leaky_bucket(
        self, r: RateLimitReq, now_ms: int, is_owner: bool
    ) -> RateLimitResp:
        if r.burst == 0:
            r.burst = r.limit  # reference algorithms.go:264-266
        created_at = r.created_at
        key = r.hash_key()
        item = self._get(r, now_ms)

        if item is not None:
            if item.algorithm != Algorithm.LEAKY_BUCKET:
                # reference algorithms.go:308-318
                self._remove(key)
                return self._leaky_bucket_new_item(r, now_ms, is_owner)

            b: LeakyBucketState = item.value

            if has_behavior(r.behavior, Behavior.RESET_REMAINING):
                b.remaining_s = r.burst << FIXED_SHIFT  # algorithms.go:320-322

            # Burst hot-change (reference algorithms.go:325-330).
            if b.burst != r.burst:
                if r.burst > (b.remaining_s >> FIXED_SHIFT):
                    b.remaining_s = r.burst << FIXED_SHIFT
                b.burst = r.burst

            b.limit = r.limit
            b.duration = r.duration  # algorithms.go:332-333

            duration = r.duration
            rate_num = duration  # rate = rate_num / limit
            if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
                # Rate uses the full Gregorian interval; effective duration
                # runs to the end of the interval (algorithms.go:338-354).
                rate_num = greg.gregorian_duration(now_ms, r.duration)
                expire = greg.gregorian_expiration(now_ms, r.duration)
                duration = expire - now_ms

            if r.hits != 0:
                item.expire_at = created_at + duration  # algorithms.go:356-358

            # Leak accrual since last update (algorithms.go:360-367).
            elapsed = created_at - b.updated_at
            leak_s = leak_fixed(elapsed, r.limit, rate_num, b.burst)
            if (leak_s >> FIXED_SHIFT) > 0:
                b.remaining_s += leak_s
                b.updated_at = created_at

            # Burst clamp (algorithms.go:369-371) — unconditional.
            if (b.remaining_s >> FIXED_SHIFT) > b.burst:
                b.remaining_s = b.burst << FIXED_SHIFT

            ri = rate_int(rate_num, r.limit)
            rem = b.remaining_s >> FIXED_SHIFT
            rl = RateLimitResp(
                status=Status.UNDER_LIMIT,
                limit=b.limit,
                remaining=rem,
                reset_time=_i64(created_at + (b.limit - rem) * ri),
            )

            self._on_change(r, item, is_owner)

            # Already at the limit (algorithms.go:389-395).
            if rem == 0 and r.hits > 0:
                rl.status = Status.OVER_LIMIT
                return rl

            # Exact drain — note this precedes the hits==0 check, so a
            # status read with zero remaining truncates the stored fraction
            # (algorithms.go:398-403).
            if rem == r.hits:
                b.remaining_s = 0
                rl.remaining = 0
                rl.reset_time = _i64(created_at + (rl.limit - 0) * ri)
                return rl

            # Over the limit: no consumption unless DRAIN_OVER_LIMIT
            # (algorithms.go:407-420).
            if r.hits > rem:
                rl.status = Status.OVER_LIMIT
                if has_behavior(r.behavior, Behavior.DRAIN_OVER_LIMIT):
                    b.remaining_s = 0
                    rl.remaining = 0
                return rl

            # Status read (algorithms.go:423-425).
            if r.hits == 0:
                return rl

            b.remaining_s -= r.hits << FIXED_SHIFT
            rl.remaining = b.remaining_s >> FIXED_SHIFT
            rl.reset_time = _i64(created_at + (rl.limit - rl.remaining) * ri)
            return rl

        return self._leaky_bucket_new_item(r, now_ms, is_owner)

    def _leaky_bucket_new_item(
        self, r: RateLimitReq, now_ms: int, is_owner: bool
    ) -> RateLimitResp:
        # reference algorithms.go:437-493. NOTE: the reference computes
        # `rate` from the raw duration field BEFORE the Gregorian override,
        # so under DURATION_IS_GREGORIAN the new-item rate is effectively 0
        # (duration holds the interval enum 0..5) — preserved bug-for-bug.
        created_at = r.created_at
        duration = r.duration
        ri = rate_int(duration, r.limit)
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            expire = greg.gregorian_expiration(now_ms, r.duration)
            duration = expire - now_ms

        b = LeakyBucketState(
            limit=r.limit,
            duration=duration,
            remaining_s=(r.burst - r.hits) << FIXED_SHIFT,
            updated_at=created_at,
            burst=r.burst,
        )
        rl = RateLimitResp(
            status=Status.UNDER_LIMIT,
            limit=b.limit,
            remaining=r.burst - r.hits,
            reset_time=_i64(created_at + (b.limit - (r.burst - r.hits)) * ri),
        )

        # First request over the burst (reference algorithms.go:469-477).
        if r.hits > r.burst:
            rl.status = Status.OVER_LIMIT
            rl.remaining = 0
            rl.reset_time = _i64(created_at + (rl.limit - 0) * ri)
            b.remaining_s = 0

        item = CacheEntry(
            algorithm=Algorithm.LEAKY_BUCKET,
            key=r.hash_key(),
            value=b,
            expire_at=created_at + duration,
        )
        self.cache[item.key] = item
        self._on_change(r, item, is_owner)
        return rl
