from gubernator_tpu.models.bucket import (
    FIXED_SHIFT,
    FIXED_ONE,
    LeakyBucketState,
    TokenBucketState,
    leak_fixed,
    rate_int,
)
from gubernator_tpu.models.oracle import CacheEntry, OracleEngine

__all__ = [
    "FIXED_SHIFT",
    "FIXED_ONE",
    "LeakyBucketState",
    "TokenBucketState",
    "leak_fixed",
    "rate_int",
    "CacheEntry",
    "OracleEngine",
]
