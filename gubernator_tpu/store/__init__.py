from gubernator_tpu.store.store import (
    Loader,
    MemoryLoader,
    MemoryStore,
    Store,
    attach_store,
    load_engine,
    save_engine,
)

__all__ = [
    "Loader",
    "MemoryLoader",
    "MemoryStore",
    "Store",
    "attach_store",
    "load_engine",
    "save_engine",
]
