"""Persistence seams: Loader (checkpoint/restore) and Store (durability).

The reference defines two plugin interfaces (reference store.go:49-78):
- Loader: bulk Load() at startup, Save() at shutdown — exactly
  checkpoint/resume (SURVEY.md §5).
- Store: OnChange after every update (write-behind) + Get on cache miss
  (read-through) + Remove.

TPU adaptation (SURVEY.md §7): hooks fire at *batch* granularity. After
each decide batch the engine gathers the touched rows from the device
(ops.decide.gather_rows — exact raw state, fixed-point leaky fraction
included) and hands them to Store.on_change; read-through consults the
store for keys this process has never seen before dispatching them.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterable, List, Optional, Protocol

from gubernator_tpu.utils import lockorder
from gubernator_tpu.api.types import Algorithm, RateLimitReq


@dataclasses.dataclass
class ItemSnapshot:
    """One key's raw counter state — the portable form of a slot row
    (the reference's CacheItem + bucket struct, store.go:29-43)."""

    key: str  # hash_key (name + "_" + unique_key)
    algorithm: int = Algorithm.TOKEN_BUCKET
    status: int = 0
    limit: int = 0
    duration: int = 0
    remaining: int = 0  # raw: whole tokens (token) / Q44.20 (leaky)
    stamp: int = 0  # created_at (token) / updated_at (leaky)
    expire_at: int = 0
    invalid_at: int = 0
    burst: int = 0


class Store(Protocol):
    """Write-behind + read-through durability plugin
    (reference store.go:49-65, batch-granular here)."""

    def on_change(self, items: List[ItemSnapshot]) -> None: ...

    def get(self, req: RateLimitReq) -> Optional[ItemSnapshot]: ...

    def remove(self, key: str) -> None: ...


class Loader(Protocol):
    """Bulk checkpoint/restore plugin (reference store.go:69-78)."""

    def load(self) -> Iterable[ItemSnapshot]: ...

    def save(self, items: Iterable[ItemSnapshot]) -> None: ...


class MemoryStore:
    """Dict-backed Store (the reference's exported MockStore analog,
    store.go:80-112) — usable in tests and as a template."""

    def __init__(self):
        self.data: Dict[str, ItemSnapshot] = {}
        self.lock = lockorder.make_lock("store.memory")
        self.get_calls = 0
        self.change_calls = 0

    def on_change(self, items: List[ItemSnapshot]) -> None:
        # Ownership of the snapshot objects transfers to the store (the
        # engine builds them fresh per flush and never mutates them
        # afterwards), so no defensive copy.
        with self.lock:
            self.change_calls += 1
            for it in items:
                self.data[it.key] = it

    def get(self, req: RateLimitReq) -> Optional[ItemSnapshot]:
        with self.lock:
            self.get_calls += 1
            it = self.data.get(req.hash_key())
            return dataclasses.replace(it) if it is not None else None

    def remove(self, key: str) -> None:
        with self.lock:
            self.data.pop(key, None)


class MemoryLoader:
    """List-backed Loader (reference MockLoader analog, store.go:114-150)."""

    def __init__(self, items: Optional[List[ItemSnapshot]] = None):
        self.items: List[ItemSnapshot] = list(items or [])
        self.called_load = 0
        self.called_save = 0

    def load(self) -> Iterable[ItemSnapshot]:
        self.called_load += 1
        return list(self.items)

    def save(self, items: Iterable[ItemSnapshot]) -> None:
        self.called_save += 1
        self.items = list(items)


# ---- engine glue -----------------------------------------------------------


def snapshots_from_engine(engine) -> List[ItemSnapshot]:
    """Drain the engine's table into portable snapshots (Loader.Save feed;
    reference workers.go:451-534)."""
    import numpy as np

    snap = engine.snapshot()
    keys = snap["key_strings"]
    used = np.asarray(snap["used"])
    out: List[ItemSnapshot] = []
    idx = np.nonzero(used)[0]
    for i in idx:
        hi, lo = int(snap["key_hi"][i]), int(snap["key_lo"][i])
        key = keys.get((hi, lo))
        if key is None:
            continue  # anonymous row (key dictionary disabled)
        out.append(
            ItemSnapshot(
                key=key,
                algorithm=int(snap["algo"][i]),
                status=int(snap["status"][i]),
                limit=int(snap["limit"][i]),
                duration=int(snap["duration"][i]),
                remaining=int(snap["remaining"][i]),
                stamp=int(snap["stamp"][i]),
                expire_at=int(snap["expire_at"][i]),
                invalid_at=int(snap["invalid_at"][i]),
                burst=int(snap["burst"][i]),
            )
        )
    return out


def merge_snapshots_lww(engine, items: List[ItemSnapshot]) -> tuple:
    """Last-writer-wins merge of incoming snapshots into an engine table
    (the receiver half of ring-change handover, docs/robustness.md).

    Unlike inject_snapshots' unconditional overwrite (correct for the
    Loader restore into an empty table and for authoritative GLOBAL
    broadcasts), a handover can race live traffic at the receiver: the
    new owner may already have served hits for a moved key by the time
    the old owner's snapshot arrives. Resolution, per key:

    - strictly newer local `stamp` wins (the receiver re-created the
      bucket after the sender snapshotted it — its writes are newer);
    - equal stamps: the MORE-CONSUMED side wins (lower `remaining`).
      Equal stamps mean both sides hold copies of the same bucket
      (handover echo, or a drain re-ship racing post-transfer hits at
      the successor); within a window hits only consume, so the lower
      remaining carries strictly more of the true count.

    Returns (accepted, stale) counts."""
    import numpy as np

    from gubernator_tpu.api.keys import key_hash128

    if not items:
        return 0, 0
    snap = engine.snapshot()
    used = np.asarray(snap["used"])
    idx = np.nonzero(used)[0]
    hi_col, lo_col = snap["key_hi"], snap["key_lo"]
    stamp_col, rem_col = snap["stamp"], snap["remaining"]
    existing: Dict[tuple, tuple] = {}
    for i in idx:
        existing[(int(hi_col[i]), int(lo_col[i]))] = (
            int(stamp_col[i]),
            int(rem_col[i]),
        )
    # inject_snapshots overwrites verbatim in list order, so same-key
    # duplicates inside one batch must be reduced by the SAME rule here
    # — otherwise the last duplicate wins positionally and the merged
    # state depends on arrival order (non-convergent under re-delivery).
    def _loses(have: tuple, s: ItemSnapshot) -> bool:
        return have[0] > s.stamp or (have[0] == s.stamp and have[1] <= s.remaining)

    keep: Dict[tuple, ItemSnapshot] = {}
    stale = 0
    for s in items:
        kh = key_hash128(s.key)
        have = existing.get(kh)
        if have is not None and _loses(have, s):
            stale += 1
            continue
        prev = keep.get(kh)
        if prev is not None:
            if _loses((prev.stamp, prev.remaining), s):
                stale += 1
                continue
            stale += 1  # prev superseded within the batch
        keep[kh] = s
    engine.inject_snapshots(list(keep.values()))
    return len(keep), stale


def save_engine(engine, loader: Loader) -> int:
    items = snapshots_from_engine(engine)
    loader.save(items)
    return len(items)


def load_engine(engine, loader: Loader) -> int:
    """Stream loader items into the engine table before serving
    (reference gubernator.go:138-148 -> workers.go:329-446)."""
    items = list(loader.load())
    engine.inject_snapshots(items)
    return len(items)


def attach_store(engine, store: Store) -> None:
    """Enable read-through + write-behind on a DeviceEngine.

    Read-through correctness is driven by the device-table residency
    probe and write-behind keys come from each request, so the host
    key-string dictionary is not required. Keeping keep_key_strings=True
    (the default) is still recommended: it lets the engine prefetch
    never-seen keys OUTSIDE the device lock and keeps Loader snapshots
    carrying original key strings."""
    engine.store = store
    # Warm the store-path kernels now: the first flush otherwise
    # cold-compiles probe_exists/gather_rows while holding the serving
    # lock (~1s on CPU, tens of seconds on TPU), stalling forwarded
    # batches past their timeout and inviting client-retry double-apply —
    # the same rationale as the engine's _warmup for decide/inject.
    warm = getattr(engine, "warm_store_path", None)
    if warm is not None:
        warm()
