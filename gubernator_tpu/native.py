"""Native batch key hasher: build-on-demand C++ via ctypes.

Loads native/_guberhash.so (building it with g++ on first use) and
exposes single and batch 128-bit hashing. The in-process table identity
hash is swappable (it never crosses process boundaries — peers route by
fnv1 over strings and all wire/state formats carry string keys), so when
the native library is available the whole process uses MurmurHash3
x64-128 from C; otherwise everything falls back to Python xxh3. The
choice is static per process, keeping hashes self-consistent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from gubernator_tpu.utils import lockorder

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "guberhash.cc")
_SO = os.path.join(_NATIVE_DIR, "_guberhash.so")

_lock = lockorder.make_lock("native.load")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.guber_hash128.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.guber_hash128_batch.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64),
                ctypes.c_int,
                ctypes.c_uint64,
                np.ctypeslib.ndpointer(np.uint64),
                np.ctypeslib.ndpointer(np.uint64),
                np.ctypeslib.ndpointer(np.int32),
            ]
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def hash128(key: str) -> Tuple[int, int]:
    """Single-key native hash as signed int64 halves."""
    lib = load()
    assert lib is not None
    raw = key.encode("utf-8")
    hi = ctypes.c_uint64()
    lo = ctypes.c_uint64()
    lib.guber_hash128(raw, len(raw), ctypes.byref(hi), ctypes.byref(lo))
    to_signed = lambda v: v - (1 << 64) if v >= (1 << 63) else v  # noqa: E731
    return to_signed(hi.value), to_signed(lo.value)


def hash128_batch_raw(
    data: bytes, offsets: np.ndarray, num_groups: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batch hash over pre-concatenated key bytes (the columnar edge path
    hands these straight from the wire parser — no string objects)."""
    lib = load()
    assert lib is not None
    n = len(offsets) - 1
    hi = np.empty(n, dtype=np.uint64)
    lo = np.empty(n, dtype=np.uint64)
    group = np.empty(n, dtype=np.int32)
    lib.guber_hash128_batch(data, offsets, n, num_groups, hi, lo, group)
    return hi.view(np.int64), lo.view(np.int64), group


def hash128_batch(
    keys: List[str], num_groups: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batch hash: returns (hi, lo) as int64 arrays and group as int32."""
    lib = load()
    assert lib is not None
    encoded = [k.encode("utf-8") for k in keys]
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    data = b"".join(encoded)
    n = len(keys)
    hi = np.empty(n, dtype=np.uint64)
    lo = np.empty(n, dtype=np.uint64)
    group = np.empty(n, dtype=np.int32)
    lib.guber_hash128_batch(data, offsets, n, num_groups, hi, lo, group)
    return hi.view(np.int64), lo.view(np.int64), group
