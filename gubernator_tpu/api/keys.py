"""Key hashing: string hash-key -> 128-bit slot-table identity.

The reference keys its cache by the raw string `name + "_" + unique_key`
(reference client.go:39-41) and routes with 64-bit fnv1 for peer ownership
(reference replicated_hash.go:104-119). The slot table instead stores a
128-bit xxh3 of the hash-key: at 10M keys the collision probability is
~2.9e-25, so two distinct strings never merge limits (SURVEY.md §7 hard
part (c)) without the table having to store strings. The host keeps the
hash -> original-string dictionary where needed (Loader snapshots,
debugging); the device never sees strings.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np
import xxhash

_M64 = (1 << 64) - 1
_SIGN = 1 << 63


def _to_signed(v: int) -> int:
    return v - (1 << 64) if v >= _SIGN else v


def _to_unsigned(v: int) -> int:
    return v & _M64


# The in-process identity hash is swappable: the native C++ batch hasher
# (gubernator_tpu.native, MurmurHash3 x64-128) when it builds, else
# Python xxh3. The GUBER_DISABLE_NATIVE_HASH toggle is read on FIRST
# USE, not at import (guberlint GL004: the daemon's --config file is
# injected into os.environ after import) — then latched for the life of
# the process, because the two hashers produce different digests and a
# mid-process flip would split every live key's slot-table identity.
_native = None
_native_decided = False


def _native_mod():
    global _native, _native_decided
    if not _native_decided:
        _native = None
        if os.environ.get("GUBER_DISABLE_NATIVE_HASH", "") not in (
            "1",
            "true",
        ):
            try:
                from gubernator_tpu import native as mod

                _native = mod if mod.available() else None
            except Exception:
                _native = None
        _native_decided = True
    return _native


def _reset_native_for_tests() -> None:
    """Unlatch the first-use decision (tests only: production must never
    flip hashers mid-process)."""
    global _native, _native_decided
    _native = None
    _native_decided = False


def native_enabled() -> bool:
    return _native_mod() is not None


def key_hash128(hash_key: str) -> Tuple[int, int]:
    """128-bit identity of a rate-limit key, as two signed int64 halves.

    (0, 0) is reserved as the empty-slot sentinel; the astronomically
    unlikely all-zero digest is nudged.
    """
    native = _native_mod()
    if native is not None:
        return native.hash128(hash_key)
    d = xxhash.xxh3_128_intdigest(hash_key.encode("utf-8"))
    hi = (d >> 64) & _M64
    lo = d & _M64
    if hi == 0 and lo == 0:
        lo = 1
    return _to_signed(hi), _to_signed(lo)


def key_hash128_batch(
    keys: List[str], num_groups: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batch form: (hi int64[n], lo int64[n], group int32[n]). One native
    call when available; the assembler hot loop uses this."""
    native = _native_mod()
    if native is not None:
        return native.hash128_batch(keys, num_groups)
    n = len(keys)
    hi = np.empty(n, dtype=np.int64)
    lo = np.empty(n, dtype=np.int64)
    grp = np.empty(n, dtype=np.int32)
    for i, k in enumerate(keys):
        h, l = key_hash128(k)
        hi[i], lo[i] = h, l
        grp[i] = _to_unsigned(l) % num_groups
    return hi, lo, grp


def group_of(key_lo: int, num_groups: int) -> int:
    """Slot-group index from the (signed) low hash half."""
    return _to_unsigned(key_lo) % num_groups
