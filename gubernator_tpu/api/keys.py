"""Key hashing: string hash-key -> 128-bit slot-table identity.

The reference keys its cache by the raw string `name + "_" + unique_key`
(reference client.go:39-41) and routes with 64-bit fnv1 for peer ownership
(reference replicated_hash.go:104-119). The slot table instead stores a
128-bit xxh3 of the hash-key: at 10M keys the collision probability is
~2.9e-25, so two distinct strings never merge limits (SURVEY.md §7 hard
part (c)) without the table having to store strings. The host keeps the
hash -> original-string dictionary where needed (Loader snapshots,
debugging); the device never sees strings.
"""

from __future__ import annotations

from typing import Tuple

import xxhash

_M64 = (1 << 64) - 1
_SIGN = 1 << 63


def _to_signed(v: int) -> int:
    return v - (1 << 64) if v >= _SIGN else v


def _to_unsigned(v: int) -> int:
    return v & _M64


def key_hash128(hash_key: str) -> Tuple[int, int]:
    """128-bit identity of a rate-limit key, as two signed int64 halves.

    (0, 0) is reserved as the empty-slot sentinel; the astronomically
    unlikely all-zero digest is nudged.
    """
    d = xxhash.xxh3_128_intdigest(hash_key.encode("utf-8"))
    hi = (d >> 64) & _M64
    lo = d & _M64
    if hi == 0 and lo == 0:
        lo = 1
    return _to_signed(hi), _to_signed(lo)


def group_of(key_lo: int, num_groups: int) -> int:
    """Slot-group index from the (signed) low hash half."""
    return _to_unsigned(key_lo) % num_groups
