"""Core request/response types and enums.

Semantics match the reference proto contract (reference gubernator.proto:56-213
and peers.proto:36-73). These are plain Python dataclasses used on the host
side; the wire formats (protobuf for gRPC, JSON for the HTTP gateway) are
defined in gubernator_tpu.service.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Duration constants in milliseconds (mirrors the reference client constants).
MILLISECOND = 1
SECOND = 1000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE

# Hard cap on items per GetRateLimits call (reference gubernator.go:40).
MAX_BATCH_SIZE = 1000


class Algorithm(enum.IntEnum):
    """Rate limit algorithm (reference gubernator.proto:56-61)."""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Behavior(enum.IntFlag):
    """Per-request behavior bit flags (reference gubernator.proto:64-135).

    Config travels with the request: the service holds no per-limit
    configuration, only counter state.
    """

    BATCHING = 0  # default; present for parity, has no effect when used
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16
    DRAIN_OVER_LIMIT = 32


class Status(enum.IntEnum):
    """Rate limit decision status (reference gubernator.proto:185-188)."""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


def has_behavior(behavior: int, flag: Behavior) -> bool:
    """Bit test (reference gubernator.go:776-778). Note the reference
    quirk: HasBehavior(b, BATCHING) is always False since BATCHING == 0;
    batching-is-default is expressed by the absence of NO_BATCHING."""
    return bool(behavior & flag)


def set_behavior(behavior: int, flag: Behavior, on: bool) -> int:
    """Set or clear a behavior flag (reference gubernator.go:781-788)."""
    return behavior | flag if on else behavior & ~flag


@dataclass
class RateLimitReq:
    """A single rate limit check (reference gubernator.proto:137-183)."""

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0  # milliseconds (or Gregorian interval enum)
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = 0
    burst: int = 0
    metadata: Dict[str, str] = field(default_factory=dict)
    # Epoch ms when the request was created; filled by the server if unset
    # (reference gubernator.proto:172-182).
    created_at: Optional[int] = None

    def hash_key(self) -> str:
        """The canonical cache/ownership key (reference client.go:39-41)."""
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResp:
    """A single rate limit decision (reference gubernator.proto:190-203)."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # epoch ms when the limit window resets
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class PeerInfo:
    """A cluster member (reference config.go:161-175)."""

    grpc_address: str = ""
    http_address: str = ""
    data_center: str = ""
    is_owner: bool = False  # true when this PeerInfo describes the local node


@dataclass
class HealthCheckResp:
    """Service health (reference gubernator.proto:206-213)."""

    status: str = "healthy"  # 'healthy' | 'unhealthy'
    message: str = ""
    peer_count: int = 0


@dataclass
class UpdatePeerGlobal:
    """Owner-to-replica state push for one GLOBAL key
    (reference peers.proto:52-72)."""

    key: str = ""
    status: RateLimitResp = field(default_factory=RateLimitResp)
    algorithm: int = Algorithm.TOKEN_BUCKET
    duration: int = 0
    created_at: int = 0


# ---- typed error statuses ---------------------------------------------------
#
# RateLimitResp.error is a free-form string on the wire (reference proto
# contract), so machine-checkable statuses are expressed as a stable
# prefix convention: "UNAVAILABLE:" marks a *retryable* condition — the
# serving node is draining or overloaded, the request was NOT applied,
# and an edge/client may safely re-dispatch it (to the same cluster,
# where discovery will route it to the new owner). Anything else is a
# terminal per-item failure.

RETRYABLE_PREFIX = "UNAVAILABLE:"

# The engine pump is shutting down and the drain budget expired before
# this request could be served (replaces the bare "engine shutdown").
ERR_ENGINE_DRAINING = RETRYABLE_PREFIX + " engine draining; retry"

# A peer's forward batch queue is full (overload shed, never blocked).
ERR_PEER_OVERLOADED = RETRYABLE_PREFIX + " peer forward queue full; retry"

# The engine intake governor shed this request before it was enqueued
# (intake budget exceeded, CoDel standing-queue shed, or brownout) —
# the request was NOT applied; responses carry retry_after_ms metadata
# with the server-suggested backoff (service/overload.py).
ERR_OVERLOADED = RETRYABLE_PREFIX + " intake overloaded; retry"


def is_retryable_error(error: str) -> bool:
    """True when a RateLimitResp.error marks a request that was NOT
    applied and can be safely re-dispatched (drain/overload shedding)."""
    return bool(error) and error.startswith(RETRYABLE_PREFIX)


def validate_request(req: RateLimitReq) -> Optional[str]:
    """Per-item validation; returns an error string or None.

    Error strings and check order match the reference exactly
    (reference gubernator.go:208-216; functional_test.go TestMissingFields).
    """
    if not req.unique_key:
        return "field 'unique_key' cannot be empty"
    if not req.name:
        return "field 'namespace' cannot be empty"
    return None
