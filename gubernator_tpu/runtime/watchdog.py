"""Self-watchdog: named heartbeats for every long-lived daemon loop.

A gubernator daemon runs seven-plus background loops (engine pump,
pipelined-completion thread, ici sync cadence, consistency auditor,
page demoter, lease sweep, profiler sampler, SLO sampler). Every one
of them fails SILENTLY: a wedged completion thread just stops draining
`_pipe_q`, the pump blocks on the pipeline semaphore, and from the
outside the daemon looks healthy — gRPC still accepts, /healthz still
200s — while every decision times out. PR 10's breaker catches a
*peer* in that state; nothing caught the local daemon.

The watchdog inverts liveness detection: each loop calls
`wd.beat(name, ...)` once per iteration, and a monitor thread flags
any heartbeat older than its deadline into `stalled`. Consumers:

  - `gubernator_thread_stalled{loop}` gauge (metrics.py scrape bridge
    reads `snapshot()` — the watchdog itself never touches metrics so
    it stays importable everywhere);
  - /debug/slo carries the full per-loop heartbeat table;
  - `serving_stalled()` — True when a loop marked `serving=True` (the
    pump / completion pair that sits on the decision path) is stalled;
    service/slo.py feeds it into the availability SLI, so a wedged
    serving loop BURNS the availability error budget rather than
    merely lighting a lamp nobody watches.

Heartbeats are plain dict stores (GIL-atomic), safe from threads and
asyncio tasks alike, ~100ns per beat — cheap enough for the pump's
per-batch loop. Loops with a long natural cadence (the demoter can
legitimately sleep 60s between passes) pass `period_s` so their
deadline is `stall + period`, not the raw stall bound.
"""

from __future__ import annotations

import threading
import time


class Watchdog:
    """Monitor thread + heartbeat table. start()/stop() lifecycle is
    owned by the daemon; loops only ever call beat()."""

    def __init__(self, stall_ms: float = 5000.0):
        self.stall_s = max(float(stall_ms), 1.0) / 1000.0
        # name -> (last_beat_monotonic, serving, period_s). Replaced
        # wholesale on every beat; readers snapshot via dict(...) so
        # iteration never races a writer.
        self._beats: dict[str, tuple[float, bool, float]] = {}
        self._stalled: dict[str, bool] = {}
        self._stall_events: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- producer side ------------------------------------------------------

    def beat(
        self, name: str, *, serving: bool = False, period_s: float = 0.0
    ) -> None:
        """Record one loop iteration. First beat auto-registers the
        loop — no separate registration step, so a loop that never
        starts simply never appears (its absence shows in /debug/slo
        as a missing row, not a false 'healthy')."""
        self._beats[name] = (time.monotonic(), serving, float(period_s))

    def unregister(self, name: str) -> None:
        """Drop a loop that shut down cleanly so its final heartbeat
        doesn't age into a false stall."""
        self._beats.pop(name, None)
        self._stalled.pop(name, None)

    # -- monitor side -------------------------------------------------------

    def _deadline_s(self, period_s: float) -> float:
        # A loop beating every period_s sits at age <= period_s in
        # steady state; stall_s on top is the wedge margin.
        return self.stall_s + max(period_s, 0.0)

    def check(self, now: float | None = None) -> dict[str, bool]:
        """One evaluation pass; also called directly by tests so stall
        detection needs no sleeping."""
        now = time.monotonic() if now is None else now
        for name, (ts, _serving, period_s) in dict(self._beats).items():
            stalled = (now - ts) > self._deadline_s(period_s)
            if stalled and not self._stalled.get(name, False):
                self._stall_events[name] = self._stall_events.get(name, 0) + 1
            self._stalled[name] = stalled
        # beats removed by unregister leave no stalled residue
        for name in list(self._stalled):
            if name not in self._beats:
                del self._stalled[name]
        return dict(self._stalled)

    def _loop(self) -> None:
        poll = min(max(self.stall_s / 4.0, 0.01), 1.0)
        while not self._stop.wait(poll):
            self.beat("watchdog-monitor", period_s=poll)
            self.check()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gubernator-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- consumers ----------------------------------------------------------

    def stalled_loops(self) -> list[str]:
        return sorted(n for n, s in self._stalled.items() if s)

    def serving_stalled(self) -> bool:
        """True when any serving-path loop is stalled — the hook the
        availability SLO burns on."""
        beats = dict(self._beats)
        return any(
            self._stalled.get(n, False) and beats.get(n, (0, False, 0))[1]
            for n in self._stalled
        )

    def snapshot(self) -> dict:
        """JSON-shaped per-loop heartbeat table for /debug/slo."""
        now = time.monotonic()
        loops = {}
        for name, (ts, serving, period_s) in sorted(dict(self._beats).items()):
            loops[name] = {
                "age_ms": round((now - ts) * 1000.0, 1),
                "deadline_ms": round(self._deadline_s(period_s) * 1000.0, 1),
                "serving": serving,
                "stalled": bool(self._stalled.get(name, False)),
                "stall_events": int(self._stall_events.get(name, 0)),
            }
        return {
            "stall_ms": round(self.stall_s * 1000.0, 1),
            "serving_stalled": self.serving_stalled(),
            "loops": loops,
        }
