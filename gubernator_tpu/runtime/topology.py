"""Topology strategies for the unified engine core (runtime/engine.py).

MeshEngine owns everything topology-independent exactly once — the pump,
the pipeline ring, ticket lifecycle, failure recovery, drain, snapshots,
census/admission caching, and flush telemetry. The per-topology delta
lives HERE, reduced to a small strategy object with three duties:

- **kernel binding** (`build_kernels`): which Kernels facade the core
  dispatches through, and whether a Pager manages page residency behind
  it. Single chip binds the plain per-layout jits (ops/kernels.py);
  the mesh binds the shard_map ownership programs (parallel/mesh.py)
  whose psum over the mesh axis replaces peer forwarding. The paged
  indirection layer rides the SAME seam on both: the core only ever
  sees a Kernels-shaped object plus an optional Pager, so per-shard
  page maps and per-shard host-DRAM cold tiers come for free on the
  multi-chip tier.
- **table residency** (mesh geometry): `n_dev` / `mesh_shape` size the
  per-shard pools; mesh shape ``(1,)`` reproduces the single-chip
  engine bit-exactly, ``(chips,)`` runs the sharded tier. The axis is
  one-dimensional on purpose — a later DCN x ICI build extends the
  mesh to ``(hosts, chips)`` and the strategy, not the core, absorbs it.
- **collective step** (`dispatch_guard` + `build_replica`): multi-device
  programs rendezvous in collectives, so every dispatch site in the
  core runs under the strategy's guard (the process-wide enqueue lock,
  parallel/mesh.collective_guard — a nullcontext on one chip), and the
  GLOBAL replica tier (parallel/ici.py) is built only where a mesh
  exists to replicate over.

Import discipline: this module imports ops/, parallel/, and
runtime/pager — NEVER runtime/engine (the engine imports us).
"""

from __future__ import annotations

import contextlib

import jax

from gubernator_tpu.ops.kernels import (
    get_admission,
    get_census,
    get_kernels,
    get_paged_kernels,
    kernel_backend,
)
from gubernator_tpu.parallel import ici
from gubernator_tpu.parallel import mesh as pmesh


class ReplicaTier:
    """The GLOBAL replica tier, bundled for the engine core: per-device
    replica tables with pending deltas (parallel/ici.py), the decide /
    sync / inject programs over them, and the stacked census/admission
    scans. The core treats it as opaque state + callables; the sync
    CADENCE (thread + tick bookkeeping) stays in IciEngine — it is
    policy, not topology."""

    def __init__(self, mesh, cfg, metrics, census_thresholds):
        self.mesh = mesh
        self._metrics = metrics
        self._layout = cfg.layout
        self.num_slots = int(cfg.num_slots)
        self.replica_ways = int(cfg.replica_ways)
        self.num_rgroups = self.num_slots // self.replica_ways
        self.state = ici.create_ici_state(
            mesh, self.num_slots, self.replica_ways, layout=cfg.layout,
            metrics=metrics,
        )
        self.decide = ici.make_replica_decide(
            mesh, self.num_slots, self.replica_ways, layout=cfg.layout
        )
        self.sync = ici.make_sync_step(
            mesh, self.num_slots, self.replica_ways, layout=cfg.layout,
            max_sync_groups=cfg.max_sync_groups,
        )
        # Collision backstop: a second, unbounded sync program selected
        # every `full_tick_every`-th tick. Only built when the regular
        # tick is actually capped (an uncapped tick IS the full tick;
        # a cap >= group count compiles to the uncapped program too).
        self.sync_full = None
        if (
            cfg.max_sync_groups is not None
            and cfg.max_sync_groups < self.num_rgroups
            and cfg.full_tick_every > 0
        ):
            self.sync_full = ici.make_sync_step(
                mesh, self.num_slots, self.replica_ways,
                layout=cfg.layout, max_sync_groups=None,
            )
        self.inject = ici.make_inject_replicas(
            mesh, self.num_slots, self.replica_ways, layout=cfg.layout
        )
        # Replica-tier observatory programs: the tier's leaves carry a
        # leading device axis, so both use the stacked variants
        # (replica 0; post-sync replicas mirror each other).
        self.census = get_census(
            cfg.layout, self.replica_ways,
            heatmap_width=int(cfg.census_heatmap_width),
            thresholds=census_thresholds,
            stacked=True,
        )
        self.admission = get_admission(
            cfg.layout, self.replica_ways, stacked=True
        )

    def recreate_state(self):
        """Fresh empty replica state after a failed donated dispatch
        (counter loss on failure matches the accepted cache-loss-on-
        restart semantics)."""
        return ici.create_ici_state(
            self.mesh, self.num_slots, self.replica_ways,
            layout=self._layout, metrics=self._metrics,
        )


class SingleChipTopology:
    """Mesh shape ``(1,)``: one chip, the plain per-layout kernels, no
    replica tier, no collective guard. Binding THIS strategy into
    MeshEngine reproduces the pre-unification DeviceEngine bit-exactly
    (pinned by tests/test_pipeline.py + tests/test_kernel_fuzz.py)."""

    n_dev = 1
    mesh_shape = (1,)
    primary_tier = "device"
    thread_name = "gubernator-tpu-engine"
    kernel_backend = "xla"  # resolved for real in build_kernels

    def build_kernels(self, cfg, metrics):
        """(Kernels, Pager|None) for one chip — the pre-unification
        DeviceEngine binding: paged facade + Pager when page_groups is
        set, the flat layout jits otherwise. The decide backend
        (GUBER_KERNEL: XLA chain vs fused Pallas program) resolves
        inside the registry at THIS moment and is pinned on the
        topology so the engine can tune/warm/report the program it
        will actually serve."""
        self.kernel_backend = kernel_backend()
        pg = int(getattr(cfg, "page_groups", 0) or 0)
        if pg > 0:
            budget = int(getattr(cfg, "page_budget", 0) or 0)
            if budget <= 0:
                raise ValueError(
                    "page_budget must be > 0 when page_groups > 0"
                )
            if pg > cfg.num_groups:
                raise ValueError(
                    f"page_groups ({pg}) exceeds num_groups "
                    f"({cfg.num_groups})"
                )
            from gubernator_tpu.runtime.pager import Pager

            K = get_paged_kernels(
                cfg.layout, cfg.num_groups, cfg.ways, pg, budget
            )
            return K, Pager(K, metrics=metrics)
        return get_kernels(cfg.layout), None

    def build_replica(self, cfg, metrics):
        return None  # no mesh to replicate over

    def dispatch_guard(self):
        """Single-device programs cannot rendezvous: no guard."""
        return contextlib.nullcontext()


class IciMeshTopology:
    """Mesh shape ``(chips,)``: the slot table shards across the mesh
    (owner-sharded decide, parallel/mesh.py), GLOBAL traffic runs on
    per-device replicas (parallel/ici.py), and every dispatch runs
    under the process-wide collective enqueue guard. Paging composes:
    the paged mesh facade keeps the physical frames sharded and the
    page map replicated, and the Pager runs one frame pool + host-DRAM
    cold tier PER SHARD (n_shards = mesh size)."""

    primary_tier = "sharded"
    thread_name = "ici-engine"
    kernel_backend = "xla"  # resolved for real in build_kernels

    def __init__(self, devices=None):
        self.devices = list(devices) if devices else jax.devices()
        self.mesh = pmesh.make_mesh(self.devices)
        self.n_dev = int(self.mesh.devices.size)
        self.mesh_shape = (self.n_dev,)

    def build_kernels(self, cfg, metrics):
        """(Kernels, Pager|None) over the mesh: shard_map ownership
        programs, with the paged indirection layer (replicated map,
        sharded frames, per-shard pools) when page_groups is set.
        Under GUBER_KERNEL=pallas the registry routes the RAW decide
        the shard_map body composes (parallel/mesh.py local_decide)
        through the fused Pallas program, so every shard dispatches
        its own pallas_call over its table slice."""
        self.kernel_backend = kernel_backend()
        pg = int(getattr(cfg, "page_groups", 0) or 0)
        budget = int(getattr(cfg, "page_budget", 0) or 0)
        if pg > 0:
            if budget <= 0:
                raise ValueError(
                    "page_budget must be > 0 when page_groups > 0"
                )
            if pg > cfg.num_groups:
                raise ValueError(
                    f"page_groups ({pg}) exceeds num_groups "
                    f"({cfg.num_groups})"
                )
        K = pmesh.make_mesh_kernels(
            self.mesh, cfg.layout, cfg.num_groups, cfg.ways,
            page_groups=pg, page_budget=budget, metrics=metrics,
        )
        if pg <= 0:
            return K, None
        from gubernator_tpu.runtime.pager import Pager

        return K, Pager(K, metrics=metrics, n_shards=self.n_dev)

    def build_replica(self, cfg, metrics):
        return ReplicaTier(
            self.mesh, cfg, metrics,
            tuple(int(k) for k in cfg.census_thresholds),
        )

    def dispatch_guard(self):
        """Process-wide multi-device enqueue lock (parallel/mesh.py):
        taken INSIDE the engine table lock at every dispatch site, so
        two engines' collectives can never interleave their per-device
        enqueues (the cross-program rendezvous deadlock)."""
        return pmesh.collective_guard()
