"""Pallas decide-kernel autotuner: pick the lane tile once, off-path.

The fused decide kernel (ops/pallas_decide.py) has exactly one tunable:
`block_b`, the per-grid-step lane tile. The right value is a device
property (VMEM budget vs DMA concurrency), so it is tuned PER DEVICE
KIND, once, during engine warmup — never on the serving path — and the
choice is cached two ways:

- in-process (`pallas_decide.register_block`), which pins the static
  jit configuration so the program warmed by `_warm_buckets` is
  byte-identical to the one serving waves dispatch (the cold-compile
  invariant, pinned by tests);
- persisted JSON beside the persistent compile cache
  (`<compile-cache-dir>/pallas_tune.json`, or GUBER_PALLAS_TUNE_CACHE),
  so an engine restart re-registers the choice WITHOUT re-running
  trials — and, because the static config is identical, the XLA/Mosaic
  executable itself comes back from the persistent compile cache
  instead of recompiling.

Trials ride the PR 11 compile telemetry (runtime/telemetry.py): each
candidate's runs are attributed via `set_shape_hint("pallas-tune:...")`
so `/debug/device`'s retrace ring shows tuning compiles as warmup-scope
(never serving-scope), and `compile_counters()` deltas are recorded per
candidate alongside wall time in the persisted stats.

Resolution order at `ensure_tuned` (env override handled downstream by
`pallas_decide.choose_block`, which always wins):

1. already registered in-process -> reuse (zero cost);
2. persisted entry for this (device kind, backend, layout, paged) key
   -> register, count a tune-cache hit;
3. tuning disabled (GUBER_PALLAS_TUNE=0) or no candidates fit -> the
   safe DEFAULT_BLOCK, NOT persisted — an unknown device falls back
   without poisoning the cache;
4. timed trials over the candidate tiles -> best wall time wins, gets
   registered + persisted.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp

from gubernator_tpu.ops import pallas_decide
from gubernator_tpu.ops.layout import RequestBatch
from gubernator_tpu.runtime import telemetry
from gubernator_tpu.utils import compilecache

log = logging.getLogger("gubernator.kerneltune")

# Candidate lane tiles, clamped per call to the serving batch width.
CANDIDATES = (128, 256, 512)

# Groups in the throwaway trial table — big enough that the DMA pattern
# is realistic, small enough that trials cost milliseconds of HBM.
_TRIAL_GROUPS = 4096
_TRIAL_RUNS = 3

# Per-key provenance for /debug + metrics: key -> dict(block=, source=,
# trials=). Sources: "persisted" | "tuned" | "default".
_stats: dict = {}
_tune_cache_hits = 0


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "no", "off")


def tune_cache_path() -> str:
    """Persisted tune-choice file: beside the persistent compile cache
    so the two survive (and are wiped) together."""
    override = os.environ.get("GUBER_PALLAS_TUNE_CACHE", "").strip()
    if override:
        return override
    base = os.environ.get("GUBER_COMPILE_CACHE") or compilecache.DEFAULT_DIR
    return os.path.join(base, "pallas_tune.json")


def device_key(layout: str, paged: bool) -> str:
    """Tune-cache key: the choice is a property of the device kind and
    the program family, not of this process."""
    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # pragma: no cover - no backend at all
        kind = "unknown"
    return "|".join(
        [kind, jax.default_backend(), layout, "paged" if paged else "flat"]
    )


def _load_persisted() -> dict:
    try:
        with open(tune_cache_path(), encoding="utf-8") as f:
            data = json.load(f)
        return dict(data.get("choices", {}))
    except (OSError, ValueError):
        return {}


def _persist(key: str, entry: dict) -> None:
    path = tune_cache_path()
    choices = _load_persisted()
    choices[key] = entry
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"choices": choices}, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except OSError as e:  # best-effort: tuning still holds in-process
        log.warning("pallas tune cache not persisted (%s): %s", path, e)


def tuning_stats() -> dict:
    """Provenance snapshot for /debug surfaces + metrics bridge."""
    return {"choices": dict(_stats), "tune_cache_hits": _tune_cache_hits}


def _trial(layout: str, batch_size: int, block: int) -> dict:
    """Time one candidate tile on a throwaway table. Runs under a tune
    shape hint so every compile it triggers is attributed to the tuner
    in the retrace ring (warmup scope, never serving)."""
    if layout == "narrow":
        from gubernator_tpu.ops.narrow import NarrowTable as T
    else:
        from gubernator_tpu.ops.fused import FusedTable as T
    ways = 8
    table = T.create(_TRIAL_GROUPS, ways)
    batch = jax.tree.map(jnp.asarray, RequestBatch.zeros(batch_size))
    now = jnp.int64(0)
    mode = pallas_decide.pallas_mode()
    telemetry.set_shape_hint(f"pallas-tune:{layout}:b{block}")
    c0 = telemetry.compile_counters()
    data = table.data
    # compile + settle
    data, out, _ = pallas_decide._flat_jit(
        data, batch, now, layout=layout, ways=ways, block_b=block, mode=mode
    )
    jax.block_until_ready(data)  # guberlint: allow-host-sync -- tune-trial compile barrier, warmup scope only
    c1 = telemetry.compile_counters()
    t0 = time.perf_counter()
    for _ in range(_TRIAL_RUNS):
        data, out, _ = pallas_decide._flat_jit(
            data, batch, now,
            layout=layout, ways=ways, block_b=block, mode=mode,
        )
    jax.block_until_ready(data)  # guberlint: allow-host-sync -- tune-trial timing barrier, warmup scope only
    wall = (time.perf_counter() - t0) / _TRIAL_RUNS
    telemetry.set_shape_hint("")
    return {
        "block": block,
        "wall_s": wall,
        "compiles": c1["compiles"] - c0["compiles"],
        "compile_seconds": round(
            c1["compile_seconds"] - c0["compile_seconds"], 4
        ),
    }


def ensure_tuned(
    layout: str, batch_size: int, *, paged: bool = False
) -> int:
    """Resolve and register the lane tile for (layout, paged) on this
    device. Called from engine warmup BEFORE the decide program warms;
    idempotent and cheap on every path but the first-ever tune."""
    global _tune_cache_hits
    if layout not in pallas_decide.PALLAS_LAYOUTS:
        return pallas_decide.DEFAULT_BLOCK
    got = pallas_decide.registered_block(layout, paged)
    if got is not None:
        return got
    key = device_key(layout, paged)

    persisted = _load_persisted().get(key)
    if isinstance(persisted, dict) and "block" in persisted:
        block = int(persisted["block"])  # guberlint: allow-host-sync -- JSON dict from disk, host-only
        pallas_decide.register_block(layout, paged, block)
        _tune_cache_hits += 1
        _stats[key] = {"block": block, "source": "persisted"}
        log.info("pallas tune: %s -> block %d (persisted)", key, block)
        return block

    candidates = sorted(
        {
            min(c, pallas_decide._pow2_at_least(max(batch_size, 1)))
            for c in CANDIDATES
        }
    )
    if not _env_flag("GUBER_PALLAS_TUNE", True) or len(candidates) < 2:
        # Unknown device / tuning off: the safe default, NOT persisted.
        block = min(
            pallas_decide.DEFAULT_BLOCK,
            pallas_decide._pow2_at_least(max(batch_size, 1)),
        )
        pallas_decide.register_block(layout, paged, block)
        _stats[key] = {"block": block, "source": "default"}
        return block

    trials = [_trial(layout, batch_size, c) for c in candidates]
    best = min(trials, key=lambda t: t["wall_s"])
    block = best["block"]
    pallas_decide.register_block(layout, paged, block)
    entry = {"block": block, "source": "tuned", "trials": trials}
    _stats[key] = entry
    _persist(key, entry)
    log.info(
        "pallas tune: %s -> block %d (%.1f us/wave, %d candidates)",
        key, block, best["wall_s"] * 1e6, len(trials),
    )
    return block
