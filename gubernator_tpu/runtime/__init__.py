from gubernator_tpu.runtime.engine import DeviceEngine, EngineConfig

__all__ = ["DeviceEngine", "EngineConfig"]
