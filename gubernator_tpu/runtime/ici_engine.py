"""IciEngine: a servable engine over a multi-device mesh.

Where DeviceEngine owns one chip, IciEngine owns a whole
jax.sharding.Mesh and replaces the host-level peer mesh *inside* the
process (SURVEY.md §2.3):

- Non-GLOBAL traffic runs through the owner-sharded decide
  (parallel/mesh.py): the table shards across devices, one SPMD call per
  wave answers every lane at its owner. This is the collective analog of
  peer forwarding.
- GLOBAL traffic runs through per-device replicas (parallel/ici.py):
  lanes are assigned a home device round-robin (modeling which "node"
  the request hit), answered locally from that device's replica, and a
  background sync thread runs the collective delta/rebroadcast tick on
  the GlobalSyncWait cadence — the globalManager with psums instead of
  gRPC.

The public surface matches DeviceEngine (check_async/check_batch/close),
so V1Service and the daemon can use either; a daemon configured with
global_mode="ici" serves a whole pod as one process with no intra-pod
RPCs.

Wave rules differ per path: sharded lanes split on slot-group conflicts
(scatter disjointness per device); replica lanes split on (home, slot)
conflicts (same key on the same replica must serialize, but the same key
on different replicas is exactly multi-node GLOBAL behavior and may
share a wave).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import jax
import numpy as np

from gubernator_tpu.api.keys import key_hash128_batch
from gubernator_tpu.api.types import (
    Behavior,
    RateLimitReq,
    RateLimitResp,
    has_behavior,
    validate_request,
)
from gubernator_tpu.ops.encode import EncodeError, encode_one
from gubernator_tpu.ops.layout import RequestBatch
from gubernator_tpu.parallel import ici
from gubernator_tpu.parallel import mesh as pmesh
from gubernator_tpu.runtime.engine import EngineMetrics, _WaveAssembler, _FLUSH, _STOP
from gubernator_tpu.utils import clock as _clock


@dataclasses.dataclass
class IciEngineConfig:
    devices: Optional[list] = None  # default: all jax.devices()
    num_groups: int = 1 << 12  # sharded-table groups (divisible by n_dev)
    ways: int = 8
    num_slots: int = 1 << 14  # replica-table slots (ways=1 geometry)
    batch_size: int = 1024
    batch_limit: int = 1000
    batch_wait_s: float = 500e-6
    max_flush_items: int = 8192
    sync_wait_s: float = 0.1  # GLOBAL sync cadence (reference 100ms)


class IciEngine:
    def __init__(self, config: IciEngineConfig = IciEngineConfig(), now_fn=_clock.now_ms):
        cfg = config
        devices = cfg.devices or jax.devices()
        if cfg.num_groups % len(devices) or cfg.num_slots % len(devices):
            raise ValueError("num_groups/num_slots must divide by device count")
        self.cfg = cfg
        self.now_fn = now_fn
        self.n_dev = len(devices)
        self.mesh = pmesh.make_mesh(devices)
        self.metrics = EngineMetrics()

        # Owner-sharded authoritative path
        self.table = pmesh.create_sharded_table(self.mesh, cfg.num_groups, cfg.ways)
        self._decide = pmesh.make_sharded_decide(self.mesh, cfg.num_groups, cfg.ways)

        # GLOBAL replica path
        self.ici_state = ici.create_ici_state(self.mesh, cfg.num_slots)
        self._replica = ici.make_replica_decide(self.mesh, cfg.num_slots)
        self._sync = ici.make_sync_step(self.mesh, cfg.num_slots)

        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._home_rr = 0

        self._warmup()
        self._running = True
        self._thread = threading.Thread(target=self._pump, daemon=True, name="ici-engine")
        self._thread.start()
        self._sync_thread = threading.Thread(
            target=self._sync_loop, daemon=True, name="ici-sync"
        )
        self._sync_thread.start()

    # -- public API (DeviceEngine-compatible) --------------------------------

    def check_async(self, req: RateLimitReq) -> "Future[RateLimitResp]":
        fut: Future = Future()
        err = validate_request(req)
        if err is not None:
            fut.set_result(RateLimitResp(error=err))
            return fut
        if req.created_at is None:
            req.created_at = self.now_fn()
        self._queue.put((req, fut))
        return fut

    def check_batch(self, reqs) -> List[RateLimitResp]:
        futs = [self.check_async(r) for r in reqs]
        return [f.result() for f in futs]

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def live_count(self) -> int:
        """Occupied slots: sharded table + each replica's owned region."""
        with self._lock:
            sharded = int(jax.numpy.sum(self.table.used))
            replica = int(jax.numpy.sum(self.ici_state.table.used)) // max(self.n_dev, 1)
        return sharded + replica

    def sync_now(self) -> None:
        """Run one GLOBAL sync tick immediately (tests/benchmarks)."""
        now = self.now_fn()
        with self._lock:
            self.ici_state = self._sync(self.ici_state, now)
            jax.block_until_ready(self.ici_state.pending)

    def close(self) -> None:
        self._running = False
        self._queue.put(_STOP)
        self._thread.join(timeout=5)
        self._sync_thread.join(timeout=5)

    # -- warmup / loops ------------------------------------------------------

    def _warmup(self) -> None:
        now = self.now_fn()
        wb = RequestBatch.zeros(self.cfg.batch_size)
        self.table, out = self._decide(self.table, wb, now)
        np.asarray(out.status)
        home = np.zeros(self.cfg.batch_size, dtype=np.int64)
        self.ici_state, out2 = self._replica(self.ici_state, wb, home, now)
        np.asarray(out2.status)
        self.ici_state = self._sync(self.ici_state, now)
        jax.block_until_ready(self.ici_state.pending)

    def _sync_loop(self) -> None:
        while self._running:
            time.sleep(self.cfg.sync_wait_s)
            try:
                self.sync_now()
            except Exception:
                pass

    def _pump(self) -> None:
        while self._running:
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is _STOP:
                break
            batch = []
            flush = item is _FLUSH
            if not flush:
                batch.append(item)
                flush = has_behavior(item[0].behavior, Behavior.NO_BATCHING)
            deadline = time.monotonic() + self.cfg.batch_wait_s
            while not flush and len(batch) < self.cfg.max_flush_items:
                remaining = deadline - time.monotonic()
                if len(batch) >= self.cfg.batch_limit or remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    self._running = False
                    break
                if nxt is _FLUSH:
                    break
                batch.append(nxt)
                if has_behavior(nxt[0].behavior, Behavior.NO_BATCHING):
                    break
            if batch:
                try:
                    self._process(batch)
                except Exception as e:
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_result(RateLimitResp(error=str(e)))

    # -- flush processing ----------------------------------------------------

    def _process(self, items) -> None:
        t0 = time.perf_counter()
        now = self.now_fn()
        cfg = self.cfg
        B = cfg.batch_size

        is_global = [
            has_behavior(req.behavior, Behavior.GLOBAL) for req, _ in items
        ]
        keys = [req.hash_key() for req, _ in items]
        # Hash once against each path's geometry.
        sh = key_hash128_batch(keys, cfg.num_groups)
        rh = key_hash128_batch(keys, cfg.num_slots)

        sharded_asm = _WaveAssembler(RequestBatch.zeros, B)
        replica_asm = _WaveAssembler(RequestBatch.zeros, B)
        replica_homes: List[np.ndarray] = []
        replica_seen: List[set] = []
        placements: List[Optional[Tuple[str, int, int]]] = []

        for i, (req, fut) in enumerate(items):
            try:
                if not is_global[i]:
                    grp = int(sh[2][i])
                    wb, w, lane = sharded_asm.place(grp)
                    encode_one(
                        wb, lane, req, now, cfg.num_groups,
                        key=(int(sh[0][i]), int(sh[1][i])),
                    )
                    sharded_asm.commit(w, grp)
                    placements.append(("s", w, lane))
                else:
                    # Home assignment round-robin; wave key = (home, slot).
                    slot = int(rh[2][i])
                    home = self._home_rr % self.n_dev
                    self._home_rr += 1
                    w = 0
                    while True:
                        if w == len(replica_asm.waves):
                            replica_asm.waves.append(RequestBatch.zeros(B))
                            replica_asm._groups.append(set())
                            replica_asm._fill.append(0)
                            replica_homes.append(np.zeros(B, dtype=np.int64))
                            replica_seen.append(set())
                        if (home, slot) not in replica_seen[w] and replica_asm._fill[w] < B:
                            break
                        w += 1
                    lane = replica_asm._fill[w]
                    encode_one(
                        replica_asm.waves[w], lane, req, now, cfg.num_slots,
                        key=(int(rh[0][i]), int(rh[1][i])),
                    )
                    replica_homes[w][lane] = home
                    replica_seen[w].add((home, slot))
                    replica_asm._fill[w] += 1
                    placements.append(("r", w, lane))
            except EncodeError as e:
                fut.set_result(RateLimitResp(error=str(e)))
                placements.append(None)
                continue

        # Execute: sharded waves then replica waves.
        s_out, r_out = [], []
        with self._lock:
            table = self.table
            for wb in sharded_asm.waves:
                table, out = self._decide(table, wb, now)
                s_out.append(out)
            self.table = table
            state = self.ici_state
            for wb, hm in zip(replica_asm.waves, replica_homes):
                state, out = self._replica(state, wb, hm, now)
                r_out.append(out)
            self.ici_state = state

        host = {
            "s": [
                (np.asarray(o.status), np.asarray(o.remaining),
                 np.asarray(o.reset_time), np.asarray(o.limit),
                 int(o.hits), int(o.misses), int(o.unexpired_evictions),
                 int(o.over_limit))
                for o in s_out
            ],
            "r": [
                (np.asarray(o.status), np.asarray(o.remaining),
                 np.asarray(o.reset_time), np.asarray(o.limit),
                 int(o.hits), int(o.misses), int(o.unexpired_evictions),
                 int(o.over_limit))
                for o in r_out
            ],
        }
        tots = [0, 0, 0, 0]
        for path in host.values():
            for h in path:
                for j in range(4):
                    tots[j] += h[4 + j]
        self.metrics.observe(
            tots[0], tots[1], tots[2], tots[3],
            len(sharded_asm.waves) + len(replica_asm.waves), len(items),
            time.perf_counter() - t0,
        )

        for (req, fut), place in zip(items, placements):
            if place is None:
                continue
            path, w, lane = place
            st, rem, rst, lim = host[path][w][0], host[path][w][1], host[path][w][2], host[path][w][3]
            fut.set_result(
                RateLimitResp(
                    status=int(st[lane]),
                    limit=int(lim[lane]),
                    remaining=int(rem[lane]),
                    reset_time=int(rst[lane]),
                )
            )
