"""IciEngine: a servable engine over a multi-device mesh.

Where DeviceEngine owns one chip, IciEngine owns a whole
jax.sharding.Mesh and replaces the host-level peer mesh *inside* the
process (SURVEY.md §2.3):

- Non-GLOBAL traffic runs through the owner-sharded decide
  (parallel/mesh.py): the table shards across devices, one SPMD call per
  wave answers every lane at its owner. This is the collective analog of
  peer forwarding.
- GLOBAL traffic runs through per-device replicas (parallel/ici.py):
  lanes are assigned a home device round-robin (modeling which "node"
  the request hit), answered locally from that device's replica, and a
  background sync thread runs the collective delta/rebroadcast tick on
  the GlobalSyncWait cadence — the globalManager with psums instead of
  gRPC.

The public surface matches DeviceEngine (check_async/check_bulk/
check_batch/close/inject_globals), so V1Service and the daemon can use
either; a daemon configured with global_mode="ici" serves a whole pod as
one process with no intra-pod RPCs.

Wave rules differ per path: sharded lanes split on slot-group conflicts
(scatter disjointness per device); replica lanes split on (home, group)
conflicts (same key on the same replica must serialize, but the same key
on different replicas is exactly multi-node GLOBAL behavior and may
share a wave).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import List, Optional, Tuple

import jax
import numpy as np

from gubernator_tpu.utils import lockorder
from gubernator_tpu.api.keys import group_of, key_hash128_batch
from gubernator_tpu.api.types import Behavior, RateLimitResp
from gubernator_tpu.ops.encode import EncodeError, encode_one
from gubernator_tpu.ops.kernels import BYTES_PER_SLOT, get_admission, get_census
from gubernator_tpu.ops.layout import RequestBatch
from gubernator_tpu.parallel import ici
from gubernator_tpu.parallel import mesh as pmesh
from gubernator_tpu.runtime.engine import (
    EngineBase,
    EngineMetrics,
    TableCommittedError,
    _FlushTicket,
    _WaveAssembler,
    _admission_combine,
    _admission_tier_dict,
    _assemble_column_waves,
    _census_combine,
    _census_tier_snapshot,
    _materialize_out,
    _note_hotkeys_columnar,
    _select_columns,
    _stack_wave_outputs,
    _wave_totals,
)
from gubernator_tpu.runtime import telemetry as _telemetry
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.utils import tracing
from gubernator_tpu.utils import transfer as _transfer

log = logging.getLogger("gubernator_tpu.ici")


@dataclasses.dataclass
class IciEngineConfig:
    devices: Optional[list] = None  # default: all jax.devices()
    num_groups: int = 1 << 12  # sharded-table groups (divisible by n_dev)
    ways: int = 8
    num_slots: int = 1 << 14  # replica-table slots (num_slots/replica_ways groups)
    replica_ways: int = 4  # replica-table associativity (parallel/ici.py)
    batch_size: int = 1024
    batch_limit: int = 1000
    batch_wait_s: float = 500e-6
    max_flush_items: int = 8192
    max_waves: int = 32  # per-flush wave cap; overflow carries over
    sync_wait_s: float = 0.1  # GLOBAL sync cadence (reference 100ms)
    # Observability knobs — same semantics as EngineConfig (GUBER_HOTKEYS_K
    # / GUBER_STAGE_METADATA / GUBER_EXEMPLARS; docs/monitoring.md).
    hotkeys_k: int = 128
    stage_metadata: bool = False
    exemplars: bool = True
    # Table-census knobs — same semantics as EngineConfig
    # (GUBER_TABLE_CENSUS_TTL / _THRESHOLDS / _HEATMAP; the census runs
    # over BOTH tiers: sharded table + replica 0 of the GLOBAL tier).
    census_ttl_s: float = 5.0
    census_thresholds: tuple = (1, 4, 16)
    census_heatmap_width: int = 64
    # Admission-accounting cadence — same semantics as EngineConfig
    # (GUBER_ADMISSION_TTL; the scan covers BOTH tiers).
    admission_ttl_s: float = 5.0
    # Table layout for BOTH the sharded and replica tiers (the
    # ops/kernels.py LAYOUTS registry; "narrow" halves probe DMA at
    # large tables); fused is the TPU production layout (VERDICT r4
    # item 2).
    layout: str = "fused"
    # Per-tick sync work cap (groups). The tick merges only groups whose
    # content diverges across replicas or that hold pending deltas, up
    # to this many per tick (overflow carries; diag backlog gauge).
    # Bounds tick device time by ACTIVE traffic instead of table size,
    # keeping the 100ms cadence at 10M+ key geometries. None = merge
    # the full table every tick.
    max_sync_groups: "int | None" = 65536
    # Fingerprint-collision backstop (GUBER_ICI_FULL_TICK_EVERY): the
    # capped tick selects groups by comparing two salted
    # non-cryptographic fingerprints across replicas — a collision makes
    # a diverged group look converged and strands it forever. Forcing a
    # full-table tick every N capped ticks bounds that window to
    # N * sync_wait_s. 0 = off; ignored when max_sync_groups is None
    # (the uncapped tick already merges the full table).
    full_tick_every: int = 64
    # Continuous-batching pipeline depth (GUBER_PIPELINE_DEPTH): max
    # flushes dispatched-but-unsynced at once; 1 = serial pump. Same
    # semantics as EngineConfig.pipeline_depth — both ici tiers'
    # (sharded + replica) waves launch in the dispatch stage and sync
    # in the completion stage.
    pipeline_depth: int = 2
    # Paged-table knobs (GUBER_TABLE_PAGE_*): accepted for config
    # parity with EngineConfig, but NOT YET IMPLEMENTED for the
    # shard_map'd ici tiers — the indirection map would have to be
    # replicated and page moves collective. Setting page_groups > 0
    # logs a warning and serves flat (docs/architecture.md "Paged
    # table", staged work).
    page_groups: int = 0
    page_budget: int = 0
    page_demote_interval_s: float = 2.0
    page_free_target: int = 1


class IciEngine(EngineBase):
    # GLOBAL-flagged requests are routed to the replica tier inside the
    # engine; V1Service must not strip the flag (see the GLOBAL bulk
    # submission in server._get_rate_limits)
    routes_global_internally = True

    # Serve-flat fallback warn-once latch: a daemon restart loop (or a
    # test suite constructing many engines) must not spam the same
    # capability warning per construction — once per process is the
    # operator signal; per-engine visibility lives in /debug/engine and
    # the census "pages" section instead.
    _paging_warned = False

    def __init__(self, config: IciEngineConfig = IciEngineConfig(), now_fn=_clock.now_ms):
        cfg = config
        devices = cfg.devices or jax.devices()
        if cfg.num_groups % len(devices):
            raise ValueError("num_groups must divide by device count")
        if cfg.num_slots % (cfg.replica_ways * len(devices)):
            raise ValueError(
                "num_slots must divide by replica_ways * device count"
            )
        if cfg.max_waves < 1:
            raise ValueError("max_waves must be >= 1")
        self._paging_requested = int(getattr(cfg, "page_groups", 0) or 0) > 0
        if self._paging_requested and not IciEngine._paging_warned:
            IciEngine._paging_warned = True
            log.warning(
                "table paging (page_groups=%d) is not yet implemented "
                "for the ici engine's sharded tiers; serving flat — "
                "the HBM budget is num_groups * ways per device",
                cfg.page_groups,
            )
        self.cfg = cfg
        self.now_fn = now_fn
        self.n_dev = len(devices)
        self.mesh = pmesh.make_mesh(devices)
        self.metrics = EngineMetrics()

        # Owner-sharded authoritative path
        self.table = pmesh.create_sharded_table(
            self.mesh, cfg.num_groups, cfg.ways, layout=cfg.layout,
            metrics=self.metrics,
        )
        self._decide = pmesh.make_sharded_decide(
            self.mesh, cfg.num_groups, cfg.ways, layout=cfg.layout
        )

        # GLOBAL replica path
        self.num_rgroups = cfg.num_slots // cfg.replica_ways
        self.ici_state = ici.create_ici_state(
            self.mesh, cfg.num_slots, cfg.replica_ways, layout=cfg.layout,
            metrics=self.metrics,
        )
        self._replica = ici.make_replica_decide(
            self.mesh, cfg.num_slots, cfg.replica_ways, layout=cfg.layout
        )
        self._sync = ici.make_sync_step(
            self.mesh, cfg.num_slots, cfg.replica_ways, layout=cfg.layout,
            max_sync_groups=cfg.max_sync_groups,
        )
        # Collision backstop: a second, unbounded sync program selected
        # every `full_tick_every`-th tick. Only built when the regular
        # tick is actually capped (an uncapped tick IS the full tick;
        # a cap >= group count compiles to the uncapped program too).
        self._sync_full = None
        if (
            cfg.max_sync_groups is not None
            and cfg.max_sync_groups < self.num_rgroups
            and cfg.full_tick_every > 0
        ):
            self._sync_full = ici.make_sync_step(
                self.mesh, cfg.num_slots, cfg.replica_ways,
                layout=cfg.layout, max_sync_groups=None,
            )
        self._inject_replicas = ici.make_inject_replicas(
            self.mesh, cfg.num_slots, cfg.replica_ways, layout=cfg.layout
        )

        # Table observatory (ops/census.py): one non-donating program per
        # tier — the sharded table scans as-is; the replica tier's leaves
        # carry a leading device axis, so it uses the stacked variant
        # (replica 0; post-sync replicas mirror each other).
        self._census_thresholds = tuple(
            int(k) for k in cfg.census_thresholds
        )
        self._census_sharded = get_census(
            cfg.layout, cfg.ways,
            heatmap_width=int(cfg.census_heatmap_width),
            thresholds=self._census_thresholds,
        )
        self._census_replica = get_census(
            cfg.layout, cfg.replica_ways,
            heatmap_width=int(cfg.census_heatmap_width),
            thresholds=self._census_thresholds,
            stacked=True,
        )
        # Admission accounting (ops/admission.py): same two-tier split.
        self._admission_sharded = get_admission(cfg.layout, cfg.ways)
        self._admission_replica = get_admission(
            cfg.layout, cfg.replica_ways, stacked=True
        )

        # HBM attribution (utils/devicemem.py): static geometry sized
        # once; EngineBase.device_memory() folds in allocator stats.
        bps = BYTES_PER_SLOT[cfg.layout]
        census_b = 8 * (
            2 * 32
            + (cfg.ways + 1) + (cfg.replica_ways + 1)
            + 2 * int(cfg.census_heatmap_width)
            + 2 * len(self._census_thresholds)
            + 32
        )
        self._mem_subsystems = {
            "slot_table": cfg.num_groups * cfg.ways * bps,
            # Every device carries a full GLOBAL replica (table +
            # pending deltas + tick scalar, ops/ici.py).
            "ici_replicas": self.n_dev * cfg.num_slots * (bps + 8) + 8 * self.n_dev,
            "census": census_b,
            # Two AdmissionOutputs: histogram + scalar rows per tier.
            "admission": 2 * 8 * (32 + 8),
            "pipeline_ring": (
                max(int(cfg.pipeline_depth), 1)
                * cfg.max_waves * cfg.batch_size * 8 * 8
            ),
        }
        self._snapshot_staging_bytes = 0

        self._lock = lockorder.make_lock("ici_engine.state")
        self._home_rr = 0
        self._sync_errors = 0
        # Overflow observability (VERDICT r3 item 5): keys degraded to
        # per-replica counting right now, and a running total of overflow
        # entries dropped under full-group pressure.
        self.overflow_keys = 0
        self.overflow_drops = 0
        self.sync_backlog = 0
        # Backstop bookkeeping (gubernator_ici_full_ticks): host-side
        # capped-tick counter and a running total of forced full ticks.
        self.full_ticks = 0
        self._capped_ticks = 0

        self._warmup()
        self._init_base("ici-engine")
        self._stop_sync = threading.Event()
        self._sync_thread = threading.Thread(
            target=self._sync_loop, daemon=True, name="ici-sync"
        )
        self._sync_thread.start()

    # -- public additions over EngineBase ------------------------------------

    def sync_now(self) -> None:
        """Run one GLOBAL sync tick immediately (tests/benchmarks; the
        background sync thread's tick body)."""
        now = self.now_fn()
        t0 = time.perf_counter()
        with self._lock:
            # The tick is warmed in _warmup and must stay compile-free on
            # the 100ms cadence — a cold tick stalls GLOBAL convergence,
            # so it counts against the cold-compile invariant too.
            with _telemetry.serving_scope(self.metrics), tracing.span(
                "ici.sync_tick", level="DEBUG"
            ) as tick_span:
                sync = self._sync
                if self._sync_full is not None:
                    self._capped_ticks += 1
                    if self._capped_ticks >= self.cfg.full_tick_every:
                        # Collision backstop: merge the FULL table this
                        # tick, healing any group a fingerprint collision
                        # hid from the capped selector.
                        self._capped_ticks = 0
                        self.full_ticks += 1
                        sync = self._sync_full
                self.ici_state, diag = sync(self.ici_state, now)
                with _transfer.account(self.metrics, "d2h", "census") as tx:
                    d = np.asarray(diag)
                    tx.add(d)
            # kept/dropped cover groups merged THIS tick; under a capped
            # backlog, retained keys in unmerged groups surface when
            # their group's turn comes. The backlog gauge (identical on
            # every device; diag rows replicate it) is the overload
            # signal.
            self.overflow_keys = int(d[:, 0].sum())
            self.overflow_drops += int(d[:, 1].sum())
            self.sync_backlog = int(d[:, 2].max())
        dur = time.perf_counter() - t0
        groups = int(d[:, 3].max())
        em = self.metrics
        em.ici_tick_duration.observe(dur)
        em.ici_tick_groups.observe(groups)
        em.recorder.record(
            path="ici-sync", layout=self.cfg.layout, groups=groups,
            backlog=self.sync_backlog, overflow_keys=self.overflow_keys,
            dur_us=int(dur * 1e6),
            trace_id=tracing.trace_id_of(tick_span),
        )

    def inject_globals(self, globals_) -> None:
        """Apply an authoritative UpdatePeerGlobals push to every replica
        (the cross-pod/DCN leg landing on an ici-mode daemon)."""
        from gubernator_tpu.models.bucket import FIXED_SHIFT
        from gubernator_tpu.ops.inject import InjectBatch

        if not globals_:
            return
        now = self.now_fn()
        cfg = self.cfg
        asm = _WaveAssembler(InjectBatch.zeros, cfg.batch_size)
        hi_a, lo_a, slot_a = key_hash128_batch(
            [g.key for g in globals_], self.num_rgroups
        )
        for i, g in enumerate(globals_):
            slot = int(slot_a[i])
            ib, w, lane = asm.place(slot)
            leaky = int(g.algorithm) == 1
            ib.key_hi[lane] = int(hi_a[i])
            ib.key_lo[lane] = int(lo_a[i])
            ib.group[lane] = slot
            ib.algo[lane] = int(g.algorithm)
            ib.status[lane] = int(g.status.status)
            ib.limit[lane] = g.status.limit
            ib.duration[lane] = g.duration
            ib.remaining[lane] = (
                g.status.remaining << FIXED_SHIFT if leaky else g.status.remaining
            )
            ib.stamp[lane] = now
            ib.expire_at[lane] = g.status.reset_time
            ib.burst[lane] = g.status.limit if leaky else 0
            ib.active[lane] = True
            asm.commit(w, slot)
        with self._lock:
            state = self.ici_state
            with _transfer.account(self.metrics, "h2d", "inject") as tx:
                for ib in asm.waves:
                    state = self._inject_replicas(state, ib, now)
                    tx.add(ib)
            self.ici_state = state

    def check_columns(
        self,
        cols,
        now: Optional[int] = None,
        select: Optional[np.ndarray] = None,
        hashes: Optional[tuple] = None,
    ):
        """Columnar serving for BOTH ici tiers — the multi-chip daemon's
        fast edge. Non-GLOBAL items feed the owner-sharded SPMD decide
        (shared wave assembler, one collective call per wave); GLOBAL
        items feed the per-device replica tier with the same round-robin
        home assignment as the object path (replica decide handles
        pending bookkeeping internally; the GLOBAL bit stays SET — this
        engine routes_global_internally). Waves always run at the full
        batch width — a narrower width would cold-compile a second SPMD
        program per shape."""
        from gubernator_tpu import native as _native

        cfg = self.cfg
        if cols.n == 0:
            return None
        t_start = time.perf_counter()
        if now is None:
            now = self.now_fn()
        if hashes is None:
            hi, lo, grp = _native.hash128_batch_raw(
                cols.key_data.tobytes(), cols.key_offsets, cfg.num_groups
            )
        else:
            hi, lo, grp = hashes
        if select is not None:
            if len(select) == 0:
                return None
            hi, lo, grp = hi[select], lo[select], grp[select]
            cols = _select_columns(cols, select)
        n = cols.n
        g_mask = (np.asarray(cols.behavior) & int(Behavior.GLOBAL)) != 0
        ng_idx = np.nonzero(~g_mask)[0]
        g_idx = np.nonzero(g_mask)[0]

        # -- assemble the sharded (non-GLOBAL) waves --
        s_asm = None
        if len(ng_idx):
            s_cols = (
                cols if len(g_idx) == 0 else _select_columns(cols, ng_idx)
            )
            s_asm = _assemble_column_waves(
                s_cols, hi[ng_idx], lo[ng_idx], grp[ng_idx], now,
                cfg.batch_size, cfg.max_waves,
            )
            if s_asm is None:
                return None

        # -- assemble the replica (GLOBAL) waves --
        r_asm, homes_wb = None, None
        if len(g_idx):
            r_cols = _select_columns(cols, g_idx)
            r_lo = lo[g_idx]
            slot = (r_lo.astype(np.uint64) % np.uint64(self.num_rgroups)
                    ).astype(np.int64)
            with self._lock:  # round-robin base, racing the pump thread
                rr0 = self._home_rr
                self._home_rr += len(g_idx)
            homes = (rr0 + np.arange(len(g_idx))) % self.n_dev
            # Wave conflicts are per (home, slot) PAIR (the object path's
            # place key): encode the pair as the assembly "group", then
            # overwrite the batch's group column with the real slot.
            pair = homes * np.int64(self.num_rgroups) + slot
            r_asm = _assemble_column_waves(
                r_cols, hi[g_idx], r_lo, pair, now,
                cfg.batch_size, cfg.max_waves,
            )
            if r_asm is None:
                return None
            r_wb, _rw, _rl, r_ix, RW, RB = r_asm
            r_wb.group[r_ix] = slot.astype(np.int32)
            homes_wb = np.zeros((RW, RB), dtype=np.int64)
            homes_wb[r_ix] = homes

        s_outs, r_outs = [], []
        _telemetry.set_shape_hint(
            f"{cfg.layout}:ici-columnar:B{cfg.batch_size}"
        )
        t_dev = time.perf_counter()
        with self._lock, _telemetry.serving_scope(self.metrics), tracing.span(
            "engine.flush", level="DEBUG", path="columnar", items=n,
            layout=cfg.layout,
        ) as fspan:
            table = self.table
            state = self.ici_state
            try:
                if s_asm is not None:
                    wb = s_asm[0]
                    for w in range(s_asm[4]):
                        ws = jax.tree.map(lambda a, w=w: a[w], wb)
                        table, out = self._decide(table, ws, now)
                        s_outs.append(out)
                if r_asm is not None:
                    r_wb = r_asm[0]
                    for w in range(r_asm[4]):
                        ws = jax.tree.map(lambda a, w=w: a[w], r_wb)
                        state, out = self._replica(
                            state, ws, homes_wb[w], now
                        )
                        r_outs.append(out)
            except Exception as e:
                # Keep the last surviving intermediates; if donated
                # buffers were consumed, rebuild so the engine keeps
                # serving. Committed waves on SURVIVING tables must NOT
                # be replayed by a fallback path.
                self.table = table
                self.ici_state = state
                rebuilt = self._recover_tables_locked()
                if (s_outs or r_outs) and not rebuilt:
                    raise TableCommittedError(str(e)) from e
                raise
            self.table = table
            self.ici_state = state

        status = np.zeros(n, np.int64)
        r_limit = np.zeros(n, np.int64)
        remaining = np.zeros(n, np.int64)
        reset_time = np.zeros(n, np.int64)
        waves_total = 0
        tots = [0, 0, 0, 0]
        with _transfer.account(self.metrics, "d2h", "serve") as tx:
            for outs, asm, idx in (
                (s_outs, s_asm, ng_idx), (r_outs, r_asm, g_idx),
            ):
                if asm is None:
                    continue
                st, li, re, rt = _stack_wave_outputs(outs)
                tx.add((st, li, re, rt))
                ix = asm[3]
                status[idx] = st[ix]
                r_limit[idx] = li[ix]
                remaining[idx] = re[ix]
                reset_time[idx] = rt[ix]
                waves_total += asm[4]
                for j, v in enumerate(_wave_totals(outs)):
                    tots[j] += v
        dev_s = time.perf_counter() - t_dev
        dur = time.perf_counter() - t_start
        flush_trace_id = tracing.trace_id_of(fspan)
        em = self.metrics
        em.observe(tots[0], tots[1], tots[2], tots[3], waves_total, n, dur)
        em.observe_flush(
            "columnar", n, waves_total, dur, dev_s,
            flush_trace_id if cfg.exemplars else "",
        )
        em.observe_stage("assemble", t_dev - t_start)
        em.observe_stage("device_sync", dev_s)
        em.recorder.record(
            path="columnar", layout=cfg.layout, n=n, waves=waves_total,
            carry=0, widths=[cfg.batch_size] * waves_total,
            dur_us=int(dur * 1e6), dev_us=int(dev_s * 1e6),
            trace_id=flush_trace_id,
        )
        if em.hotkeys.k > 0:
            _note_hotkeys_columnar(em.hotkeys, hi, lo, cols.hits, status)
        return (status, r_limit, remaining, reset_time)

    def _recover_tables_locked(self) -> bool:
        """Called with the lock held after a failed device call: the
        jitted decide/replica programs donate their table buffers, so a
        failure may leave self.table / self.ici_state pointing at
        consumed arrays — every later call would then fail forever.
        Rebuild whichever was consumed (counter loss on failure matches
        the accepted cache-loss-on-restart semantics). Returns True when
        anything was rebuilt (a fallback replay is then safe, not a
        double-apply)."""
        cfg = self.cfg

        def consumed(tree) -> bool:
            try:
                leaf = jax.tree_util.tree_leaves(tree)[0]
                if getattr(leaf, "is_deleted", lambda: False)():
                    return True
                # Error-path-only health probe: a failed ASYNC dispatch
                # (pipelined completion) leaves the state reference
                # pointing at poisoned arrays whose deferred error only
                # surfaces on sync — catch it here, once, instead of on
                # every future flush.
                jax.block_until_ready(leaf)  # guberlint: allow-host-sync -- error-path state health probe
                return False
            except Exception:
                return True

        rebuilt = False
        if consumed(self.table):
            self.table = pmesh.create_sharded_table(
                self.mesh, cfg.num_groups, cfg.ways, layout=cfg.layout,
                metrics=self.metrics,
            )
            rebuilt = True
        if consumed(self.ici_state):
            self.ici_state = ici.create_ici_state(
                self.mesh, cfg.num_slots, cfg.replica_ways,
                layout=cfg.layout, metrics=self.metrics,
            )
            rebuilt = True
        return rebuilt

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def live_count(self) -> int:
        """Occupied slots: sharded table + one replica's worth of the
        GLOBAL tier. Thin view over the TTL-cached census (GL009: no
        device reductions on the scrape path)."""
        return self.table_census()["live"]

    def occupancy_stats(self) -> dict:
        """Back-compat occupancy dict across BOTH tiers: the sharded
        authoritative table plus one replica's worth of the GLOBAL tier
        (replicas mirror each other post-sync). Probe pressure is
        reported for the sharded tier, where a full group forces an
        eviction on insert. A thin view over the TTL-cached census —
        zero scrape-triggered device work (see metrics.engine_sync)."""
        c = self.table_census()
        return {
            "live": c["live"],
            "slots": c["slots"],
            "occupancy": c["occupancy"],
            "full_group_ratio": c["full_group_ratio"],
        }

    def _census_scan(self) -> dict:
        """One census pass over both tiers (called by table_census with
        _census_lock held): dispatch both non-donating programs under
        the engine lock (async — no host sync while the pump or sync
        tick could be waiting), materialize after release. The combined
        view takes structural fields (heatmap, probe pressure) from the
        sharded tier — the authoritative table a paged cold tier would
        page — while additive fields (live, waste, cold sets,
        histograms) sum across tiers."""
        cfg = self.cfg
        now = self.now_fn()
        with self._lock:
            out_s = self._census_sharded(self.table, now)
            out_r = self._census_replica(self.ici_state.table, now)
        bps = BYTES_PER_SLOT[cfg.layout]
        tiers = {
            "sharded": _census_tier_snapshot(
                out_s,
                now=now,
                layout=cfg.layout,
                groups=cfg.num_groups,
                ways=cfg.ways,
                bytes_per_slot=bps,
                thresholds=self._census_thresholds,
                heatmap_width=int(cfg.census_heatmap_width),
            ),
            "replica": _census_tier_snapshot(
                out_r,
                now=now,
                layout=cfg.layout,
                groups=self.num_rgroups,
                ways=cfg.replica_ways,
                bytes_per_slot=bps,
                thresholds=self._census_thresholds,
                heatmap_width=int(cfg.census_heatmap_width),
            ),
        }
        snap = _census_combine(tiers, primary="sharded")
        if self._paging_requested:
            # Same section the paged DeviceEngine fills from its Pager:
            # an operator who set GUBER_TABLE_PAGE_* sees WHY there is
            # no resident/host breakdown instead of a silent absence.
            snap["pages"] = {"enabled": False, "paging": "unsupported (flat)"}
        return snap

    def _admission_scan(self) -> dict:
        """One admission pass over both tiers (called by
        admission_snapshot with _admission_lock held): dispatch both
        non-donating programs under the engine lock, materialize after
        release. A key lives in exactly one tier (GLOBAL keys count in
        the replica tier, everything else in the sharded table), so the
        combine's additive sums stay a true fleet count."""
        now = self.now_fn()
        with self._lock:
            out_s = self._admission_sharded(self.table, now)
            out_r = self._admission_replica(self.ici_state.table, now)
        with _transfer.account(self.metrics, "d2h", "admission") as tx:
            tiers = {
                "sharded": _admission_tier_dict(out_s),
                "replica": _admission_tier_dict(out_r),
            }
            tx.add(out_s)
            tx.add(out_r)
        snap = _admission_combine(tiers)
        snap["now_ms"] = now
        return snap

    def debug_snapshot(self) -> dict:
        snap = super().debug_snapshot()
        if self._paging_requested:
            snap["paging"] = "unsupported (flat)"
        return snap

    def close(self) -> None:
        self._stop_sync.set()
        super().close()
        self._sync_thread.join(timeout=5)

    # -- warmup / sync loop --------------------------------------------------

    def _warmup(self) -> None:
        now = self.now_fn()
        wb = RequestBatch.zeros(self.cfg.batch_size)
        with _transfer.account(self.metrics, "d2h", "warmup") as tx:
            self.table, out = self._decide(self.table, wb, now)
            tx.add(np.asarray(out.status))
            home = np.zeros(self.cfg.batch_size, dtype=np.int64)
            self.ici_state, out2 = self._replica(
                self.ici_state, wb, home, now
            )
            tx.add(np.asarray(out2.status))
            self.ici_state, _diag = self._sync(self.ici_state, now)
            if self._sync_full is not None:
                # Warm the backstop program too — its first forced tick
                # must not pay a cold compile on the 100ms cadence.
                self.ici_state, _diag = self._sync_full(self.ici_state, now)
            # Census compiles here for both tiers: the first /metrics or
            # /debug/table scrape must dispatch warm programs, not
            # compile.
            cs = self._census_sharded(self.table, now)
            cr = self._census_replica(self.ici_state.table, now)
            tx.add(np.asarray(cs.live))  # guberlint: allow-host-sync -- warmup: compile both census programs before serving
            tx.add(np.asarray(cr.live))  # guberlint: allow-host-sync -- warmup: compile both census programs before serving
            # Admission accounting likewise, both tiers.
            ads = self._admission_sharded(self.table, now)
            adr = self._admission_replica(self.ici_state.table, now)
            tx.add(np.asarray(ads.keys))  # guberlint: allow-host-sync -- warmup: compile both admission programs before serving
            tx.add(np.asarray(adr.keys))  # guberlint: allow-host-sync -- warmup: compile both admission programs before serving
        # Final fence: __init__ returns with every program compiled and
        # the replica state resident.
        jax.block_until_ready(self.ici_state.pending)

    def _sync_loop(self) -> None:
        while not self._stop_sync.wait(self.cfg.sync_wait_s):
            try:
                self.sync_now()
                self._sync_errors = 0
            except Exception:
                # Surface persistent failures: without sync, replicas stop
                # converging and GLOBAL limits silently stop aggregating.
                self._sync_errors += 1
                if self._sync_errors in (1, 10) or self._sync_errors % 100 == 0:
                    log.exception(
                        "GLOBAL ICI sync tick failed (%d consecutive)",
                        self._sync_errors,
                    )

    # -- flush processing ----------------------------------------------------

    def _dispatch(self, items):
        """Pipeline stage 1 (both ici tiers): assemble + encode on host,
        launch the sharded SPMD waves then the replica waves without a
        host sync. Returns (carry, ticket) for _complete."""
        t0 = time.perf_counter()
        now = self.now_fn()
        cfg = self.cfg
        B = cfg.batch_size
        GLOBAL = int(Behavior.GLOBAL)

        # Hash once; derive each path's index from lo (group/slot are just
        # lo mod geometry). One-shot tolist: per-item numpy scalar boxing
        # dominated this loop.
        keys = [req.hash_key() for req, _ in items]
        hi_a, lo_a, grp_a = key_hash128_batch(keys, cfg.num_groups)
        hi_l, lo_l, grp_l = hi_a.tolist(), lo_a.tolist(), grp_a.tolist()

        sharded_asm = _WaveAssembler(RequestBatch.zeros, B)
        replica_asm = _WaveAssembler(RequestBatch.zeros, B)
        replica_homes: List[np.ndarray] = []
        placements: List[Optional[Tuple[str, int, int]]] = []

        carry = []
        for i, (req, fut) in enumerate(items):
            hi, lo = hi_l[i], lo_l[i]
            try:
                if not (req.behavior & GLOBAL):
                    grp = grp_l[i]
                    placed = sharded_asm.place(grp, cfg.max_waves)
                    if placed is None:
                        carry.append((req, fut))
                        placements.append("carry")
                        continue
                    wb, w, lane = placed
                    encode_one(wb, lane, req, now, cfg.num_groups, key=(hi, lo))
                    sharded_asm.commit(w, grp)
                    placements.append(("s", w, lane, hi, lo))
                else:
                    slot = group_of(lo, self.num_rgroups)
                    home = self._home_rr % self.n_dev
                    placed = replica_asm.place((home, slot), cfg.max_waves)
                    if placed is None:
                        carry.append((req, fut))
                        placements.append("carry")
                        continue
                    self._home_rr += 1  # only consumed on placement
                    wb, w, lane = placed
                    encode_one(wb, lane, req, now, self.num_rgroups, key=(hi, lo))
                    while len(replica_homes) < len(replica_asm.waves):
                        replica_homes.append(np.zeros(B, dtype=np.int64))
                    replica_homes[w][lane] = home
                    replica_asm.commit(w, (home, slot))
                    placements.append(("r", w, lane, hi, lo))
            except EncodeError as e:
                fut.set_result(RateLimitResp(error=str(e)))
                placements.append(None)
                continue

        # Execute: sharded waves then replica waves. On failure keep the
        # surviving intermediates and rebuild any consumed donated table
        # (the futures resolve with errors; nothing replays this flush).
        s_out, r_out = [], []
        waves_total = len(sharded_asm.waves) + len(replica_asm.waves)
        seq = self._flush_seq()
        fspan = self._start_flush_span(
            items, seq, path="object", layout=cfg.layout,
            items=len(items), waves=waves_total,
            batch_width=len(items) - len(carry),
        )
        _telemetry.set_shape_hint(f"{cfg.layout}:ici-object:B{B}")
        t_dev = time.perf_counter()
        try:
            with self._lock, _telemetry.serving_scope(
                self.metrics
            ), tracing.use_span_ctx(fspan):
                table = self.table
                state = self.ici_state
                try:
                    for wb in sharded_asm.waves:
                        table, out = self._decide(table, wb, now)
                        s_out.append(out)
                    for wb, hm in zip(replica_asm.waves, replica_homes):
                        state, out = self._replica(state, wb, hm, now)
                        r_out.append(out)
                except Exception:
                    self.table = table
                    self.ici_state = state
                    self._recover_tables_locked()
                    raise
                self.table = table
                self.ici_state = state
        except Exception as e:
            tracing.end_span(fspan, error=e)
            raise

        return carry, _FlushTicket(
            items=items, placements=placements, outs=s_out, r_outs=r_out,
            served=len(items) - len(carry), carry_n=len(carry),
            waves=waves_total, widths=[B] * waves_total,
            t0=t0, t_dev=t_dev, seq=seq, span=fspan,
            otel_ctx=tracing.context_of(fspan),
            trace_id=tracing.trace_id_of(fspan),
        )

    def _complete(self, t) -> None:
        """Pipeline stage 2: materialize both tiers' wave outputs, feed
        telemetry, resolve futures (FIFO dispatch order when
        pipelined)."""
        cfg = self.cfg
        t_c0 = time.perf_counter()
        host = {
            "s": [_materialize_out(o) for o in t.outs],
            "r": [_materialize_out(o) for o in t.r_outs],
        }
        t_sync = time.perf_counter()
        dev_s = t_sync - t.t_dev
        # Transfer ledger: the serve-path d2h readback (blocking sync).
        _transfer.record(
            self.metrics, "d2h", "serve", _transfer.nbytes(host),
            t_sync - t_c0,
        )
        tots = [0, 0, 0, 0]
        for path in host.values():
            for h in path:
                for j in range(4):
                    tots[j] += h[4 + j]
        dur = time.perf_counter() - t.t0
        em = self.metrics
        trace_id = (t.trace_id or "") if cfg.exemplars else ""
        em.observe(tots[0], tots[1], tots[2], tots[3], t.waves, t.served, dur)
        em.observe_flush("object", t.served, t.waves, dur, dev_s, trace_id)
        em.observe_stage("assemble", t.t_dev - t.t0)
        em.observe_stage("dispatch", t.t_disp_end - t.t_dev)
        em.observe_stage("inflight_wait", max(t_c0 - t.t_disp_end, 0.0))
        em.observe_stage("device_sync", t_sync - t_c0)
        em.recorder.record(
            path="object", layout=cfg.layout, n=t.served, waves=t.waves,
            carry=t.carry_n, widths=t.widths,
            dur_us=int(dur * 1e6), dev_us=int(dev_s * 1e6),
            ticket=t.seq, trace_id=t.trace_id or "",
        )

        stage_base = None
        if self._stage_md:
            stage_base = (
                f"assemble={int((t.t_dev - t.t0) * 1e6)}"
                f",dispatch={int((t.t_disp_end - t.t_dev) * 1e6)}"
                f",inflight_wait={int(max(t_c0 - t.t_disp_end, 0.0) * 1e6)}"
                f",device_sync={int((t_sync - t_c0) * 1e6)}"
            )
        hk = em.hotkeys if em.hotkeys.k > 0 else None
        hk_agg = {}
        OVER = 1  # api.types.Status.OVER_LIMIT
        for (req, fut), place in zip(t.items, t.placements):
            if place is None or place == "carry":
                continue
            path, w, lane = place[0], place[1], place[2]
            st, rem, rst, lim = host[path][w][:4]
            status = int(st[lane])  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
            if hk is not None:
                k = (place[3], place[4])
                ent = hk_agg.get(k)
                if ent is None:
                    hk_agg[k] = [
                        max(int(req.hits), 0), int(status == OVER),
                        req.hash_key(),
                    ]
                else:
                    ent[0] += max(int(req.hits), 0)
                    ent[1] += int(status == OVER)
            md = None
            if stage_base is not None:
                t_enq = getattr(fut, "t_enq", None)
                md = {
                    "stage_breakdown_us": (
                        f"queue={int((t.t0 - t_enq) * 1e6)},{stage_base}"
                        if t_enq is not None
                        else stage_base
                    )
                }
            fut.set_result(
                RateLimitResp(
                    status=status,
                    limit=int(lim[lane]),  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
                    remaining=int(rem[lane]),  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
                    reset_time=int(rst[lane]),  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
                    **({"metadata": md} if md else {}),
                )
            )
        if hk is not None and hk_agg:
            hk.update([(k, v[0], v[1], v[2]) for k, v in hk_agg.items()])
        em.observe_stage("resolve", time.perf_counter() - t_sync)
        self._observe_overlap(t)

    def _recover_after_failure(self) -> bool:
        """Completion-stage recovery entry (EngineBase._ticket_failed):
        rebuild whichever tier's donated state the failed flush consumed
        or poisoned, at most once."""
        with self._lock:
            return self._recover_tables_locked()
