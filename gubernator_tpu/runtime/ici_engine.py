"""IciEngine: the unified engine core served over a multi-device mesh.

IciEngine IS MeshEngine (runtime/engine.py) bound to the mesh topology
strategy (runtime/topology.py IciMeshTopology): the pump, pipeline ring,
ticket lifecycle, failure recovery, drain, snapshots, and census /
admission caching are the single core's — this file adds only what is
genuinely ici-specific policy: the GLOBAL sync *cadence* (background
tick thread + overflow/backlog counters) and the replica-targeted
`inject_globals`. It replaces the host-level peer mesh *inside* the
process (SURVEY.md §2.3):

- Non-GLOBAL traffic runs through the owner-sharded decide
  (parallel/mesh.py): the table shards across devices, one SPMD call per
  wave answers every lane at its owner. This is the collective analog of
  peer forwarding.
- GLOBAL traffic runs through per-device replicas (parallel/ici.py):
  lanes are assigned a home device round-robin (modeling which "node"
  the request hit), answered locally from that device's replica, and a
  background sync thread runs the collective delta/rebroadcast tick on
  the GlobalSyncWait cadence — the globalManager with psums instead of
  gRPC.
- The paged table works here exactly as on one chip: the mesh kernel
  facade keeps the physical frames sharded and the page map replicated,
  and the Pager runs one frame pool + host-DRAM cold tier PER SHARD
  (docs/architecture.md "Paged table").

The public surface matches DeviceEngine (check_async/check_bulk/
check_batch/close/inject_globals/snapshot/restore), so V1Service and the
daemon can use either; a daemon configured with global_mode="ici" serves
a whole pod as one process with no intra-pod RPCs.

Wave rules differ per path: sharded lanes split on slot-group conflicts
(scatter disjointness per device); replica lanes split on (home, group)
conflicts (same key on the same replica must serialize, but the same key
on different replicas is exactly multi-node GLOBAL behavior and may
share a wave).

guberlint GL013 (engine-core-drift) ratchets this file: a method here
whose name shadows a MeshEngine core method needs an explicit pragma —
the dispatch/complete/recovery logic must never re-fork.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

import jax
import numpy as np

from gubernator_tpu.api.keys import key_hash128_batch
from gubernator_tpu.runtime.engine import MeshEngine, _WaveAssembler
from gubernator_tpu.runtime.topology import IciMeshTopology
from gubernator_tpu.runtime import telemetry as _telemetry
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.utils import tracing
from gubernator_tpu.utils import transfer as _transfer

log = logging.getLogger("gubernator_tpu.ici")


@dataclasses.dataclass
class IciEngineConfig:
    devices: Optional[list] = None  # default: all jax.devices()
    num_groups: int = 1 << 12  # sharded-table groups (divisible by n_dev)
    ways: int = 8
    num_slots: int = 1 << 14  # replica-table slots (num_slots/replica_ways groups)
    replica_ways: int = 4  # replica-table associativity (parallel/ici.py)
    batch_size: int = 1024
    batch_limit: int = 1000
    batch_wait_s: float = 500e-6
    max_flush_items: int = 8192
    max_waves: int = 32  # per-flush wave cap; overflow carries over
    sync_wait_s: float = 0.1  # GLOBAL sync cadence (reference 100ms)
    # Observability knobs — same semantics as EngineConfig (GUBER_HOTKEYS_K
    # / GUBER_STAGE_METADATA / GUBER_EXEMPLARS; docs/monitoring.md).
    hotkeys_k: int = 128
    stage_metadata: bool = False
    exemplars: bool = True
    # Table-census knobs — same semantics as EngineConfig
    # (GUBER_TABLE_CENSUS_TTL / _THRESHOLDS / _HEATMAP; the census runs
    # over BOTH tiers: sharded table + replica 0 of the GLOBAL tier).
    census_ttl_s: float = 5.0
    census_thresholds: tuple = (1, 4, 16)
    census_heatmap_width: int = 64
    # Admission-accounting cadence — same semantics as EngineConfig
    # (GUBER_ADMISSION_TTL; the scan covers BOTH tiers).
    admission_ttl_s: float = 5.0
    # Table layout for BOTH the sharded and replica tiers (the
    # ops/kernels.py LAYOUTS registry; "narrow" halves probe DMA at
    # large tables); fused is the TPU production layout (VERDICT r4
    # item 2).
    layout: str = "fused"
    # Per-tick sync work cap (groups). The tick merges only groups whose
    # content diverges across replicas or that hold pending deltas, up
    # to this many per tick (overflow carries; diag backlog gauge).
    # Bounds tick device time by ACTIVE traffic instead of table size,
    # keeping the 100ms cadence at 10M+ key geometries. None = merge
    # the full table every tick.
    max_sync_groups: "int | None" = 65536
    # Fingerprint-collision backstop (GUBER_ICI_FULL_TICK_EVERY): the
    # capped tick selects groups by comparing two salted
    # non-cryptographic fingerprints across replicas — a collision makes
    # a diverged group look converged and strands it forever. Forcing a
    # full-table tick every N capped ticks bounds that window to
    # N * sync_wait_s. 0 = off; ignored when max_sync_groups is None
    # (the uncapped tick already merges the full table).
    full_tick_every: int = 64
    # Continuous-batching pipeline depth (GUBER_PIPELINE_DEPTH): max
    # flushes dispatched-but-unsynced at once; 1 = serial pump. Same
    # semantics as EngineConfig.pipeline_depth — both ici tiers'
    # (sharded + replica) waves launch in the dispatch stage and sync
    # in the completion stage.
    pipeline_depth: int = 2
    # Paged-table knobs (GUBER_TABLE_PAGE_*) — same semantics as
    # EngineConfig: page_groups > 0 swaps the sharded tier to the paged
    # addressing layer (parallel/mesh.py), with the page map replicated
    # across the mesh, the physical frames owner-sharded, and one
    # resident-frame pool + host-DRAM cold tier per shard. The replica
    # tier stays flat (it is already capacity-bounded per device).
    page_groups: int = 0
    page_budget: int = 0
    page_demote_interval_s: float = 2.0
    page_free_target: int = 1
    # Key-string dictionary (GUBER_KEEP_KEY_STRINGS semantics): needed
    # for routable Loader/handover snapshots — same default as
    # EngineConfig. record_columnar_keys stays off (the columnar edge
    # on this engine predates the dictionary; object-path and inject
    # traffic keep it complete enough for handover).
    keep_key_strings: bool = True
    record_columnar_keys: bool = False
    # Columnar width buckets stay off: every narrowed width would
    # cold-compile a second SPMD program per shape on the mesh.
    fast_buckets: bool = False


class IciEngine(MeshEngine):
    # GLOBAL-flagged requests are routed to the replica tier inside the
    # engine; V1Service must not strip the flag (see the GLOBAL bulk
    # submission in server._get_rate_limits)
    routes_global_internally = True

    def __init__(self, config: IciEngineConfig = IciEngineConfig(), now_fn=_clock.now_ms):
        cfg = config
        devices = cfg.devices or jax.devices()
        if cfg.num_groups % len(devices):
            raise ValueError("num_groups must divide by device count")
        if cfg.num_slots % (cfg.replica_ways * len(devices)):
            raise ValueError(
                "num_slots must divide by replica_ways * device count"
            )
        # Sync-cadence counters exist BEFORE the core constructor: the
        # metrics bridge may scrape a half-built engine during warmup.
        # Overflow observability (VERDICT r3 item 5): keys degraded to
        # per-replica counting right now, and a running total of overflow
        # entries dropped under full-group pressure.
        self._sync_errors = 0
        self.overflow_keys = 0
        self.overflow_drops = 0
        self.sync_backlog = 0
        # Backstop bookkeeping (gubernator_ici_full_ticks): host-side
        # capped-tick counter and a running total of forced full ticks.
        self.full_ticks = 0
        self._capped_ticks = 0

        super().__init__(cfg, now_fn, topology=IciMeshTopology(devices))

        self._stop_sync = threading.Event()
        self._sync_thread = threading.Thread(
            target=self._sync_loop, daemon=True, name="ici-sync"
        )
        self._sync_thread.start()

    # -- compat views over the core's topology state --------------------------

    @property
    def n_dev(self) -> int:
        return self.topo.n_dev

    @property
    def mesh(self):
        return self.topo.mesh

    @property
    def num_rgroups(self) -> int:
        return self._rtier.num_rgroups

    @property
    def ici_state(self):
        return self._rtier.state

    @ici_state.setter
    def ici_state(self, state) -> None:
        self._rtier.state = state

    # -- public additions over the core ---------------------------------------

    def sync_now(self) -> None:
        """Run one GLOBAL sync tick immediately (tests/benchmarks; the
        background sync thread's tick body)."""
        now = self.now_fn()
        t0 = time.perf_counter()
        rt = self._rtier
        with self._lock, self.topo.dispatch_guard():
            # The tick is warmed in _warmup and must stay compile-free on
            # the 100ms cadence — a cold tick stalls GLOBAL convergence,
            # so it counts against the cold-compile invariant too.
            with _telemetry.serving_scope(self.metrics), tracing.span(
                "ici.sync_tick", level="DEBUG"
            ) as tick_span:
                sync = rt.sync
                if rt.sync_full is not None:
                    self._capped_ticks += 1
                    if self._capped_ticks >= self.cfg.full_tick_every:
                        # Collision backstop: merge the FULL table this
                        # tick, healing any group a fingerprint collision
                        # hid from the capped selector.
                        self._capped_ticks = 0
                        self.full_ticks += 1
                        sync = rt.sync_full
                rt.state, diag = sync(rt.state, now)
                with _transfer.account(self.metrics, "d2h", "census") as tx:
                    d = np.asarray(diag)
                    tx.add(d)
            # kept/dropped cover groups merged THIS tick; under a capped
            # backlog, retained keys in unmerged groups surface when
            # their group's turn comes. The backlog gauge (identical on
            # every device; diag rows replicate it) is the overload
            # signal.
            self.overflow_keys = int(d[:, 0].sum())
            self.overflow_drops += int(d[:, 1].sum())
            self.sync_backlog = int(d[:, 2].max())
        dur = time.perf_counter() - t0
        groups = int(d[:, 3].max())
        em = self.metrics
        em.ici_tick_duration.observe(dur)
        em.ici_tick_groups.observe(groups)
        em.recorder.record(
            path="ici-sync", layout=self.cfg.layout, groups=groups,
            backlog=self.sync_backlog, overflow_keys=self.overflow_keys,
            dur_us=int(dur * 1e6),
            trace_id=tracing.trace_id_of(tick_span),
        )

    def inject_globals(self, globals_) -> None:  # guberlint: allow-engine-core-drift -- replica-tier semantics: authoritative pushes land on EVERY replica, not the sharded table
        """Apply an authoritative UpdatePeerGlobals push to every replica
        (the cross-pod/DCN leg landing on an ici-mode daemon)."""
        from gubernator_tpu.models.bucket import FIXED_SHIFT
        from gubernator_tpu.ops.inject import InjectBatch

        if not globals_:
            return
        now = self.now_fn()
        cfg = self.cfg
        rt = self._rtier
        asm = _WaveAssembler(InjectBatch.zeros, cfg.batch_size)
        hi_a, lo_a, slot_a = key_hash128_batch(
            [g.key for g in globals_], rt.num_rgroups
        )
        for i, g in enumerate(globals_):
            slot = int(slot_a[i])
            ib, w, lane = asm.place(slot)
            leaky = int(g.algorithm) == 1
            ib.key_hi[lane] = int(hi_a[i])
            ib.key_lo[lane] = int(lo_a[i])
            ib.group[lane] = slot
            ib.algo[lane] = int(g.algorithm)
            ib.status[lane] = int(g.status.status)
            ib.limit[lane] = g.status.limit
            ib.duration[lane] = g.duration
            ib.remaining[lane] = (
                g.status.remaining << FIXED_SHIFT if leaky else g.status.remaining
            )
            ib.stamp[lane] = now
            ib.expire_at[lane] = g.status.reset_time
            ib.burst[lane] = g.status.limit if leaky else 0
            ib.active[lane] = True
            asm.commit(w, slot)
        with self._lock, self.topo.dispatch_guard():
            state = rt.state
            with _transfer.account(self.metrics, "h2d", "inject") as tx:
                for ib in asm.waves:
                    state = rt.inject(state, ib, now)
                    tx.add(ib)
            rt.state = state

    def close(self) -> None:  # guberlint: allow-engine-core-drift -- adds the sync-thread teardown around the core's close; all drain logic stays super()'s
        self._stop_sync.set()
        super().close()
        self._sync_thread.join(timeout=5)

    # -- sync loop -------------------------------------------------------------

    def _sync_loop(self) -> None:
        while not self._stop_sync.wait(self.cfg.sync_wait_s):
            wd = self.watchdog
            if wd is not None:
                wd.beat("ici-sync", period_s=self.cfg.sync_wait_s)
            try:
                self.sync_now()
                self._sync_errors = 0
            except Exception:
                # Surface persistent failures: without sync, replicas stop
                # converging and GLOBAL limits silently stop aggregating.
                self._sync_errors += 1
                if self._sync_errors in (1, 10) or self._sync_errors % 100 == 0:
                    log.exception(
                        "GLOBAL ICI sync tick failed (%d consecutive)",
                        self._sync_errors,
                    )
