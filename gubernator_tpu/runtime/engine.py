"""Unified mesh engine: micro-batch assembly + the TPU-resident counter
table, ONE core parameterized by mesh shape (runtime/topology.py).

This is the TPU-native replacement for the reference's entire execution
engine (reference workers.go:54-626): instead of sharding the key space
across single-threaded goroutine workers with channel hops, requests
accumulate into fixed-shape device batches and one jitted decide() call
updates the HBM slot table in place. At mesh shape ``(1,)`` that table
lives on one chip (DeviceEngine); at ``(chips,)`` it shards across the
mesh under shard_map with psum-merged outputs, plus a per-device GLOBAL
replica tier (IciEngine, runtime/ici_engine.py) — same core, same wave
assembler, same pipeline, different strategy object.

The micro-batching policy transfers directly from the reference's peer
batching (reference peer_client.go:284-337; config.go:126-128): flush at
`batch_limit` items or after `batch_wait` (default 500µs), whichever
first; NO_BATCHING requests flush immediately.

Duplicate handling (SURVEY.md §7 hard part (a)): the reference serializes
same-key requests through one worker, so in-batch duplicates see each
other's effects in request order, and an over-limit rejection does NOT
consume. The assembler reproduces this with *waves*: within one flush,
requests whose slot-group is already taken by an earlier request go to the
next wave; waves execute as sequential decide() calls. Group (not key)
granularity also guarantees scatter-disjointness inside each wave.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from gubernator_tpu.utils import lockorder
from gubernator_tpu.utils import raceguard
from gubernator_tpu.api.keys import group_of, key_hash128, key_hash128_batch
from gubernator_tpu.api.types import (
    Behavior,
    ERR_ENGINE_DRAINING,
    RateLimitReq,
    RateLimitResp,
    validate_request,
)
from gubernator_tpu.ops.encode import EncodeError, encode_one, encode_rows
from gubernator_tpu.ops.layout import RequestBatch, SlotTable
from gubernator_tpu.ops.kernels import (
    get_admission,
    get_census,
    get_kernels,
    get_paged_kernels,
)
from gubernator_tpu.runtime import telemetry as _telemetry
from gubernator_tpu.runtime.topology import SingleChipTopology
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.utils import tracing
from gubernator_tpu.utils import transfer as _transfer


class TableCommittedError(RuntimeError):
    """A device/store failure occurred AFTER waves of this flush already
    committed hits to a still-valid table. Callers must NOT silently
    retry through another path (that would re-apply the committed hits);
    surface the failure to the client instead."""


@dataclasses.dataclass
class EngineConfig:
    """Sizing and batching knobs (defaults mirror the reference's
    BehaviorConfig, config.go:126-140, adapted to device batches)."""

    num_groups: int = 1 << 15  # 32k groups x 8 ways = 256k slots
    ways: int = 8
    batch_size: int = 1024  # lanes per device batch (fixed shape)
    batch_limit: int = 1000  # max requests accumulated per flush
    batch_wait_s: float = 500e-6  # 500 µs
    max_flush_items: int = 8192  # hard cap pulled off the queue per flush
    # Bound per-flush latency: a flush full of same-key duplicates would
    # otherwise serialize into thousands of waves; overflow items carry
    # over to the next flush in arrival order.
    max_waves: int = 32
    keep_key_strings: bool = True  # hash -> string dict (Loader/debug)
    # Record key strings on the STORE-LESS columnar edge too (bulk
    # membership probe + decode of never-seen keys only). Required for
    # ownership handover — an anonymous row cannot be ring-placed at its
    # new owner; daemons running GUBER_HANDOVER=off with no Loader can
    # drop it for the last word of fastpath host time.
    record_columnar_keys: bool = True
    # Graceful-drain budget (GUBER_DRAIN_TIMEOUT): on close() the pump
    # keeps serving whatever is already queued for up to this long;
    # only stragglers past the budget fail, and they fail with the
    # typed retryable status (api.types.ERR_ENGINE_DRAINING) so edges
    # and clients can re-dispatch instead of reporting a loss.
    drain_timeout_s: float = 5.0
    # Continuous-batching pipeline depth (GUBER_PIPELINE_DEPTH): max
    # flushes in flight at once — dispatched to the device (JAX async
    # dispatch; the table threads flush-to-flush as a device-side
    # dependency through the donated buffers) but not yet synced. Depth
    # 1 = the classic serial pump (dispatch, sync, resolve, repeat);
    # depth >= 2 adds a completion thread that syncs tickets in FIFO
    # order while the pump encodes the NEXT flush, so the device never
    # waits on host encode and p99 tracks device time, not dispatch
    # RTT. Decisions are bit-exact across depths (device execution
    # order == dispatch order). A Store pins the effective depth at 1:
    # its read-through probes sync inside the dispatch stage and
    # write-behind must not race the next flush's prefetch.
    pipeline_depth: int = 2
    # Top-K hot-key attribution (GUBER_HOTKEYS_K): tracked entries in
    # the space-saving sketch updated at the flush boundary (keys are
    # already on host there) and served at /debug/hotkeys + as the
    # cardinality-bounded gubernator_hotkey_hits metric. 0 disables the
    # sketch entirely (update sites check once per flush, no per-item
    # cost).
    hotkeys_k: int = 128
    # Per-request stage breakdown in response metadata
    # (GUBER_STAGE_METADATA, default off): when on, each response
    # carries a `stage_breakdown_us` metadata entry with the serving
    # flush's intake->resolve stage times so clients can see where
    # their p99 went. Off = zero per-item bookkeeping.
    stage_metadata: bool = False
    # OpenMetrics exemplars (GUBER_EXEMPLARS): attach the flush span's
    # trace id to the histogram bucket each flush lands in. Only does
    # anything when an OTel SDK records spans AND the scraper negotiates
    # OpenMetrics; off = never attach.
    exemplars: bool = True
    # Background-compile power-of-two batch widths (128..batch_size) so
    # the columnar edge can size the kernel to each call's occupancy.
    fast_buckets: bool = False
    device: Optional[object] = None  # jax device for the table
    # Table layout: "wide" (one int64 column per field), "packed"
    # (narrowed columns, 3-gather probe), "fused" (one (N, C) tensor,
    # one gather + one scatter, see ops/fused.py), or "narrow" (fused
    # v2: probe reads a 5-column row prefix, half the probe DMA — see
    # ops/narrow.py). All are oracle-exact; Loader snapshots are
    # portable across them (ops/kernels.py LAYOUTS).
    layout: str = "fused"
    # Table observatory (docs/monitoring.md "Table census"): TTL of the
    # cached census snapshot (GUBER_TABLE_CENSUS_TTL) — every scrape
    # surface (occupancy gauges, /debug/table, DebugInfo) reads the
    # cache, so at most ONE census program runs per interval and a
    # slow/concurrent scrape can never stall the pump.
    census_ttl_s: float = 5.0
    # Cold-set idleness thresholds (GUBER_TABLE_CENSUS_THRESHOLDS): a
    # used slot is "cold at kx" when its idle time exceeds k x its own
    # duration; each threshold reports count + reclaimable bytes.
    census_thresholds: tuple = (1, 4, 16)
    # Occupancy heatmap width (GUBER_TABLE_CENSUS_HEATMAP): the group
    # axis aggregates into this many contiguous regions — the future
    # paged-table "page" axis (ROADMAP item 1).
    census_heatmap_width: int = 64
    # Admission observatory (docs/monitoring.md "Admission"): TTL of
    # the cached admitted-vs-limit accounting scan (GUBER_ADMISSION_TTL)
    # — every scrape surface (/debug/admission, the SLI gauges, the
    # auditor's admission pass) reads the cache, so at most ONE
    # admission program runs per interval.
    admission_ttl_s: float = 5.0
    # ---- paged table (GUBER_TABLE_PAGE_*, docs/architecture.md
    # "Paged table") ----
    # Groups per page (GUBER_TABLE_PAGE_GROUPS): 0 keeps the classic
    # flat table; > 0 carves the table into fixed-size pages behind a
    # device-resident indirection map (ops/paged.py) with a host-DRAM
    # cold tier for demoted pages (runtime/pager.py). The keyspace
    # (num_groups) stays logical; HBM holds only page_budget pages.
    page_groups: int = 0
    # Resident-page budget (GUBER_TABLE_PAGE_BUDGET): physical page
    # frames in HBM. Required > 0 when page_groups > 0. HBM table bytes
    # = page_budget x page_groups x ways x bytes_per_slot.
    page_budget: int = 0
    # Background demoter cadence (GUBER_TABLE_PAGE_DEMOTE_INTERVAL):
    # seconds between demoter passes; 0 disables the thread (pages then
    # demote only on free-frame pressure in the serving path).
    page_demote_interval_s: float = 2.0
    # Free-frame target (GUBER_TABLE_PAGE_FREE_TARGET): the demoter
    # keeps at least this many frames free so promotions on the serving
    # path rarely pay a demand demote (a device sync under the lock).
    page_free_target: int = 1


class EngineMetrics:
    """Counters + device-tier distributions the observability layer
    exports (scalar names map to the reference's Prometheus catalog,
    docs/prometheus.md; the histogram families, flight recorder, and
    cold-compile counter are this port's device-tier additions —
    docs/monitoring.md). Wired into a daemon's Metrics registry by
    metrics.wire_engine_telemetry()."""

    def __init__(self):
        from gubernator_tpu.metrics import engine_histograms
        from gubernator_tpu.runtime.telemetry import (
            FlightRecorder,
            install_compile_listener,
        )

        self.lock = lockorder.make_lock("engine.metrics")
        self.cache_hits = 0
        self.cache_misses = 0
        self.unexpired_evictions = 0
        self.over_limit = 0
        self.batches = 0
        self.waves = 0
        self.requests = 0
        self.batch_duration_sum = 0.0
        self.cold_compiles = 0
        # Device-tier histograms (families defined once in metrics.py so
        # the exposition catalog and this class cannot drift).
        hists = engine_histograms()
        for attr, h in hists.items():
            setattr(self, attr, h)
        self._histograms = tuple(hists.values())
        # Pre-resolved stage children (labels() lookups are per-flush
        # hot-path cost; see observe_stages).
        self._stage = {
            s: self.stage_duration.labels(s)
            for s in (
                "intake", "assemble", "dispatch", "inflight_wait",
                "device_sync", "resolve",
            )
        }
        self.recorder = FlightRecorder()
        install_compile_listener()

    def histograms(self) -> tuple:
        return self._histograms

    def observe_stage(self, stage: str, dur: float) -> None:
        self._stage[stage].observe(dur)

    def observe_transfer(self, direction: str, purpose: str,
                         n_bytes: int, dur: float) -> None:
        """One accounted host<->device transfer (utils/transfer.py):
        per-(direction, purpose) bytes + latency distributions — the
        promote/demote bandwidth ledger (docs/monitoring.md "Device
        resources")."""
        self.transfer_duration.labels(direction, purpose).observe(dur)
        self.transfer_bytes.labels(direction, purpose).observe(n_bytes)

    def transfer_snapshot(self) -> dict:
        """JSON ledger view: per-(direction, purpose) transfer counts,
        total bytes, and latency quantiles — /debug/device and the
        bench `device` blob read this."""
        out = {}
        for key, s in self.transfer_bytes.label_summaries(qs=()).items():
            out["/".join(key)] = {
                "count": s["count"],
                "bytes": int(s["sum"]),  # guberlint: allow-host-sync -- histogram summary dict, host-only data
            }
        for key, s in self.transfer_duration.label_summaries(
            qs=(0.5, 0.99)
        ).items():
            ent = out.setdefault(
                "/".join(key), {"count": s["count"], "bytes": 0}
            )
            ent["seconds"] = s["sum"]
            ent["p50_s"] = s["p50"]
            ent["p99_s"] = s["p99"]
            secs = ent.get("seconds") or 0.0
            ent["bytes_per_s"] = (
                ent["bytes"] / secs if secs > 0 else 0.0
            )
        return out

    def note_cold_compile(self) -> None:
        with self.lock:
            self.cold_compiles += 1

    def observe(self, hits, misses, evic, over, waves, n, dur):
        with self.lock:
            self.cache_hits += hits
            self.cache_misses += misses
            self.unexpired_evictions += evic
            self.over_limit += over
            self.batches += 1
            self.waves += waves
            self.requests += n
            self.batch_duration_sum += dur

    def observe_flush(self, path: str, n: int, waves: int, dur: float,
                      dev: float, trace_id: str = "",
                      collective: bool = False) -> None:
        """One flush's distribution samples (per FLUSH, not per
        request). A non-empty trace_id attaches an OpenMetrics exemplar
        to the latency buckets this flush lands in, so a p99 spike in
        Grafana clicks through to the exact trace. `collective` (mesh
        topologies) additionally lands the device time in the
        collective-tick histogram: on a sharded decide the psum merge
        rendezvouses every shard, so this distribution is the
        shard-skew amplifier the SLO layer watches."""
        self.flush_duration.labels(path).observe(dur, trace_id)
        self.device_sync.labels(path).observe(dev, trace_id)
        self.batch_width.labels(path).observe(n)
        self.flush_waves.observe(waves)
        if collective:
            self.collective_tick.observe(dev)


class _Slot:
    """Lock-free result slot for bulk submissions: Future.set_result costs
    ~12µs in lock/notify overhead per item; bulk callers only need the
    final list, so members use plain assignment and ONE real Future
    resolves when the whole entry is processed.

    `span` (the caller's request span, captured once per bulk) and
    `t_enq` (enqueue stamp for GUBER_STAGE_METADATA) are observability
    side-channels — both stay None on the knob-off path. `deadline_ms`
    (absolute epoch ms, GUBER_OVERLOAD only) lets the pump drop the
    member at pickup when the caller already gave up."""

    __slots__ = ("value", "_done", "span", "t_enq", "deadline_ms")

    def __init__(self):
        self.value = None
        self._done = False
        self.span = None
        self.t_enq = None
        self.deadline_ms = None

    def set_result(self, v) -> None:
        self.value = v
        self._done = True

    def done(self) -> bool:
        return self._done


class _FlushTicket:
    """One dispatched-but-unsynced flush traveling the dispatch ->
    completion pipeline: the device outputs (un-materialized JAX arrays),
    the host bookkeeping needed to demux them, and the timing marks the
    completion stage turns into histogram samples. Built by an engine's
    _dispatch, consumed exactly once by its _complete (FIFO)."""

    __slots__ = (
        "items",        # [(req, future-like)] — the flush's intake
        "placements",   # per-item routing (engine-specific)
        "outs",         # per-wave DecideOutputs (device arrays)
        "r_outs",       # ici replica-tier outputs (device arrays)
        "rows",         # store path: materialized per-wave gathered rows
        "events",       # store path: ('d'|'i', key) displacement events
        "served",       # items answered by this flush (excludes carry)
        "carry_n",      # items deferred to the next flush (wave cap)
        "waves",        # wave count
        "widths",       # per-wave device batch widths
        "t0",           # flush assembly start (perf_counter)
        "t_dev",        # device dispatch start
        "t_disp_end",   # dispatch stage end (set by EngineBase._process)
        "host_mark",    # cumulative pump host-busy time at dispatch end
        "seq",          # monotonic flush-ticket sequence (join key)
        "span",         # flush OTel span (dispatch->completion lifecycle)
        "otel_ctx",     # dispatch-time trace context for _complete
        "trace_id",     # sampled trace id hex ('' when unsampled/off)
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


def _materialize_out(o) -> tuple:
    """One wave's DecideOutputs pulled to host — THE completion-stage
    flush-boundary readback (pipelined engines run it off the pump
    thread, so the device never waits on host encode)."""
    return (
        np.asarray(o.status),  # guberlint: allow-host-sync -- completion-stage flush-boundary readback
        np.asarray(o.remaining),  # guberlint: allow-host-sync -- completion-stage flush-boundary readback
        np.asarray(o.reset_time),  # guberlint: allow-host-sync -- completion-stage flush-boundary readback
        np.asarray(o.limit),  # guberlint: allow-host-sync -- completion-stage flush-boundary readback
        int(o.hits),
        int(o.misses),
        int(o.unexpired_evictions),
        int(o.over_limit),
    )


class _WaveAssembler:
    """First-fit placement of requests into scatter-disjoint waves: a
    request goes to the first wave where its slot-group is unused and a
    lane is free. Same key => same group => strictly increasing wave
    index, which preserves per-key request order."""

    def __init__(self, make_batch, batch_size: int):
        self._make = make_batch
        self._B = batch_size
        self.waves: List[object] = []
        self._groups: List[set] = []
        self._fill: List[int] = []

    def place(self, grp: int, max_waves: Optional[int] = None):
        """Returns (wave_batch, wave_index, lane), or None if placement
        would exceed max_waves (caller carries the item to the next
        flush)."""
        w = 0
        while True:
            if w == len(self.waves):
                if max_waves is not None and w >= max_waves:
                    return None
                self.waves.append(self._make(self._B))
                self._groups.append(set())
                self._fill.append(0)
            if grp not in self._groups[w] and self._fill[w] < self._B:
                return self.waves[w], w, self._fill[w]
            w += 1

    def commit(self, w: int, grp: int) -> None:
        self._groups[w].add(grp)
        self._fill[w] += 1

    def fill(self, w: int) -> int:
        """Occupied lanes in wave w (the wave's device-width floor)."""
        return self._fill[w]


class EngineBase:
    """Shared request intake for device engines: the queue, the bulk
    submission path, and the pump thread's accumulate-and-flush loop
    (the reference's micro-batch policy, peer_client.go:284-337).

    Subclasses provide cfg (batch_wait_s/batch_limit/max_flush_items/
    max_waves/pipeline_depth), now_fn, metrics, and the two pipeline
    stages: _dispatch(items) -> (carry, ticket) — assemble + encode on
    host and launch the kernels WITHOUT a host sync — and
    _complete(ticket) — materialize device results, feed telemetry, and
    resolve futures. carry is the list of (req, future) pairs the flush
    could not place (wave cap); the pump re-presents them first on the
    next flush. _process glues the stages: serially at depth 1 (today's
    pump, bit-exact), through the bounded in-flight ring + completion
    thread at depth >= 2 (continuous batching: host encode of flush N+1
    overlaps device execution of flush N)."""

    @raceguard.init_path
    def _init_base(self, thread_name: str) -> None:
        # guberlint: allow-unbounded-queue -- bounded at intake by the overload governor (GUBER_INTAKE_LIMIT sheds past-budget puts in check_async/check_bulk); knob-off keeps the historical unbounded bit-exact contract
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._running = True
        # Intake admission governor (service/overload.py IntakeGovernor,
        # duck-typed like the watchdog seam): the daemon injects it when
        # GUBER_OVERLOAD is on; None means admit everything (bit-exact).
        self.overload = None
        self._draining = False
        # Flush-ticket sequence (pump-thread only; the drain pass runs
        # on the same thread): the /debug/engine <-> trace join key.
        self._ticket_seq = 0
        self._stage_md = bool(getattr(self.cfg, "stage_metadata", False))
        hk = getattr(self.metrics, "hotkeys", None)
        if hk is not None:
            hk.configure(int(getattr(self.cfg, "hotkeys_k", 128) or 0))
            if hasattr(self, "key_string"):
                hk.set_resolver(self.key_string)
        # Bulk entries whose members may span flushes (wave-cap carry);
        # resolved by whichever thread completes their last member.
        self._bulks: List[_Bulk] = []
        self._bulks_lock = lockorder.make_lock("engine.bulks")
        # Table-census cache (docs/monitoring.md "Table census"): every
        # scrape surface reads this snapshot, so at most one census
        # program runs per TTL interval and scrapes never hold the
        # serving lock through device work (guberlint GL009).
        self._census_lock = lockorder.make_lock("engine.census")
        self._census_cache: Optional[dict] = None
        self._census_ts = 0.0
        self._census_prev = None  # (t_mono, misses, evictions, live)
        # Admission-accounting cache (docs/monitoring.md "Admission"):
        # same single-scan-per-TTL contract as the census, separate
        # cadence knob (GUBER_ADMISSION_TTL).
        self._admission_lock = lockorder.make_lock("engine.admission")
        self._admission_cache: Optional[dict] = None
        self._admission_ts = 0.0
        # Shard-skew attribution (multi-device topologies only):
        # cumulative per-shard decided-lane counts, host numpy, updated
        # by the pump at wave granularity (docs/monitoring.md "SLOs &
        # burn rates"). The future PodSliceTopology placement work will
        # be judged against this skew signal (ROADMAP item 1).
        self._shard_lock = lockorder.make_lock("engine.shards")
        self._shard_decisions = (
            np.zeros(self.topo.n_dev, dtype=np.int64)
            if self.topo.n_dev > 1
            else None
        )
        # Cumulative pump time spent in _dispatch (host encode + launch);
        # pump-thread-only writer, read by the completion stage for the
        # host/device overlap ratio.
        self._host_busy = 0.0
        # Liveness (runtime/watchdog.py): the daemon injects its
        # Watchdog after construction; until then beats are no-ops.
        # The pump and completion threads are SERVING loops — their
        # stall burns the availability SLO, not just a lamp.
        self.watchdog = None
        depth = max(int(getattr(self.cfg, "pipeline_depth", 1) or 1), 1)
        self._pipe_depth = depth
        self._pipe_q: Optional["queue.SimpleQueue"] = None
        self._pipe_thread: Optional[threading.Thread] = None
        if depth > 1:
            # In-flight ring: the semaphore's permits ARE the ring slots
            # (backpressure: the pump blocks acquiring a slot before it
            # launches more device work); the SimpleQueue carries tickets
            # to the completion thread in FIFO dispatch order.
            self._pipe_sem = threading.Semaphore(depth)
            # guberlint: allow-unbounded-queue -- bounded by construction: the pipeline semaphore's `depth` permits cap how many tickets can be in the queue at once
            self._pipe_q = queue.SimpleQueue()
            self._pipe_lock = lockorder.make_lock("engine.pipeline")
            self._inflight = 0
            self._pipe_thread = threading.Thread(
                target=self._completion_loop,
                name=thread_name + "-complete", daemon=True,
            )
            self._pipe_thread.start()
        self._thread = threading.Thread(
            target=self._pump, name=thread_name, daemon=True
        )
        self._thread.start()

    # -- two-stage pipeline --------------------------------------------------

    def _pipeline_active(self) -> bool:
        """Pipelined completion applies only while serving (the drain
        pass completes inline for deterministic straggler accounting)
        and only store-less: the Store path's read-through probes sync
        inside the dispatch stage anyway, and its write-behind must not
        race the NEXT flush's prefetch."""
        return (
            self._pipe_q is not None
            and not self._draining
            and getattr(self, "store", None) is None
        )

    def _process(self, items: List[Tuple[RateLimitReq, object]]) -> list:
        """One flush through both stages. Serial mode (depth 1, store
        attached, or draining): dispatch then complete inline — exactly
        the classic pump. Pipelined mode: dispatch, then hand the ticket
        to the completion thread and return immediately so the pump can
        assemble the next flush while the device executes this one."""
        pipelined = self._pipeline_active()
        if pipelined:
            # Backpressure BEFORE launching more device work: a full
            # ring means the device is the bottleneck — adding waves
            # would only grow the unsynced frontier.
            self._pipe_sem.acquire()
        t_host0 = time.perf_counter()
        try:
            carry, ticket = self._dispatch(items)
        except Exception:
            if pipelined:
                self._pipe_sem.release()
            raise
        end = time.perf_counter()
        self._host_busy += end - t_host0
        if ticket is None:
            if pipelined:
                self._pipe_sem.release()
            return carry
        ticket.t_disp_end = end
        ticket.host_mark = self._host_busy
        if pipelined:
            with self._pipe_lock:
                self._inflight += 1
                depth = self._inflight
            self.metrics.pipeline_inflight.observe(depth)
            self._pipe_q.put(ticket)
        else:
            self.metrics.pipeline_inflight.observe(1)
            self._complete_ticket(ticket)
        return carry

    def _complete_ticket(self, t) -> None:
        """Run the completion stage under the ticket's dispatch-time
        trace context (the completion thread otherwise runs
        context-less — write-behind / resolve errors would land
        trace-orphaned), then end the flush span. The `engine.complete`
        child span gives the completion stage its own timing node with
        thread-crossing parentage under the flush span."""
        err = None
        try:
            # The completion stage is serving-path device work too: its
            # materializations must never compile. PR 6 moved them off
            # the pump thread (whose dispatch-site scope no longer
            # covers them), so mark this thread for the ticket's
            # duration or a completion-side retrace goes uncounted.
            with _telemetry.serving_scope(self.metrics), tracing.attached(
                t.otel_ctx
            ):
                if t.span is not None:
                    with tracing.span(
                        "engine.complete", level="DEBUG", ticket_seq=t.seq
                    ):
                        self._complete(t)
                else:
                    self._complete(t)
        except Exception as e:
            err = e
            raise
        finally:
            tracing.end_span(t.span, error=err)
            t.span = None

    def _completion_loop(self) -> None:
        """Completion stage: sync each in-flight ticket in FIFO dispatch
        order, resolve its futures, feed the histograms. A failed ticket
        fails ONLY its own futures (earlier tickets already completed;
        later ones dispatched against the recovered table) — the loop
        itself never dies while the engine runs."""
        while True:
            # Bounded get so the idle loop still heartbeats: a blocking
            # get() would look wedged to the watchdog whenever no
            # tickets flow, and a REAL wedge (stuck device sync inside
            # _complete_ticket) would be indistinguishable from idle.
            wd = self.watchdog
            if wd is not None:
                wd.beat("engine-complete", serving=True)
            try:
                t = self._pipe_q.get(timeout=0.5)
            except queue.Empty:
                continue
            if t is _STOP:
                return
            try:
                self._complete_ticket(t)
            except Exception as e:
                self._ticket_failed(t, e)
            finally:
                with self._pipe_lock:
                    self._inflight -= 1
                self._pipe_sem.release()
                self._sweep_bulks()

    def _ticket_failed(self, ticket, exc) -> None:
        """An in-flight ticket's results could not be materialized: fail
        that ticket's unresolved futures, then rebuild the table if the
        failed device call consumed (or poisoned) its donated buffers.
        Recovery is idempotent — a healthy table is left alone — so a
        burst of failing tickets rebuilds exactly once."""
        import logging

        err = str(exc)
        # Failure handling runs under the ticket's dispatch-time trace
        # context too: the ERROR-level span (kept at every configured
        # trace level) lands the failure under the flush's trace.
        with tracing.attached(getattr(ticket, "otel_ctx", None)):
            with tracing.span(
                "engine.ticket_failed", level="ERROR", error=err,
                ticket_seq=getattr(ticket, "seq", None) or 0,
            ):
                for _req, fut in ticket.items:
                    if not fut.done():
                        fut.set_result(RateLimitResp(error=err))
                try:
                    self._recover_after_failure()
                except Exception:
                    logging.getLogger(__name__).exception(
                        "table recovery after failed in-flight flush failed"
                    )

    def _observe_overlap(self, ticket) -> None:
        """Host/device overlap sample for one completed flush: host
        dispatch work done for OTHER flushes while this one was in
        flight, as a fraction of its in-flight window. Serial mode pins
        this at 0 — the pump idles while the device runs."""
        window = time.perf_counter() - ticket.t_disp_end
        overlap = self._host_busy - ticket.host_mark
        ratio = min(overlap / window, 1.0) if window > 0 else 0.0
        self.metrics.pipeline_overlap.observe(ratio)

    def _pipeline_quiesce(self) -> None:
        """Wait until every in-flight ticket has completed, and switch
        _process to inline completion (drain mode). Pump-thread only —
        acquiring every ring slot is only ticket-free when no other
        producer can interleave."""
        self._draining = True
        if self._pipe_q is None:
            return
        for _ in range(self._pipe_depth):
            self._pipe_sem.acquire()
        for _ in range(self._pipe_depth):
            self._pipe_sem.release()

    def _sweep_bulks(self) -> None:
        """Resolve bulk futures whose members have all been answered.
        Serial mode sweeps from the pump after each flush; pipelined
        mode sweeps from the completion thread after each ticket."""
        done: List[_Bulk] = []
        with self._bulks_lock:
            still = []
            for b in self._bulks:
                if all(s.done() for s in b.slots):
                    done.append(b)
                else:
                    still.append(b)
            self._bulks[:] = still
        for b in done:
            b.resolve()

    def _resolve_all_bulks(self) -> None:
        """Shutdown tail: resolve every remaining bulk — members never
        served fill in as typed-retryable (see _Bulk.resolve)."""
        with self._bulks_lock:
            rest = list(self._bulks)
            self._bulks[:] = []
        for b in rest:
            b.resolve()

    # -- flush-span lifecycle (docs/monitoring.md "Tracing the pipeline") ----

    def _flush_seq(self) -> int:
        """Next ticket sequence. Pump-thread only (the drain pass runs
        on the pump thread too), so a plain increment suffices."""
        self._ticket_seq += 1
        return self._ticket_seq

    def _start_flush_span(self, flush_items, seq: int, **attributes):
        """Start the per-ticket flush span (ends at completion, possibly
        on another thread) and wire the batch-boundary links: the flush
        span links to each distinct request span it serves, and each
        request span links back to the flush span. Returns None when
        tracing is off — the entire method is then two cheap calls."""
        fspan = tracing.start_span(
            "engine.flush", level="DEBUG",
            pipeline_depth=self._pipe_depth, ticket_seq=seq, **attributes,
        )
        if fspan is None:
            return None
        seen = set()
        for _req, fut in flush_items:
            rs = getattr(fut, "span", None)
            if rs is None or id(rs) in seen:
                continue
            seen.add(id(rs))
            tracing.link(fspan, rs)
            tracing.link(rs, fspan)
        return fspan

    def hotkeys_snapshot(self) -> dict:
        """JSON payload for /debug/hotkeys (service/gateway.py)."""
        hk = getattr(self.metrics, "hotkeys", None)
        if hk is None:
            return {"k": 0, "total_hits": 0, "max_error": 0, "entries": []}
        return hk.snapshot()

    def device_memory(self) -> dict:
        """Per-subsystem HBM attribution + headroom (utils/devicemem.py,
        docs/monitoring.md "Device resources"). Host arithmetic over
        geometry sized at init plus one allocator stats query — never
        dispatches device work, so the scrape-path sync and
        /debug/device can call it freely (GL009)."""
        from gubernator_tpu.utils import devicemem

        subs = dict(getattr(self, "_mem_subsystems", None) or {})
        # snapshot_staging is transient: report the latest staging
        # high-water mark (bytes the last snapshot()/restore() staged),
        # not a phantom always-resident copy.
        subs["snapshot_staging"] = int(
            getattr(self, "_snapshot_staging_bytes", 0)
        )
        subs.setdefault("ici_replicas", 0)
        return devicemem.snapshot(
            subs, device=getattr(self.cfg, "device", None)
        )

    # -- public intake -------------------------------------------------------

    def check_async(self, req: RateLimitReq) -> "Future[RateLimitResp]":
        """Enqueue one request; resolves after its wave executes."""
        t_in = time.perf_counter()
        fut: Future = Future()
        if not self._running:
            # The pump already exited its drain phase; nothing will ever
            # pull this entry, so fail it typed-retryable immediately
            # instead of letting the future hang.
            fut.set_result(RateLimitResp(error=ERR_ENGINE_DRAINING))
            return fut
        err = validate_request(req)
        if err is not None:
            fut.set_result(RateLimitResp(error=err))
            return fut
        ov = self.overload
        if ov is not None:
            shed, dl = ov.admit(req, self._queue.qsize())
            if shed is not None:
                fut.set_result(shed)
                return fut
            if dl is not None:
                fut.deadline_ms = dl
        if req.created_at is None:
            req.created_at = self.now_fn()
        # Request-span capture for the batch-boundary link (None unless
        # an SDK records a span in this caller's context).
        rs = tracing.current_span()
        if rs is not None:
            fut.span = rs
        t_enq = time.perf_counter()
        self.metrics.observe_stage("intake", t_enq - t_in)
        if self._stage_md:
            fut.t_enq = t_enq
        self._queue.put((req, fut, t_enq))
        return fut

    def check_bulk(self, reqs: Sequence[RateLimitReq]) -> "Future[List[RateLimitResp]]":
        """Bulk check: ONE queue entry and ONE Future for N requests
        (amortizes pump wakeups and future overhead; the natural fit for
        the batched GetRateLimits API). Resolves in request order."""
        t_in = time.perf_counter()
        out: Future = Future()
        if not self._running:
            out.set_result(
                [RateLimitResp(error=ERR_ENGINE_DRAINING) for _ in reqs]
            )
            return out
        slots: List[_Slot] = []
        work = []
        now = None
        ov = self.overload
        depth = self._queue.qsize() if ov is not None else 0
        # One request-span capture per BULK (members share the caller's
        # context): the flush that serves them links back to this span.
        rs = tracing.current_span()
        for req in reqs:
            slot = _Slot()
            slot.span = rs
            slots.append(slot)
            err = validate_request(req)
            if err is not None:
                slot.set_result(RateLimitResp(error=err))
                continue
            if ov is not None:
                shed, dl = ov.admit(req, depth)
                if shed is not None:
                    slot.set_result(shed)
                    continue
                if dl is not None:
                    slot.deadline_ms = dl
            if req.created_at is None:
                if now is None:
                    now = self.now_fn()
                req.created_at = now
            work.append((req, slot))
        if work:
            b = _Bulk(work, slots, out)
            self.metrics.observe_stage("intake", b.t_enq - t_in)
            if self._stage_md:
                for s in slots:
                    s.t_enq = b.t_enq
            self._queue.put(b)
        else:
            out.set_result([s.value for s in slots])
        return out

    def check_batch(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        """Synchronous batched check (returns in request order)."""
        return self.check_bulk(reqs).result()

    def flush_now(self) -> None:
        """Force the pump to flush without waiting the batch window."""
        self._queue.put(_FLUSH)

    def close(self) -> None:
        """Drain, then stop. The pump keeps serving whatever is already
        queued (the FIFO guarantees everything enqueued before this call
        is seen before _STOP), then runs a bounded drain pass for
        entries that raced the shutdown; only stragglers past
        cfg.drain_timeout_s fail, with the typed retryable status
        (api.types.ERR_ENGINE_DRAINING) so callers can re-dispatch."""
        drain_s = max(float(getattr(self.cfg, "drain_timeout_s", 5.0)), 0.0)
        self._queue.put(_STOP)
        self._thread.join(timeout=5 + drain_s)
        self._running = False  # backstop for a wedged pump
        # The bucket warmer compiles inside XLA C++ frames; if it is
        # still alive when the interpreter finalizes, its GIL touch
        # turns into pthread_exit's forced unwind through C++ catch(...)
        # blocks — glibc aborts with "FATAL: exception not rethrown".
        # _running=False stops it between shapes; join past the current
        # compile.
        warm = getattr(self, "_warm_thread", None)
        if warm is not None and warm.is_alive():
            warm.join(timeout=60)
        comp = self._pipe_thread
        if comp is not None and comp.is_alive():
            # The pump sends _STOP at the end of its drain; this second
            # sentinel is a backstop for a wedged pump (extra sentinels
            # are harmless — the loop exits on the first one it sees).
            self._pipe_q.put(_STOP)
            comp.join(timeout=5 + drain_s)

    # -- introspection (shared) ----------------------------------------------

    def debug_snapshot(self) -> dict:
        """Telemetry + flight-recorder snapshot served as JSON by the
        /debug/engine endpoint (service/gateway.py). Host-side state
        plus one occupancy readback; safe at poll cadence."""
        em = self.metrics
        cfg = self.cfg
        with em.lock:
            counters = {
                "requests": em.requests,
                "batches": em.batches,
                "waves": em.waves,
                "cache_hits": em.cache_hits,
                "cache_misses": em.cache_misses,
                "unexpired_evictions": em.unexpired_evictions,
                "over_limit": em.over_limit,
                "cold_compiles": em.cold_compiles,
            }
        snap = {
            "engine": type(self).__name__,
            "layout": getattr(cfg, "layout", ""),
            "batch_size": cfg.batch_size,
            "max_waves": cfg.max_waves,
            "pipeline_depth": self._pipe_depth,
            "kernel_backend": getattr(self, "kernel_backend", "xla"),
            "pallas_block": getattr(self, "pallas_block", 0),
            "inflight": getattr(self, "_inflight", 0),
            "queue_depth": self.queue_depth(),
            "counters": counters,
            "histograms": {h.name: h.summary() for h in em.histograms()},
            "flight_recorder": em.recorder.snapshot(),
        }
        if hasattr(self, "occupancy_stats"):
            snap["occupancy"] = self.occupancy_stats()
        return snap

    # -- table census (docs/monitoring.md "Table census") --------------------

    def table_census(self, max_age_s: Optional[float] = None) -> dict:
        """TTL-cached table census — the table observatory's single
        entry point (occupancy gauges, /debug/table, DebugInfo, and the
        occupancy_stats()/live_count() back-compat views all read it).

        The scan runs OFF the hot path and OUTSIDE the pump-critical
        lock section: the engine lock is held only long enough to
        dispatch the NON-donating census program against the live table
        reference (JAX async dispatch — no host sync under the lock);
        the O(buckets) materialization happens after release, in
        _census_scan. Pass max_age_s=0 to force a fresh scan."""
        ttl = (
            float(getattr(self.cfg, "census_ttl_s", 5.0))
            if max_age_s is None
            else float(max_age_s)
        )
        with self._census_lock:
            if (
                self._census_cache is not None
                and time.monotonic() - self._census_ts < ttl
            ):
                return self._census_cache
            snap = self._census_scan()
            snap["churn"] = self._census_churn(snap)
            self._census_cache = snap
            self._census_ts = time.monotonic()
            return snap

    # -- admission accounting (docs/monitoring.md "Admission") ---------------

    def admission_snapshot(self, max_age_s: Optional[float] = None) -> dict:
        """TTL-cached admitted-vs-limit accounting — the admission
        observatory's single entry point (/debug/admission, the SLI
        gauges, DebugInfo, and the auditor's admission pass all read
        it). Same dispatch discipline as table_census: the engine lock
        is held only long enough to dispatch the NON-donating admission
        program (async — no host sync under the lock); the O(buckets)
        materialization happens after release, in _admission_scan.
        Pass max_age_s=0 to force a fresh scan."""
        ttl = (
            float(getattr(self.cfg, "admission_ttl_s", 5.0))
            if max_age_s is None
            else float(max_age_s)
        )
        with self._admission_lock:
            if (
                self._admission_cache is not None
                and time.monotonic() - self._admission_ts < ttl
            ):
                return self._admission_cache
            snap = self._admission_scan()
            self._admission_cache = snap
            self._admission_ts = time.monotonic()
            return snap

    def cached_census(self) -> Optional[dict]:
        """The census snapshot ONLY if already cached — never scans.
        The SLO sampler reads SLIs at a fixed cadence and must do zero
        device work (GL009/cold_compiles==0 pinned): table_census(ttl)
        dispatches a device program when the cache is stale, which a
        background sampler must never trigger on its own clock. Returns
        None until some scrape/debug hit has populated the cache."""
        with self._census_lock:
            return self._census_cache

    def cached_admission(self) -> Optional[dict]:
        """The admission snapshot ONLY if already cached — never scans.
        Same zero-device-work contract as cached_census()."""
        with self._admission_lock:
            return self._admission_cache

    # -- shard-skew attribution (docs/monitoring.md "SLOs & burn rates") -----

    def _note_shard_decisions(self, waves) -> None:
        """Fold each wave's active lanes onto their owning shard.
        Groups map to shards contiguously (parallel/mesh.py
        _mask_to_local: shard = group // groups_per_shard), so a host
        bincount reproduces the device-side ownership split exactly.
        Pure numpy over already-host wave batches — no device work."""
        n_dev = self.topo.n_dev
        groups = (
            self.K.num_phys_pages * self.K.groups_per_page
            if self._pager is not None
            else self.cfg.num_groups
        )
        groups_per = max(groups // n_dev, 1)
        counts = np.zeros(n_dev, dtype=np.int64)
        for wb in waves:
            act = np.asarray(wb.active)  # guberlint: allow-host-sync -- wave batches carry host-built columns, never device tensors
            grp = np.asarray(wb.group)[act]  # guberlint: allow-host-sync -- wave batches carry host-built columns, never device tensors
            if grp.size:
                counts += np.bincount(
                    np.minimum(grp // groups_per, n_dev - 1),
                    minlength=n_dev,
                )
        with self._shard_lock:
            self._shard_decisions += counts

    def shard_stats(self) -> Optional[dict]:
        """Per-shard skew attribution for the mesh path: decisions (the
        ownership split of served lanes), occupancy (census heatmap
        folded onto shard boundaries — regions and shards are both
        contiguous over groups), page-churn / frame-pool pressure (the
        pager's per-shard rows), and the derived max/mean imbalance
        ratio that feeds the shard-balance SLO. None on single-device
        topologies. Zero device work: reads the cumulative host
        counters and the ALREADY-CACHED census only."""
        n_dev = self.topo.n_dev
        if n_dev <= 1 or self._shard_decisions is None:
            return None
        with self._shard_lock:
            decisions = self._shard_decisions.tolist()

        def imbalance(vals) -> Optional[float]:
            total = sum(vals)
            if total <= 0:
                return None
            mean = total / float(len(vals))
            return round(max(vals) / mean, 4)

        out: dict = {
            "n_shards": n_dev,
            "decisions": decisions,
            "decision_imbalance": imbalance(decisions),
        }
        census = self.cached_census()
        if census is not None:
            tier = census.get("tiers", {}).get(
                self.topo.primary_tier, census
            )
            heat = tier.get("heatmap") or []
            gpr = int(tier.get("heatmap_groups_per_region", 1) or 1)
            groups = int(tier.get("groups", 0) or 0)
            if heat and groups:
                per = max(groups // n_dev, 1)
                occ = [0] * n_dev
                for r, live in enumerate(heat):
                    s = min((r * gpr) // per, n_dev - 1)
                    occ[s] += int(live)
                out["occupancy"] = occ
                out["occupancy_imbalance"] = imbalance(occ)
            pages = census.get("pages")
            if pages and pages.get("shards"):
                out["pages"] = pages["shards"]
                resident = [
                    int(s.get("resident", 0)) for s in pages["shards"]
                ]
                out["resident_imbalance"] = imbalance(resident)
        # Headline gauge: the worst imbalance across dimensions — max/
        # mean == 1.0 is perfectly balanced; the SLO spec alerts on
        # sustained excess.
        dims = [
            v
            for v in (
                out.get("decision_imbalance"),
                out.get("occupancy_imbalance"),
                out.get("resident_imbalance"),
            )
            if v is not None
        ]
        out["imbalance_ratio"] = max(dims) if dims else None
        return out

    @raceguard.holds_lock("engine.census")
    def _census_churn(self, snap: dict) -> dict:
        """Churn ledger: interval deltas of the flush bookkeeping the
        engine already keeps, turned into rates at census cadence.
        `overwrite_recycles` (inserts that reclaimed an expired/freed
        resident slot) is derived by conservation: every insert either
        lands on an empty slot (live grows), evicts an unexpired
        occupant (counted), or recycles a dead resident — the
        remainder. Called with _census_lock held."""
        em = self.metrics
        with em.lock:
            misses, evics = em.cache_misses, em.unexpired_evictions
        t = time.monotonic()
        prev = self._census_prev
        self._census_prev = (t, misses, evics, snap["live"])
        if prev is None:
            return {
                "interval_s": 0.0,
                "insertions": 0,
                "evictions": 0,
                "overwrite_recycles": 0,
                "insert_per_s": 0.0,
                "evict_per_s": 0.0,
                "recycle_per_s": 0.0,
            }
        dt = max(t - prev[0], 1e-9)
        d_ins = max(misses - prev[1], 0)
        d_ev = max(evics - prev[2], 0)
        d_live = snap["live"] - prev[3]
        d_rec = max(d_ins - d_ev - max(d_live, 0), 0)
        return {
            "interval_s": round(dt, 6),
            "insertions": d_ins,
            "evictions": d_ev,
            "overwrite_recycles": d_rec,
            "insert_per_s": round(d_ins / dt, 3),
            "evict_per_s": round(d_ev / dt, 3),
            "recycle_per_s": round(d_rec / dt, 3),
        }

    # -- pump ----------------------------------------------------------------

    def _pump(self) -> None:
        NB = int(Behavior.NO_BATCHING)
        carry: List[Tuple[RateLimitReq, object]] = []
        while self._running:
            wd = self.watchdog
            if wd is not None:
                # Serving heartbeat: a pump stuck behind the pipeline
                # semaphore (wedged completion thread) stops beating
                # here and burns the availability SLO.
                wd.beat("engine-pump", serving=True)
            if not carry:
                try:
                    item = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            else:
                # Wave-capped leftovers from the previous flush go first
                # (preserves per-key arrival order); drain anything queued
                # without waiting.
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    item = _FLUSH
            if item is _STOP:
                self._running = False
                break
            batch: List[Tuple[RateLimitReq, object]] = list(carry)
            carry = []

            def _extend(entry) -> bool:
                """Add a queue entry (single triple or bulk); True if it
                asks for an immediate flush. Queue wait (enqueue ->
                pump pickup) feeds the queue_wait histogram: sustained
                growth means the pump is falling behind intake. With the
                overload governor injected, the same wait drives its
                CoDel controller, and members whose caller deadline
                already expired are refused HERE — before any device
                work — instead of being flushed."""
                qw = self.metrics.queue_wait
                ov = self.overload
                if type(entry) is _Bulk:
                    w = time.perf_counter() - entry.t_enq
                    qw.observe(w)
                    live = entry.work
                    if ov is not None:
                        ov.observe_wait(w)
                        live = []
                        for req, slot in entry.work:
                            dl = slot.deadline_ms
                            if dl is not None and ov.deadline_expired(dl):
                                slot.set_result(ov.refuse_expired(req))
                            else:
                                live.append((req, slot))
                        entry.work = live
                    batch.extend(live)
                    with self._bulks_lock:
                        self._bulks.append(entry)
                    if not live:
                        # Every member expired at pickup: the slots are
                        # all resolved, so the bulk future must resolve
                        # now — no flush will ever sweep it.
                        self._sweep_bulks()
                        return False
                    return any(r.behavior & NB for r, _ in live)
                req, fut, t_enq = entry
                w = time.perf_counter() - t_enq
                qw.observe(w)
                if ov is not None:
                    ov.observe_wait(w)
                    dl = getattr(fut, "deadline_ms", None)
                    if dl is not None and ov.deadline_expired(dl):
                        fut.set_result(ov.refuse_expired(req))
                        return False
                batch.append((req, fut))
                return bool(req.behavior & NB)

            flush = item is _FLUSH
            if not flush:
                flush = _extend(item)
            deadline = time.monotonic() + self.cfg.batch_wait_s
            while not flush and len(batch) < self.cfg.max_flush_items:
                remaining = deadline - time.monotonic()
                if len(batch) >= self.cfg.batch_limit or remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    self._running = False
                    break
                if nxt is _FLUSH:
                    break
                if _extend(nxt):
                    break
            if batch:
                try:
                    carry = self._process(batch) or []
                    # Resolve bulks whose members have all been answered.
                    # Pipelined mode leaves this to the completion
                    # thread's per-ticket sweep — slots are not set yet
                    # here, and a redundant pump-side scan of every
                    # pending bulk's slots is pure overhead; wave-capped
                    # bulks wait for their carried items either way.
                    if not self._pipeline_active():
                        self._sweep_bulks()
                except Exception as e:  # never kill the pump
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_result(RateLimitResp(error=str(e)))
                    carry = []
                    self._sweep_bulks()
        # Shutdown: sync every in-flight ticket FIRST (FIFO future
        # order; zero-loss elasticity must cover dispatched-but-unsynced
        # flushes too), then drain whatever is still queued within the
        # drain budget (docs/robustness.md), then fail stragglers with
        # the typed retryable status.
        self._pipeline_quiesce()
        carry = self._drain_tail(carry)
        for _, fut in carry:
            if not fut.done():
                fut.set_result(RateLimitResp(error=ERR_ENGINE_DRAINING))
        self._resolve_all_bulks()
        if self._pipe_q is not None:
            self._pipe_q.put(_STOP)

    def _drain_tail(self, carry):
        """Serve queue entries that raced the shutdown signal. Entries
        enqueued before close() are already handled by the main loop
        (FIFO order puts them ahead of _STOP); this pass covers carried
        wave overflow and producers that slipped in between the _STOP
        being seen and _running going False. Flushes complete INLINE
        here (_pipeline_quiesce flipped drain mode). Returns the pairs
        the drain budget could not serve."""
        deadline = time.monotonic() + max(
            float(getattr(self.cfg, "drain_timeout_s", 5.0)), 0.0
        )
        pending = list(carry)

        def pull(entry) -> None:
            if entry is _STOP or entry is _FLUSH:
                return
            if type(entry) is _Bulk:
                pending.extend(entry.work)
                with self._bulks_lock:
                    self._bulks.append(entry)
            else:
                req, fut, _t = entry
                pending.append((req, fut))

        while time.monotonic() <= deadline:
            # Sweep everything currently queued into `pending`.
            while True:
                try:
                    pull(self._queue.get_nowait())
                except queue.Empty:
                    break
            if not pending:
                # Idle: wait one short beat for producers that raced the
                # intake guard (checked _running before it went False),
                # then exit.
                try:
                    pull(self._queue.get(timeout=0.02))
                except queue.Empty:
                    break
                continue
            batch = pending[: self.cfg.max_flush_items]
            pending = pending[self.cfg.max_flush_items:]
            try:
                extra = self._process(batch) or []
            except Exception as e:  # never die mid-drain
                for _, fut in batch:
                    if not fut.done():
                        fut.set_result(RateLimitResp(error=str(e)))
                extra = []
            # Wave-capped leftovers retry first (per-key arrival order).
            pending = list(extra) + pending
            self._sweep_bulks()
        # Past the budget (or idle): hand back the stragglers — including
        # anything still sitting in the queue — so the caller fails them
        # with the typed retryable status instead of leaving futures
        # hanging.
        while True:
            try:
                pull(self._queue.get_nowait())
            except queue.Empty:
                break
        return pending


def _census_tier_snapshot(
    out, *, now, layout, groups, ways, bytes_per_slot, thresholds,
    heatmap_width,
) -> dict:
    """Materialize one tier's CensusOutput (O(buckets) scalars) into a
    JSON-safe dict. Runs OUTSIDE the engine lock — the program was
    dispatched under it; this is the publish step."""
    a = {
        f: np.asarray(getattr(out, f)).tolist()  # guberlint: allow-host-sync -- census readback: O(buckets) scalars at TTL cadence, outside the serving lock
        for f in out._fields
    }
    slots = groups * ways
    live = a["live"]
    waste = a["waste"]
    full_groups = a["full_groups"]
    return {
        "layout": layout,
        "groups": groups,
        "ways": ways,
        "slots": slots,
        "bytes_per_slot": bytes_per_slot,
        "now_ms": int(now),
        "live": live,
        "occupancy": live / float(slots) if slots else 0.0,
        "full_groups": full_groups,
        "full_group_ratio": full_groups / float(groups) if groups else 0.0,
        "waste": waste,
        "waste_frac": waste / float(slots) if slots else 0.0,
        "age_ms_hist": a["age_hist"],
        "age_ms_sum": a["age_sum"],
        "idle_ms_hist": a["idle_hist"],
        "idle_ms_sum": a["idle_sum"],
        "heatmap": a["heatmap"],
        "cold_heatmap": a["cold_heatmap"],
        "heatmap_groups_per_region": -(-groups // heatmap_width),
        "fill_hist": a["fill_hist"],
        "max_full_run": a["max_full_run"],
        "cold": [
            {
                "multiplier": int(k),
                "slots": c,
                "frac": c / float(slots) if slots else 0.0,
                "reclaimable_bytes": c * bytes_per_slot,
            }
            for k, c in zip(thresholds, a["cold"])
        ],
    }


def _census_combine(tiers: Dict[str, dict], primary: str) -> dict:
    """Top-level census snapshot: tier-summed residency/age/cold
    numbers (what capacity planning wants) plus the primary tier's
    structural fields (heatmap, fill histogram, probe pressure —
    geometry-specific, meaningless summed across different group/way
    shapes). Full per-tier payloads ride under "tiers"."""
    p = tiers[primary]
    live = sum(t["live"] for t in tiers.values())
    slots = sum(t["slots"] for t in tiers.values())
    waste = sum(t["waste"] for t in tiers.values())

    def vsum(field):
        its = [t[field] for t in tiers.values()]
        return [sum(vals) for vals in zip(*its)]

    cold = []
    for i, entry in enumerate(p["cold"]):
        cold.append(
            {
                "multiplier": entry["multiplier"],
                "slots": sum(t["cold"][i]["slots"] for t in tiers.values()),
                "frac": (
                    sum(t["cold"][i]["slots"] for t in tiers.values())
                    / float(slots)
                    if slots
                    else 0.0
                ),
                "reclaimable_bytes": sum(
                    t["cold"][i]["reclaimable_bytes"] for t in tiers.values()
                ),
            }
        )
    return {
        "v": 1,
        "layout": p["layout"],
        "groups": p["groups"],
        "ways": p["ways"],
        "slots": slots,
        "bytes_per_slot": p["bytes_per_slot"],
        "now_ms": p["now_ms"],
        "live": live,
        "occupancy": live / float(slots) if slots else 0.0,
        "full_groups": p["full_groups"],
        "full_group_ratio": p["full_group_ratio"],
        "waste": waste,
        "waste_frac": waste / float(slots) if slots else 0.0,
        "age_ms_hist": vsum("age_ms_hist"),
        "age_ms_sum": sum(t["age_ms_sum"] for t in tiers.values()),
        "idle_ms_hist": vsum("idle_ms_hist"),
        "idle_ms_sum": sum(t["idle_ms_sum"] for t in tiers.values()),
        "heatmap": p["heatmap"],
        "cold_heatmap": p["cold_heatmap"],
        "heatmap_groups_per_region": p["heatmap_groups_per_region"],
        "fill_hist": p["fill_hist"],
        "max_full_run": p["max_full_run"],
        "cold": cold,
        "tiers": tiers,
    }


def _admission_tier_dict(out) -> dict:
    """Materialize one AdmissionOutput (or an oracle dict) into plain
    host ints/lists — the per-tier payload of admission_snapshot."""
    if isinstance(out, dict):
        d = dict(out)
    else:
        d = {
            f: np.asarray(getattr(out, f))  # guberlint: allow-host-sync -- admission readback: O(buckets) scalars at TTL cadence, outside the serving lock
            for f in out._fields
        }
    keys, admitted, limit, excess, excess_keys, max_excess, over, hist = (
        d[f]
        for f in (
            "keys", "admitted_sum", "limit_sum", "excess_sum",
            "excess_keys", "max_excess", "over_limit_keys", "excess_hist",
        )
    )
    return {
        "keys": int(keys),
        "admitted_hits": int(admitted),
        "limit_hits": int(limit),
        "excess_hits": int(excess),
        "excess_keys": int(excess_keys),
        "max_excess": int(max_excess),
        "over_limit_keys": int(over),
        "excess_hist": [int(x) for x in hist],
    }


def _admission_combine(tiers: Dict[str, dict]) -> dict:
    """Top-level admission snapshot: everything is additive across
    tiers (each key lives in exactly one tier) except max_excess, which
    takes the max. The over-admission SLI ratio is derived at the top:
    excess hits per configured limit hit, 0 on an empty table."""
    excess = sum(t["excess_hits"] for t in tiers.values())
    limit = sum(t["limit_hits"] for t in tiers.values())
    snap = {
        "v": 1,
        "keys": sum(t["keys"] for t in tiers.values()),
        "admitted_hits": sum(t["admitted_hits"] for t in tiers.values()),
        "limit_hits": limit,
        "excess_hits": excess,
        "excess_keys": sum(t["excess_keys"] for t in tiers.values()),
        "max_excess": max(t["max_excess"] for t in tiers.values()),
        "over_limit_keys": sum(
            t["over_limit_keys"] for t in tiers.values()
        ),
        "excess_ratio": excess / float(limit) if limit else 0.0,
        "excess_hist": [
            sum(vals)
            for vals in zip(*(t["excess_hist"] for t in tiers.values()))
        ],
        "tiers": tiers,
    }
    return snap


class MeshEngine(EngineBase):
    """Owns the slot table; turns request streams into decisions.

    ONE engine core, parameterized by mesh shape (runtime/topology.py):
    the strategy object binds the kernels (plain jits at mesh shape
    ``(1,)``, shard_map ownership programs at ``(chips,)``), decides
    whether a Pager manages page residency behind them, builds the
    GLOBAL replica tier where a mesh exists, and supplies the
    collective-dispatch guard. Everything else — pump, pipeline ring,
    ticket lifecycle, failure recovery, drain, snapshots, census /
    admission caching, flush telemetry — lives here exactly once.

    Thread model: callers (any thread / asyncio executor) enqueue
    (request, Future) pairs; one pump thread drains the queue, assembles
    waves, runs the kernel, and resolves futures. All device state is
    touched only by the pump thread — the moral equivalent of the
    reference's single-writer worker exclusivity (workers.go:19-25)
    with one writer for the whole table.
    """

    # V1Service/fastpath read this to decide whether GLOBAL traffic can
    # be answered locally; the ICI subclass (replica tier) flips it.
    routes_global_internally = False

    def __init__(
        self,
        config: EngineConfig = EngineConfig(),
        now_fn: Callable[[], int] = _clock.now_ms,
        topology=None,
    ):
        self.cfg = config
        self.now_fn = now_fn
        self.topo = topology if topology is not None else SingleChipTopology()
        self.metrics = EngineMetrics()
        self.store = None  # optional Store plugin (gubernator_tpu.store)
        self._key_strings: Dict[Tuple[int, int], str] = {}
        self._lock = lockorder.make_lock("engine.table")  # guards table swap (load/restore)
        # guards the host key dictionaries (pump + executor threads)
        self._keys_lock = lockorder.make_lock("engine.keys")
        # Standby replication dirty-key harvest (parallel/standby.py):
        # key string -> hits dirtied since the last drain, fed by the
        # flush completion paths alongside the hotkey aggregation — no
        # extra device work, no extra table pass. None (the default)
        # keeps both flush paths bit-exact; only the ReplicationManager
        # enables it.
        self._dirty: Optional[Dict[str, int]] = None
        self._dirty_lock = lockorder.make_lock("engine.dirty")

        if config.max_waves < 1:
            raise ValueError("max_waves must be >= 1")
        dev = getattr(config, "device", None)

        # Kernel binding + table residency are the topology's call: the
        # paged facade (docs/architecture.md "Paged table") swaps in the
        # paged addressing layer — PHYSICAL table shrunk to the
        # resident-frame budget, Pager tracking residency and the
        # host-DRAM cold tier (one frame pool + cold tier PER SHARD on
        # a mesh) — while flat binds the full-size table directly.
        self.K, self._pager = self.topo.build_kernels(config, self.metrics)
        # Decide backend provenance (GUBER_KERNEL, resolved by the
        # topology's registry build) + the Pallas lane tile. Tuning runs
        # HERE — before _warmup compiles the decide program — so the
        # tile the trials pick is the tile the warmed (and therefore
        # served) executable is built with; the serving path never
        # retunes (pinned by tests/test_pallas_engine.py).
        self.kernel_backend = getattr(self.topo, "kernel_backend", "xla")
        self.pallas_block = 0
        if self.kernel_backend == "pallas":
            from gubernator_tpu.runtime import kerneltune

            self.pallas_block = kerneltune.ensure_tuned(
                config.layout,
                config.batch_size,
                paged=int(getattr(config, "page_groups", 0) or 0) > 0,
            )
        with (
            jax.default_device(dev) if dev is not None
            else _nullcontext()
        ):
            # Every facade accepts (and the paged/mesh ones ignore) the
            # flat geometry args, so creation is uniform across all
            # four kernel cases.
            self.table = self.K.create(config.num_groups, config.ways)

        # GLOBAL replica tier (parallel/ici.py) — mesh topologies only.
        self._rtier = self.topo.build_replica(config, self.metrics)
        # Round-robin home cursor for GLOBAL replica placement; host
        # bookkeeping shared by _dispatch and the columnar split.
        self._home_rr = 0

        # Table-observatory program (ops/census.py): one jitted,
        # non-donating scan per (layout, geometry, knobs); warmed in
        # _warmup so the first scrape never compiles. On a mesh the
        # same plain program runs over the sharded array under GSPMD.
        self._census_thresholds = tuple(
            int(k) for k in config.census_thresholds
        )
        self._census = get_census(
            config.layout,
            config.ways,
            heatmap_width=int(config.census_heatmap_width),
            thresholds=self._census_thresholds,
        )
        # Admission-accounting program (ops/admission.py): same
        # non-donating scan contract as the census, warmed alongside it.
        self._admission = get_admission(config.layout, config.ways)

        # HBM attribution (utils/devicemem.py): static geometry sized
        # once; device_memory() folds in allocator stats per call.
        self._mem_subsystems = self._memory_subsystems()
        self._snapshot_staging_bytes = 0

        self._warmup()
        self._init_base(self.topo.thread_name)
        # Columnar-path batch-width buckets compile in the background; the
        # fast path only uses already-warm shapes (a cold compile mid-
        # request would blow through forwarding timeouts — same reason
        # _warmup exists). batch_size itself is warm from _warmup.
        # Published as an immutable tuple swapped atomically by the warmer
        # thread; readers iterate whatever snapshot they observe (mutating
        # a shared set mid-iteration can raise in the reader).
        self._warm_shapes = (config.batch_size,)
        self._warm_thread = None
        if getattr(config, "fast_buckets", False):
            self._warm_thread = threading.Thread(
                target=self._warm_buckets, name="gubernator-warm-buckets",
                daemon=True,
            )
            self._warm_thread.start()
        # Background demoter (paged mode): keeps free-frame headroom by
        # evacuating census-cold pages to the host tier, so serving-path
        # promotions rarely pay a demand demote under the lock.
        self._demote_stop = threading.Event()
        self._demote_thread = None
        if (
            self._pager is not None
            and float(getattr(config, "page_demote_interval_s", 0) or 0) > 0
        ):
            self._demote_thread = threading.Thread(
                target=self._demote_loop, name="gubernator-page-demoter",
                daemon=True,
            )
            self._demote_thread.start()

    def wait_warm(self, timeout_s: float = 600.0) -> bool:
        """Block until the bucket ladder has finished warming (VERDICT r3
        item 7: the cold-bucket latency cliff must be closable at
        startup, not discovered by the first NO_BATCHING request).

        Returns True when no further shape will ever compile on this
        engine: either the warmer thread finished (all ladder widths
        warm, or it intentionally stopped — store attached / oversized
        table), or fast_buckets is off (batch_size is the only shape and
        _warmup already compiled it). The serving path itself NEVER
        compiles: it narrows only to already-warm widths, so "not yet
        warm" costs a wide-kernel dispatch, never a JIT stall."""
        warm = self._warm_thread
        if warm is None:
            return True
        warm.join(timeout=timeout_s)
        return not warm.is_alive()

    def close(self) -> None:
        """Stop the page demoter before the base drain: the demoter
        takes the engine lock and dispatches device work, and the base
        close tears the pump down around that same lock."""
        self._demote_stop.set()
        dem = self._demote_thread
        if dem is not None and dem.is_alive():
            dem.join(timeout=30)
        super().close()

    def _demote_loop(self) -> None:
        """Background demoter (paged mode). Each cycle: read the
        TTL-cached census, and when the resident tier shows cold slots
        (or holds no live rows at all) AND the free-frame list is below
        page_free_target, evacuate LRU pages under the engine lock
        until the headroom target is met. The census gate keeps a fully
        hot working set resident instead of thrashing it through the
        host tier; min_idle_ticks=1 additionally spares pages touched
        by the most recent wave round."""
        interval = max(float(self.cfg.page_demote_interval_s), 0.05)
        while not self._demote_stop.wait(interval):
            wd = self.watchdog
            if wd is not None:
                # period_s widens the stall deadline to cover the
                # configured sleep — a 60s demote cadence is not a wedge.
                wd.beat("page-demoter", period_s=interval)
            try:
                pager = self._pager
                want = int(getattr(self.cfg, "page_free_target", 1) or 0)
                with raceguard.racy_read(
                    "free",
                    reason="lock-free headroom precheck; demote_victims "
                    "re-reads under the table lock",
                ):
                    if want <= 0 or len(pager.free) >= want:
                        continue
                census = self.table_census()
                dev = census.get("tiers", {}).get(
                    self.topo.primary_tier, census
                )
                cold = dev.get("cold") or []
                cold_slots = int(cold[0]["slots"]) if cold else 0  # guberlint: allow-host-sync -- census dict is host data (TTL-cached scrape)
                if int(dev.get("live", 0)) > 0 and cold_slots == 0:
                    continue  # resident set is fully hot: don't thrash
                # Victim policy: fold the census per-region cold-slot
                # heatmap into per-page coldness so the demoter evicts
                # pages whose SLOTS are idle, not merely pages with the
                # oldest touch tick (a single probe re-warms a page's
                # tick; the census still sees its other slots as cold).
                ch = dev.get("cold_heatmap")
                with self._lock, self.topo.dispatch_guard():
                    # The heatmap fold reads page_map, which serving
                    # threads rebind under the table lock — folding
                    # outside it can index a page demoted mid-scan.
                    # Demote cadence only, so holding the lock is cheap.
                    coldness = None
                    if ch:
                        coldness = pager.coldness_from_heatmap(
                            ch, int(dev.get("heatmap_groups_per_region", 1))
                        )
                    self.table = pager.demote_victims(
                        self.table, want_free=want, min_idle_ticks=1,
                        coldness=coldness,
                    )
            except Exception:  # pragma: no cover - defensive
                # The demoter is an optimization: serving-path demand
                # demotes cover for it, so a transient failure (device
                # teardown races at close) must not kill the thread.
                if self._demote_stop.is_set():
                    return
                continue

    # Scratch-table budget for the bucket-warm ladder: beyond this the
    # throwaway compile copy is skipped and only batch_size stays warm —
    # a single-request flush then pays one batch_size-wide dispatch, a
    # LATENCY cost, never a JIT stall (tests/test_engine.py pins this).
    _WARM_TABLE_BUDGET = 512 << 20

    def _warm_buckets(self) -> None:
        """Compile decide at each power-of-two width below batch_size
        against a THROWAWAY table of the same shape — never the live one:
        holding the serving lock through a ~1s compile stalls forwarded
        batches past their timeout, and the resulting client retries
        double-apply hits. The jit cache is keyed on shapes/dtypes, so
        the real table hits the warm entry afterwards."""
        cfg = self.cfg
        # A second table is transient compile fodder; skip bucket warming
        # when that copy would be expensive (huge HBM tables) — the
        # always-warm batch_size shape still serves the fast path. Sized
        # by the LAYOUT's resident bytes/slot (a narrow table crosses
        # the threshold later than a wide one).
        # Paged mode subsumes the old whole-table gate: the RESIDENT
        # footprint (physical frames, not the logical keyspace) is what
        # a scratch copy costs, and paging keeps it bounded regardless
        # of num_groups — the budget skip only fires when the resident
        # budget itself is huge.
        if self._pager is not None:
            resident_slots = self.K.num_phys_pages * self.K.page_slots
            approx_bytes = resident_slots * self.K.bytes_per_slot
        else:
            approx_bytes = cfg.num_groups * cfg.ways * self.K.bytes_per_slot
        if approx_bytes > self._WARM_TABLE_BUDGET:
            return
        shapes = []
        b = 128
        while b < cfg.batch_size:
            shapes.append(b)
            b <<= 1
        dev = cfg.device
        for B in shapes:
            if not self._running:
                return
            if self.store is not None:
                # Store-path flushes pin the batch width to batch_size
                # (check_columns skips bucket narrowing), so narrower
                # decide shapes would be dead weight: seconds of compile
                # plus a throwaway table per shape, used by nothing.
                return
            try:
                # Same device placement as the live table, or the compile
                # lands in a different jit cache entry and the "warm"
                # shape still cold-compiles on first real use.
                with jax.default_device(dev) if dev is not None else _nullcontext():
                    scratch = self.K.create(cfg.num_groups, cfg.ways)
                    scratch, out = self.K.decide(
                        scratch, RequestBatch.zeros(B), self.now_fn(),
                        cfg.ways, self.store is not None,
                    )
                    np.asarray(out.status)
                    del scratch
            except Exception:
                return  # engine closing / device issue: keep batch_size only
            self._warm_shapes = self._warm_shapes + (B,)

    def _memory_subsystems(self) -> dict:
        """Static HBM attribution from engine geometry (bytes, computed
        once — device_memory() reads this every scrape without touching
        the device). Estimates, not allocator truth: the gap shows up
        as unattributed_bytes in the snapshot."""
        cfg = self.cfg
        if self._pager is not None:
            # Paged table: HBM holds only the physical frames plus the
            # int32 indirection map; demoted pages live in host DRAM
            # (reported via the census "pages" section, not here —
            # this map attributes DEVICE memory).
            slots = self.K.num_phys_pages * self.K.page_slots
            table_b = slots * self.K.bytes_per_slot
        else:
            slots = cfg.num_groups * cfg.ways
            table_b = slots * self.K.bytes_per_slot
        # Census output: two fixed-width histograms (age/idle), the
        # fill histogram, the heatmap regions, one bucket per coldness
        # threshold, and a handful of scalars — all int64.
        census_b = 8 * (
            2 * 32
            + (cfg.ways + 1)
            + int(cfg.census_heatmap_width)
            + len(self._census_thresholds)
            + 16
        )
        # In-flight decide outputs pinned by the continuous-batching
        # ring: depth x waves x batch lanes x ~8 int64 output columns.
        ring_b = (
            max(int(cfg.pipeline_depth), 1)
            * cfg.max_waves
            * cfg.batch_size
            * 8
            * 8
        )
        # Admission output: one excess histogram plus a handful of int64
        # scalars (ops/admission.py AdmissionOutput).
        admission_b = 8 * (32 + 8)
        subs = {
            "slot_table": table_b,
            "census": census_b,
            "admission": admission_b,
            "pipeline_ring": ring_b,
        }
        if self._pager is not None:
            subs["page_map"] = 4 * self.K.num_logical_pages
        rt = self._rtier
        if rt is not None:
            # GLOBAL replica tier: per-device stacked replica tables +
            # int64 pending deltas (parallel/ici.py IciState) plus the
            # per-device tick scalars.
            subs["ici_replicas"] = (
                self.topo.n_dev * rt.num_slots * (self.K.bytes_per_slot + 8)
                + 8 * self.topo.n_dev
            )
            # Second census/admission program pair over the replica tier.
            subs["census"] += 8 * (
                (rt.replica_ways + 1)
                + int(cfg.census_heatmap_width)
                + len(self._census_thresholds)
                + 16
            )
            subs["admission"] += admission_b
        return subs

    @raceguard.init_path
    def _warmup(self) -> None:
        """Compile the decide AND inject kernels before serving: first XLA
        compilation takes seconds (tens of seconds on TPU), which would
        blow through peer-forwarding / GLOBAL broadcast timeouts (500ms
        default) on the first request."""
        from gubernator_tpu.ops.inject import InjectBatch

        now = self.now_fn()
        wb = RequestBatch.zeros(self.cfg.batch_size)
        with self.topo.dispatch_guard():
            with _transfer.account(self.metrics, "d2h", "warmup") as tx:
                table, out = self.K.decide(
                    self.table, wb, now, self.cfg.ways, self.store is not None
                )
                tx.add(np.asarray(out.status))
                table, _, _ = self.K.inject(
                    table, InjectBatch.zeros(self.cfg.batch_size), now,
                    self.cfg.ways,
                )
                tx.add(np.asarray(table.used[:1]))  # guberlint: allow-raw-table-index -- warmup sync probe: any one physical row works, logical identity irrelevant
                # Census compiles here too: the first /metrics or /debug/table
                # scrape must dispatch a warm program, not pay a compile.
                c = self._census(self._census_view(table), now)
                tx.add(np.asarray(c.live))  # guberlint: allow-host-sync -- warmup: compile the census program before serving
                # Admission accounting likewise: the first /debug/admission
                # scrape or auditor pass must never compile.
                a = self._admission(self._census_view(table), now)
                tx.add(np.asarray(a.keys))  # guberlint: allow-host-sync -- warmup: compile the admission program before serving
            if self._pager is not None:
                # Compile the page-migration programs (bind/extract/write/
                # unbind) on a throwaway cycle over frame 0: the first
                # demand promote/demote must not pay a compile under the
                # serving lock. Leaves the table empty and the map unbound.
                PK = self.K
                z = np.int32(0)
                table = PK.bind_page(table, z, z)
                rows = PK.extract_page(table, z)
                with _transfer.account(self.metrics, "d2h", "warmup") as tx:
                    host = {
                        f: np.asarray(getattr(rows, f))  # guberlint: allow-host-sync -- warmup: compile the demote extract path before serving
                        for f in SlotTable._fields
                    }
                    tx.add(host)
                table = PK.write_page(table, z, z, SlotTable(**host))
                table = PK.unbind_page(table, z, z)
            rt = self._rtier
            if rt is not None:
                # Replica-tier programs: decide, the sync tick (both
                # variants), and the stacked census/admission scans —
                # the first GLOBAL request or sync tick must dispatch
                # warm programs.
                home = np.zeros(self.cfg.batch_size, np.int64)
                with _transfer.account(self.metrics, "d2h", "warmup") as tx:
                    rt.state, r_out = rt.decide(rt.state, wb, home, now)
                    tx.add(np.asarray(r_out.status))  # guberlint: allow-host-sync -- warmup: compile the replica decide program before serving
                    rt.state, diag = rt.sync(rt.state, now)
                    tx.add(np.asarray(diag))  # guberlint: allow-host-sync -- warmup: compile the sync tick before the cadence thread runs it
                    if rt.sync_full is not None:
                        rt.state, diag = rt.sync_full(rt.state, now)
                        tx.add(np.asarray(diag))  # guberlint: allow-host-sync -- warmup: compile the full-tick backstop before its first forced tick
                    rc = rt.census(rt.state.table, now)
                    tx.add(np.asarray(rc.live))  # guberlint: allow-host-sync -- warmup: compile the replica census program before serving
                    ra = rt.admission(rt.state.table, now)
                    tx.add(np.asarray(ra.keys))  # guberlint: allow-host-sync -- warmup: compile the replica admission program before serving
                jax.block_until_ready(rt.state.pending)
        self.table = table

    def _census_view(self, table):
        """The tensor the census program scans: the PHYSICAL table in
        paged mode (the host tier is censused separately with the numpy
        oracle in _census_scan), the table itself otherwise."""
        return table.data if self._pager is not None else table

    def warm_store_path(self) -> None:
        """Compile the store-path kernels (the with_store decide variant,
        probe_exists, gather_rows) at serving shapes so the first flush
        doesn't cold-compile under the serving lock. Called by
        attach_store — at daemon init, before traffic, so briefly holding
        the lock here is free."""
        B = self.cfg.batch_size
        cfg = self.cfg
        z64 = np.zeros(B, np.int64)
        now = self.now_fn()
        with self._lock, self.topo.dispatch_guard(), _transfer.account(
            self.metrics, "d2h", "warmup"
        ) as tx:
            table, out = self.K.decide(
                self.table, RequestBatch.zeros(B), now, cfg.ways, True
            )
            tx.add(np.asarray(out.status))
            self.table = table
            tx.add(np.asarray(
                self.K.probe_exists(
                    table, z64, z64, np.zeros(B, np.int32), now, cfg.ways
                )
            ))
            tx.add(np.asarray(
                self.K.gather_rows(
                    table, np.full(B, table.num_slots, np.int64)
                ).used
            ))

    # ---- introspection -----------------------------------------------------

    def key_string(self, hi: int, lo: int) -> Optional[str]:
        return self._key_strings.get((hi, lo))

    # ---- standby dirty-key harvest (parallel/standby.py) -------------------

    def enable_dirty_tracking(self) -> None:
        """Turn on the dirty-key registry the standby ReplicationManager
        drains each ship pass. Idempotent. The None default keeps both
        flush paths bit-exact with tracking off (GUBER_STANDBY=0)."""
        with raceguard.racy_read(
            "_dirty", reason="double-checked enable; re-read under the lock"
        ):
            off = self._dirty is None
        if off:
            with self._dirty_lock:
                if self._dirty is None:
                    self._dirty = {}

    def disable_dirty_tracking(self) -> None:
        with self._dirty_lock:
            self._dirty = None

    def drain_dirty_keys(self, max_keys: int = 0) -> Dict[str, int]:
        """Return-and-clear the dirtied {key: hits} accumulated since
        the last drain. With max_keys > 0, at most that many keys drain
        (the rest stay pending for the next pass — the standby loss
        bound keeps counting them). {} when tracking is off."""
        with self._dirty_lock:
            d = self._dirty
            if not d:
                return {}
            if max_keys <= 0 or len(d) <= max_keys:
                out = dict(d)
                d.clear()
                return out
            out = {}
            for k in list(d.keys())[:max_keys]:
                out[k] = d.pop(k)
            return out

    def dirty_hits(self) -> int:
        """Peek (no drain): hits dirtied since the last drain. Feeds the
        live half of the standby loss bound."""
        with self._dirty_lock:
            d = self._dirty
            return sum(d.values()) if d else 0

    def _note_dirty(self, pairs) -> None:
        """Merge [(key, hits)] into the dirty registry (callers already
        checked self._dirty is not None; re-checked under the lock)."""
        with self._dirty_lock:
            d = self._dirty
            if d is None:
                return
            for k, n in pairs:
                d[k] = d.get(k, 0) + n

    def _note_dirty_columnar(self, hi, lo, hits) -> None:
        """Columnar-path harvest: resolve (hi, lo) through the host
        key-string dictionary (anonymous rows are skipped — they are not
        ring-routable, the same contract as handover snapshots)."""
        with self._keys_lock:
            ks = self._key_strings
            resolved = [
                (ks.get((int(h), int(l))), int(n))
                for h, l, n in zip(hi.tolist(), lo.tolist(), hits.tolist())
            ]
        self._note_dirty(
            (k, max(n, 0)) for k, n in resolved if k is not None
        )

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def live_count(self) -> int:
        """Number of occupied slots (gubernator_cache_size analog).
        Thin view over the TTL-cached census: scrapes never run a
        device reduction under the engine lock (guberlint GL009)."""
        return self.table_census()["live"]

    def occupancy_stats(self) -> dict:
        """Back-compat occupancy dict (/debug/engine, DebugInfo): a
        thin view over the TTL-cached census — same shape as the old
        per-scrape device reductions, zero scrape-triggered device
        work (docs/monitoring.md "Table census")."""
        c = self.table_census()
        return {
            "live": c["live"],
            "slots": c["slots"],
            "occupancy": c["occupancy"],
            "full_group_ratio": c["full_group_ratio"],
        }

    def _census_scan(self) -> dict:
        """One census pass (called by table_census with _census_lock
        held): dispatch the non-donating program on the live table
        reference under the engine lock, materialize after release."""
        cfg = self.cfg
        now = self.now_fn()
        host_pages = None
        pages_snap = None
        out_r = None
        with self._lock, self.topo.dispatch_guard():
            out = self._census(self._census_view(self.table), now)
            if self._rtier is not None:
                out_r = self._rtier.census(self._rtier.state.table, now)
            if self._pager is not None:
                # Reference copies under the lock; the numpy census walk
                # happens after release (rows blocks are replace-only).
                host_pages = self._pager.host_tier_copy()
                pages_snap = self._pager.pages_snapshot()
        dev_groups = (
            self.K.num_phys_pages * self.K.groups_per_page
            if self._pager is not None
            else cfg.num_groups
        )
        with _transfer.account(self.metrics, "d2h", "census") as tx:
            tier = _census_tier_snapshot(
                out,
                now=now,
                layout=cfg.layout,
                groups=dev_groups,
                ways=cfg.ways,
                bytes_per_slot=self.K.bytes_per_slot,
                thresholds=self._census_thresholds,
                heatmap_width=int(cfg.census_heatmap_width),
            )
            tx.add(out)
        primary = self.topo.primary_tier
        tiers = {primary: tier}
        if out_r is not None:
            rt = self._rtier
            with _transfer.account(self.metrics, "d2h", "census") as tx:
                tiers["replica"] = _census_tier_snapshot(
                    out_r,
                    now=now,
                    layout=cfg.layout,
                    groups=rt.num_rgroups,
                    ways=rt.replica_ways,
                    bytes_per_slot=self.K.bytes_per_slot,
                    thresholds=self._census_thresholds,
                    heatmap_width=int(cfg.census_heatmap_width),
                )
                tx.add(out_r)
        if self._pager is not None:
            # Host-DRAM tier census (satellite: per-tier counts — the
            # census must not under-report live keys once demotion is
            # on). Pure numpy over the demoted pages' wide rows
            # (ops/census.py census_oracle), no device work.
            tiers["host"] = self._census_host_tier(host_pages, now)
        snap = _census_combine(tiers, primary=primary)
        if pages_snap is not None:
            snap["pages"] = pages_snap
        return snap

    def _census_host_tier(self, host_pages: dict, now: int) -> dict:
        """Census the demoted pages with the numpy oracle; returns the
        same tier dict shape as the device tier so _census_combine sums
        them. Empty host tier -> an all-zero tier (stable schema)."""
        import types

        from gubernator_tpu.ops.census import census_oracle
        from gubernator_tpu.runtime.pager import wide_zeros

        cfg = self.cfg
        ps = self.K.page_slots
        if host_pages:
            lps = sorted(host_pages)
            fields = {
                f: np.concatenate([host_pages[lp][f] for lp in lps])
                for f in SlotTable._fields
            }
        else:
            fields = wide_zeros(ps)  # one empty page: zero counts
        wide = SlotTable(**fields)
        d = census_oracle(
            wide,
            now,
            ways=cfg.ways,
            heatmap_width=int(cfg.census_heatmap_width),
            thresholds=self._census_thresholds,
        )
        groups = (len(host_pages) if host_pages else 0) * (
            ps // cfg.ways
        )
        # groups=0 when the host tier is empty: the zero-page
        # placeholder censused above contributes zero counts and the
        # tier reports 0 slots (fracs guard on slots == 0).
        return _census_tier_snapshot(
            types.SimpleNamespace(_fields=tuple(d.keys()), **d),
            now=now,
            layout=cfg.layout,
            groups=groups,
            ways=cfg.ways,
            bytes_per_slot=self.K.bytes_per_slot,
            thresholds=self._census_thresholds,
            heatmap_width=int(cfg.census_heatmap_width),
        )

    def _admission_scan(self) -> dict:
        """One admission-accounting pass (called by admission_snapshot
        with _admission_lock held): dispatch the non-donating program on
        the live table reference under the engine lock, materialize
        after release. Paged mode scans the PHYSICAL frames on device
        and the demoted host pages with the numpy oracle (same split as
        the census) — a demoted key's window still counts."""
        now = self.now_fn()
        host_pages = None
        out_r = None
        with self._lock, self.topo.dispatch_guard():
            out = self._admission(self._census_view(self.table), now)
            if self._rtier is not None:
                out_r = self._rtier.admission(self._rtier.state.table, now)
            if self._pager is not None:
                host_pages = self._pager.host_tier_copy()
        with _transfer.account(self.metrics, "d2h", "admission") as tx:
            tier = _admission_tier_dict(out)
            tx.add(out)
        tiers = {self.topo.primary_tier: tier}
        if out_r is not None:
            with _transfer.account(self.metrics, "d2h", "admission") as tx:
                tiers["replica"] = _admission_tier_dict(out_r)
                tx.add(out_r)
        if self._pager is not None:
            tiers["host"] = self._admission_host_tier(host_pages, now)
        snap = _admission_combine(tiers)
        snap["now_ms"] = now
        return snap

    def _admission_host_tier(self, host_pages: dict, now: int) -> dict:
        """Admission-account the demoted pages with the numpy oracle;
        returns the same tier dict shape as the device tier so
        _admission_combine sums them. Empty host tier -> all zeros."""
        from gubernator_tpu.ops.admission import admission_oracle
        from gubernator_tpu.runtime.pager import wide_zeros

        ps = self.K.page_slots
        if host_pages:
            lps = sorted(host_pages)
            fields = {
                f: np.concatenate([host_pages[lp][f] for lp in lps])
                for f in SlotTable._fields
            }
        else:
            fields = wide_zeros(ps)  # one empty page: zero counts
        return _admission_tier_dict(
            admission_oracle(SlotTable(**fields), now)
        )

    def hotkeys_snapshot(self) -> dict:
        """/debug/hotkeys payload with the census join: each sketch row
        gains the key's residency bucket — `resident`, `cold` (idle
        past the census cold threshold), `expired` (window elapsed but
        slot still held), or `evicted` — so operators can see whether
        hot keys are fighting cold residents for slots."""
        snap = super().hotkeys_snapshot()
        if self._rtier is not None:
            # GLOBAL keys hash into the replica keyspace (num_rgroups),
            # not the sharded table's groups — the join below would
            # mislabel them, so the replica topology serves the plain
            # sketch snapshot (pre-unification IciEngine behavior).
            return snap
        entries = snap.get("entries") or []
        hashes = [e.get("key_hash") for e in entries]
        if not hashes or any(h is None for h in hashes):
            return snap  # no sketch / legacy rows: nothing to join on
        cfg = self.cfg
        W = cfg.ways
        hi = np.array([h[0] for h in hashes], dtype=np.int64)
        lo = np.array([h[1] for h in hashes], dtype=np.int64)
        grp = np.array(
            [group_of(int(l), cfg.num_groups) for l in lo], dtype=np.int64
        )
        demoted = np.zeros(len(grp), dtype=bool)
        with self._lock:
            if self._pager is not None:
                # Logical -> physical translation through the pager's
                # host mirror; keys on demoted pages gather the
                # out-of-range sentinel (zero rows) and are labeled
                # below instead of probed.
                pgrp = self._pager.phys_groups(grp)
                demoted = pgrp < 0
                grp_dev = np.where(demoted, self.table.num_slots // W, pgrp)
            else:
                grp_dev = grp
            slots = (
                grp_dev[:, None] * np.int64(W)
                + np.arange(W, dtype=np.int64)[None, :]
            ).reshape(-1)
            rows = self.K.gather_rows(self.table, slots)
        # Bounded O(K x ways) readback at debug-poll cadence; the
        # census bucket thresholds mirror table_census semantics.
        n = len(hashes)

        def mat(col):
            return np.asarray(col).reshape(n, W)  # guberlint: allow-host-sync -- hotkeys census join: O(K x ways) rows at debug cadence, outside the serving lock

        with _transfer.account(self.metrics, "d2h", "census") as tx:
            r_hi, r_lo = mat(rows.key_hi), mat(rows.key_lo)
            r_used, r_lru = mat(rows.used), mat(rows.lru)
            r_dur, r_exp = mat(rows.duration), mat(rows.expire_at)
            tx.add((r_hi, r_lo, r_used, r_lru, r_dur, r_exp))
        now = self.now_fn()
        cold_k = self._census_thresholds[
            min(1, len(self._census_thresholds) - 1)
        ]
        for i, e in enumerate(entries):
            if demoted[i]:
                e["census"] = "demoted"  # its page is in the host tier
                continue
            match = r_used[i] & (r_hi[i] == hi[i]) & (r_lo[i] == lo[i])
            if not match.any():
                e["census"] = "evicted"
                continue
            w = int(np.argmax(match))
            if r_exp[i, w] <= now:
                e["census"] = "expired"
            elif now - r_lru[i, w] > cold_k * r_dur[i, w]:
                e["census"] = "cold"
            else:
                e["census"] = "resident"
        snap["cold_multiplier"] = int(cold_k)
        return snap

    # ---- wave assembly + kernel dispatch -----------------------------------

    def _dispatch(
        self, items: List[Tuple[RateLimitReq, object]]
    ) -> Tuple[List[Tuple[RateLimitReq, object]], Optional[_FlushTicket]]:
        """Pipeline stage 1: assemble + encode the flush on host and
        launch its waves (no host sync — JAX async dispatch; the table
        threads flush-to-flush through the donated buffers). Returns
        (carry, ticket); _complete materializes the ticket."""
        t0 = time.perf_counter()
        now = self.now_fn()
        cfg = self.cfg
        B = cfg.batch_size

        # One native batch-hash call for the whole flush (assembler hot
        # loop; gubernator_tpu.native), then one-shot tolist conversions
        # — per-item numpy scalar boxing dominated the assembler loop.
        hashes = key_hash128_batch(
            [req.hash_key() for req, _ in items], cfg.num_groups
        )
        hi_l, lo_l, grp_l = (
            hashes[0].tolist(), hashes[1].tolist(), hashes[2].tolist()
        )

        # Store read-through happens per WAVE inside the execution loop
        # below, driven by a table-residency probe — the table, not host
        # bookkeeping, defines a cache miss (reference algorithms.go:45-51
        # consults the store on every cache miss). To keep blocking store
        # I/O outside the device lock, keys this process has never seen
        # (absent from _key_strings, which is a superset of table
        # residency) are prefetched HERE; the per-wave probe catches the
        # rare remainder (displaced keys) with a direct fetch.
        prefetched: Dict[Tuple[int, int], object] = {}
        if self.store is not None and cfg.keep_key_strings:
            with self._keys_lock:
                need = []
                seen = set()
                for i, (req, _) in enumerate(items):
                    k = (hi_l[i], lo_l[i])
                    if k not in self._key_strings and k not in seen:
                        seen.add(k)
                        need.append((req, k))
            for req, k in need:
                try:
                    snap = self.store.get(req)
                except Exception:
                    snap = None  # store outage == cache miss, not a crash
                if snap is not None:
                    prefetched[k] = snap

        if cfg.keep_key_strings:
            self._maybe_prune_key_strings()

        asm = _WaveAssembler(RequestBatch.zeros, B)
        placements: List[Optional[tuple]] = []
        wave_rows: List[list] = []  # per-wave (req, hi, lo, grp) for bulk fill
        wave_lanes: List[list] = []
        GREG = int(Behavior.DURATION_IS_GREGORIAN)
        GLOBAL = int(Behavior.GLOBAL)
        keep = cfg.keep_key_strings
        rt = self._rtier
        # GLOBAL replica routing (replica topologies): keys re-hash into
        # the replica keyspace, waves assemble per (home, slot) so the
        # round-robin home device rides the wave batch, and placements
        # carry an "r" tag so _complete demuxes from the replica outputs.
        r_asm = _WaveAssembler(RequestBatch.zeros, B) if rt is not None else None
        replica_homes: List[np.ndarray] = []

        carry: List[Tuple[RateLimitReq, object]] = []
        new_strings: Dict[Tuple[int, int], str] = {}
        for i, (req, fut) in enumerate(items):
            hi, lo = hi_l[i], lo_l[i]
            if keep:
                new_strings[(hi, lo)] = req.hash_key()
            if rt is not None and (req.behavior & GLOBAL):
                slot = group_of(lo, rt.num_rgroups)
                home = self._home_rr % self.topo.n_dev
                placed = r_asm.place((home, slot), cfg.max_waves)
                if placed is None:
                    carry.append((req, fut))
                    placements.append("carry")
                    continue
                self._home_rr += 1
                wb, w, lane = placed
                try:
                    encode_one(wb, lane, req, now, rt.num_rgroups, key=(hi, lo))
                except EncodeError as e:
                    fut.set_result(RateLimitResp(error=str(e)))
                    placements.append(None)
                    continue
                while len(replica_homes) < len(r_asm.waves):
                    replica_homes.append(np.zeros(B, dtype=np.int64))
                replica_homes[w][lane] = home
                r_asm.commit(w, (home, slot))
                placements.append(("r", w, lane, hi, lo))
                continue
            grp = grp_l[i]
            placed = asm.place(grp, cfg.max_waves)
            if placed is None:
                # Wave cap reached for this group: defer to the next flush
                # (the pump re-presents carried items first, preserving
                # per-key arrival order).
                carry.append((req, fut))
                placements.append("carry")
                continue
            wb, w, lane = placed
            if req.behavior & GREG:
                # calendar resolution stays per-item (rare path)
                try:
                    encode_one(wb, lane, req, now, cfg.num_groups, key=(hi, lo))
                except EncodeError as e:
                    fut.set_result(RateLimitResp(error=str(e)))
                    placements.append(None)
                    continue
            else:
                while len(wave_rows) < len(asm.waves):
                    wave_rows.append([])
                    wave_lanes.append([])
                wave_rows[w].append((req, hi, lo, grp))
                wave_lanes[w].append(lane)
            asm.commit(w, grp)
            placements.append(("s", w, lane, hi, lo))

        if new_strings:
            with self._keys_lock:
                self._key_strings.update(new_strings)

        for w, rows in enumerate(wave_rows):
            if rows:
                encode_rows(asm.waves[w], wave_lanes[w], rows, now)
        waves = asm.waves

        # Bucket each wave's device width to its occupancy (the kernel's
        # cost is per-LANE: a NO_BATCHING single-request flush must not
        # pay a batch_size-wide kernel). Lane indices are arrival ranks,
        # so every occupied lane survives the narrowing; only ALREADY-
        # WARM shapes are used — same policy as the columnar path. With
        # a store, flushes stay batch_size-wide (warm_store_path pins
        # that width for probe/inject/gather).
        if self.store is None:
            warm = self._warm_shapes  # immutable snapshot
            for w in range(len(waves)):
                fill, Bn = asm.fill(w), B
                for s in warm:
                    if s >= fill and s < Bn:
                        Bn = s
                if Bn < B:
                    waves[w] = jax.tree.map(lambda a: a[:Bn], waves[w])

        # Execute waves sequentially against the (donated) table. With a
        # Store attached, each wave runs the reference's exact per-request
        # sequence at wave granularity (algorithms.go:45-51):
        #   probe (cache lookup) -> Store.Get for misses -> insert -> decide
        # and then gathers its touched rows from the intermediate table so
        # write-behind persists the value the caller observed even if a
        # later wave displaces the slot (OnChange runs within the request,
        # algorithms.go:149-153).
        wave_lane_req: List[Dict[int, tuple]] = [dict() for _ in waves]
        if self.store is not None:
            for i, place in enumerate(placements):
                if isinstance(place, tuple) and place[0] == "s":
                    wave_lane_req[place[1]][place[2]] = (
                        items[i][0], place[3], place[4],
                    )
        # Per-ticket flush span: starts here, rides the ticket across
        # the pipeline boundary, ends when _complete finishes (the
        # completion thread re-attaches its context — see
        # _complete_ticket). Request spans link to it and back.
        r_waves = r_asm.waves if r_asm is not None else []
        n_waves = len(waves) + len(r_waves)
        seq = self._flush_seq()
        fspan = self._start_flush_span(
            items, seq, path="object", layout=cfg.layout,
            items=len(items), waves=n_waves,
            batch_width=len(items) - len(carry),
        )
        widths = [int(w.active.shape[0]) for w in waves]  # guberlint: allow-host-sync -- static shape metadata, no device readback
        widths += [B] * len(r_waves)  # replica waves stay full-width
        # Retrace attribution (runtime/telemetry.py): stamp this
        # thread's shape signature so a compile observed during the
        # flush names the widths that retraced, not just the program.
        _telemetry.set_shape_hint(f"{cfg.layout}:object:{widths}")
        t_dev = time.perf_counter()
        try:
            with _telemetry.serving_scope(self.metrics), tracing.use_span_ctx(
                fspan
            ):
                outs, r_outs, wave_rows_host, events = self._execute_waves(
                    waves, wave_lane_req, now, prefetched,
                    r_waves=r_waves, r_homes=replica_homes,
                )
        except Exception as e:
            tracing.end_span(fspan, error=e)
            raise
        return carry, _FlushTicket(
            items=items, placements=placements, outs=outs,
            r_outs=r_outs,
            rows=wave_rows_host, events=events,
            served=len(items) - len(carry), carry_n=len(carry),
            waves=n_waves,
            widths=widths,
            t0=t0, t_dev=t_dev, seq=seq, span=fspan,
            otel_ctx=tracing.context_of(fspan),
            trace_id=tracing.trace_id_of(fspan),
        )

    def _complete(self, t: _FlushTicket) -> None:
        """Pipeline stage 2: materialize the ticket's device results
        (one host sync per wave), feed telemetry, run write-behind, and
        resolve the futures — in FIFO dispatch order when pipelined."""
        cfg = self.cfg
        t_c0 = time.perf_counter()
        # The np.asarray syncs live in _materialize_out (the sanctioned
        # completion-stage readback). Sharded ("s") and replica ("r")
        # outputs materialize side by side; placements tag which list a
        # lane demuxes from.
        host = {
            "s": [_materialize_out(o) for o in t.outs],
            "r": [_materialize_out(o) for o in t.r_outs],
        }
        t_sync = time.perf_counter()
        dev_s = t_sync - t.t_dev
        # Transfer ledger: the serve-path d2h readback. Duration is the
        # blocking sync (copy + any pending compute it waited on).
        _transfer.record(
            self.metrics, "d2h", "serve", _transfer.nbytes(host),
            t_sync - t_c0,
        )

        if cfg.keep_key_strings:
            self._drop_displaced_strings(t.events)
        tot = [
            sum(h[i] for hs in host.values() for h in hs)
            for i in (4, 5, 6, 7)
        ]
        dur = time.perf_counter() - t.t0
        em = self.metrics
        trace_id = (t.trace_id or "") if cfg.exemplars else ""
        em.observe(tot[0], tot[1], tot[2], tot[3], t.waves, t.served, dur)
        em.observe_flush(
            "object", t.served, t.waves, dur, dev_s, trace_id,
            collective=self.topo.n_dev > 1,
        )
        em.observe_stage("assemble", t.t_dev - t.t0)
        em.observe_stage("dispatch", t.t_disp_end - t.t_dev)
        em.observe_stage("inflight_wait", max(t_c0 - t.t_disp_end, 0.0))
        em.observe_stage("device_sync", t_sync - t_c0)
        em.recorder.record(
            path="object", layout=cfg.layout, n=t.served, waves=t.waves,
            carry=t.carry_n, widths=t.widths,
            dur_us=int(dur * 1e6), dev_us=int(dev_s * 1e6),
            ticket=t.seq, trace_id=t.trace_id or "",
        )

        # Write-behind BEFORE resolving futures, so a caller that observed
        # its response can rely on the store reflecting it (the reference's
        # OnChange runs within the request, algorithms.go:149-153).
        if self.store is not None:
            self._store_write_behind(t.items, t.placements, t.outs, t.rows)

        # GUBER_STAGE_METADATA: the flush-level stage times every served
        # item shares, built once; each response appends its own queue
        # wait (resolve time is unknowable before resolution and is
        # reported as the flush-level histogram only).
        stage_base = None
        if self._stage_md:
            stage_base = (
                f"assemble={int((t.t_dev - t.t0) * 1e6)}"
                f",dispatch={int((t.t_disp_end - t.t_dev) * 1e6)}"
                f",inflight_wait={int(max(t_c0 - t.t_disp_end, 0.0) * 1e6)}"
                f",device_sync={int((t_sync - t_c0) * 1e6)}"
            )
        hk = em.hotkeys if em.hotkeys.k > 0 else None
        hk_agg: Dict[Tuple[int, int], list] = {}
        # Standby dirty harvest rides the same demux loop as the hotkey
        # aggregation: zero extra passes, None when tracking is off.
        with raceguard.racy_read(
            "_dirty",
            reason="None-gate only; _note_dirty re-checks under the lock",
        ):
            dirty_agg: Optional[list] = (
                [] if self._dirty is not None else None
            )
        OVER = 1  # api.types.Status.OVER_LIMIT
        for (req, fut), place in zip(t.items, t.placements):
            if place is None or place == "carry":
                continue  # resolved (encode error) or deferred
            path, w, lane = place[0], place[1], place[2]
            hw = host[path][w]
            st, rem, rst, lim = hw[0], hw[1], hw[2], hw[3]
            status = int(st[lane])  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
            if dirty_agg is not None:
                dirty_agg.append((req.hash_key(), max(int(req.hits), 0)))
            if hk is not None:
                k = (place[3], place[4])
                ent = hk_agg.get(k)
                if ent is None:
                    hk_agg[k] = [
                        max(int(req.hits), 0), int(status == OVER),
                        req.hash_key(),
                    ]
                else:
                    ent[0] += max(int(req.hits), 0)
                    ent[1] += int(status == OVER)
            md = None
            if stage_base is not None:
                t_enq = getattr(fut, "t_enq", None)
                md = {
                    "stage_breakdown_us": (
                        f"queue={int((t.t0 - t_enq) * 1e6)},{stage_base}"
                        if t_enq is not None
                        else stage_base
                    )
                }
            fut.set_result(
                RateLimitResp(
                    status=status,
                    limit=int(lim[lane]),  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
                    remaining=int(rem[lane]),  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
                    reset_time=int(rst[lane]),  # guberlint: allow-host-sync -- numpy demux of already-materialized rows
                    **({"metadata": md} if md else {}),
                )
            )
        if hk is not None and hk_agg:
            hk.update(
                [(k, v[0], v[1], v[2]) for k, v in hk_agg.items()]
            )
        if dirty_agg:
            self._note_dirty(dirty_agg)
        em.observe_stage("resolve", time.perf_counter() - t_sync)
        self._observe_overlap(t)

    @staticmethod
    def _snapshot_from_row(r, lane: int, key: str):
        """ItemSnapshot from one materialized gathered-row lane."""
        from gubernator_tpu.store.store import ItemSnapshot

        return ItemSnapshot(
            key=key,
            algorithm=int(r.algo[lane]),
            status=int(r.status[lane]),
            limit=int(r.limit[lane]),
            duration=int(r.duration[lane]),
            remaining=int(r.remaining[lane]),
            stamp=int(r.stamp[lane]),
            expire_at=int(r.expire_at[lane]),
            invalid_at=int(r.invalid_at[lane]),
            burst=int(r.burst[lane]),
        )

    # ---- columnar fast path (the serving edge; see service/fastpath.py) ----

    def check_columns(
        self,
        cols,
        now: Optional[int] = None,
        select: Optional[np.ndarray] = None,
        hashes: Optional[tuple] = None,
    ):
        """Vectorized decide over wire columns: no per-item Python objects
        anywhere — hashing, wave/lane assignment, encoding, and response
        demux are all batch array ops. Returns (status, limit, remaining,
        reset_time) int arrays in request order, or None when this batch
        needs the object path (wave/lane bounds are exceeded, or the
        batch is empty). A Store does NOT force a fallback: the store
        path runs the object path's per-wave sequence here (probe ->
        read-through -> decide -> write-behind) with request objects
        built only for actual miss lanes.

        Semantics mirror encode_one/encode_rows + the pump's wave
        assembler exactly (equivalence is fuzz-tested against the object
        path in tests/test_fastpath.py): stable sorting by group gives
        each request its occurrence rank as its wave, preserving per-key
        request order; within a wave, groups are distinct, so scatters
        stay disjoint.

        The caller guarantees: no GLOBAL / DURATION_IS_GREGORIAN items,
        no per-item metadata, and validation already handled.

        `select` serves a SUBSET of the batch (the mixed-ownership edge:
        locally-owned lanes go columnar while the rest forward), with
        `hashes` = (hi, lo, grp) precomputed over the FULL batch so key
        bytes need no re-slicing. Results align with `select`'s order.
        """
        from gubernator_tpu import native as _native

        cfg = self.cfg
        store = self.store
        if cols.n == 0:
            return None
        t_start = time.perf_counter()
        if now is None:
            now = self.now_fn()

        if hashes is None:
            hi, lo, grp = _native.hash128_batch_raw(
                cols.key_data.tobytes(), cols.key_offsets, cfg.num_groups
            )
        else:
            hi, lo, grp = hashes
        if self._rtier is not None:
            # Replica topologies serve GLOBAL columns internally: split
            # the batch between the sharded decide and the replica tier
            # (routes_global_internally — the caller does NOT filter
            # GLOBAL out for this engine).
            return self._check_columns_replica_split(
                cols, now, select, (hi, lo, grp), t_start
            )
        # Key strings resolve through the ORIGINAL columns (select drops
        # key_offsets); the store path decodes every key, the store-less
        # path only never-seen ones (record_columnar_keys).
        orig_cols, sel_map = cols, None
        if select is not None:
            if len(select) == 0:
                return None
            hi, lo, grp = hi[select], lo[select], grp[select]
            cols = _select_columns(cols, select)
            sel_map = select
        n = cols.n

        def key_str(j: int) -> str:
            return orig_cols.key_string(
                int(sel_map[j]) if sel_map is not None else j
            )

        asm = _assemble_column_waves(
            cols, hi, lo, grp, now, cfg.batch_size, cfg.max_waves,
            # Width bucketing uses only ALREADY-WARM shapes (batch_size
            # always is). With a store, only batch_size-wide store-path
            # kernels are warmed (warm_store_path); narrower buckets
            # would cold-compile probe/inject/gather under the lock.
            width_candidates=self._warm_shapes if store is None else (),
        )
        if asm is None:
            return None
        wb, wave, lane, ix, W, B = asm

        # Store path pre-work (the columnar twin of _process's read-through
        # plumbing): request objects are built LAZILY, only for miss lanes;
        # key strings are decoded once for the dictionary + write-behind;
        # never-seen keys prefetch OUTSIDE the device lock.
        prefetched: Dict[Tuple[int, int], object] = {}
        strs = None
        if store is not None:
            from gubernator_tpu import wire as _wire

            if sel_map is None:
                strs = cols.key_strings_all()
            else:
                strs = [key_str(j) for j in range(n)]

            def req_of(j: int) -> RateLimitReq:
                i = int(sel_map[j]) if sel_map is not None else j
                return _wire.req_from_columns(orig_cols, i)

            # One-shot tolist conversions: per-item numpy scalar boxing
            # (int(hi[j]) etc.) dominated this path's host cost.
            hi_l, lo_l = hi.tolist(), lo.tolist()
            wave_l, lane_l = wave.tolist(), lane.tolist()
            keys_l = list(zip(hi_l, lo_l))
            keep = cfg.keep_key_strings
            if keep:
                # Prefetch never-seen keys OUTSIDE the lock (the dict is
                # a superset of table residency, as in _process). Without
                # the dictionary there is no never-seen predicate: rely
                # on the in-lock per-wave probe alone rather than issuing
                # a blocking store.get for every key of every flush.
                need = []
                seen = set()
                with self._keys_lock:
                    for j, k in enumerate(keys_l):
                        if k not in self._key_strings and k not in seen:
                            seen.add(k)
                            need.append((j, k))
                    self._key_strings.update(zip(keys_l, strs))
                for j, k in need:
                    try:
                        snap = store.get(req_of(j))
                    except Exception:
                        snap = None  # store outage == cache miss
                    if snap is not None:
                        prefetched[k] = snap
                self._maybe_prune_key_strings()
            # item indices per wave (for the lazy lane_req dicts)
            by_wave = [[] for _ in range(W)]
            for j, w_ in enumerate(wave_l):
                by_wave[w_].append(j)
        elif cfg.keep_key_strings and cfg.record_columnar_keys:
            # Store-less columnar edge: keep the key-string dictionary
            # complete so handover/Loader snapshots are routable
            # (docs/robustness.md "Rolling restarts & handover" — an
            # anonymous row cannot be ring-placed at its new owner).
            # Cost discipline: a bulk (hi, lo) membership probe, and
            # string decodes ONLY for never-seen keys — steady-state
            # traffic pays dict lookups, not Python string builds.
            keys_l = list(zip(hi.tolist(), lo.tolist()))
            with self._keys_lock:
                miss = [
                    (j, k)
                    for j, k in enumerate(keys_l)
                    if k not in self._key_strings
                ]
            if miss:
                decoded = [(k, key_str(j)) for j, k in miss]
                with self._keys_lock:
                    self._key_strings.update(decoded)
                self._maybe_prune_key_strings()

        wave_slices = [jax.tree.map(lambda a, w=w: a[w], wb) for w in range(W)]
        lane_reqs: List[Dict[int, tuple]] = [{} for _ in range(W)]
        resolver = None
        if store is not None:
            resolver = req_of
            for w in range(W):
                lane_reqs[w] = {
                    lane_l[j]: (j, hi_l[j], lo_l[j]) for j in by_wave[w]
                }
        _telemetry.set_shape_hint(f"{cfg.layout}:columnar:{W}x{B}")
        t_dev = time.perf_counter()
        with _telemetry.serving_scope(self.metrics), tracing.span(
            "engine.flush", level="DEBUG", path="columnar", items=n, waves=W,
            layout=cfg.layout,
        ) as fspan:
            outs, _r_outs, wave_rows_host, events = self._execute_waves(
                wave_slices, lane_reqs, now, prefetched,
                req_resolver=resolver,
            )

            with _transfer.account(self.metrics, "d2h", "serve") as tx:
                status, r_limit, remaining, reset_time = (
                    _stack_wave_outputs(outs)
                )
                tx.add((status, r_limit, remaining, reset_time))
        dev_s = time.perf_counter() - t_dev
        flush_trace_id = tracing.trace_id_of(fspan)

        if store is not None:
            # Write-behind from the per-wave gathered rows (last-op-wins
            # per key, request order) + key-dictionary hygiene — same
            # semantics as the object path's flush.
            self._store_write_behind_core(
                list(zip(strs, wave_l, lane_l, hi_l, lo_l)),
                outs, wave_rows_host,
            )
            if cfg.keep_key_strings:
                self._drop_displaced_strings(events)

        tot_hits, tot_miss, tot_evic, tot_over = _wave_totals(outs)
        dur = time.perf_counter() - t_start
        em = self.metrics
        em.observe(tot_hits, tot_miss, tot_evic, tot_over, W, n, dur)
        em.observe_flush(
            "columnar", n, W, dur, dev_s,
            flush_trace_id if cfg.exemplars else "",
            collective=self.topo.n_dev > 1,
        )
        em.observe_stage("assemble", t_dev - t_start)
        em.observe_stage("device_sync", dev_s)
        em.recorder.record(
            path="columnar", layout=cfg.layout, n=n, waves=W, carry=0,
            widths=[B] * W, dur_us=int(dur * 1e6), dev_us=int(dev_s * 1e6),
            trace_id=flush_trace_id,
        )
        st_req = status[ix]
        if em.hotkeys.k > 0:
            _note_hotkeys_columnar(em.hotkeys, hi, lo, cols.hits, st_req)
        with raceguard.racy_read(
            "_dirty",
            reason="None-gate only; _note_dirty re-checks under the lock",
        ):
            track_dirty = self._dirty is not None
        if track_dirty:
            self._note_dirty_columnar(hi, lo, cols.hits)
        return (st_req, r_limit[ix], remaining[ix], reset_time[ix])

    def _check_columns_replica_split(self, cols, now, select, hashes, t_start):
        """Columnar serving for replica topologies — the multi-chip
        daemon's fast edge. Non-GLOBAL items feed the owner-sharded SPMD
        decide (shared wave assembler, one collective call per wave);
        GLOBAL items feed the per-device replica tier with the same
        round-robin home assignment as the object path (replica decide
        handles pending bookkeeping internally; the GLOBAL bit stays SET
        — this engine routes_global_internally). Waves always run at the
        full batch width — a narrower width would cold-compile a second
        SPMD program per shape."""
        cfg = self.cfg
        rt = self._rtier
        hi, lo, grp = hashes
        if select is not None:
            if len(select) == 0:
                return None
            hi, lo, grp = hi[select], lo[select], grp[select]
            cols = _select_columns(cols, select)
        n = cols.n
        g_mask = (np.asarray(cols.behavior) & int(Behavior.GLOBAL)) != 0  # guberlint: allow-host-sync -- wire columns are host numpy (wire.parse_requests output), no device readback
        ng_idx = np.nonzero(~g_mask)[0]
        g_idx = np.nonzero(g_mask)[0]

        # -- assemble the sharded (non-GLOBAL) waves --
        s_asm = None
        if len(ng_idx):
            s_cols = (
                cols if len(g_idx) == 0 else _select_columns(cols, ng_idx)
            )
            s_asm = _assemble_column_waves(
                s_cols, hi[ng_idx], lo[ng_idx], grp[ng_idx], now,
                cfg.batch_size, cfg.max_waves,
            )
            if s_asm is None:
                return None

        # -- assemble the replica (GLOBAL) waves --
        r_asm, homes_wb = None, None
        if len(g_idx):
            r_cols = _select_columns(cols, g_idx)
            r_lo = lo[g_idx]
            slot = (r_lo.astype(np.uint64) % np.uint64(rt.num_rgroups)
                    ).astype(np.int64)
            with self._lock:  # round-robin base, racing the pump thread
                rr0 = self._home_rr
                self._home_rr += len(g_idx)
            homes = (rr0 + np.arange(len(g_idx))) % self.topo.n_dev
            # Wave conflicts are per (home, slot) PAIR (the object path's
            # place key): encode the pair as the assembly "group", then
            # overwrite the batch's group column with the real slot.
            pair = homes * np.int64(rt.num_rgroups) + slot
            r_asm = _assemble_column_waves(
                r_cols, hi[g_idx], r_lo, pair, now,
                cfg.batch_size, cfg.max_waves,
            )
            if r_asm is None:
                return None
            r_wb, _rw, _rl, r_ix, RW, RB = r_asm
            r_wb.group[r_ix] = slot.astype(np.int32)
            homes_wb = np.zeros((RW, RB), dtype=np.int64)
            homes_wb[r_ix] = homes

        wave_slices, r_slices, r_homes = [], [], []
        if s_asm is not None:
            wb = s_asm[0]
            wave_slices = [
                jax.tree.map(lambda a, w=w: a[w], wb)
                for w in range(s_asm[4])
            ]
        if r_asm is not None:
            r_wb = r_asm[0]
            r_slices = [
                jax.tree.map(lambda a, w=w: a[w], r_wb)
                for w in range(r_asm[4])
            ]
            r_homes = [homes_wb[w] for w in range(r_asm[4])]

        _telemetry.set_shape_hint(
            f"{cfg.layout}:mesh-columnar:B{cfg.batch_size}"
        )
        t_dev = time.perf_counter()
        with _telemetry.serving_scope(self.metrics), tracing.span(
            "engine.flush", level="DEBUG", path="columnar", items=n,
            layout=cfg.layout,
        ) as fspan:
            # _execute_waves supplies the lock, the collective guard,
            # page residency (paged mesh), and unified recovery.
            s_outs, r_outs, _rows, _events = self._execute_waves(
                wave_slices, [{} for _ in wave_slices], now, {},
                r_waves=r_slices, r_homes=r_homes,
            )

        status = np.zeros(n, np.int64)
        r_limit = np.zeros(n, np.int64)
        remaining = np.zeros(n, np.int64)
        reset_time = np.zeros(n, np.int64)
        waves_total = 0
        tots = [0, 0, 0, 0]
        with _transfer.account(self.metrics, "d2h", "serve") as tx:
            for outs, asm, idx in (
                (s_outs, s_asm, ng_idx), (r_outs, r_asm, g_idx),
            ):
                if asm is None:
                    continue
                st, li, re, rst = _stack_wave_outputs(outs)
                tx.add((st, li, re, rst))
                ix = asm[3]
                status[idx] = st[ix]
                r_limit[idx] = li[ix]
                remaining[idx] = re[ix]
                reset_time[idx] = rst[ix]
                waves_total += asm[4]
                for j, v in enumerate(_wave_totals(outs)):
                    tots[j] += v
        dev_s = time.perf_counter() - t_dev
        dur = time.perf_counter() - t_start
        flush_trace_id = tracing.trace_id_of(fspan)
        em = self.metrics
        em.observe(tots[0], tots[1], tots[2], tots[3], waves_total, n, dur)
        em.observe_flush(
            "columnar", n, waves_total, dur, dev_s,
            flush_trace_id if cfg.exemplars else "",
            collective=self.topo.n_dev > 1,
        )
        em.observe_stage("assemble", t_dev - t_start)
        em.observe_stage("device_sync", dev_s)
        em.recorder.record(
            path="columnar", layout=cfg.layout, n=n, waves=waves_total,
            carry=0, widths=[cfg.batch_size] * waves_total,
            dur_us=int(dur * 1e6), dev_us=int(dev_s * 1e6),
            trace_id=flush_trace_id,
        )
        if em.hotkeys.k > 0:
            _note_hotkeys_columnar(em.hotkeys, hi, lo, cols.hits, status)
        return (status, r_limit, remaining, reset_time)

    def _execute_waves(
        self, waves, lane_reqs, now, prefetched, req_resolver=None,
        r_waves=(), r_homes=(),
    ):
        """Run decide over scatter-disjoint waves under the device lock,
        with the store's per-wave sequence when a Store is attached:
        probe (cache lookup) -> Store.Get for misses -> insert -> decide
        -> gather touched rows (reference algorithms.go:45-51, 149-153 —
        the gathered rows let write-behind persist the value the caller
        observed even if a later wave displaces the slot).

        lane_reqs: per-wave {lane: (req_or_index, key_hi, key_lo)}; with
        req_resolver set, the first element is an index resolved lazily
        (columnar path). r_waves/r_homes: GLOBAL replica waves + their
        per-lane home devices (replica topologies only), decided against
        the replica tier after the sharded waves. Returns
        (outs, r_outs, wave_rows_host, events).

        All dispatches run under the topology's collective guard (inside
        the table lock): on a mesh, concurrent multi-device programs
        from another engine in the same process would interleave
        per-device enqueues and deadlock in the collective rendezvous.

        On failure: keeps the last valid intermediate state if still
        held; a failed jitted call may have consumed the donated table
        buffers, in which case recovery rebuilds an empty table so the
        engine keeps serving (counter loss on failure matches the
        reference's accepted cache-loss-on-restart semantics,
        docs/architecture.md:5-11). If waves already committed to a
        SURVIVING table, raises TableCommittedError so no caller retries
        the batch through another path (double-apply)."""
        store = self.store
        cfg = self.cfg
        rt = self._rtier
        outs: List[object] = []
        r_outs: List[object] = []
        wave_rows_host: List[object] = []  # materialized post-decide rows
        served: Dict[Tuple[int, int], Tuple[int, int]] = {}  # key->(w,lane)
        events: List[Tuple[str, Tuple[int, int]]] = []  # ('d'|'i', key)
        if self.topo.n_dev > 1:
            # Shard-skew attribution (docs/monitoring.md "SLOs & burn
            # rates"): host-side bincount over the waves' group arrays
            # BEFORE device dispatch — this is the one choke point both
            # the object and columnar paths flow through.
            self._note_shard_decisions(waves)
        with self._lock, self.topo.dispatch_guard():
            table = self.table
            rstate = rt.state if rt is not None else None
            try:
                for w, wb in enumerate(waves):
                    if self._pager is not None:
                        # Promote every page this wave touches BEFORE
                        # its probe/decide (a probe-miss against a
                        # demoted page must resolve against promoted
                        # state, not the sentinel). Same lock as the
                        # decide: a promotion can never race a flush.
                        table = self._pager.ensure_resident(
                            table,
                            self._pager.touched_pages(wb.group, wb.active),
                        )
                    if store is not None:
                        table = self._wave_readthrough(
                            table, wb, lane_reqs[w], now,
                            prefetched, served, wave_rows_host, events,
                            req_resolver=req_resolver,
                        )
                    table, out = self.K.decide(
                        table, wb, now, cfg.ways, store is not None
                    )
                    outs.append(out)
                    if store is not None:
                        rows = self.K.gather_rows(table, out.slot)
                        with _transfer.account(
                            self.metrics, "d2h", "serve"
                        ) as tx:
                            rows_h = jax.tree.map(np.asarray, rows)
                            tx.add(rows_h)
                            ehi = np.asarray(out.evicted_hi)
                            elo = np.asarray(out.evicted_lo)
                            tx.add((ehi, elo))
                        wave_rows_host.append(rows_h)
                        for j in np.nonzero((ehi != 0) | (elo != 0))[0]:
                            events.append(("d", (int(ehi[j]), int(elo[j]))))
                        for lane, entry in lane_reqs[w].items():
                            served[(entry[1], entry[2])] = (w, lane)
                            events.append(("i", (entry[1], entry[2])))
                for wb, hm in zip(r_waves, r_homes):
                    rstate, out = rt.decide(rstate, wb, hm, now)
                    r_outs.append(out)
                self.table = table
                if rt is not None:
                    rt.state = rstate
            except Exception as e:
                self.table = table
                if rt is not None:
                    rt.state = rstate
                rebuilt = self._recover_table_locked()
                if (outs or r_outs) and not rebuilt:
                    raise TableCommittedError(str(e)) from e
                raise
        return outs, r_outs, wave_rows_host, events

    def _drop_displaced_strings(self, events) -> None:
        """Key-dictionary hygiene (store path): a key whose LAST flush
        event was a displacement is gone from the table — drop its string
        so its next request prefetches store state OUTSIDE the device
        lock. A key re-inserted after its displacement (read-through or a
        later wave) keeps its entry; Loader snapshots need strings for
        every live key. Read-through correctness never depends on this —
        the per-wave probe is ground truth."""
        if not events:
            return
        last: Dict[Tuple[int, int], str] = {}
        for ev, k in events:
            last[k] = ev
        dead = [k for k, ev in last.items() if ev == "d"]
        if dead:
            with self._keys_lock:
                for k in dead:
                    self._key_strings.pop(k, None)

    def _wave_readthrough(
        self,
        table,
        wb,
        lane_req: Dict[int, tuple],
        now,
        prefetched: Dict,
        served: Dict,
        wave_rows_host: List,
        events: List,
        req_resolver=None,
    ):
        """Reference miss path at wave granularity: probe the table for
        each lane's key; for actual misses, recover the freshest state and
        inject it so the wave's decide continues the counter (reference
        algorithms.go:45-51). Freshness order:

        1. a row this SAME flush already decided (the key was displaced
           between its own waves — pre-flush store state would drop the
           earlier hits, and a RESET-freed row must stay gone because the
           store.remove only lands at flush end);
        2. the pre-flush prefetch (keys never seen by this process);
        3. Store.Get under the lock (rare: displaced in a prior flush but
           raced back before hygiene dropped its string).

        Runs under self._lock; store outages degrade to misses, never
        table-fatal."""
        from gubernator_tpu.ops.inject import InjectBatch

        cfg = self.cfg
        exists = np.asarray(
            self.K.probe_exists(table, wb.key_hi, wb.key_lo, wb.group, now, cfg.ways)
        )
        rows = []
        for lane, (req, hi, lo) in lane_req.items():
            if exists[lane]:
                continue
            if req_resolver is not None:
                # Columnar path: lane_req carries item indices; request
                # objects are built lazily, only for actual misses
                # (steady state has none).
                req = req_resolver(req)
            snap = None
            sv = served.get((hi, lo))
            if sv is not None:
                pw, plane = sv
                r = wave_rows_host[pw]
                if (
                    bool(r.used[plane])
                    and int(r.key_hi[plane]) == hi
                    and int(r.key_lo[plane]) == lo
                ):
                    snap = self._snapshot_from_row(r, plane, req.hash_key())
                # else: that wave freed the entry (RESET_REMAINING) — it
                # must look absent; do NOT fall back to the stale store.
            else:
                snap = prefetched.get((hi, lo))
                if snap is None:
                    try:
                        snap = self.store.get(req)
                    except Exception:
                        snap = None  # store outage == cache miss
            if snap is not None:
                rows.append((lane, snap, hi, lo))
        if not rows:
            return table
        ib = InjectBatch.zeros(cfg.batch_size)
        for j, (lane, s, hi, lo) in enumerate(rows):
            ib.key_hi[j] = hi
            ib.key_lo[j] = lo
            ib.group[j] = wb.group[lane]
            ib.algo[j] = int(s.algorithm)
            ib.status[j] = int(s.status)
            ib.limit[j] = s.limit
            ib.duration[j] = s.duration
            ib.remaining[j] = s.remaining
            ib.stamp[j] = s.stamp
            ib.expire_at[j] = s.expire_at
            ib.invalid_at[j] = int(getattr(s, "invalid_at", 0))
            ib.burst[j] = s.burst
            ib.active[j] = True
        with _transfer.account(self.metrics, "h2d", "inject") as tx:
            table, ehi, elo = self.K.inject(table, ib, now, cfg.ways)
            tx.add(ib)
        ehi = np.asarray(ehi)
        elo = np.asarray(elo)
        for j in np.nonzero((ehi != 0) | (elo != 0))[0]:
            events.append(("d", (int(ehi[j]), int(elo[j]))))
        for lane, snap, hi, lo in rows:
            events.append(("i", (hi, lo)))
        return table

    def _store_write_behind(self, items, placements, outs, rows) -> None:
        def seq():
            for (req, _), place in zip(items, placements):
                if place is None or place == "carry":
                    continue
                tag, w, lane, hi, lo = place
                if tag != "s":
                    continue  # replica lanes never persist to a Store
                yield req.hash_key(), w, lane, hi, lo

        self._store_write_behind_core(seq(), outs, rows)

    _WB_FIELDS = (
        "used", "key_hi", "key_lo", "algo", "status", "limit", "duration",
        "remaining", "stamp", "expire_at", "invalid_at", "burst",
    )

    def _store_write_behind_core(self, seq, outs, rows) -> None:
        """seq yields (hash_key, wave, lane, hi, lo) in REQUEST order.

        Rows were gathered per-wave from the intermediate tables (and
        already materialized), so each lane sees exactly the state its
        own decide produced even when a later wave in the same flush
        displaced or freed the slot.
        """
        from gubernator_tpu.store.store import ItemSnapshot

        entries = list(seq)
        if not entries:
            return
        # Vectorized row extraction: one advanced-index per field over the
        # stacked (W, B) wave rows, then plain-list indexing per item —
        # per-item numpy scalar boxing dominated this loop before.
        w_arr = np.fromiter((e[1] for e in entries), np.int64, len(entries))
        l_arr = np.fromiter((e[2] for e in entries), np.int64, len(entries))
        v = {
            f: np.stack([np.asarray(getattr(r, f)) for r in rows])[
                w_arr, l_arr
            ].tolist()
            for f in self._WB_FIELDS
        }
        freed_v = np.stack([np.asarray(o.freed) for o in outs])[
            w_arr, l_arr
        ].tolist()

        # Per-key LAST op wins, in request order: a hit followed by a
        # same-flush RESET_REMAINING must end as a remove (not resurrect
        # the pre-reset snapshot via a late batched on_change), and a
        # RESET followed by a new hit must end as the new snapshot.
        ops: Dict[str, Optional[ItemSnapshot]] = {}
        for i, (key, w, lane, hi, lo) in enumerate(entries):
            # Only a token-bucket RESET_REMAINING free deletes the
            # persisted entry (reference algorithms.go:78-90); the
            # reference keeps Store entries across cache eviction and
            # restores them via Store.Get on the next cache miss.
            if freed_v[i]:
                ops[key] = None
                continue
            if not v["used"][i] or v["key_hi"][i] != hi or v["key_lo"][i] != lo:
                # Shouldn't happen with per-wave gathers; skip defensively
                # without touching the persisted entry.
                continue
            ops[key] = ItemSnapshot(
                key=key,
                algorithm=v["algo"][i],
                status=v["status"][i],
                limit=v["limit"][i],
                duration=v["duration"][i],
                remaining=v["remaining"][i],
                stamp=v["stamp"][i],
                expire_at=v["expire_at"][i],
                invalid_at=v["invalid_at"][i],
                burst=v["burst"][i],
            )
        changes = [s for s in ops.values() if s is not None]
        # Store failures here must NEVER propagate: write-behind runs
        # AFTER the table commit, and the columnar edge's caller treats a
        # check_columns exception as "safe to retry via the object path"
        # — re-applying every already-committed hit. The reference's
        # Store.OnChange has no error return either (store.go:49-65);
        # durability degrades, serving does not.
        try:
            for key, s in ops.items():
                if s is None:
                    self.store.remove(key)
            if changes:
                self.store.on_change(changes)
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "store write-behind failed (%d changes dropped)", len(changes)
            )

    def _maybe_prune_key_strings(self) -> None:
        """Bound host memory: under key churn the hash->string dict keeps
        entries for keys long evicted from the device table. When it
        exceeds 2x the slot count, rebuild it from the table's live keys
        (one device readback). Dropped strings only cost an extra store
        read-through if the key returns; Loader snapshots stay complete
        because live entries always retain their strings."""
        n = self.cfg.num_groups * self.cfg.ways
        if len(self._key_strings) <= max(2 * n, 4096):
            return
        with self._lock, self.topo.dispatch_guard(), _transfer.account(
            self.metrics, "d2h", "census"
        ) as tx:
            used = np.asarray(self.table.used)  # guberlint: allow-raw-table-index -- prune wants the PHYSICAL resident set; demoted keys join via host_live_keys below
            hi = np.asarray(self.table.key_hi)[used]  # guberlint: allow-raw-table-index -- same physical scan as line above
            lo = np.asarray(self.table.key_lo)[used]  # guberlint: allow-raw-table-index -- same physical scan as line above
            tx.add((used, hi, lo))
        live = set(zip(hi.tolist(), lo.tolist()))
        if self._pager is not None:
            # Demoted keys are still live — their pages promote back
            # verbatim and Loader snapshots must stay routable — so the
            # host tier's keys survive the prune too.
            with self._lock:
                live |= self._pager.host_live_keys()
        with self._keys_lock:
            self._key_strings = {
                k: v for k, v in self._key_strings.items() if k in live
            }

    @raceguard.holds_lock("engine.table")
    def _recover_table_locked(self) -> bool:
        """Called with the lock held after a failed device call: if the
        donated table buffers were consumed — or the table points at an
        array poisoned by a failed ASYNC dispatch (pipelined mode: the
        error only surfaces at the completion stage's sync, after the
        table reference already advanced) — rebuild an empty table so
        subsequent requests serve instead of failing forever. Returns
        True when the table was rebuilt (all counters lost — a fallback
        replay is then safe, not a double-apply)."""
        try:
            deleted = getattr(self.table.key_hi, "is_deleted", lambda: False)()
            if not deleted:
                # Error-path-only health probe, never on the serving path:
                # a poisoned dependency chain raises its deferred error
                # here instead of on every future flush.
                jax.block_until_ready(self.table.key_hi)  # guberlint: allow-host-sync -- error-path table health probe
        except Exception:
            deleted = True
        if deleted:
            self.table = self.K.create(self.cfg.num_groups, self.cfg.ways)
            if self._pager is not None:
                # The rebuilt paged table is empty with an unbound map;
                # the pager's mirror, frames, and host tier must match
                # (counter loss on failure covers the cold tier too —
                # stale host pages promoted into a fresh table would
                # resurrect pre-failure state for SOME keys only).
                self._pager.reset()
            with self._keys_lock:
                self._key_strings.clear()
        rt = self._rtier
        if rt is not None:
            # Replica tier: same consumed-or-poisoned probe on its
            # donated state; rebuild empty on damage (counter loss on
            # failure matches the accepted semantics).
            try:
                r_deleted = getattr(
                    rt.state.pending, "is_deleted", lambda: False
                )()
                if not r_deleted:
                    jax.block_until_ready(rt.state.pending)  # guberlint: allow-host-sync -- error-path replica health probe
            except Exception:
                r_deleted = True
            if r_deleted:
                rt.state = rt.recreate_state()
                deleted = True
        return deleted

    def _recover_after_failure(self) -> bool:
        """Completion-stage recovery entry (EngineBase._ticket_failed):
        same rebuild-once semantics as the dispatch path, taken under
        the device lock."""
        with self._lock:
            return self._recover_table_locked()

    # ---- direct state injection (AddCacheItem analog) ----------------------

    def inject_globals(self, globals_: Sequence) -> None:
        """Overwrite local state with authoritative GLOBAL updates from the
        owner (reference gubernator.go:425-459: rebuilds a CacheItem with
        stamp=now, expire=status.reset_time, leaky burst=limit)."""
        from gubernator_tpu.api.types import Algorithm
        from gubernator_tpu.models.bucket import FIXED_SHIFT
        from gubernator_tpu.store.store import ItemSnapshot

        now = self.now_fn()
        snaps = []
        for g in globals_:
            leaky = int(g.algorithm) == int(Algorithm.LEAKY_BUCKET)
            snaps.append(
                ItemSnapshot(
                    key=g.key,
                    algorithm=int(g.algorithm),
                    status=int(g.status.status),
                    limit=g.status.limit,
                    duration=g.duration,
                    remaining=(
                        g.status.remaining << FIXED_SHIFT
                        if leaky
                        else g.status.remaining
                    ),
                    stamp=now,
                    expire_at=g.status.reset_time,
                    burst=g.status.limit if leaky else 0,
                )
            )
        self.inject_snapshots(snaps)

    def inject_snapshots(self, items: Sequence) -> None:
        """Write raw per-key state rows into the table (Loader restore and
        Store read-through feed; reference workers.go:537-580)."""
        from gubernator_tpu.ops.inject import InjectBatch

        if not items:
            return
        now = self.now_fn()
        cfg = self.cfg

        asm = _WaveAssembler(InjectBatch.zeros, cfg.batch_size)
        new_strings: Dict[Tuple[int, int], str] = {}
        for s in items:
            hi, lo = key_hash128(s.key)
            if cfg.keep_key_strings:
                new_strings[(hi, lo)] = s.key
            grp = group_of(lo, cfg.num_groups)
            ib, w, lane = asm.place(grp)
            ib.key_hi[lane] = hi
            ib.key_lo[lane] = lo
            ib.group[lane] = grp
            ib.algo[lane] = int(s.algorithm)
            ib.status[lane] = int(s.status)
            ib.limit[lane] = s.limit
            ib.duration[lane] = s.duration
            ib.remaining[lane] = s.remaining
            ib.stamp[lane] = s.stamp
            ib.expire_at[lane] = s.expire_at
            ib.invalid_at[lane] = getattr(s, "invalid_at", 0)
            ib.burst[lane] = s.burst
            ib.active[lane] = True
            asm.commit(w, grp)

        with self._keys_lock:
            self._key_strings.update(new_strings)

        with self._lock, self.topo.dispatch_guard():
            table = self.table
            with _transfer.account(self.metrics, "h2d", "inject") as tx:
                for ib in asm.waves:
                    if self._pager is not None:
                        table = self._pager.ensure_resident(
                            table,
                            self._pager.touched_pages(ib.group, ib.active),
                        )
                    table, _ehi, _elo = self.K.inject(
                        table, ib, now, cfg.ways
                    )
                    tx.add(ib)
            self.table = table

    # ---- snapshot / restore (Loader seam, task: store) ---------------------

    def snapshot(self) -> dict:
        """Device -> host snapshot of the table (the Loader.Save analog,
        reference store.go:76-78; SURVEY.md §5 checkpoint/resume).

        Paged mode: the snapshot is the LOGICAL wide image — resident
        pages are extracted positionally into their logical offsets and
        host-tier pages are copied in place — so Loader files are
        identical to (and interchangeable with) an all-resident or flat
        table's snapshot of the same keys."""
        if self._pager is not None:
            return self._snapshot_paged()
        with self._lock, self.topo.dispatch_guard():
            tbl = self.K.to_wide(self.table)  # canonical wide snapshot
            with _transfer.account(self.metrics, "d2h", "snapshot") as tx:
                host = {f: np.asarray(getattr(tbl, f)) for f in tbl._fields}
                tx.add(host)
            self._snapshot_staging_bytes = tx.bytes
        with self._keys_lock:
            host["key_strings"] = dict(self._key_strings)
        return host

    def _snapshot_paged(self) -> dict:
        from gubernator_tpu.runtime.pager import wide_zeros

        cfg = self.cfg
        PK = self.K
        ps = PK.page_slots
        n_logical = cfg.num_groups * cfg.ways
        host = wide_zeros(PK.num_logical_pages * ps)
        with self._lock, self.topo.dispatch_guard():
            pager = self._pager
            with _transfer.account(self.metrics, "d2h", "snapshot") as tx:
                for lp in np.nonzero(pager.page_map >= 0)[0].tolist():
                    rows = PK.extract_page(
                        self.table, np.int32(int(pager.page_map[lp]))  # guberlint: allow-host-sync -- page_map is the pager's host numpy mirror
                    )
                    for f in SlotTable._fields:
                        # guberlint: allow-host-sync -- snapshot assembly: accounted page-at-a-time d2h
                        host[f][lp * ps:(lp + 1) * ps] = np.asarray(
                            getattr(rows, f)
                        )
                    tx.add(ps * PK.bytes_per_slot)
            for lp, rows in pager.host_tier.items():
                for f in SlotTable._fields:
                    host[f][lp * ps:(lp + 1) * ps] = rows[f]
            self._snapshot_staging_bytes = sum(
                a.nbytes for a in host.values()
            )
        # Trim the tail-page padding back to the logical slot count.
        host = {f: a[:n_logical] for f, a in host.items()}
        with self._keys_lock:
            host["key_strings"] = dict(self._key_strings)
        return host

    def restore(self, snap: dict) -> None:
        """Host -> device restore (the Loader.Load analog).

        Replaces the table AND the host key-string dictionary under their
        locks (the pump/executor threads read both); invalidation state
        lives in the table's own invalid_at column, which the per-wave
        read-through probe consults directly.

        Paged mode: pages with live rows fill the resident frames first
        (in logical order); the overflow restores into the host tier —
        no data is dropped even when the image holds more live pages
        than the resident budget."""
        if self._pager is not None:
            self._restore_paged(snap)
            return
        with _transfer.account(self.metrics, "h2d", "snapshot") as tx:
            fields = {
                f: jax.numpy.asarray(snap[f]) for f in SlotTable._fields
            }
            tx.add(fields)
        self._snapshot_staging_bytes = tx.bytes
        with self._lock, self.topo.dispatch_guard():
            self.table = self.K.from_wide(SlotTable(**fields))
        with self._keys_lock:
            self._key_strings = dict(snap.get("key_strings", {}))

    def _restore_paged(self, snap: dict) -> None:
        from gubernator_tpu.runtime.pager import wide_zeros

        PK = self.K
        ps = PK.page_slots
        fields = {f: np.asarray(snap[f]) for f in SlotTable._fields}  # guberlint: allow-host-sync -- snap is the Loader's host-side image, not device data
        n = fields["used"].shape[0]
        with self._lock, self.topo.dispatch_guard():
            self.table = PK.create()
            self._pager.reset()
            pager = self._pager
            with _transfer.account(self.metrics, "h2d", "snapshot") as tx:
                for lp in range(PK.num_logical_pages):
                    lo, hi = lp * ps, min((lp + 1) * ps, n)
                    if lo >= n or not fields["used"][lo:hi].any():
                        continue
                    page = wide_zeros(ps)
                    for f in SlotTable._fields:
                        page[f][: hi - lo] = fields[f][lo:hi]
                    # acquire_frame is the single bind gate: on a mesh
                    # it draws from the page's own shard pool, so the
                    # restore preserves per-shard placement invariants.
                    pp = pager.acquire_frame(lp)
                    if pp is not None:
                        self.table = PK.write_page(
                            self.table, np.int32(lp), np.int32(pp),
                            SlotTable(**page),
                        )
                        pager.page_map[lp] = pp
                        tx.add(page)
                    else:
                        pager.host_tier[lp] = page
            self._snapshot_staging_bytes = tx.bytes
        with self._keys_lock:
            self._key_strings = dict(snap.get("key_strings", {}))


class DeviceEngine(MeshEngine):
    """MeshEngine at mesh shape ``(1,)`` — the single-chip engine name
    that V1Service, the daemon, and the test suites construct. The
    default topology (SingleChipTopology) IS the pre-unification
    DeviceEngine binding, so this shell only preserves the public type
    name; every behavior lives in the core."""


def _assemble_column_waves(
    cols, hi, lo, grp, now, batch_size: int, max_waves: int,
    width_candidates=(),
):
    """Vectorized wave assembly shared by the engines' columnar paths:
    wave = occurrence rank within the group (stable sort keeps arrival
    order, preserving per-key sequencing); lane = arrival rank within
    the wave. Returns (wb, wave, lane, ix, W, B) with `wb` a (W, B)
    stacked RequestBatch, or None when the batch exceeds the wave/lane
    bounds (caller falls back to the object path).

    `width_candidates` optionally narrows the device batch width to the
    actual occupancy — the kernel's cost is per-LANE — using only
    already-compiled widths."""
    from gubernator_tpu.models.bucket import MAX_COUNT, MAX_DURATION_MS

    n = cols.n
    order = np.argsort(grp, kind="stable")
    sg = grp[order]
    wave_sorted = np.arange(n) - np.searchsorted(sg, sg, side="left")
    wave = np.empty(n, np.int64)
    wave[order] = wave_sorted
    num_waves = int(wave.max()) + 1
    if num_waves > max_waves:
        return None
    order2 = np.argsort(wave, kind="stable")
    sw = wave[order2]
    lane_sorted = np.arange(n) - np.searchsorted(sw, sw, side="left")
    max_lane = int(lane_sorted.max())
    if max_lane >= batch_size:
        return None
    lane = np.empty(n, np.int64)
    lane[order2] = lane_sorted

    B = batch_size
    for s in width_candidates:  # immutable snapshot; warmer swaps atomically
        if s > max_lane and s < B:
            B = s

    # Encode columns (the encode_one clamps, vectorized).
    hits = np.clip(cols.hits, -MAX_COUNT, MAX_COUNT)
    limit = np.clip(cols.limit, -MAX_COUNT, MAX_COUNT)
    duration = np.clip(cols.duration, 0, MAX_DURATION_MS)
    burst = np.clip(cols.burst, 0, MAX_COUNT)
    is_leaky = cols.algo.astype(np.int64) == 1
    burst = np.where(is_leaky & (burst == 0), limit, burst)
    # created_at==0 counts as absent, like the object path (server.py
    # treats 0 the same as unset before handing to the engine).
    created = np.where(
        cols.has_created.astype(bool) & (cols.created_at != 0),
        cols.created_at,
        np.int64(now),
    )

    W = num_waves

    def stack(dtype):
        return np.zeros((W, B), dtype=dtype)

    wb = RequestBatch(
        key_hi=stack(np.int64),
        key_lo=stack(np.int64),
        group=stack(np.int32),
        algo=stack(np.int8),
        behavior=stack(np.int32),
        hits=stack(np.int64),
        limit=stack(np.int64),
        duration=stack(np.int64),
        rate_num=stack(np.int64),
        eff_duration=stack(np.int64),
        greg_expire=stack(np.int64),
        burst=stack(np.int64),
        created_at=stack(np.int64),
        active=stack(bool),
    )
    ix = (wave, lane)
    wb.key_hi[ix] = hi
    wb.key_lo[ix] = lo
    wb.group[ix] = grp
    wb.algo[ix] = cols.algo.astype(np.int8)
    wb.behavior[ix] = cols.behavior.astype(np.int32)
    wb.hits[ix] = hits
    wb.limit[ix] = limit
    wb.duration[ix] = duration
    wb.rate_num[ix] = duration
    wb.eff_duration[ix] = duration
    wb.burst[ix] = burst
    wb.created_at[ix] = created
    wb.active[ix] = True
    return wb, wave, lane, ix, W, B


def _stack_wave_outputs(outs):
    """(status, limit, remaining, reset_time) stacked (W, B) host arrays
    from per-wave DecideOutputs — the demux shared by the engines'
    columnar paths."""
    return (
        np.stack([np.asarray(o.status) for o in outs]),
        np.stack([np.asarray(o.limit) for o in outs]),
        np.stack([np.asarray(o.remaining) for o in outs]),
        np.stack([np.asarray(o.reset_time) for o in outs]),
    )


def _note_hotkeys_columnar(hk, hi, lo, hits, status) -> None:
    """Aggregate one columnar flush into the hot-key sketch. Keyed by
    the 128-bit hash pair — the columnar edge never decodes key strings
    for this (cost discipline); display names resolve lazily at
    snapshot/render time through the sketch's resolver or the object
    path's updates. All inputs are already-materialized host arrays."""
    agg: Dict[Tuple[int, int], list] = {}
    for h, l, w, s in zip(
        hi.tolist(), lo.tolist(), hits.tolist(), status.tolist()
    ):
        o = 1 if s == 1 else 0  # api.types.Status.OVER_LIMIT
        k = (h, l)
        ent = agg.get(k)
        if ent is None:
            agg[k] = [max(int(w), 0), o]
        else:
            ent[0] += max(int(w), 0)
            ent[1] += o
    if agg:
        hk.update([(k, v[0], v[1], None) for k, v in agg.items()])


def _wave_totals(outs):
    """(hits, misses, unexpired_evictions, over_limit) summed across
    waves for EngineMetrics.observe."""
    return (
        sum(int(o.hits) for o in outs),
        sum(int(o.misses) for o in outs),
        sum(int(o.unexpired_evictions) for o in outs),
        sum(int(o.over_limit) for o in outs),
    )


def _select_columns(cols, select: np.ndarray):
    """Subset view of RequestColumns for check_columns(select=...): field
    arrays are fancy-indexed; key bytes are NOT re-sliced — key hashes
    are computed from the ORIGINAL columns before selection, and
    key_string() must be called on the original columns too. key_offsets
    is poisoned to None so any code path that tries to hash or slice
    keys on the subset view fails loudly (TypeError) instead of reading
    misaligned offsets."""
    import dataclasses as _dc

    return _dc.replace(
        cols,
        n=int(len(select)),
        hits=cols.hits[select],
        limit=cols.limit[select],
        duration=cols.duration[select],
        algo=cols.algo[select],
        behavior=cols.behavior[select],
        burst=cols.burst[select],
        created_at=cols.created_at[select],
        has_created=cols.has_created[select],
        slow=cols.slow[select],
        name_lens=cols.name_lens[select],
        key_data=cols.key_data,
        key_offsets=None,  # poisoned: unusable after select (see above)
    )


class _Bulk:
    """A bulk queue entry: N (req, _Slot) pairs resolved by one Future."""

    __slots__ = ("work", "slots", "future", "t_enq")

    def __init__(self, work, slots, future):
        self.work = work
        self.slots = slots
        self.future = future
        self.t_enq = time.perf_counter()

    def resolve(self) -> None:
        if not self.future.done():
            self.future.set_result(
                [
                    s.value
                    if s.done()
                    else RateLimitResp(error=ERR_ENGINE_DRAINING)
                    for s in self.slots
                ]
            )


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


_FLUSH = object()
_STOP = object()


# Declared lock protocol (docs/robustness.md "Race sanitizer").
# Write-only ("w:") fields are read racily on purpose by the debug
# snapshot, the SLO sampler, and the test suites (single reference or
# int reads); the tight read+write protocol applies to the bulk/census/
# admission caches and the dirty-key registry, whose readers all take
# the matching lock (the deliberate lock-free None-gates sit inside
# racy_read escapes above).
raceguard.guarded_by(EngineBase, {
    "_bulks": "engine.bulks",
    "_census_cache": "engine.census",
    "_census_ts": "engine.census",
    "_census_prev": "engine.census",
    "_admission_cache": "engine.admission",
    "_admission_ts": "engine.admission",
    "_shard_decisions": "w:engine.shards",
    "_inflight": "w:engine.pipeline",
})
raceguard.guarded_by(MeshEngine, {
    "table": "w:engine.table",
    "_key_strings": "w:engine.keys",
    "_dirty": "engine.dirty",
})
