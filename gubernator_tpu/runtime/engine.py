"""Device engine: micro-batch assembly + the TPU-resident counter table.

This is the TPU-native replacement for the reference's entire execution
engine (reference workers.go:54-626): instead of sharding the key space
across single-threaded goroutine workers with channel hops, requests
accumulate into fixed-shape device batches and one jitted decide() call
updates the HBM slot table in place.

The micro-batching policy transfers directly from the reference's peer
batching (reference peer_client.go:284-337; config.go:126-128): flush at
`batch_limit` items or after `batch_wait` (default 500µs), whichever
first; NO_BATCHING requests flush immediately.

Duplicate handling (SURVEY.md §7 hard part (a)): the reference serializes
same-key requests through one worker, so in-batch duplicates see each
other's effects in request order, and an over-limit rejection does NOT
consume. The assembler reproduces this with *waves*: within one flush,
requests whose slot-group is already taken by an earlier request go to the
next wave; waves execute as sequential decide() calls. Group (not key)
granularity also guarantees scatter-disjointness inside each wave.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from gubernator_tpu.api.keys import group_of, key_hash128
from gubernator_tpu.api.types import (
    Behavior,
    RateLimitReq,
    RateLimitResp,
    has_behavior,
    validate_request,
)
from gubernator_tpu.ops.encode import EncodeError, encode_one
from gubernator_tpu.ops.layout import RequestBatch, SlotTable
from gubernator_tpu.ops.decide import decide
from gubernator_tpu.utils import clock as _clock


@dataclasses.dataclass
class EngineConfig:
    """Sizing and batching knobs (defaults mirror the reference's
    BehaviorConfig, config.go:126-140, adapted to device batches)."""

    num_groups: int = 1 << 15  # 32k groups x 8 ways = 256k slots
    ways: int = 8
    batch_size: int = 1024  # lanes per device batch (fixed shape)
    batch_limit: int = 1000  # max requests accumulated per flush
    batch_wait_s: float = 500e-6  # 500 µs
    max_flush_items: int = 8192  # hard cap pulled off the queue per flush
    keep_key_strings: bool = True  # hash -> string dict (Loader/debug)
    device: Optional[object] = None  # jax device for the table


class EngineMetrics:
    """Counters the observability layer exports (names map to the
    reference's Prometheus catalog, docs/prometheus.md)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.cache_hits = 0
        self.cache_misses = 0
        self.unexpired_evictions = 0
        self.over_limit = 0
        self.batches = 0
        self.waves = 0
        self.requests = 0
        self.batch_duration_sum = 0.0

    def observe(self, hits, misses, evic, over, waves, n, dur):
        with self.lock:
            self.cache_hits += hits
            self.cache_misses += misses
            self.unexpired_evictions += evic
            self.over_limit += over
            self.batches += 1
            self.waves += waves
            self.requests += n
            self.batch_duration_sum += dur


class DeviceEngine:
    """Owns the device slot table; turns request streams into decisions.

    Thread model: callers (any thread / asyncio executor) enqueue
    (request, Future) pairs; one pump thread drains the queue, assembles
    waves, runs the kernel, and resolves futures. All device state is
    touched only by the pump thread — the moral equivalent of the
    reference's single-writer worker exclusivity (workers.go:19-25)
    with one writer for the whole table.
    """

    def __init__(
        self,
        config: EngineConfig = EngineConfig(),
        now_fn: Callable[[], int] = _clock.now_ms,
    ):
        self.cfg = config
        self.now_fn = now_fn
        self.metrics = EngineMetrics()
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._key_strings: Dict[Tuple[int, int], str] = {}
        self._lock = threading.Lock()  # guards table swap (load/restore)

        dev = config.device

        with jax.default_device(dev) if dev is not None else _nullcontext():
            self.table: SlotTable = SlotTable.create(config.num_groups, config.ways)

        self._running = True
        self._thread = threading.Thread(
            target=self._pump, name="gubernator-tpu-engine", daemon=True
        )
        self._thread.start()

    # ---- public API --------------------------------------------------------

    def check_async(self, req: RateLimitReq) -> "Future[RateLimitResp]":
        """Enqueue one request; resolves after its wave executes."""
        fut: Future = Future()
        err = validate_request(req)
        if err is not None:
            fut.set_result(RateLimitResp(error=err))
            return fut
        if req.created_at is None:
            req.created_at = self.now_fn()
        self._queue.put((req, fut))
        return fut

    def check_batch(self, reqs: Sequence[RateLimitReq]) -> List[RateLimitResp]:
        """Synchronous batched check (returns in request order)."""
        futs = [self.check_async(r) for r in reqs]
        return [f.result() for f in futs]

    def flush_now(self) -> None:
        """Force the pump to flush without waiting the batch window."""
        self._queue.put(_FLUSH)

    def close(self) -> None:
        self._running = False
        self._queue.put(_STOP)
        self._thread.join(timeout=5)

    def key_string(self, hi: int, lo: int) -> Optional[str]:
        return self._key_strings.get((hi, lo))

    # ---- pump --------------------------------------------------------------

    def _pump(self) -> None:
        while self._running:
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is _STOP:
                break
            batch: List[Tuple[RateLimitReq, Future]] = []
            flush = item is _FLUSH
            if not flush:
                batch.append(item)
                flush = has_behavior(item[0].behavior, Behavior.NO_BATCHING)
            deadline = time.monotonic() + self.cfg.batch_wait_s
            while not flush and len(batch) < self.cfg.max_flush_items:
                remaining = deadline - time.monotonic()
                if len(batch) >= self.cfg.batch_limit or remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    self._running = False
                    break
                if nxt is _FLUSH:
                    break
                batch.append(nxt)
                if has_behavior(nxt[0].behavior, Behavior.NO_BATCHING):
                    break
            if batch:
                try:
                    self._process(batch)
                except Exception as e:  # never kill the pump
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_result(RateLimitResp(error=str(e)))

    # ---- wave assembly + kernel dispatch -----------------------------------

    def _process(self, items: List[Tuple[RateLimitReq, Future]]) -> None:
        t0 = time.perf_counter()
        now = self.now_fn()
        cfg = self.cfg
        B = cfg.batch_size

        # Assign each request to (wave, lane): first wave where its group is
        # unused and a lane is free. Preserves per-key request order because
        # same key => same group => strictly increasing wave index.
        waves: List[RequestBatch] = []
        wave_groups: List[set] = []
        wave_fill: List[int] = []
        placements: List[Optional[Tuple[int, int]]] = []

        for req, fut in items:
            hi, lo = key_hash128(req.hash_key())
            if cfg.keep_key_strings:
                self._key_strings[(hi, lo)] = req.hash_key()
            grp = group_of(lo, cfg.num_groups)
            w = 0
            while True:
                if w == len(waves):
                    waves.append(RequestBatch.zeros(B))
                    wave_groups.append(set())
                    wave_fill.append(0)
                if grp not in wave_groups[w] and wave_fill[w] < B:
                    break
                w += 1
            lane = wave_fill[w]
            try:
                encode_one(waves[w], lane, req, now, cfg.num_groups, key=(hi, lo))
            except EncodeError as e:
                fut.set_result(RateLimitResp(error=str(e)))
                placements.append(None)
                continue
            wave_groups[w].add(grp)
            wave_fill[w] += 1
            placements.append((w, lane))

        # Execute waves sequentially against the (donated) table.
        outs = []
        with self._lock:
            table = self.table
            for wb in waves:
                table, out = decide(table, wb, now, ways=cfg.ways)
                outs.append(out)
            self.table = table

        # Materialize results (one host sync per wave) and demux.
        host = [
            (
                np.asarray(o.status),
                np.asarray(o.remaining),
                np.asarray(o.reset_time),
                np.asarray(o.limit),
                int(o.hits),
                int(o.misses),
                int(o.unexpired_evictions),
                int(o.over_limit),
            )
            for o in outs
        ]
        tot = [sum(h[i] for h in host) for i in (4, 5, 6, 7)]
        self.metrics.observe(
            tot[0], tot[1], tot[2], tot[3], len(waves), len(items),
            time.perf_counter() - t0,
        )

        for (req, fut), place in zip(items, placements):
            if place is None:
                continue  # already resolved (encode error)
            w, lane = place
            st, rem, rst, lim = host[w][0], host[w][1], host[w][2], host[w][3]
            fut.set_result(
                RateLimitResp(
                    status=int(st[lane]),
                    limit=int(lim[lane]),
                    remaining=int(rem[lane]),
                    reset_time=int(rst[lane]),
                )
            )

    # ---- snapshot / restore (Loader seam, task: store) ---------------------

    def snapshot(self) -> dict:
        """Device -> host snapshot of the table (the Loader.Save analog,
        reference store.go:76-78; SURVEY.md §5 checkpoint/resume)."""
        with self._lock:
            tbl = self.table
            host = {f: np.asarray(getattr(tbl, f)) for f in tbl._fields}
        host["key_strings"] = dict(self._key_strings)
        return host

    def restore(self, snap: dict) -> None:
        """Host -> device restore (the Loader.Load analog)."""
        fields = {f: jax.numpy.asarray(snap[f]) for f in SlotTable._fields}
        with self._lock:
            self.table = SlotTable(**fields)
        self._key_strings.update(snap.get("key_strings", {}))


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


_FLUSH = object()
_STOP = object()
