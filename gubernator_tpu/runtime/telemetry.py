"""Device-tier telemetry plumbing: flight recorder + cold-compile
detection.

Two observability gaps the service-tier Prometheus catalog cannot cover
(the reference stops at the Go tier, docs/prometheus.md; the engine under
it is this port's addition):

- FlightRecorder: a fixed-size ring of the last K flush/tick records
  (width, waves, carry, duration, layout). When a latency spike is
  already minutes old, the histograms say *that* it happened; the
  recorder says *what the engine was doing* — the black-box data an
  operator reads first. Served as JSON at /debug/engine
  (service/gateway.py).

- Cold-compile detection: the serving path must NEVER trigger an XLA
  compile (engine warmup pins every servable shape; a mid-request
  compile blows through forwarding timeouts — see
  DeviceEngine._warmup/_warm_buckets). jax.monitoring emits
  `/jax/core/compile/backend_compile_duration` on the DISPATCHING
  thread exactly when a backend compile runs, so the engines mark their
  serving-path dispatch regions with serving_scope(); a compile event
  landing inside a marked region increments that engine's cold-compile
  counter (exposed as gubernator_engine_cold_compile_count). Warmup,
  the bucket-warmer thread, and scrape-time reductions never enter a
  scope, so their compiles are expected and uncounted.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Optional

from gubernator_tpu.utils import lockorder

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_tls = threading.local()
_install_lock = lockorder.make_lock("telemetry.install")
_installed = False

# Process-wide compile telemetry (docs/monitoring.md "Device
# resources"): every backend compile is a retrace somewhere — these
# feed the gubernator_compile_* families and the /debug/device
# attribution table. Bounded: counters + a fixed-size recent-retrace
# ring.
_RETRACE_KEEP = 64
_compile_lock = lockorder.make_lock("telemetry.compile_stats")
_compile_counts = {"compiles": 0, "compile_seconds": 0.0, "cache_hits": 0}
_retraces: collections.deque = collections.deque(maxlen=_RETRACE_KEEP)


def _program_from_stack() -> str:
    """Attribute a compile to the outermost gubernator_tpu frame on the
    compiling thread's stack ("path:function:line" — which jitted
    program retraced). Stack-walk attribution is jax-version-
    independent: the duration event carries no program metadata."""
    import traceback

    for fr in traceback.extract_stack():
        fn = fr.filename or ""
        if "gubernator_tpu" in fn:
            mod = fn.split("gubernator_tpu", 1)[-1].lstrip("/\\")
            return f"{mod}:{fr.name}:{fr.lineno}"
    return ""


def set_shape_hint(hint: str) -> None:
    """Stamp this thread's current dispatch shape signature (one cheap
    attribute write per flush). A compile observed on this thread
    attributes to the stamped signature — the "which shape retraced"
    half of compile attribution."""
    _tls.shape_hint = hint


def _on_event_duration(event: str, duration: float, **kw) -> None:
    # Hot only on compile/cache events (never per dispatch); attribute
    # to whichever engine marked this thread as serving, if any.
    if event != _COMPILE_EVENT:
        return
    owner = getattr(_tls, "owner", None)
    if owner is not None:
        owner.note_cold_compile()
    entry = {
        "ts": time.time(),
        "duration_s": float(duration),
        "program": _program_from_stack(),
        "shape": getattr(_tls, "shape_hint", ""),
        "thread": threading.current_thread().name,
        "serving": owner is not None,
    }
    with _compile_lock:
        _compile_counts["compiles"] += 1
        _compile_counts["compile_seconds"] += float(duration)
        _retraces.append(entry)


def _on_event(event: str, **kw) -> None:
    if event == _CACHE_HIT_EVENT:
        with _compile_lock:
            _compile_counts["cache_hits"] += 1


def compile_counters() -> dict:
    """Process-wide compile counters: backend compiles (every one is a
    cache miss or an uncached program), cumulative compile seconds, and
    persistent-cache hits. Zeros until the listener installs."""
    with _compile_lock:
        return dict(_compile_counts)


def compile_attribution() -> dict:
    """Retrace attribution for /debug/device: the bounded ring of
    recent compiles (program, shape signature, thread, serving flag)
    plus per-program aggregates."""
    with _compile_lock:
        recent = list(_retraces)
        counts = dict(_compile_counts)
    by_program: dict = {}
    for e in recent:
        agg = by_program.setdefault(
            e["program"] or "<external>",
            {"count": 0, "total_s": 0.0, "serving": 0},
        )
        agg["count"] += 1
        agg["total_s"] += e["duration_s"]
        agg["serving"] += int(e["serving"])  # guberlint: allow-host-sync -- retrace ring entry, host-only dict
    return {"counters": counts, "recent": recent, "by_program": by_program}


def install_compile_listener() -> bool:
    """Idempotently register the process-global jax.monitoring
    listeners (compile durations + cache-hit events). Returns False
    when jax (or its monitoring API) is unavailable — compile telemetry
    then degrades to permanent zeros, never an import error."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            import jax

            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
        except Exception:
            return False
        try:
            jax.monitoring.register_event_listener(_on_event)
        except Exception:
            pass  # older jax: plain-event API absent — hits stay 0
        _installed = True
        return True


@contextlib.contextmanager
def serving_scope(owner):
    """Mark this thread as executing serving-path device dispatch for
    `owner` (an EngineMetrics). Compiles observed while the scope is
    active count as cold compiles against that engine. Scopes nest;
    the innermost owner wins (re-entrancy from engine-in-engine setups
    attributes to the engine actually dispatching)."""
    prev = getattr(_tls, "owner", None)
    _tls.owner = owner
    try:
        yield
    finally:
        _tls.owner = prev


class FlightRecorder:
    """Fixed-size ring buffer of the last K flush/tick records.

    record() is one lock hold + one deque append per FLUSH (never per
    request); snapshot() returns newest-last copies for /debug/engine.
    `seq` is a monotonic record id so a poller can detect how many
    records it missed between reads."""

    def __init__(self, capacity: int = 128):
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = lockorder.make_lock("telemetry.flight_recorder")
        self._seq = 0

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    def record(self, **fields) -> None:
        with self._lock:
            self._seq += 1
            self._buf.append({"seq": self._seq, "ts": time.time(), **fields})

    def snapshot(self) -> list:
        with self._lock:
            return list(self._buf)

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._buf[-1] if self._buf else None
