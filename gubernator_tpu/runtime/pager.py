"""Host-side page-residency manager for the paged slot table.

The device half of paging lives in ops/paged.py (indirection map +
positional page moves); this module owns the HOST half: which logical
page sits in which physical frame, the free-frame list, per-page touch
recency, and the host-DRAM cold tier (demoted pages as wide numpy row
blocks). The engine consults it at one choke point — `ensure_resident`
inside `_execute_waves`' per-wave loop, under the engine lock — so a
probe against a demoted page promotes it back BEFORE the wave's decide
runs, and the flush resolves against resident state.

Locking: the Pager has no lock of its own. Every mutating method is
called with the owning engine's table lock held (the serving pump, the
background demoter, inject/restore paths all already serialize on it);
read-only snapshot helpers copy references under that same lock.

Transfer accounting: demote = d2h `purpose="demote"`, promote = h2d
`purpose="promote"` (utils/transfer.py, GL010). A demote's np.asarray
materialization synchronizes pending async flushes — acceptable at
demote cadence (background thread / free-list pressure), never per
request.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from gubernator_tpu.ops.layout import SlotTable
from gubernator_tpu.utils import raceguard
from gubernator_tpu.utils import transfer as _transfer
from gubernator_tpu.utils.raceguard import holds_lock

# Wide-row dtypes for assembling logical snapshot images (layout.py).
_WIDE_DTYPES = {
    "used": np.bool_,
    "algo": np.int8,
    "status": np.int8,
}


def wide_zeros(n: int) -> Dict[str, np.ndarray]:
    """One n-row block of empty wide (SlotTable-shaped) host rows."""
    return {
        f: np.zeros(n, dtype=_WIDE_DTYPES.get(f, np.int64))
        for f in SlotTable._fields
    }


class PageBudgetError(RuntimeError):
    """One wave touches more distinct pages than there are physical
    frames — the resident-page budget cannot hold a single wave's
    working set. Raise loudly: silently dropping lanes would serve
    wrong decisions."""


class Pager:
    """Tracks residency for a PagedKernels-backed table.

    State (all engine-lock guarded):
      page_map:  host mirror of the device map (lp -> pp, -1 demoted)
      free:      physical frames not bound to any logical page
      touch:     per-logical-page monotonic touch tick (LRU victims)
      host_tier: lp -> {field: np.ndarray(page_slots,)} wide rows

    n_shards > 1 runs the SAME bookkeeping over a mesh-sharded physical
    table (parallel/mesh.make_mesh_kernels): the physical frames split
    into n_shards contiguous per-device pools, a logical page only ever
    binds a frame in its own shard's pool (its groups' owner device),
    and victim selection / the background free target apply PER SHARD —
    so each device's HBM pages its own keys and one shard's pressure
    never evicts another shard's residents. The host tier is keyed by
    logical page either way; `shard_of_page` gives the per-shard
    breakdown for observability."""

    def __init__(self, kernels, metrics=None, *, n_shards: int = 1):
        self.PK = kernels
        self.metrics = metrics
        if n_shards <= 0:
            raise ValueError(f"n_shards must be >= 1: {n_shards}")
        if n_shards > 1:
            if kernels.num_phys_pages % n_shards:
                raise ValueError(
                    f"page budget {kernels.num_phys_pages} must divide by "
                    f"mesh size {n_shards} (equal per-shard frame pools)"
                )
            if kernels.num_logical_pages % n_shards:
                raise ValueError(
                    f"logical page count {kernels.num_logical_pages} must "
                    f"divide by mesh size {n_shards} (pages must not "
                    "straddle shard boundaries)"
                )
        self.n_shards = n_shards
        self.frames_per_shard = kernels.num_phys_pages // n_shards
        self.pages_per_shard = -(-kernels.num_logical_pages // n_shards)
        self.page_map = np.full(
            kernels.num_logical_pages, -1, dtype=np.int32
        )
        self.free: List[int] = list(range(kernels.num_phys_pages))
        self.touch = np.zeros(kernels.num_logical_pages, dtype=np.int64)
        self._tick = 0
        self.host_tier: Dict[int, Dict[str, np.ndarray]] = {}
        self.demotes = 0
        self.promotes = 0
        self.binds = 0

    # ---- shard geometry ----------------------------------------------------

    def shard_of_page(self, lp: int) -> int:
        """Owner shard of a logical page (0 on a single chip)."""
        return int(lp) // self.pages_per_shard

    def shard_of_frame(self, pp: int) -> int:
        """Owner shard of a physical frame (0 on a single chip)."""
        return int(pp) // self.frames_per_shard

    # ---- residency queries -------------------------------------------------

    def resident_count(self) -> int:
        return self.PK.num_phys_pages - len(self.free)

    def host_count(self) -> int:
        return len(self.host_tier)

    def host_bytes(self) -> int:
        return sum(
            sum(a.nbytes for a in rows.values())
            for rows in self.host_tier.values()
        )

    def touched_pages(self, groups, active=None) -> np.ndarray:
        """Distinct logical pages hit by a batch's group column."""
        g = np.asarray(groups)  # guberlint: allow-host-sync -- wave batches carry host-built group columns, never device tensors
        if active is not None:
            g = g[np.asarray(active)]  # guberlint: allow-host-sync -- host-built active mask, same as the group column
        if g.size == 0:
            return g.astype(np.int64)
        return np.unique(g.astype(np.int64) // self.PK.groups_per_page)

    def phys_groups(self, groups: np.ndarray) -> np.ndarray:
        """Host-side logical->physical group translation (hotkeys /
        debug joins). Non-resident groups map to -1."""
        g = np.asarray(groups, dtype=np.int64)  # guberlint: allow-host-sync -- host-built group column (hotkeys/debug joins)
        gpp = self.PK.groups_per_page
        pp = self.page_map[g // gpp].astype(np.int64)
        return np.where(pp >= 0, pp * gpp + g % gpp, np.int64(-1))

    def host_live_keys(self) -> Set[Tuple[int, int]]:
        """(key_hi, key_lo) of every used slot in the host tier — key
        pruning must keep strings for demoted keys (they are still
        live; a promote brings them back verbatim)."""
        out: Set[Tuple[int, int]] = set()
        for rows in self.host_tier.values():
            used = rows["used"]
            for hi, lo in zip(
                rows["key_hi"][used].tolist(), rows["key_lo"][used].tolist()
            ):
                out.add((hi, lo))
        return out

    @holds_lock("engine.table")
    def host_tier_copy(self) -> Dict[int, Dict[str, np.ndarray]]:
        """Shallow copy for off-lock readers (census, snapshot). Stored
        row blocks are never mutated in place — demote replaces the dict
        entry — so the copied references are stable."""
        return dict(self.host_tier)

    # ---- residency transitions (engine lock held) --------------------------

    @holds_lock("engine.table")
    def ensure_resident(self, table, pages) -> object:
        """Promote every page in `pages` (logical page indices), demoting
        LRU victims if no frame is free. Returns the updated table."""
        pages = [int(p) for p in np.atleast_1d(pages)]
        self._tick += 1
        for lp in pages:
            self.touch[lp] = self._tick
        protect = set(pages)
        for lp in pages:
            if self.page_map[lp] < 0:
                table = self._promote_one(table, lp, protect)
        return table

    @holds_lock("engine.table")
    def acquire_frame(self, lp: int) -> Optional[int]:
        """Pop a free frame eligible to hold logical page `lp` — any
        frame on one chip, the page's own shard pool on a mesh. None
        when the (per-shard) pool is dry. Every bind site (promote AND
        the engine's paged restore) goes through this one gate so shard
        placement can never be bypassed."""
        if self.n_shards == 1:
            return self.free.pop() if self.free else None
        shard = self.shard_of_page(lp)
        for i in range(len(self.free) - 1, -1, -1):
            if self.shard_of_frame(self.free[i]) == shard:
                return self.free.pop(i)
        return None

    @holds_lock("engine.table")
    def _promote_one(self, table, lp: int, protect: Set[int]):
        pp = self.acquire_frame(lp)
        if pp is None:
            victim = self._coldest_resident(
                protect, shard=self.shard_of_page(lp)
            )
            if victim is None:
                budget = (
                    self.frames_per_shard
                    if self.n_shards > 1
                    else self.PK.num_phys_pages
                )
                raise PageBudgetError(
                    f"page budget {budget}"
                    + (f" (per shard, x{self.n_shards})" if self.n_shards > 1 else "")
                    + f" cannot hold {len(protect)} distinct pages touched "
                    "by one wave; raise GUBER_TABLE_PAGE_BUDGET"
                )
            table = self.demote(table, victim)
            pp = self.acquire_frame(lp)
        rows = self.host_tier.pop(lp, None)
        if rows is None:
            table = self.PK.bind_page(table, np.int32(lp), np.int32(pp))
            self.binds += 1
        else:
            with _transfer.account(self.metrics, "h2d", "promote") as tx:
                table = self.PK.write_page(
                    table, np.int32(lp), np.int32(pp), SlotTable(**rows)
                )
                tx.add(rows)
            self.promotes += 1
        self.page_map[lp] = pp
        return table

    @holds_lock("engine.table")
    def demote(self, table, lp: int):
        """Evacuate one resident page to the host tier (positional wide
        rows) and unbind its frame. All-empty pages are dropped, not
        stored — a later touch rebinds a zeroed frame."""
        pp = int(self.page_map[lp])  # guberlint: allow-host-sync -- page_map is a host numpy mirror, not device data
        if pp < 0:
            return table
        with _transfer.account(self.metrics, "d2h", "demote") as tx:
            rows = self.PK.extract_page(table, np.int32(pp))
            host = {
                f: np.asarray(getattr(rows, f))  # guberlint: allow-host-sync -- page evacuation: demote-cadence d2h, never per request
                for f in SlotTable._fields
            }
            tx.add(host)
        if host["used"].any():
            self.host_tier[lp] = host
        table = self.PK.unbind_page(table, np.int32(lp), np.int32(pp))
        self.page_map[lp] = -1
        self.free.append(pp)
        self.demotes += 1
        return table

    def _coldest_resident(
        self, protect: Set[int], shard: Optional[int] = None
    ) -> Optional[int]:
        resident = np.nonzero(self.page_map >= 0)[0]
        candidates = [
            lp
            for lp in resident.tolist()
            if lp not in protect
            and (shard is None or self.shard_of_page(lp) == shard)
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda lp: int(self.touch[lp]))  # guberlint: allow-host-sync -- touch ticks are a host numpy mirror

    @holds_lock("engine.table")
    def coldness_from_heatmap(
        self, cold_heatmap, groups_per_region: int
    ) -> Dict[int, float]:
        """Fold the census per-region cold-slot heatmap (physical-group
        axis — the census scans the resident table) into a per-LOGICAL-
        page coldness score: each resident page sums the cold counts of
        the regions its frame's group span overlaps, weighted by overlap
        fraction. O(resident x regions-per-page), demote cadence only."""
        hm = np.asarray(cold_heatmap, dtype=np.float64)  # guberlint: allow-host-sync -- census heatmap fold runs at demote cadence, never per request
        per = max(1, int(groups_per_region))
        gpp = self.PK.groups_per_page
        out: Dict[int, float] = {}
        for lp in np.nonzero(self.page_map >= 0)[0].tolist():
            pp = int(self.page_map[lp])  # guberlint: allow-host-sync -- page_map is a host numpy mirror, not device data
            g0, g1 = pp * gpp, (pp + 1) * gpp
            total = 0.0
            for r in range(g0 // per, min((g1 - 1) // per, len(hm) - 1) + 1):
                overlap = min(g1, (r + 1) * per) - max(g0, r * per)
                if overlap > 0:
                    total += float(hm[r]) * (overlap / float(per))  # guberlint: allow-host-sync -- census heatmap fold runs at demote cadence, never per request
            out[lp] = total
        return out

    def _pick_victim(
        self,
        coldness: Optional[Dict[int, float]],
        shard: Optional[int] = None,
    ) -> Optional[int]:
        """Demoter victim: census-coldest resident page first, LRU touch
        tick as the tiebreak (and the whole ordering when no census
        coldness is available). The census sees what touch ticks cannot:
        a single probe re-warms a page's tick while the census still
        counts every other slot on it as idle — such a hot-touched but
        census-cold page should go before a genuinely busy one. With
        `shard` set, only that shard's residents are candidates."""
        resident = [
            lp
            for lp in np.nonzero(self.page_map >= 0)[0].tolist()
            if shard is None or self.shard_of_page(lp) == shard
        ]
        if not resident:
            return None
        cold = coldness or {}
        return min(
            resident,
            key=lambda lp: (-cold.get(lp, 0.0), int(self.touch[lp])),  # guberlint: allow-host-sync -- touch ticks are a host numpy mirror
        )

    @holds_lock("engine.table")
    def demote_victims(
        self, table, want_free: int, min_idle_ticks: int = 0, coldness=None
    ):
        """Background-demoter entry: demote resident pages until
        `want_free` frames are free — census-coldest first when the
        engine passes the per-page `coldness` fold (coldness_from_
        heatmap), pure LRU otherwise. With min_idle_ticks > 0, pages
        touched within that many ensure_resident rounds are spared
        UNLESS the census marks them cold (the census is the stronger
        signal: it counts idle slots, a touch tick only remembers the
        last probe). On a mesh the target applies PER SHARD: every
        shard's frame pool is driven to `want_free` free frames from its
        own residents, so one busy shard cannot starve another's pool.
        Returns the updated table."""
        for shard in range(self.n_shards):
            while self._free_in_shard(shard) < want_free:
                victim = self._pick_victim(
                    coldness, shard=shard if self.n_shards > 1 else None
                )
                if victim is None:
                    break
                census_cold = (
                    bool(coldness) and coldness.get(victim, 0.0) > 0
                )
                if (
                    not census_cold
                    and min_idle_ticks > 0
                    and self._tick - int(self.touch[victim]) < min_idle_ticks  # guberlint: allow-host-sync -- touch ticks are a host numpy mirror
                ):
                    break  # everything left is too recently touched
                table = self.demote(table, victim)
        return table

    def _free_in_shard(self, shard: int) -> int:
        if self.n_shards == 1:
            return len(self.free)
        return sum(
            1 for pp in self.free if self.shard_of_frame(pp) == shard
        )

    @holds_lock("engine.table")
    def reset(self) -> None:
        """Post-recovery zeroing: the engine rebuilt an empty paged
        table, so every mirror entry, frame, and host page is gone."""
        self.page_map.fill(-1)
        self.free = list(range(self.PK.num_phys_pages))
        self.touch.fill(0)
        self.host_tier.clear()

    # ---- observability -----------------------------------------------------

    @holds_lock("engine.table")
    def pages_snapshot(self) -> dict:
        """/debug/table "pages" section + metrics-bridge source."""
        nlp = self.PK.num_logical_pages
        snap = {
            "enabled": True,
            "groups_per_page": self.PK.groups_per_page,
            "page_slots": self.PK.page_slots,
            "logical_pages": nlp,
            "budget": self.PK.num_phys_pages,
            "resident": self.resident_count(),
            "free": len(self.free),
            "host": len(self.host_tier),
            "host_bytes": self.host_bytes(),
            "demotes": self.demotes,
            "promotes": self.promotes,
            "binds": self.binds,
        }
        if self.n_shards > 1:
            # Per-shard residency/pressure breakdown (docs/monitoring.md
            # "pages.shards"): each shard pages independently, so a
            # healthy aggregate can hide one starved pool.
            shards = []
            for s in range(self.n_shards):
                p0, p1 = s * self.pages_per_shard, (s + 1) * self.pages_per_shard
                res = int((self.page_map[p0:p1] >= 0).sum())
                host = sum(
                    1
                    for lp in self.host_tier
                    if self.shard_of_page(lp) == s
                )
                shards.append(
                    {
                        "resident": res,
                        "free": self._free_in_shard(s),
                        "host": host,
                    }
                )
            snap["n_shards"] = self.n_shards
            snap["frames_per_shard"] = self.frames_per_shard
            snap["shards"] = shards
        if nlp <= 4096:  # bounded debug payload
            snap["page_map"] = self.page_map.tolist()
        return snap


# Declared lock protocol (docs/robustness.md "Race sanitizer"). The
# Pager owns no lock: every structural field is guarded by the OWNING
# engine's table lock (matched by name — any engine's "engine.table"
# counts, and each engine has exactly one pager). The cumulative move
# counters are write-guarded only: the SLO sampler and tests read them
# racily on purpose (monotonic ints).
raceguard.guarded_by(Pager, {
    "page_map": "engine.table",
    "free": "engine.table",
    "touch": "engine.table",
    "host_tier": "engine.table",
    "_tick": "engine.table",
    "demotes": "w:engine.table",
    "promotes": "w:engine.table",
    "binds": "w:engine.table",
})
