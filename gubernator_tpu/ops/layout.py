"""HBM slot table layout and batch operand structs.

The table replaces the reference's per-worker LRU caches + bucket structs
(reference lrucache.go:32-214, store.go:29-43, cache.go:29-41) with one
struct-of-arrays region designed for vectorized gather/scatter:

- W-way set-associative: a key's 128-bit hash picks a *group* of W
  contiguous slots; matching, insertion, and LRU eviction all happen
  inside the decide kernel over the W gathered candidates — no host
  round-trips (SURVEY.md §7 hard part (d)).
- Eviction policy is least-recently-used within the group, preferring
  expired slots, mirroring the reference cache's evict-oldest +
  lazy-expiry behavior (reference lrucache.go:98-100, 115-118) at group
  granularity.
- `remaining` holds whole tokens for TOKEN_BUCKET and Q44.20 fixed point
  for LEAKY_BUCKET (see models/bucket.py).
- `stamp` is TokenBucketItem.CreatedAt / LeakyBucketItem.UpdatedAt.
- `invalid_at` supports the Store plugin's re-fetch hint
  (reference cache.go:35-40).

All arrays are int64/bool; (key_hi, key_lo) == (0, 0) marks empty.

SlotTable is also the CANONICAL interchange row format: every other
layout (ops/packed.py, ops/fused.py, ops/narrow.py) converts to/from it
for Loader snapshots, the ici sync tick's merge, and store write-behind
rows, so on-disk state and cross-layer seams never depend on the
device-resident packing (ops/kernels.py to_wide/from_wide).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

DEFAULT_WAYS = 8


class SlotTable(NamedTuple):
    """Struct-of-arrays counter table; a JAX pytree."""

    key_hi: jnp.ndarray  # (N,) int64
    key_lo: jnp.ndarray  # (N,) int64
    used: jnp.ndarray  # (N,) bool
    algo: jnp.ndarray  # (N,) int8
    status: jnp.ndarray  # (N,) int8 (token-bucket sticky status)
    limit: jnp.ndarray  # (N,) int64
    duration: jnp.ndarray  # (N,) int64
    remaining: jnp.ndarray  # (N,) int64 (token: tokens; leaky: Q44.20)
    stamp: jnp.ndarray  # (N,) int64 (token: created_at; leaky: updated_at)
    expire_at: jnp.ndarray  # (N,) int64 epoch ms
    invalid_at: jnp.ndarray  # (N,) int64 epoch ms, 0 = unset
    burst: jnp.ndarray  # (N,) int64 (leaky only)
    lru: jnp.ndarray  # (N,) int64 last-access epoch ms

    @property
    def num_slots(self) -> int:
        return self.key_hi.shape[0]

    @staticmethod
    def create(num_groups: int, ways: int = DEFAULT_WAYS) -> "SlotTable":
        n = num_groups * ways
        i64 = lambda: jnp.zeros((n,), dtype=jnp.int64)  # noqa: E731
        return SlotTable(
            key_hi=i64(),
            key_lo=i64(),
            used=jnp.zeros((n,), dtype=bool),
            algo=jnp.zeros((n,), dtype=jnp.int8),
            status=jnp.zeros((n,), dtype=jnp.int8),
            limit=i64(),
            duration=i64(),
            remaining=i64(),
            stamp=i64(),
            expire_at=i64(),
            invalid_at=i64(),
            burst=i64(),
            lru=i64(),
        )


class RequestBatch(NamedTuple):
    """Device operands for one decide() call, padded to a fixed batch size.

    Host-resolved fields (the kernel is calendar/string-free):
    - key_hi/key_lo: 128-bit key hash (api/keys.py)
    - group: key's slot-group index (key_lo mod num_groups)
    - rate_num: leaky rate numerator — duration, or the full Gregorian
      interval under DURATION_IS_GREGORIAN (reference algorithms.go:336,349-351)
    - eff_duration: effective duration — duration, or time to end of the
      Gregorian interval (reference algorithms.go:353, 449)
    - greg_expire: gregorian_expiration(now), or 0 when not Gregorian

    Invariant the assembler maintains: within one batch, all active lanes
    have distinct `group` values (duplicate keys and group collisions go to
    subsequent waves), so scatters never collide and per-key request order
    is preserved across waves.
    """

    key_hi: jnp.ndarray  # (B,) int64
    key_lo: jnp.ndarray  # (B,) int64
    group: jnp.ndarray  # (B,) int32
    algo: jnp.ndarray  # (B,) int8
    behavior: jnp.ndarray  # (B,) int32 bit flags
    hits: jnp.ndarray  # (B,) int64
    limit: jnp.ndarray  # (B,) int64
    duration: jnp.ndarray  # (B,) int64 (raw request field)
    rate_num: jnp.ndarray  # (B,) int64
    eff_duration: jnp.ndarray  # (B,) int64
    greg_expire: jnp.ndarray  # (B,) int64
    burst: jnp.ndarray  # (B,) int64 (leaky: 0 already replaced by limit)
    created_at: jnp.ndarray  # (B,) int64 epoch ms
    active: jnp.ndarray  # (B,) bool padding mask

    @property
    def batch_size(self) -> int:
        return self.key_hi.shape[0]

    @staticmethod
    def zeros(b: int) -> "RequestBatch":
        i64 = lambda: np.zeros((b,), dtype=np.int64)  # noqa: E731
        return RequestBatch(
            key_hi=i64(),
            key_lo=i64(),
            group=np.zeros((b,), dtype=np.int32),
            algo=np.zeros((b,), dtype=np.int8),
            behavior=np.zeros((b,), dtype=np.int32),
            hits=i64(),
            limit=i64(),
            duration=i64(),
            rate_num=i64(),
            eff_duration=i64(),
            greg_expire=i64(),
            burst=i64(),
            created_at=i64(),
            active=np.zeros((b,), dtype=bool),
        )


class DecideOutput(NamedTuple):
    """Per-lane decisions plus batch metrics."""

    status: jnp.ndarray  # (B,) int8
    limit: jnp.ndarray  # (B,) int64
    remaining: jnp.ndarray  # (B,) int64
    reset_time: jnp.ndarray  # (B,) int64
    slot: jnp.ndarray  # (B,) int64 slot each lane touched (N for padding)
    # Displaced occupant's key when this lane's insert evicted a DIFFERENT
    # key from the slot ((0,0) = none). The engine's store path tracks
    # these as flush events: a key whose last event is a displacement is
    # dropped from the host key dictionary so its next request prefetches
    # the persisted counter outside the device lock (the reference
    # re-consults the store on every cache miss, algorithms.go:45-51).
    evicted_hi: jnp.ndarray  # (B,) int64
    evicted_lo: jnp.ndarray  # (B,) int64
    # Slot freed by token-bucket RESET_REMAINING (the only path where the
    # reference removes the persisted entry, algorithms.go:78-90).
    freed: jnp.ndarray  # (B,) bool
    # metrics (scalars): cache hits, misses, unexpired evictions, over-limit
    hits: jnp.ndarray
    misses: jnp.ndarray
    unexpired_evictions: jnp.ndarray
    over_limit: jnp.ndarray
