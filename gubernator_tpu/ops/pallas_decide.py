"""Pallas fused decide kernel: one HBM pass for probe, paging, and update.

The XLA decide path is a chain of separately-materialized HBM ops —
narrow-slice gather -> way-select -> (paged: page-map gather) -> chosen-row
gather -> scatter — and each link is a full HBM round trip for the rows it
touches. This module collapses the chain into ONE Pallas program per wave
("Ragged Paged Attention" shape, PAPERS.md): the kernel

- folds the `ops/paged.py` page-map lookup INSIDE the kernel (a scalar
  SMEM read per lane while computing the DMA offset), so the PR 12
  "one extra gather" disappears from the paged hot path;
- DMAs each lane's contiguous (W, C) group block into VMEM once and keeps
  it resident across way-selection AND token/leaky arithmetic — each slot
  row crosses HBM exactly once (the XLA narrow path re-gathers the chosen
  row after the prefix probe; here it is already on-chip);
- writes exactly one row per active resident lane back via a guarded DMA
  (sentinel/non-resident lanes and padding lanes write NOTHING — the
  paged scatter-drop contract holds by construction, not by clamping);
- emits the admission/census scalars the PR 10/14 observatories consume
  (`ops/admission.py` / `ops/census.py` input conventions) as a fused
  side-output over the rows the wave wrote, for free.

Branch semantics are bit-exact with the XLA layouts: the kernel body
reuses the SHARED policy/arithmetic verbatim — `probe_ways` from
ops/fused.py and `_token_paths`/`_leaky_paths` from ops/decide.py — on
the VMEM-resident block, so the pallas path can never drift from the
oracle-fuzzed XLA path (tests/test_kernel_fuzz.py runs the differential
suite pallas-vs-XLA, flat and paged).

Three lowerings, resolved at dispatch time (`pallas_mode()`):

- "mosaic":    real `pl.pallas_call` on TPU backends.
- "interpret": the same `pl.pallas_call` with `interpret=True` — tier-1
  CPU tests exercise the kernel logic (DMA sequencing, SMEM page-map
  reads, guarded stores, grid accumulation) without a TPU.
- "reference": a plain-XLA lowering of the identical fused program (one
  block gather + shared compute + one scatter + fused side-outputs) for
  non-TPU backends where interpret-mode's per-lane emulation would be
  benchmark noise. All three share `_wave_compute`, so they are
  bit-exact with each other by construction.

Deliberate divergences from the XLA path, confined to SENTINEL
(non-resident-page) lanes — where the XLA kernels compute way selection
over clamped out-of-range gathers and can report garbage-derived
`evicted_hi/lo` / `unexpired_evictions`:

- the kernel treats a sentinel lane's group as EMPTY (zeroed block), so
  its way-choice metadata is deterministic: no spurious displaced-key
  report, no spurious unexpired-eviction count, `slot == num_slots`
  exactly. Response fields (status/remaining/reset_time) are unaffected
  in either path (state is zero-masked on `~exists` everywhere), and the
  dropped-write guarantee is identical.

Block size (`block_b`, the per-grid-step lane tile) is the autotuned
parameter — see runtime/kerneltune.py; `GUBER_PALLAS_BLOCK` pins it by
hand. TPU-side Mosaic lowering of the int64 policy arithmetic is staged
behind the tools/jobs/42_pallas_ab.py device job; tier-1 correctness
evidence runs interpret-mode.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.api.types import Algorithm, Behavior, Status
from gubernator_tpu.ops import fused as _f
from gubernator_tpu.ops import narrow as _n
from gubernator_tpu.ops.admission import ADMISSION_SHIFT
from gubernator_tpu.ops.decide import _leaky_paths, _token_paths
from gubernator_tpu.ops.fused import probe_ways
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch
from gubernator_tpu.ops.packed import (
    META_ALGO_SHIFT,
    META_STATUS_SHIFT,
    META_USED,
    _pack_meta,
)

I64 = jnp.int64
I32 = jnp.int32

# Layouts this module lowers; everything else stays on the XLA path
# (ops/kernels.py silently keeps wide/packed on XLA under
# GUBER_KERNEL=pallas — they are diagnostic layouts, not serving ones).
PALLAS_LAYOUTS = ("narrow", "fused")

# Lane-tile bounds for the batch grid dimension. The default is the
# safe-everywhere fallback used when no autotuned choice is registered
# (runtime/kerneltune.py) and no GUBER_PALLAS_BLOCK override is set.
DEFAULT_BLOCK = 256
MIN_BLOCK = 8
MAX_BLOCK = 1024

# Fused side-output scalar slots (one (1, N_SCAL) accumulated output).
_S_HITS, _S_MISSES, _S_EVICTS, _S_OVER = 0, 1, 2, 3
_S_ADM_KEYS, _S_ADM_ADMITTED, _S_ADM_LIMIT = 4, 5, 6
_S_CENSUS_LIVE, _S_CENSUS_WASTE = 7, 8
N_SCAL = 9


class WaveScan(NamedTuple):
    """Admission/census side-output for ONE wave, over the rows the wave
    actually wrote (post-update state at the wave's `now`). These are the
    per-wave contributions the observatories accumulate; bit-exactness
    against the standalone scans is pinned by running
    `admission_oracle`/`census_oracle` over the written rows
    (tests/test_kernel_fuzz.py pallas section)."""

    adm_keys: jnp.ndarray  # () int64 written rows active for admission
    adm_admitted: jnp.ndarray  # () int64 sum clamp(limit - tokens, >=0)
    adm_limit: jnp.ndarray  # () int64 sum limit over admission-active rows
    census_live: jnp.ndarray  # () int64 written rows left used
    census_waste: jnp.ndarray  # () int64 written used rows already expired


# ---------------------------------------------------------------------------
# dispatch-time knobs (env reads at call time — GL004)

_block_choice: dict = {}  # (layout, paged) -> autotuned block_b


def register_block(layout: str, paged: bool, block: int) -> None:
    """Record the autotuned lane tile for (layout, paged) — called by
    runtime/kerneltune.py BEFORE the engine warms the decide program, so
    the warmed executable and the serving executable share one static
    configuration (the cold-compile invariant)."""
    _block_choice[(layout, bool(paged))] = _clamp_block(block)


def registered_block(layout: str, paged: bool) -> Optional[int]:
    return _block_choice.get((layout, bool(paged)))


def _clamp_block(block: int) -> int:
    b = max(MIN_BLOCK, min(int(block), MAX_BLOCK))
    # power-of-two tiles only: keeps the padded batch small and the
    # autotuner's candidate space aligned with the warm-bucket widths
    p = MIN_BLOCK
    while p * 2 <= b:
        p *= 2
    return p


def _pow2_at_least(n: int) -> int:
    p = MIN_BLOCK
    while p < n:
        p *= 2
    return p


def choose_block(layout: str, paged: bool, batch_size: int) -> int:
    """Lane tile for this dispatch: GUBER_PALLAS_BLOCK override, else the
    autotuned registration, else DEFAULT_BLOCK; never larger than the
    padded batch needs."""
    env = os.environ.get("GUBER_PALLAS_BLOCK", "").strip()
    if env:
        blk = _clamp_block(int(env))
    else:
        blk = _block_choice.get(
            (layout, bool(paged)), _clamp_block(DEFAULT_BLOCK)
        )
    return min(blk, _pow2_at_least(max(batch_size, 1)))


def pallas_mode() -> str:
    """Lowering for this dispatch: forced interpret, else mosaic on TPU,
    else the XLA reference lowering (bit-exact; see module docstring)."""
    v = os.environ.get("GUBER_PALLAS_INTERPRET", "auto").strip().lower()
    if v in ("1", "true", "yes", "on", "interpret"):
        return "interpret"
    if jax.default_backend() == "tpu":
        return "mosaic"
    return "reference"


# ---------------------------------------------------------------------------
# shared wave computation (bit-exactness seam: every lowering calls this)


def _pick_way(vals: jnp.ndarray, way: jnp.ndarray) -> jnp.ndarray:
    """Select vals[b, way[b]] via a one-hot reduce — the Mosaic-friendly
    spelling of the XLA kernels' vmap'd row indexing; bit-exact for
    integer selection (single non-zero term per lane)."""
    oh = (
        lax.broadcasted_iota(I64, vals.shape[:2], 1)
        == way.astype(I64)[:, None]
    )
    if vals.ndim == 3:
        oh = oh[:, :, None]
    return jnp.sum(jnp.where(oh, vals, 0), axis=1)


def _wave_compute(
    layout, rows, batch, now, n, resident, phys_grp, ways,
    *, probe=None, st_row=None,
):
    """One wave over a VMEM/registers-resident (B, W, C) block.

    rows      : the gathered group blocks, ZEROED for non-resident lanes.
    phys_grp  : (B,) physical group per lane (valid only where resident).
    probe     : optional pre-staged way-selection columns ({col: (B, W)})
                — the reference lowering gathers ONLY these off HBM.
    st_row    : optional pre-gathered selected row (B, C). When both
                overrides are given `rows` is never read (pass None);
                the mosaic/interpret kernels keep the VMEM-block path.
    Returns (new_row (B, C), out: DecideOutput, scan: WaveScan). Every
    value is computed with the exact arithmetic of the XLA layout impls
    (ops/narrow.py / ops/fused.py) — this function is shared by the
    mosaic, interpret, and reference lowerings.
    """
    if layout == "narrow":
        KHI, KLO, META, EXPC, INVC = _n.KHI, _n.KLO, _n.META, _n.EXP, _n.INV
        ncols = _n.NCOLS
    elif layout == "fused":
        KHI, KLO, META, EXPC, INVC = _f.KHI, _f.KLO, _f.META, _f.EXP, _f.INV
        ncols = _f.NCOLS
    else:  # pragma: no cover - guarded by PALLAS_LAYOUTS at the facade
        raise ValueError(f"pallas decide does not lower layout {layout!r}")

    if probe is None:
        probe = {
            KHI: rows[..., KHI], KLO: rows[..., KLO],
            META: rows[..., META], EXPC: rows[..., EXPC],
            INVC: rows[..., INVC],
        }
    exists, matched_way, insert_way, cat = probe_ways(
        probe[KHI], probe[KLO], probe[META], probe[EXPC], probe[INVC],
        batch, now,
    )
    way = jnp.where(exists, matched_way, insert_way)
    if st_row is None:
        st_row = _pick_way(rows, way)  # (B, C) — on-chip, no re-gather

    sel = _pick_way(cat, insert_way)
    evicts_live = (~exists) & (sel == 3) & batch.active

    old_used = (st_row[:, META] & META_USED) != 0
    displaced = (
        batch.active
        & ~exists
        & old_used
        & (
            (st_row[:, KHI] != batch.key_hi)
            | (st_row[:, KLO] != batch.key_lo)
        )
    )
    evicted_hi = jnp.where(displaced, st_row[:, KHI], 0)
    evicted_lo = jnp.where(displaced, st_row[:, KLO], 0)

    meta_sel = st_row[:, META]
    if layout == "narrow":
        limit_sel, burst_sel = _n._unpack_limbur(st_row[:, _n.LIMBUR])
        st = dict(
            algo=((meta_sel >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
            status=((meta_sel >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
            limit=limit_sel,
            duration=st_row[:, _n.DUR],
            remaining=st_row[:, _n.REM],
            stamp=st_row[:, _n.STM],
            expire_at=st_row[:, _n.EXP],
            burst=burst_sel,
            invalid_at=st_row[:, _n.INV],
        )
    else:
        st = dict(
            algo=((meta_sel >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
            status=((meta_sel >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
            limit=st_row[:, _f.LIM],
            duration=st_row[:, _f.DUR],
            remaining=st_row[:, _f.REM],
            stamp=st_row[:, _f.STM],
            expire_at=st_row[:, _f.EXP],
            burst=st_row[:, _f.BUR],
            invalid_at=st_row[:, _f.INV],
        )
    for k in st:
        st[k] = jnp.where(exists, st[k], jnp.zeros_like(st[k]))

    bhv = batch.behavior
    b_greg = (bhv & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    b_reset = (bhv & int(Behavior.RESET_REMAINING)) != 0
    b_drain = (bhv & int(Behavior.DRAIN_OVER_LIMIT)) != 0

    tok_state, tok_resp = _token_paths(
        batch, st, b_greg, b_reset, b_drain, exists, now
    )
    lky_state, lky_resp = _leaky_paths(
        batch, st, b_greg, b_reset, b_drain, exists, now
    )

    is_leaky = batch.algo == jnp.int8(Algorithm.LEAKY_BUCKET)

    def both(t, l):
        return jnp.where(is_leaky, l, t)

    new_state = {k: both(tok_state[k], lky_state[k]) for k in tok_state}
    resp = {k: both(tok_resp[k], lky_resp[k]) for k in tok_resp}

    freed = ~new_state["used"]
    cols = [None] * ncols
    cols[KHI] = jnp.where(freed, 0, batch.key_hi)
    cols[KLO] = jnp.where(freed, 0, batch.key_lo)
    cols[META] = jnp.where(
        freed,
        0,
        _pack_meta(
            jnp.ones_like(freed),
            batch.algo,
            new_state["status"],
            jnp.broadcast_to(now, freed.shape),
        ),
    )
    cols[EXPC] = new_state["expire_at"]
    cols[INVC] = jnp.where(exists & ~freed, st["invalid_at"], 0)
    if layout == "narrow":
        cols[_n.LIMBUR] = _n._pack_limbur(
            new_state["limit"], new_state["burst"]
        )
        cols[_n.DUR] = new_state["duration"]
        cols[_n.REM] = new_state["remaining"]
        cols[_n.STM] = new_state["stamp"]
    else:
        cols[_f.LIM] = new_state["limit"]
        cols[_f.DUR] = new_state["duration"]
        cols[_f.REM] = new_state["remaining"]
        cols[_f.STM] = new_state["stamp"]
        cols[_f.BUR] = new_state["burst"]
    new_row = jnp.stack([c.astype(I64) for c in cols], axis=-1)  # (B, C)

    # Sentinel lanes land exactly on n (the drop index); resident lanes
    # on their physical slot. Inactive lanes are n, as in the XLA path.
    slot = jnp.where(
        resident, phys_grp.astype(I64) * ways + way, jnp.int64(n)
    )
    idx = jnp.where(batch.active, slot, n)

    act = batch.active
    out = DecideOutput(
        status=jnp.where(act, resp["status"], jnp.int8(0)),
        limit=jnp.where(act, batch.limit, 0),
        remaining=jnp.where(act, resp["remaining"], 0),
        reset_time=jnp.where(act, resp["reset_time"], 0),
        slot=idx,
        evicted_hi=evicted_hi,
        evicted_lo=evicted_lo,
        freed=act & freed,
        hits=jnp.sum(act & exists),
        misses=jnp.sum(act & ~exists),
        unexpired_evictions=jnp.sum(evicts_live),
        over_limit=jnp.sum(act & resp["over"]),
    )

    # Fused admission/census side-output over the rows this wave WROTE,
    # with the standalone scans' exact conventions (ops/admission.py
    # `_admission_wide`, ops/census.py `_census_wide`) applied to the
    # post-update state at this wave's `now`.
    written = act & resident
    row_used = written & ~freed
    lim_new = new_state["limit"]
    exp_new = new_state["expire_at"]
    adm_active = row_used & (lim_new > 0) & (exp_new > now)
    tokens = jnp.where(
        is_leaky, new_state["remaining"] >> ADMISSION_SHIFT,
        new_state["remaining"],
    )
    admitted = jnp.where(
        adm_active, jnp.maximum(lim_new - tokens, jnp.int64(0)), jnp.int64(0)
    )
    scan = WaveScan(
        adm_keys=jnp.sum(adm_active, dtype=I64),
        adm_admitted=jnp.sum(admitted, dtype=I64),
        adm_limit=jnp.sum(
            jnp.where(adm_active, lim_new, jnp.int64(0)), dtype=I64
        ),
        census_live=jnp.sum(row_used, dtype=I64),
        census_waste=jnp.sum(row_used & (exp_new <= now), dtype=I64),
    )
    return new_row, out, scan


def _scalars_vector(out: DecideOutput, scan: WaveScan) -> jnp.ndarray:
    v = [jnp.int64(0)] * N_SCAL
    v[_S_HITS] = out.hits.astype(I64)
    v[_S_MISSES] = out.misses.astype(I64)
    v[_S_EVICTS] = out.unexpired_evictions.astype(I64)
    v[_S_OVER] = out.over_limit.astype(I64)
    v[_S_ADM_KEYS] = scan.adm_keys
    v[_S_ADM_ADMITTED] = scan.adm_admitted
    v[_S_ADM_LIMIT] = scan.adm_limit
    v[_S_CENSUS_LIVE] = scan.census_live
    v[_S_CENSUS_WASTE] = scan.census_waste
    return jnp.stack(v)


# ---------------------------------------------------------------------------
# reference lowering (plain XLA, same fused structure, bit-exact)


def _reference_wave(layout, data, page_map, batch, now, *, ways, gpp):
    """Plain-XLA lowering with the mosaic kernel's read discipline
    translated to gather shapes: a probe gather of ONLY the way-
    selection columns plus ONE full-row gather at the selected slot —
    never a full (B, W, C) block off HBM. The gathered pieces are
    reassembled into the (B, W, C) layout `_wave_compute` expects (true
    probe columns everywhere, selected-row state one-hot-placed at its
    way, zeros elsewhere); since the shared compute body reads state
    columns only through `_pick_way`'s one-hot reduce, the assembly is
    bit-exact with a full gather while moving ~half the bytes."""
    n = data.shape[0]
    if page_map is not None:
        g32 = batch.group.astype(I32)
        pp = page_map[g32 // gpp]
        resident = pp >= 0
        phys_grp = jnp.where(resident, pp * gpp + g32 % gpp, 0)
    else:
        resident = jnp.ones_like(batch.active)
        phys_grp = batch.group.astype(I32)
    way_ix = (
        phys_grp.astype(I64)[:, None] * ways
        + jnp.arange(ways, dtype=I64)[None, :]
    )
    res_bw = resident[:, None]
    if layout == "narrow":
        # probe columns ARE the row prefix (the layout's design)
        hot = jnp.where(
            res_bw[..., None], _n._gather_cols(data, way_ix, _n.N_HOT), 0
        )
        probe = {
            _n.KHI: hot[..., _n.KHI], _n.KLO: hot[..., _n.KLO],
            _n.META: hot[..., _n.META], _n.EXP: hot[..., _n.EXP],
            _n.INV: hot[..., _n.INV],
        }
    else:
        # fused: KHI KLO META EXP are the prefix; INV sits at col 9
        hot = jnp.where(
            res_bw[..., None], _n._gather_cols(data, way_ix, 4), 0
        )
        probe = {
            _f.KHI: hot[..., _f.KHI], _f.KLO: hot[..., _f.KLO],
            _f.META: hot[..., _f.META], _f.EXP: hot[..., _f.EXP],
            _f.INV: jnp.where(res_bw, data[way_ix, _f.INV], 0),
        }
        KHI, KLO, META, EXPC, INVC = _f.KHI, _f.KLO, _f.META, _f.EXP, _f.INV
    if layout == "narrow":
        KHI, KLO, META, EXPC, INVC = _n.KHI, _n.KLO, _n.META, _n.EXP, _n.INV
    # Same way selection _wave_compute re-derives from the same probe
    # dict (same function, same inputs — XLA CSEs the duplicate); the
    # selected-row gather this slot feeds is therefore bit-identical to
    # the VMEM-block path's `_pick_way(rows, way)`.
    exists, matched_way, insert_way, _cat = probe_ways(
        probe[KHI], probe[KLO], probe[META], probe[EXPC], probe[INVC],
        batch, now,
    )
    way = jnp.where(exists, matched_way, insert_way)
    sel_slot = phys_grp.astype(I64) * ways + way
    sel_row = jnp.where(res_bw, data[sel_slot], 0)  # (B, C)
    new_row, out, scan = _wave_compute(
        layout, None, batch, now, n, resident, phys_grp, ways,
        probe=probe, st_row=sel_row,
    )
    new_data = data.at[out.slot].set(new_row, mode="drop")
    return new_data, out, scan


# ---------------------------------------------------------------------------
# pallas lowering (mosaic on TPU, interpret on CPU)

# Batch columns fed to the kernel as (block_b,) VMEM blocks, in order.
_VMEM_COLS = (
    "key_hi", "key_lo", "hits", "limit", "duration", "rate_num",
    "eff_duration", "greg_expire", "burst", "created_at",
)


def _make_kernel(layout, ways, ncols, block_b, n, paged, gpp):
    """Build the kernel body for one static configuration."""

    def kernel(*refs):
        it = iter(refs)
        group_ref = next(it)  # SMEM (block_b,) i32
        active_ref = next(it)  # SMEM (block_b,) i32
        algo_ref = next(it)  # SMEM (block_b,) i32
        behavior_ref = next(it)  # SMEM (block_b,) i32
        now_ref = next(it)  # SMEM (1,) i64
        pmap_ref = next(it) if paged else None  # SMEM (n_log_pages,) i32
        vmem_cols = [next(it) for _ in _VMEM_COLS]  # VMEM (block_b,) i64
        data_ref = next(it)  # ANY (n+? rows, C) — aliased input
        out_data_ref = next(it)  # ANY — aliased output (same buffer)
        status_ref = next(it)  # VMEM (block_b,) i32
        limit_ref = next(it)
        remaining_ref = next(it)
        reset_ref = next(it)
        slot_ref = next(it)
        ehi_ref = next(it)
        elo_ref = next(it)
        freed_ref = next(it)  # VMEM (block_b,) i32
        scal_ref = next(it)  # VMEM (1, N_SCAL) i64, accumulated
        rows = next(it)  # VMEM scratch (block_b, W, C) i64
        newrow = next(it)  # VMEM scratch (block_b, C) i64
        physg = next(it)  # SMEM scratch (block_b,) i32
        res = next(it)  # SMEM scratch (block_b,) i32
        slotg = next(it)  # SMEM scratch (block_b,) i32
        lsem = next(it)  # DMA sems (block_b,)
        ssem = next(it)  # DMA sems (block_b,)

        @pl.when(pl.program_id(0) == 0)
        def _init():
            scal_ref[...] = jnp.zeros_like(scal_ref)

        now = now_ref[0]

        def _load_copy(j):
            start = physg[j] * ways
            return pltpu.make_async_copy(
                data_ref.at[pl.ds(start, ways), :], rows.at[j], lsem.at[j]
            )

        # Phase 1: translate + start one DMA per lane. The page-map
        # lookup happens HERE, as a scalar SMEM read folded into the DMA
        # offset computation — the paged path's former standalone gather.
        def load(j, _):
            g = group_ref[j]
            if paged:
                pp = pmap_ref[g // gpp]
                r = pp >= 0
                physg[j] = jnp.where(r, pp * gpp + g % gpp, 0)
                res[j] = r.astype(I32)
            else:
                physg[j] = g
                res[j] = jnp.int32(1)

            @pl.when(res[j] != 0)
            def _go():
                _load_copy(j).start()

            @pl.when(res[j] == 0)
            def _zero():
                # Sentinel lane: treat the group as empty (deterministic
                # way-choice metadata; see module docstring).
                rows[j] = jnp.zeros((ways, ncols), dtype=I64)

            return 0

        lax.fori_loop(0, block_b, load, 0)

        def wait(j, _):
            @pl.when(res[j] != 0)
            def _w():
                _load_copy(j).wait()

            return 0

        lax.fori_loop(0, block_b, wait, 0)

        # Phase 2: the whole wave's policy + token arithmetic on the
        # VMEM-resident block — the shared bit-exact compute.
        act = active_ref[...] != 0
        batch = RequestBatch(
            key_hi=vmem_cols[0][...],
            key_lo=vmem_cols[1][...],
            group=group_ref[...],
            algo=algo_ref[...].astype(jnp.int8),
            behavior=behavior_ref[...],
            hits=vmem_cols[2][...],
            limit=vmem_cols[3][...],
            duration=vmem_cols[4][...],
            rate_num=vmem_cols[5][...],
            eff_duration=vmem_cols[6][...],
            greg_expire=vmem_cols[7][...],
            burst=vmem_cols[8][...],
            created_at=vmem_cols[9][...],
            active=act,
        )
        resident = res[...] != 0
        new_row, out, scan = _wave_compute(
            layout, rows[...], batch, now, n, resident, physg[...], ways
        )
        newrow[...] = new_row
        status_ref[...] = out.status.astype(I32)
        limit_ref[...] = out.limit
        remaining_ref[...] = out.remaining
        reset_ref[...] = out.reset_time
        slot_ref[...] = out.slot
        ehi_ref[...] = out.evicted_hi
        elo_ref[...] = out.evicted_lo
        freed_ref[...] = out.freed.astype(I32)
        scal_ref[...] += _scalars_vector(out, scan)[None, :]
        # Physical row index for the store loop's scalar reads (row
        # indices fit i32: tables cap far below 2^31 slots).
        slotg[...] = jnp.where(
            act & resident, out.slot, jnp.int64(n)
        ).astype(I32)

        def _store_copy(j):
            return pltpu.make_async_copy(
                newrow.at[pl.ds(j, 1), :],
                out_data_ref.at[pl.ds(slotg[j], 1), :],
                ssem.at[j],
            )

        # Phase 3: one guarded row store per active resident lane.
        # Sentinel and padding lanes start no DMA at all — scatter-drop
        # by omission. Distinct-group batches (the assembler invariant)
        # make the unsynchronized per-lane stores race-free.
        def store(j, _):
            @pl.when(slotg[j] < n)
            def _go():
                _store_copy(j).start()

            return 0

        lax.fori_loop(0, block_b, store, 0)

        def drain(j, _):
            @pl.when(slotg[j] < n)
            def _w():
                _store_copy(j).wait()

            return 0

        lax.fori_loop(0, block_b, drain, 0)

    return kernel


@functools.lru_cache(maxsize=None)
def _build_pallas_call(
    layout, ways, ncols, n, bp, block_b, paged, gpp, n_log_pages, interpret
):
    nb = bp // block_b
    grid = (nb,)

    def blk(space=None):
        if space is None:
            return pl.BlockSpec((block_b,), lambda i: (i,))
        return pl.BlockSpec((block_b,), lambda i: (i,), memory_space=space)

    in_specs = [
        blk(pltpu.SMEM),  # group
        blk(pltpu.SMEM),  # active
        blk(pltpu.SMEM),  # algo
        blk(pltpu.SMEM),  # behavior
        pl.BlockSpec(memory_space=pltpu.SMEM),  # now
    ]
    if paged:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))  # page_map
    in_specs.extend(blk() for _ in _VMEM_COLS)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))  # data
    data_index = len(in_specs) - 1

    out_specs = [
        pl.BlockSpec(memory_space=pltpu.ANY),  # data (aliased)
        blk(),  # status (i32)
        blk(),  # limit
        blk(),  # remaining
        blk(),  # reset_time
        blk(),  # slot
        blk(),  # evicted_hi
        blk(),  # evicted_lo
        blk(),  # freed (i32)
        pl.BlockSpec((1, N_SCAL), lambda i: (0, 0)),  # scalars, accumulated
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n, ncols), I64),
        jax.ShapeDtypeStruct((bp,), I32),
        jax.ShapeDtypeStruct((bp,), I64),
        jax.ShapeDtypeStruct((bp,), I64),
        jax.ShapeDtypeStruct((bp,), I64),
        jax.ShapeDtypeStruct((bp,), I64),
        jax.ShapeDtypeStruct((bp,), I64),
        jax.ShapeDtypeStruct((bp,), I64),
        jax.ShapeDtypeStruct((bp,), I32),
        jax.ShapeDtypeStruct((1, N_SCAL), I64),
    ]
    scratch_shapes = [
        pltpu.VMEM((block_b, ways, ncols), I64),
        pltpu.VMEM((block_b, ncols), I64),
        pltpu.SMEM((block_b,), I32),
        pltpu.SMEM((block_b,), I32),
        pltpu.SMEM((block_b,), I32),
        pltpu.SemaphoreType.DMA((block_b,)),
        pltpu.SemaphoreType.DMA((block_b,)),
    ]
    kernel = _make_kernel(layout, ways, ncols, block_b, n, paged, gpp)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        input_output_aliases={data_index: 0},
        interpret=bool(interpret),
    )


def _pad_to(x, bp):
    b = x.shape[0]
    if b == bp:
        return x
    pad = [(0, bp - b)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _pallas_wave(
    layout, data, page_map, batch, now, *, ways, gpp, block_b, interpret
):
    n, ncols = data.shape
    b = batch.key_hi.shape[0]
    bp = -(-b // block_b) * block_b
    paged = page_map is not None
    call = _build_pallas_call(
        layout, ways, ncols, n, bp, block_b, paged, gpp,
        page_map.shape[0] if paged else 0, interpret,
    )
    pb = jax.tree.map(lambda x: _pad_to(jnp.asarray(x, x.dtype), bp), batch)
    args = [
        pb.group.astype(I32),
        pb.active.astype(I32),
        pb.algo.astype(I32),
        pb.behavior.astype(I32),
        jnp.asarray(now, dtype=I64).reshape((1,)),
    ]
    if paged:
        args.append(page_map.astype(I32))
    args.extend(getattr(pb, c).astype(I64) for c in _VMEM_COLS)
    args.append(data)
    (
        new_data, status, limit, remaining, reset_time, slot,
        ehi, elo, freed, scal,
    ) = call(*args)
    sv = scal[0]
    out = DecideOutput(
        status=status[:b].astype(jnp.int8),
        limit=limit[:b],
        remaining=remaining[:b],
        reset_time=reset_time[:b],
        slot=slot[:b],
        evicted_hi=ehi[:b],
        evicted_lo=elo[:b],
        freed=freed[:b] != 0,
        hits=sv[_S_HITS],
        misses=sv[_S_MISSES],
        unexpired_evictions=sv[_S_EVICTS],
        over_limit=sv[_S_OVER],
    )
    scan = WaveScan(
        adm_keys=sv[_S_ADM_KEYS],
        adm_admitted=sv[_S_ADM_ADMITTED],
        adm_limit=sv[_S_ADM_LIMIT],
        census_live=sv[_S_CENSUS_LIVE],
        census_waste=sv[_S_CENSUS_WASTE],
    )
    return new_data, out, scan


def _wave(layout, data, page_map, batch, now, *, ways, gpp, block_b, mode):
    """One decide wave through the selected lowering; the traceable core
    every public entry point (and the shard_map raw path) goes through."""
    now = jnp.asarray(now, dtype=I64)
    if mode == "reference":
        return _reference_wave(
            layout, data, page_map, batch, now, ways=ways, gpp=gpp
        )
    return _pallas_wave(
        layout, data, page_map, batch, now,
        ways=ways, gpp=gpp, block_b=block_b,
        interpret=(mode == "interpret"),
    )


# ---------------------------------------------------------------------------
# public entry points (flat + paged, single wave + scan, raw for shard_map)


@functools.partial(
    jax.jit,
    static_argnames=("layout", "ways", "block_b", "mode"),
    donate_argnums=(0,),
)
def _flat_jit(data, batch, now, *, layout, ways, block_b, mode):
    return _wave(
        layout, data, None, batch, now,
        ways=ways, gpp=0, block_b=block_b, mode=mode,
    )


@functools.partial(
    jax.jit,
    static_argnames=("layout", "ways", "block_b", "mode"),
    donate_argnums=(0,),
)
def _flat_scan_jit(data, batches, nows, *, layout, ways, block_b, mode):
    def step(d, xs):
        b, t = xs
        d, out, _scan = _wave(
            layout, d, None, b, t,
            ways=ways, gpp=0, block_b=block_b, mode=mode,
        )
        return d, out

    return lax.scan(step, data, (batches, nows))


@functools.partial(
    jax.jit,
    static_argnames=("layout", "ways", "gpp", "block_b", "mode"),
    donate_argnums=(0,),
)
def _paged_jit(data, page_map, batch, now, *, layout, ways, gpp, block_b, mode):
    return _wave(
        layout, data, page_map, batch, now,
        ways=ways, gpp=gpp, block_b=block_b, mode=mode,
    )


@functools.partial(
    jax.jit,
    static_argnames=("layout", "ways", "gpp", "block_b", "mode"),
    donate_argnums=(0,),
)
def _paged_scan_jit(
    data, page_map, batches, nows, *, layout, ways, gpp, block_b, mode
):
    def step(d, xs):
        b, t = xs
        d, out, _scan = _wave(
            layout, d, page_map, b, t,
            ways=ways, gpp=gpp, block_b=block_b, mode=mode,
        )
        return d, out

    return lax.scan(step, data, (batches, nows))


def _check_layout(layout: str) -> None:
    if layout not in PALLAS_LAYOUTS:
        raise ValueError(
            f"pallas decide lowers {PALLAS_LAYOUTS}, not {layout!r}"
        )


def decide_flat(table, batch, now, *, layout: str, ways: int):
    """Registry-facing flat decide: (table, batch, now) -> (table', out).
    Resolves lowering + lane tile at dispatch time, then runs one cached
    jitted program per static configuration."""
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, False, batch.key_hi.shape[0])
    data, out, _scan = _flat_jit(
        table.data, batch, now,
        layout=layout, ways=ways, block_b=blk, mode=mode,
    )
    return type(table)(data), out


def decide_flat_with_scan(table, batch, now, *, layout: str, ways: int):
    """decide_flat plus the fused WaveScan side-output (the observatory
    seam; also the bit-exactness surface the fuzz suite pins)."""
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, False, batch.key_hi.shape[0])
    data, out, scan = _flat_jit(
        table.data, batch, now,
        layout=layout, ways=ways, block_b=blk, mode=mode,
    )
    return type(table)(data), out, scan


def decide_scan_flat(table, batches, nows, *, layout: str, ways: int):
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, False, batches.key_hi.shape[1])
    data, outs = _flat_scan_jit(
        table.data, batches, nows,
        layout=layout, ways=ways, block_b=blk, mode=mode,
    )
    return type(table)(data), outs


def decide_paged(pt, batch, now, *, layout: str, ways: int, gpp: int):
    """Paged decide with the page-map translation folded into the kernel
    (no standalone translation gather). pt is an ops.paged.PagedTable."""
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, True, batch.key_hi.shape[0])
    data, out, _scan = _paged_jit(
        pt.data.data, pt.page_map, batch, now,
        layout=layout, ways=ways, gpp=gpp, block_b=blk, mode=mode,
    )
    inner = type(pt.data)(data)
    return type(pt)(inner, pt.page_map), out


def decide_paged_with_scan(pt, batch, now, *, layout: str, ways: int, gpp: int):
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, True, batch.key_hi.shape[0])
    data, out, scan = _paged_jit(
        pt.data.data, pt.page_map, batch, now,
        layout=layout, ways=ways, gpp=gpp, block_b=blk, mode=mode,
    )
    inner = type(pt.data)(data)
    return type(pt)(inner, pt.page_map), out, scan


def decide_scan_paged(pt, batches, nows, *, layout: str, ways: int, gpp: int):
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, True, batches.key_hi.shape[1])
    data, outs = _paged_scan_jit(
        pt.data.data, pt.page_map, batches, nows,
        layout=layout, ways=ways, gpp=gpp, block_b=blk, mode=mode,
    )
    inner = type(pt.data)(data)
    return type(pt)(inner, pt.page_map), outs


def raw_decide_flat(table, batch, now, *, layout: str, ways: int):
    """UNJITTED flat decide for composition inside shard_map (the
    parallel/mesh.py ownership programs) — same contract as the XLA
    RawKernels.decide. Lowering/tile resolve at trace time."""
    _check_layout(layout)
    mode = pallas_mode()
    blk = choose_block(layout, False, batch.key_hi.shape[0])
    data, out, _scan = _wave(
        layout, table.data, None, batch, now,
        ways=ways, gpp=0, block_b=blk, mode=mode,
    )
    return type(table)(data), out
