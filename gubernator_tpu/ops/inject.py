"""Direct state injection into the slot table (AddCacheItem analog).

Used by the GLOBAL replication path — replicas overwrite local state with
the owner's authoritative broadcast (reference gubernator.go:425-459 →
workers.go:537-580) — and by the Loader restore path. Probes each key's
group with the same policy as decide() and overwrites/creates the entry.

The caller guarantees distinct groups within one call (the engine's wave
logic is reused).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.decide import _choose_slot
from gubernator_tpu.ops.layout import RequestBatch, SlotTable

I64 = jnp.int64


class InjectBatch(NamedTuple):
    """Authoritative per-key state to write (padded, distinct groups)."""

    key_hi: jnp.ndarray  # (B,) int64
    key_lo: jnp.ndarray  # (B,) int64
    group: jnp.ndarray  # (B,) int32
    algo: jnp.ndarray  # (B,) int8
    status: jnp.ndarray  # (B,) int8
    limit: jnp.ndarray  # (B,) int64
    duration: jnp.ndarray  # (B,) int64
    remaining: jnp.ndarray  # (B,) int64 (already Q44.20 for leaky)
    stamp: jnp.ndarray  # (B,) int64
    expire_at: jnp.ndarray  # (B,) int64
    invalid_at: jnp.ndarray  # (B,) int64
    burst: jnp.ndarray  # (B,) int64
    active: jnp.ndarray  # (B,) bool

    @staticmethod
    def zeros(b: int) -> "InjectBatch":
        i64 = lambda: np.zeros((b,), dtype=np.int64)  # noqa: E731
        return InjectBatch(
            key_hi=i64(),
            key_lo=i64(),
            group=np.zeros((b,), dtype=np.int32),
            algo=np.zeros((b,), dtype=np.int8),
            status=np.zeros((b,), dtype=np.int8),
            limit=i64(),
            duration=i64(),
            remaining=i64(),
            stamp=i64(),
            expire_at=i64(),
            invalid_at=i64(),
            burst=i64(),
            active=np.zeros((b,), dtype=bool),
        )


def _inject_impl(table: SlotTable, items: InjectBatch, now, ways: int = 8):
    now = jnp.asarray(now, dtype=I64)
    # Reuse decide's probe by viewing the inject batch as a request batch
    # (only key/group fields are read by _choose_slot).
    probe = RequestBatch(
        key_hi=items.key_hi,
        key_lo=items.key_lo,
        group=items.group,
        algo=items.algo,
        behavior=jnp.zeros_like(items.group),
        hits=items.limit,
        limit=items.limit,
        duration=items.duration,
        rate_num=items.duration,
        eff_duration=items.duration,
        greg_expire=items.expire_at,
        burst=items.burst,
        created_at=items.stamp,
        active=items.active,
    )
    slot, exists, _ev, evicted_hi, evicted_lo = _choose_slot(
        table, probe, now, ways
    )
    n = table.num_slots
    idx = jnp.where(items.active, slot, n)

    def upd(arr, val):
        return arr.at[idx].set(val, mode="drop")

    new_table = SlotTable(
        key_hi=upd(table.key_hi, items.key_hi),
        key_lo=upd(table.key_lo, items.key_lo),
        used=upd(table.used, jnp.ones_like(items.active)),
        algo=upd(table.algo, items.algo),
        status=upd(table.status, items.status),
        limit=upd(table.limit, items.limit),
        duration=upd(table.duration, items.duration),
        remaining=upd(table.remaining, items.remaining),
        stamp=upd(table.stamp, items.stamp),
        expire_at=upd(table.expire_at, items.expire_at),
        invalid_at=upd(table.invalid_at, items.invalid_at),
        burst=upd(table.burst, items.burst),
        lru=upd(table.lru, jnp.broadcast_to(now, idx.shape)),
    )
    return new_table, evicted_hi, evicted_lo


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def inject(table: SlotTable, items: InjectBatch, now, ways: int = 8):
    """Jitted entry with donated table buffers.

    Returns (table', evicted_hi, evicted_lo): displaced occupant keys per
    lane ((0,0) = none), same contract as DecideOutput.evicted_hi/lo (see
    ops/layout.py) — the engine's store path uses them to keep the host
    key dictionary aligned with table residency."""
    return _inject_impl(table, items, now, ways=ways)
