"""Fused slot-table layout: ONE (N, C) tensor, one gather, one scatter.

Round-3 profiling showed the multi-column SoA kernels lose 2+ orders of
magnitude at large tables: XLA (CPU at least) fails to elide defensive
whole-table copies when many same-buffer gather->scatter column chains
are composed in one program — per-step cost became linear in TABLE size
(the 10M-key collapse: 341ms/batch at 16M slots where the constituent
gathers/scatters each cost ~1ms). Fusing every column into a single
(N, C) int64 tensor reduces the program to ONE row-block gather
(B, W, C) and ONE row scatter (B, C): 3.6ms/batch at 16M slots on the
same machine, ~95x faster, and per-step cost is once again O(batch), not
O(table).

This shape is also what a TPU wants: a group's W x C block is contiguous
in HBM, so the probe is a coalesced DMA stream rather than W x C strided
loads; the chosen way's state needs NO second gather (it is a slice of
the already-fetched block); and the scatter writes one contiguous row
per lane.

Columns (all int64; META packs lru<<4 | status<<2 | algo<<1 | used, as
in ops/packed.py):

  KHI KLO META EXP LIM DUR REM STM BUR INV

Branch semantics are bit-exact with the wide kernel: _token_paths /
_leaky_paths from ops/decide.py are reused verbatim, and the layout runs
the full oracle fuzz (tests/test_kernel_fuzz.py). Bucket field contract:
reference store.go:29-43; LRU/expiry policy: reference lrucache.go:98-118,
cache.go:43-57.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from gubernator_tpu.api.types import Algorithm, Behavior, Status
from gubernator_tpu.ops.decide import _leaky_paths, _token_paths
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch, SlotTable

# The meta-word bit layout is a cross-layout contract (Loader snapshot
# interop): share packed.py's definition, never redeclare it.
from gubernator_tpu.ops.packed import (
    META_ALGO_SHIFT,
    META_LRU_SHIFT,
    META_STATUS_SHIFT,
    META_USED,
    _pack_meta,
)

I64 = jnp.int64

KHI, KLO, META, EXP, LIM, DUR, REM, STM, BUR, INV = range(10)
NCOLS = 10


class FusedTable(NamedTuple):
    """One (N, NCOLS) int64 tensor; a JAX pytree with a single leaf."""

    data: jnp.ndarray  # (N, NCOLS) int64

    @property
    def num_slots(self) -> int:
        return self.data.shape[0]

    # Wide-compatible host views (live_count, key pruning, tests).
    # `...` indexing so they also work on a device-stacked (D, N, C)
    # table (parallel/ici.py IciState).
    @property
    def used(self) -> jnp.ndarray:
        return (self.data[..., META] & META_USED) != 0

    @property
    def key_hi(self) -> jnp.ndarray:
        return self.data[..., KHI]

    @property
    def key_lo(self) -> jnp.ndarray:
        return self.data[..., KLO]

    @property
    def expire_at(self) -> jnp.ndarray:
        return self.data[..., EXP]

    @property
    def remaining(self) -> jnp.ndarray:
        return self.data[..., REM]

    @staticmethod
    def create(num_groups: int, ways: int = 8) -> "FusedTable":
        return FusedTable(
            data=jnp.zeros((num_groups * ways, NCOLS), dtype=jnp.int64)
        )


@jax.jit
def pack_table(wide: SlotTable) -> FusedTable:
    """Wide -> fused conversion (canonical snapshot interop)."""
    cols = [None] * NCOLS
    cols[KHI] = wide.key_hi
    cols[KLO] = wide.key_lo
    cols[META] = _pack_meta(wide.used, wide.algo, wide.status, wide.lru)
    cols[EXP] = wide.expire_at
    cols[LIM] = wide.limit
    cols[DUR] = wide.duration
    cols[REM] = wide.remaining
    cols[STM] = wide.stamp
    cols[BUR] = wide.burst
    cols[INV] = wide.invalid_at
    return FusedTable(data=jnp.stack(cols, axis=-1))


@jax.jit
def unpack_table(fused: FusedTable) -> SlotTable:
    d = fused.data
    meta = d[:, META]
    return SlotTable(
        key_hi=d[:, KHI],
        key_lo=d[:, KLO],
        used=(meta & META_USED) != 0,
        algo=((meta >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=d[:, LIM],
        duration=d[:, DUR],
        remaining=d[:, REM],
        stamp=d[:, STM],
        expire_at=d[:, EXP],
        invalid_at=d[:, INV],
        burst=d[:, BUR],
        lru=meta >> META_LRU_SHIFT,
    )


def probe_ways(w_khi, w_klo, w_meta, w_exp, w_inv, batch, now):
    """Way-selection policy over per-way column arrays (each (B, W)):
    returns (exists, matched_way, insert_way, cat). Policy identical to
    the wide kernel's _choose_slot: matched-expired > empty > expired >
    LRU. Shared by the fused and narrow layouts so the two can never
    drift — narrow feeds it slices of its (B, W, C64) hot block."""
    w_used = (w_meta & META_USED) != 0
    w_lru = w_meta >> META_LRU_SHIFT
    w_expired = w_used & ((w_exp < now) | ((w_inv != 0) & (w_inv < now)))
    w_match = (
        w_used
        & (w_khi == batch.key_hi[:, None])
        & (w_klo == batch.key_lo[:, None])
    )
    live_match = w_match & ~w_expired
    exists = jnp.any(live_match, axis=1)
    matched_way = jnp.argmax(live_match, axis=1)

    cat = jnp.where(
        w_match & w_expired,
        0,
        jnp.where(~w_used, 1, jnp.where(w_expired, 2, 3)),
    ).astype(I64)
    way_off = jnp.arange(w_meta.shape[1], dtype=I64)[None, :]
    tie = jnp.where(cat == 3, jnp.clip(w_lru, 0, (1 << 44) - 1), way_off)
    score = (cat << 44) + tie
    insert_way = jnp.argmin(score, axis=1)
    return exists, matched_way, insert_way, cat


def _probe(rows, batch, now):
    """Way selection over a gathered (B, W, C) block (see probe_ways)."""
    return probe_ways(
        rows[..., KHI], rows[..., KLO], rows[..., META],
        rows[..., EXP], rows[..., INV], batch, now,
    )


def _decide_fused_impl(table: FusedTable, batch: RequestBatch, now, *, ways: int):
    now = jnp.asarray(now, dtype=I64)
    data = table.data
    n = data.shape[0]
    grp_base = batch.group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]

    rows = data[way_ix]  # (B, W, C) — the ONE gather
    exists, matched_way, insert_way, cat = _probe(rows, batch, now)

    way = jnp.where(exists, matched_way, insert_way)
    slot = grp_base + way
    st_row = jnp.take_along_axis(rows, way[:, None, None], axis=1)[:, 0]  # (B, C)

    pick = jax.vmap(lambda r, w: r[w])
    sel = pick(cat, insert_way)
    evicts_live = (~exists) & (sel == 3) & batch.active

    old_used = (st_row[:, META] & META_USED) != 0
    displaced = (
        batch.active
        & ~exists
        & old_used
        & (
            (st_row[:, KHI] != batch.key_hi)
            | (st_row[:, KLO] != batch.key_lo)
        )
    )
    evicted_hi = jnp.where(displaced, st_row[:, KHI], 0)
    evicted_lo = jnp.where(displaced, st_row[:, KLO], 0)

    meta_sel = st_row[:, META]
    st = dict(
        algo=((meta_sel >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta_sel >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=st_row[:, LIM],
        duration=st_row[:, DUR],
        remaining=st_row[:, REM],
        stamp=st_row[:, STM],
        expire_at=st_row[:, EXP],
        burst=st_row[:, BUR],
        invalid_at=st_row[:, INV],
    )
    for k in st:
        st[k] = jnp.where(exists, st[k], jnp.zeros_like(st[k]))

    bhv = batch.behavior
    b_greg = (bhv & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    b_reset = (bhv & int(Behavior.RESET_REMAINING)) != 0
    b_drain = (bhv & int(Behavior.DRAIN_OVER_LIMIT)) != 0

    tok_state, tok_resp = _token_paths(batch, st, b_greg, b_reset, b_drain, exists, now)
    lky_state, lky_resp = _leaky_paths(batch, st, b_greg, b_reset, b_drain, exists, now)

    is_leaky = batch.algo == jnp.int8(Algorithm.LEAKY_BUCKET)

    def both(t, l):
        return jnp.where(is_leaky, l, t)

    new_state = {k: both(tok_state[k], lky_state[k]) for k in tok_state}
    resp = {k: both(tok_resp[k], lky_resp[k]) for k in tok_resp}

    freed = ~new_state["used"]
    cols = [None] * NCOLS
    cols[KHI] = jnp.where(freed, 0, batch.key_hi)
    cols[KLO] = jnp.where(freed, 0, batch.key_lo)
    cols[META] = jnp.where(
        freed,
        0,
        _pack_meta(
            jnp.ones_like(freed),
            batch.algo,
            new_state["status"],
            jnp.broadcast_to(now, freed.shape),
        ),
    )
    cols[EXP] = new_state["expire_at"]
    cols[LIM] = new_state["limit"]
    cols[DUR] = new_state["duration"]
    cols[REM] = new_state["remaining"]
    cols[STM] = new_state["stamp"]
    cols[BUR] = new_state["burst"]
    # The store's invalidation mark survives updates on a live entry
    # (reference: algorithms never touch CacheItem.InvalidAt); fresh
    # inserts and freed slots clear it.
    cols[INV] = jnp.where(exists & ~freed, st["invalid_at"], 0)
    new_row = jnp.stack([c.astype(I64) for c in cols], axis=-1)  # (B, C)

    idx = jnp.where(batch.active, slot, n)
    new_data = data.at[idx].set(new_row, mode="drop")  # the ONE scatter

    act = batch.active
    out = DecideOutput(
        status=jnp.where(act, resp["status"], jnp.int8(0)),
        limit=jnp.where(act, batch.limit, 0),
        remaining=jnp.where(act, resp["remaining"], 0),
        reset_time=jnp.where(act, resp["reset_time"], 0),
        slot=idx,
        evicted_hi=evicted_hi,
        evicted_lo=evicted_lo,
        freed=act & freed,
        hits=jnp.sum(act & exists),
        misses=jnp.sum(act & ~exists),
        unexpired_evictions=jnp.sum(evicts_live),
        over_limit=jnp.sum(act & resp["over"]),
    )
    return FusedTable(data=new_data), out


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_fused(table: FusedTable, batch: RequestBatch, now, ways: int = 8):
    return _decide_fused_impl(table, batch, now, ways=ways)


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_scan_fused(table: FusedTable, batches: RequestBatch, nows, ways: int = 8):
    def step(tbl, xs):
        b, now = xs
        tbl, out = _decide_fused_impl(tbl, b, now, ways=ways)
        return tbl, out

    return jax.lax.scan(step, table, (batches, nows))


@functools.partial(jax.jit, static_argnames=("ways",))
def probe_exists_fused(table: FusedTable, key_hi, key_lo, group, now, ways: int = 8):
    """Residency probe (store read-through seam), fused layout."""
    now = jnp.asarray(now, dtype=I64)
    grp_base = group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]
    rows = table.data[way_ix]
    w_meta = rows[..., META]
    w_used = (w_meta & META_USED) != 0
    w_invalid = rows[..., INV]
    w_expired = w_used & (
        (rows[..., EXP] < now) | ((w_invalid != 0) & (w_invalid < now))
    )
    live = (
        w_used
        & ~w_expired
        & (rows[..., KHI] == key_hi[:, None])
        & (rows[..., KLO] == key_lo[:, None])
    )
    return jnp.any(live, axis=1)


@jax.jit
def gather_rows_fused(table: FusedTable, slots) -> SlotTable:
    """Post-decide row readback, expanded to the wide row struct so the
    engine's store write-behind code is layout-agnostic."""
    n = table.num_slots
    safe = jnp.clip(slots, 0, n - 1)
    valid = slots < n
    rows = jnp.where(valid[:, None], table.data[safe], 0)  # (B, C)
    meta = rows[:, META]
    return SlotTable(
        key_hi=rows[:, KHI],
        key_lo=rows[:, KLO],
        used=(meta & META_USED) != 0,
        algo=((meta >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=rows[:, LIM],
        duration=rows[:, DUR],
        remaining=rows[:, REM],
        stamp=rows[:, STM],
        expire_at=rows[:, EXP],
        invalid_at=rows[:, INV],
        burst=rows[:, BUR],
        lru=meta >> META_LRU_SHIFT,
    )


def _inject_fused_impl(table: FusedTable, items, now, ways: int):
    now = jnp.asarray(now, dtype=I64)
    data = table.data
    n = data.shape[0]
    batch_like = RequestBatch.zeros(items.key_hi.shape[0])._replace(
        key_hi=items.key_hi,
        key_lo=items.key_lo,
        group=items.group,
        active=items.active,
    )
    grp_base = batch_like.group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]
    rows = data[way_ix]
    exists, matched_way, insert_way, _cat = _probe(rows, batch_like, now)
    way = jnp.where(exists, matched_way, insert_way)
    slot = grp_base + way
    st_row = jnp.take_along_axis(rows, way[:, None, None], axis=1)[:, 0]
    old_used = (st_row[:, META] & META_USED) != 0
    displaced = (
        items.active
        & ~exists
        & old_used
        & ((st_row[:, KHI] != items.key_hi) | (st_row[:, KLO] != items.key_lo))
    )
    evicted_hi = jnp.where(displaced, st_row[:, KHI], 0)
    evicted_lo = jnp.where(displaced, st_row[:, KLO], 0)

    cols = [None] * NCOLS
    cols[KHI] = items.key_hi
    cols[KLO] = items.key_lo
    cols[META] = _pack_meta(
        jnp.ones_like(items.active),
        items.algo,
        items.status,
        jnp.broadcast_to(now, items.key_hi.shape),
    )
    cols[EXP] = items.expire_at
    cols[LIM] = items.limit
    cols[DUR] = items.duration
    cols[REM] = items.remaining
    cols[STM] = items.stamp
    cols[BUR] = items.burst
    cols[INV] = items.invalid_at
    new_row = jnp.stack([c.astype(I64) for c in cols], axis=-1)
    idx = jnp.where(items.active, slot, n)
    return (
        FusedTable(data=data.at[idx].set(new_row, mode="drop")),
        evicted_hi,
        evicted_lo,
    )


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def inject_fused(table: FusedTable, items, now, ways: int = 8):
    return _inject_fused_impl(table, items, now, ways)
