"""Narrow slot-table layout (fused v2): a split-word tensor that halves
the probe DMA stream.

The fused layout (ops/fused.py) gathers ALL 10 int64 columns for every
way it probes — 80 B/way — even though way selection only consults five
of them (key_hi, key_lo, meta, expire_at, invalid_at) and the clamped
columns never need 64 bits (limit/burst are bounded by the documented
MAX_COUNT = 2^31-1 encode contract, models/bucket.py). At large tables
the W x C probe gather is the memory-bound term of the kernel
(VERDICT r5 "what's weak" #2), so bytes-per-probed-row is the lever.

This layout keeps ONE (N, 9) int64 tensor — one gather + one scatter,
exactly like fused — but orders the row so the probe touches only a
PREFIX of it:

- cols 0:5 (KHI KLO META EXP INV, int64): precisely the columns way
  selection reads. The probe is an explicit narrow-slice gather
  (slice_sizes=(1, 5)) pulling the (B, W, 5) block: 40 B/way, HALF of
  fused's 80. META packs lru<<4 | status<<2 | algo<<1 | used exactly as
  in ops/packed.py (the cross-layout contract — never redeclared), so
  algo/status ride free for the state phase.
- cols 5:9 (LIMBUR DUR REM STM): per-LANE state, read by one full-row
  gather at the chosen slot only. LIMBUR packs the two int32-clamped
  counters into one word (limit in the low half, burst in the high half
  — the same MAX_COUNT clamp contract ops/packed.py relies on);
  duration, remaining, and stamp stay native int64, so leaky Q44.20
  remaining, Gregorian durations, and arbitrary created_at stamps all
  round-trip exactly with no split/join arithmetic on the hot path.

Per-slot bytes: 72 (vs 80 fused, 83 wide). Probe bytes per way: 40 (vs
80 fused). Group blocks stay contiguous in HBM, so the probe remains
one coalesced DMA stream per lane.

Why one tensor and bit-packing rather than an int64/int32 tensor PAIR
(the first cut of this layout): scatter cost is per-ROW dispatch work,
not per-byte — a second (B, C32) scatter per step cost more than the
40 int32 bytes it saved, and a two-leaf table doubles the donation /
scan-carry aliasing surface. Same 72 B/slot, same 40 B/way probe,
strictly fewer gathers and scatters.

Branch semantics are bit-exact with the wide/packed/fused kernels:
way-selection policy is the SHARED ops/fused.py `probe_ways`, and
_token_paths/_leaky_paths from ops/decide.py are reused verbatim after
widening the row at load. The layout runs the full oracle fuzz
(tests/test_kernel_fuzz.py) and snapshots round-trip narrow<->wide
losslessly within the encode clamp contract (tests/test_narrow.py).
Bucket field contract: reference store.go:29-43; LRU/expiry policy:
reference lrucache.go:98-118, cache.go:43-57.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from gubernator_tpu.api.types import Algorithm, Behavior, Status
from gubernator_tpu.ops.decide import _leaky_paths, _token_paths
from gubernator_tpu.ops.fused import probe_ways
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch, SlotTable

# The meta-word bit layout is a cross-layout contract (Loader snapshot
# interop): share packed.py's definition, never redeclare it.
from gubernator_tpu.ops.packed import (
    META_ALGO_SHIFT,
    META_LRU_SHIFT,
    META_STATUS_SHIFT,
    META_USED,
    _pack_meta,
)

I64 = jnp.int64
I32 = jnp.int32

# Row columns. The probe reads ONLY the first N_HOT; the rest is
# per-lane state.
KHI, KLO, META, EXP, INV, LIMBUR, DUR, REM, STM = range(9)
N_HOT = 5
NCOLS = 9

# LIMBUR packs both int32-clamped counters into one word; DUR/REM/STM
# are native int64 — so the row's INFORMATION is 72 bytes even though
# the tensor is int64 throughout (and on TPU, where int64 is emulated
# as int32 pairs, the narrow-slice probe moves exactly 40 B/way).
BYTES_PER_SLOT = N_HOT * 8 + 4 + 4 + 3 * 8  # 72
PROBE_BYTES_PER_WAY = N_HOT * 8  # 40 (fused: 80)


def _split64(v):
    """int64 -> (lo, hi) int32 halves; exact for every int64 value
    (astype truncates to the low 32 bits; the arithmetic shift keeps the
    sign in the high half)."""
    v = v.astype(I64)
    return v.astype(I32), (v >> 32).astype(I32)


def _join64(lo, hi):
    """(lo, hi) int32 halves -> the original int64, exactly."""
    return (hi.astype(I64) << 32) | (lo.astype(I64) & 0xFFFFFFFF)


def _pack_limbur(limit, burst):
    """(limit, burst) -> one word: limit in the low 32 bits, burst in
    the high. Lossless for values inside the int32 clamp contract
    (MAX_COUNT = 2^31-1, models/bucket.py) — including negative limits."""
    return (burst.astype(I64) << 32) | (limit.astype(I64) & 0xFFFFFFFF)


def _unpack_limbur(word):
    """LIMBUR word -> (limit, burst), sign-extending both halves."""
    limit = word.astype(I32).astype(I64)  # low 32, sign-extended
    burst = word >> 32  # arithmetic shift keeps burst's sign
    return limit, burst


def _gather_cols(data, ix, ncols: int):
    """Gather `ncols`-column row PREFIXES of `data` at row indices `ix`
    (any index shape) — slice_sizes below the operand's column count is
    what keeps the probe at 40 B/way instead of the full 72-B row.
    Indices are in-bounds by construction (group ids are table-ranged)."""
    dn = lax.GatherDimensionNumbers(
        offset_dims=(ix.ndim,),
        collapsed_slice_dims=(0,),
        start_index_map=(0,),
    )
    return lax.gather(
        data, ix[..., None], dn, slice_sizes=(1, ncols),
        mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS,
    )


class NarrowTable(NamedTuple):
    """Split-word counter table; a JAX pytree with ONE leaf."""

    data: jnp.ndarray  # (N, 9) int64: KHI KLO META EXP INV LIMBUR DUR REM STM

    @property
    def num_slots(self) -> int:
        return self.data.shape[-2]

    # Wide-compatible host views (live_count, key pruning, ici sync
    # fingerprint/merge seams). `...` indexing so they also work on a
    # device-stacked (D, N, C) table (parallel/ici.py IciState).
    @property
    def used(self) -> jnp.ndarray:
        return (self.data[..., META] & META_USED) != 0

    @property
    def key_hi(self) -> jnp.ndarray:
        return self.data[..., KHI]

    @property
    def key_lo(self) -> jnp.ndarray:
        return self.data[..., KLO]

    @property
    def expire_at(self) -> jnp.ndarray:
        return self.data[..., EXP]

    @property
    def remaining(self) -> jnp.ndarray:
        return self.data[..., REM]

    @staticmethod
    def create(num_groups: int, ways: int = 8) -> "NarrowTable":
        return NarrowTable(
            data=jnp.zeros((num_groups * ways, NCOLS), dtype=I64)
        )


@jax.jit
def pack_table(wide: SlotTable) -> NarrowTable:
    """Wide -> narrow conversion (canonical snapshot interop). Lossless
    within the encode clamp contract: limit/burst must fit int32
    (MAX_COUNT, the same contract ops/packed.py relies on); every other
    column round-trips any int64 value exactly."""
    cols = [None] * NCOLS
    cols[KHI] = wide.key_hi
    cols[KLO] = wide.key_lo
    cols[META] = _pack_meta(wide.used, wide.algo, wide.status, wide.lru)
    cols[EXP] = wide.expire_at
    cols[INV] = wide.invalid_at
    cols[LIMBUR] = _pack_limbur(wide.limit, wide.burst)
    cols[DUR] = wide.duration
    cols[REM] = wide.remaining
    cols[STM] = wide.stamp
    return NarrowTable(
        data=jnp.stack([c.astype(I64) for c in cols], axis=-1)
    )


@jax.jit
def unpack_table(narrow: NarrowTable) -> SlotTable:
    d = narrow.data
    meta = d[:, META]
    limit, burst = _unpack_limbur(d[:, LIMBUR])
    return SlotTable(
        key_hi=d[:, KHI],
        key_lo=d[:, KLO],
        used=(meta & META_USED) != 0,
        algo=((meta >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=limit,
        duration=d[:, DUR],
        remaining=d[:, REM],
        stamp=d[:, STM],
        expire_at=d[:, EXP],
        invalid_at=d[:, INV],
        burst=burst,
        lru=meta >> META_LRU_SHIFT,
    )


def _probe_hot(data, batch, now, ways: int):
    """Gather each lane's (W, 5) hot-prefix block and run the shared
    way-selection policy. Returns (grp_base, exists, matched_way,
    insert_way, cat)."""
    grp_base = batch.group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]
    rows = _gather_cols(data, way_ix, N_HOT)  # (B, W, 5) — 40 B/way
    exists, matched_way, insert_way, cat = probe_ways(
        rows[..., KHI], rows[..., KLO], rows[..., META],
        rows[..., EXP], rows[..., INV], batch, now,
    )
    return grp_base, exists, matched_way, insert_way, cat


def _decide_narrow_impl(table: NarrowTable, batch: RequestBatch, now, *, ways: int):
    now = jnp.asarray(now, dtype=I64)
    data = table.data
    n = data.shape[0]

    grp_base, exists, matched_way, insert_way, cat = _probe_hot(
        data, batch, now, ways
    )
    way = jnp.where(exists, matched_way, insert_way)
    slot = grp_base + way
    row = data[slot]  # (B, 9) — the chosen lane's FULL row, per lane only

    pick = jax.vmap(lambda r, w: r[w])
    sel = pick(cat, insert_way)
    evicts_live = (~exists) & (sel == 3) & batch.active

    old_used = (row[:, META] & META_USED) != 0
    displaced = (
        batch.active
        & ~exists
        & old_used
        & (
            (row[:, KHI] != batch.key_hi)
            | (row[:, KLO] != batch.key_lo)
        )
    )
    evicted_hi = jnp.where(displaced, row[:, KHI], 0)
    evicted_lo = jnp.where(displaced, row[:, KLO], 0)

    meta_sel = row[:, META]
    limit_sel, burst_sel = _unpack_limbur(row[:, LIMBUR])
    st = dict(
        algo=((meta_sel >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta_sel >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=limit_sel,
        duration=row[:, DUR],
        remaining=row[:, REM],
        stamp=row[:, STM],
        expire_at=row[:, EXP],
        burst=burst_sel,
        invalid_at=row[:, INV],
    )
    for k in st:
        st[k] = jnp.where(exists, st[k], jnp.zeros_like(st[k]))

    bhv = batch.behavior
    b_greg = (bhv & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    b_reset = (bhv & int(Behavior.RESET_REMAINING)) != 0
    b_drain = (bhv & int(Behavior.DRAIN_OVER_LIMIT)) != 0

    tok_state, tok_resp = _token_paths(batch, st, b_greg, b_reset, b_drain, exists, now)
    lky_state, lky_resp = _leaky_paths(batch, st, b_greg, b_reset, b_drain, exists, now)

    is_leaky = batch.algo == jnp.int8(Algorithm.LEAKY_BUCKET)

    def both(t, l):
        return jnp.where(is_leaky, l, t)

    new_state = {k: both(tok_state[k], lky_state[k]) for k in tok_state}
    resp = {k: both(tok_resp[k], lky_resp[k]) for k in tok_resp}

    freed = ~new_state["used"]
    cols = [None] * NCOLS
    cols[KHI] = jnp.where(freed, 0, batch.key_hi)
    cols[KLO] = jnp.where(freed, 0, batch.key_lo)
    cols[META] = jnp.where(
        freed,
        0,
        _pack_meta(
            jnp.ones_like(freed),
            batch.algo,
            new_state["status"],
            jnp.broadcast_to(now, freed.shape),
        ),
    )
    cols[EXP] = new_state["expire_at"]
    # The store's invalidation mark survives updates on a live entry
    # (reference: algorithms never touch CacheItem.InvalidAt); fresh
    # inserts and freed slots clear it.
    cols[INV] = jnp.where(exists & ~freed, st["invalid_at"], 0)
    cols[LIMBUR] = _pack_limbur(new_state["limit"], new_state["burst"])
    cols[DUR] = new_state["duration"]
    cols[REM] = new_state["remaining"]
    cols[STM] = new_state["stamp"]
    new_row = jnp.stack([c.astype(I64) for c in cols], axis=-1)  # (B, 9)

    idx = jnp.where(batch.active, slot, n)
    new_data = data.at[idx].set(new_row, mode="drop")  # the ONE scatter

    act = batch.active
    out = DecideOutput(
        status=jnp.where(act, resp["status"], jnp.int8(0)),
        limit=jnp.where(act, batch.limit, 0),
        remaining=jnp.where(act, resp["remaining"], 0),
        reset_time=jnp.where(act, resp["reset_time"], 0),
        slot=idx,
        evicted_hi=evicted_hi,
        evicted_lo=evicted_lo,
        freed=act & freed,
        hits=jnp.sum(act & exists),
        misses=jnp.sum(act & ~exists),
        unexpired_evictions=jnp.sum(evicts_live),
        over_limit=jnp.sum(act & resp["over"]),
    )
    return NarrowTable(data=new_data), out


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_narrow(table: NarrowTable, batch: RequestBatch, now, ways: int = 8):
    return _decide_narrow_impl(table, batch, now, ways=ways)


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_scan_narrow(table: NarrowTable, batches: RequestBatch, nows, ways: int = 8):
    def step(tbl, xs):
        b, now = xs
        tbl, out = _decide_narrow_impl(tbl, b, now, ways=ways)
        return tbl, out

    return jax.lax.scan(step, table, (batches, nows))


@functools.partial(jax.jit, static_argnames=("ways",))
def probe_exists_narrow(table: NarrowTable, key_hi, key_lo, group, now, ways: int = 8):
    """Residency probe (store read-through seam): touches ONLY the hot
    row prefix — 40 B/way, the cheapest probe of any layout."""
    now = jnp.asarray(now, dtype=I64)
    grp_base = group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]
    rows = _gather_cols(table.data, way_ix, N_HOT)
    w_meta = rows[..., META]
    w_used = (w_meta & META_USED) != 0
    w_invalid = rows[..., INV]
    w_expired = w_used & (
        (rows[..., EXP] < now) | ((w_invalid != 0) & (w_invalid < now))
    )
    live = (
        w_used
        & ~w_expired
        & (rows[..., KHI] == key_hi[:, None])
        & (rows[..., KLO] == key_lo[:, None])
    )
    return jnp.any(live, axis=1)


@jax.jit
def gather_rows_narrow(table: NarrowTable, slots) -> SlotTable:
    """Post-decide row readback, expanded to the wide row struct so the
    engine's store write-behind code is layout-agnostic."""
    n = table.num_slots
    safe = jnp.clip(slots, 0, n - 1)
    valid = slots < n
    d = jnp.where(valid[:, None], table.data[safe], 0)  # (B, 9)
    meta = d[:, META]
    limit, burst = _unpack_limbur(d[:, LIMBUR])
    return SlotTable(
        key_hi=d[:, KHI],
        key_lo=d[:, KLO],
        used=(meta & META_USED) != 0,
        algo=((meta >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=limit,
        duration=d[:, DUR],
        remaining=d[:, REM],
        stamp=d[:, STM],
        expire_at=d[:, EXP],
        invalid_at=d[:, INV],
        burst=burst,
        lru=meta >> META_LRU_SHIFT,
    )


def _inject_narrow_impl(table: NarrowTable, items, now, ways: int):
    now = jnp.asarray(now, dtype=I64)
    data = table.data
    n = data.shape[0]
    batch_like = RequestBatch.zeros(items.key_hi.shape[0])._replace(
        key_hi=items.key_hi,
        key_lo=items.key_lo,
        group=items.group,
        active=items.active,
    )
    grp_base, exists, matched_way, insert_way, _cat = _probe_hot(
        data, batch_like, now, ways
    )
    way = jnp.where(exists, matched_way, insert_way)
    slot = grp_base + way
    row = data[slot]
    old_used = (row[:, META] & META_USED) != 0
    displaced = (
        items.active
        & ~exists
        & old_used
        & (
            (row[:, KHI] != items.key_hi)
            | (row[:, KLO] != items.key_lo)
        )
    )
    evicted_hi = jnp.where(displaced, row[:, KHI], 0)
    evicted_lo = jnp.where(displaced, row[:, KLO], 0)

    cols = [None] * NCOLS
    cols[KHI] = items.key_hi
    cols[KLO] = items.key_lo
    cols[META] = _pack_meta(
        jnp.ones_like(items.active),
        items.algo,
        items.status,
        jnp.broadcast_to(now, items.key_hi.shape),
    )
    cols[EXP] = items.expire_at
    cols[INV] = items.invalid_at
    cols[LIMBUR] = _pack_limbur(items.limit, items.burst)
    cols[DUR] = items.duration
    cols[REM] = items.remaining
    cols[STM] = items.stamp
    new_row = jnp.stack([c.astype(I64) for c in cols], axis=-1)

    idx = jnp.where(items.active, slot, n)
    return (
        NarrowTable(data=data.at[idx].set(new_row, mode="drop")),
        evicted_hi,
        evicted_lo,
    )


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def inject_narrow(table: NarrowTable, items, now, ways: int = 8):
    return _inject_narrow_impl(table, items, now, ways)
