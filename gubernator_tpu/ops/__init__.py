"""Device-side ops: the HBM slot table and the vectorized decide kernel.

All counter math is int64; jax x64 mode is enabled at import. (This package
is a rate limiter, not an ML trainer — there is no f32 ML math to slow
down, and epoch-millisecond timestamps require 64-bit integers.)
"""

import jax

jax.config.update("jax_enable_x64", True)

from gubernator_tpu.ops.layout import SlotTable, RequestBatch, DecideOutput  # noqa: E402
from gubernator_tpu.ops.decide import decide, decide_scan, make_decide  # noqa: E402

__all__ = [
    "SlotTable",
    "RequestBatch",
    "DecideOutput",
    "decide",
    "decide_scan",
    "make_decide",
]
