"""Paged slot-table addressing: device-resident indirection over any layout.

The flat table makes capacity a boot-time bet — every logical group owns
HBM whether or not its keys are warm. This module carves the PHYSICAL
table into fixed-size pages of `groups_per_page` contiguous groups and
routes every kernel through a device-resident page map:

    logical group g
      -> logical page   lp = g // groups_per_page
      -> physical page  pp = page_map[lp]        (ONE extra gather)
      -> physical group pp * groups_per_page + (g % groups_per_page)

Everything downstream of the translation is the UNMODIFIED layout kernel
(ops/kernels.py registry): every decide/inject/probe impl derives slot
indices exclusively from the batch's `group` field (`grp_base = group *
ways`), so translating the batch — not the kernel — keeps the paged path
bit-exact with the flat table for resident pages across all four
layouts (pinned by tests/test_kernel_fuzz.py's paged differential
suite).

Non-resident pages map to -1; translation sends those lanes to the
sentinel physical group `num_phys_pages * groups_per_page`, one past the
end of the physical table. That is safe by construction:

- gathers clamp to the last physical slot, and a clamped row can never
  spuriously match the probed key: a key's group is a pure function of
  its hash, so an equal (key_hi, key_lo) would live on the SAME
  (non-resident) logical page, never in a resident slot;
- scatters use the layouts' `idx = where(active, slot, n)` +
  `.at[idx].set(..., mode="drop")` discipline, so sentinel lanes write
  nothing.

The runtime pager (runtime/pager.py) promotes touched pages BEFORE
dispatching a wave, so sentinel lanes never carry live traffic; the
sentinel exists so a race or bug degrades to a dropped write, not
corruption of an unrelated page.

Page migration is POSITIONAL, not probe-based: `extract_page` gathers
the page's slot range as wide (SlotTable) rows and `write_page` packs
them back with `lax.dynamic_update_slice` at the new physical offset.
Way order and LRU stamps survive byte-for-byte, so demote -> promote is
an identity on table state (acceptance: zero-loss round trip). Every
layout keeps axis 0 == num_slots on every pytree leaf, which is what
lets the page ops be one generic `jax.tree.map` over the native table.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.kernels import (
    BYTES_PER_SLOT,
    get_kernels,
    get_raw_kernels,
    kernel_backend,
)
from gubernator_tpu.ops.layout import SlotTable


class PagedTable(NamedTuple):
    """The paged table pytree the engine holds in place of a flat table.

    data:     the inner layout's native table, sized to the PHYSICAL
              group count (num_phys_pages * groups_per_page groups).
    page_map: (num_logical_pages,) int32 — physical page index per
              logical page, -1 when the page is demoted/never-bound.
    """

    data: object
    page_map: jnp.ndarray

    # Wide-compatible host views over the PHYSICAL table, mirroring the
    # layout tables' own properties (live_count, key pruning, recovery
    # probes) — engine host-side sites read `table.used`/`key_hi`
    # without knowing whether the table is paged.
    @property
    def used(self) -> jnp.ndarray:
        return self.data.used

    @property
    def key_hi(self) -> jnp.ndarray:
        return self.data.key_hi

    @property
    def key_lo(self) -> jnp.ndarray:
        return self.data.key_lo

    @property
    def num_slots(self) -> int:
        return self.data.num_slots


class PagedKernels(NamedTuple):
    """Kernels-compatible facade (same field names/signatures as
    ops.kernels.Kernels where they overlap, so engine call sites don't
    fork) plus the page-management ops and geometry the runtime pager
    needs. `from_wide` intentionally raises: a paged table cannot be
    rebuilt from one flat wide image without placement decisions — the
    engine's paged restore path goes through `write_page`."""

    layout: str
    create: object  # () -> PagedTable (empty map, zeroed physical table)
    decide: object  # (pt, batch, now, ways, with_store) -> (pt, out)
    decide_scan: object  # (pt, batches, nows, ways, with_store)
    inject: object  # (pt, items, now, ways) -> (pt, ehi, elo)
    probe_exists: object  # (pt, hi, lo, group, now, ways) -> bool[B]
    gather_rows: object  # (pt, PHYSICAL slots) -> SlotTable rows
    to_wide: object  # pt -> SlotTable view of the PHYSICAL table
    from_wide: object  # raises NotImplementedError
    bytes_per_slot: int
    # --- page ops (all donate the PagedTable) ---
    bind_page: object  # (pt, lp, pp) -> pt: zero phys page, map lp->pp
    unbind_page: object  # (pt, lp, pp) -> pt: zero phys page, map lp->-1
    extract_page: object  # (pt, pp) -> SlotTable rows (page_slots,)
    write_page: object  # (pt, lp, pp, wide_rows) -> pt (positional)
    # --- geometry ---
    ways: int
    groups_per_page: int
    page_slots: int  # groups_per_page * ways
    num_phys_pages: int
    num_logical_pages: int
    num_logical_groups: int


def logical_page_of(group: int, groups_per_page: int) -> int:
    """Host-side logical-page index for one group (pager bookkeeping)."""
    return group // groups_per_page


def make_paged_kernels(
    layout: str,
    num_groups: int,
    ways: int,
    groups_per_page: int,
    num_phys_pages: int,
) -> PagedKernels:
    """Build the paged kernel set for `layout` with a fixed geometry.

    num_groups:      LOGICAL group count (the keyspace the engine hashes
                     into — unchanged from the flat table).
    groups_per_page: page granularity; the last logical page may be
                     partially used when num_groups isn't a multiple.
    num_phys_pages:  resident-page budget — the HBM footprint is
                     num_phys_pages * groups_per_page * ways slots.
    """
    if groups_per_page <= 0:
        raise ValueError(f"groups_per_page must be > 0: {groups_per_page}")
    if num_phys_pages <= 0:
        raise ValueError(f"num_phys_pages must be > 0: {num_phys_pages}")
    base = get_kernels(layout)
    raw = get_raw_kernels(layout)
    gpp = groups_per_page
    page_slots = gpp * ways
    num_logical_pages = -(-num_groups // gpp)  # ceil
    num_phys_groups = num_phys_pages * gpp
    sentinel = jnp.int32(num_phys_groups)

    def _xlate(page_map, group):
        """Logical -> physical group: the one extra gather of the paged
        probe path. Non-resident lanes -> sentinel (out of range)."""
        g = group.astype(jnp.int32)
        pp = page_map[g // gpp]
        phys = jnp.where(pp >= 0, pp * gpp + g % gpp, sentinel)
        return phys.astype(group.dtype)

    if kernel_backend() == "pallas" and layout in ("narrow", "fused"):
        # Pallas backend: the page-map lookup happens INSIDE the decide
        # kernel (a scalar SMEM read folded into each lane's DMA offset),
        # so the standalone `_xlate` gather disappears from the decide
        # hot path. Every other kernel (inject/probe/page ops — not
        # wave-rate) keeps the translate-then-XLA path above.
        from gubernator_tpu.ops import pallas_decide as _pd

        def _decide(pt, batch, now):
            return _pd.decide_paged(
                pt, batch, now, layout=layout, ways=ways, gpp=gpp
            )

        def _decide_scan(pt, batches, nows):
            return _pd.decide_scan_paged(
                pt, batches, nows, layout=layout, ways=ways, gpp=gpp
            )

    else:

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _decide(pt, batch, now):
            b = batch._replace(group=_xlate(pt.page_map, batch.group))
            data, out = raw.decide(pt.data, b, now, ways)
            return PagedTable(data, pt.page_map), out

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _decide_scan(pt, batches, nows):
            pm = pt.page_map

            def step(data, xs):
                b, now = xs
                b = b._replace(group=_xlate(pm, b.group))
                data, out = raw.decide(data, b, now, ways)
                return data, out

            data, outs = jax.lax.scan(step, pt.data, (batches, nows))
            return PagedTable(data, pm), outs

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _inject(pt, items, now):
        i = items._replace(group=_xlate(pt.page_map, items.group))
        data, ehi, elo = raw.inject(pt.data, i, now, ways)
        return PagedTable(data, pt.page_map), ehi, elo

    @jax.jit
    def _probe_exists(pt, hi, lo, group, now):
        g = _xlate(pt.page_map, group)
        return base.probe_exists(pt.data, hi, lo, g, now, ways)

    def _starts(start, ndim):
        z = jnp.asarray(0, dtype=jnp.int32)
        return (jnp.asarray(start, dtype=jnp.int32),) + (z,) * (ndim - 1)

    def _zero_region(data, start):
        def z(leaf):
            blk = jnp.zeros((page_slots,) + leaf.shape[1:], dtype=leaf.dtype)
            return jax.lax.dynamic_update_slice(
                leaf, blk, _starts(start, leaf.ndim)
            )

        return jax.tree.map(z, data)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _bind_page(pt, lp, pp):
        data = _zero_region(pt.data, pp * page_slots)
        return PagedTable(data, pt.page_map.at[lp].set(pp))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _unbind_page(pt, lp, pp):
        # Zero the evacuated frame too: census and key-string pruning
        # scan the PHYSICAL table and must not see ghost rows.
        data = _zero_region(pt.data, pp * page_slots)
        return PagedTable(data, pt.page_map.at[lp].set(jnp.int32(-1)))

    @jax.jit
    def _extract_page(pt, pp):
        slots = pp * page_slots + jnp.arange(page_slots, dtype=jnp.int64)
        return base.gather_rows(pt.data, slots)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _write_page(pt, lp, pp, rows_wide):
        rows = raw.from_wide(SlotTable(*rows_wide))
        start = pp * page_slots

        def upd(leaf, r):
            return jax.lax.dynamic_update_slice(
                leaf, r.astype(leaf.dtype), _starts(start, leaf.ndim)
            )

        data = jax.tree.map(upd, pt.data, rows)
        return PagedTable(data, pt.page_map.at[lp].set(pp))

    def _create(*_a, **_k) -> PagedTable:
        return PagedTable(
            data=base.create(num_phys_groups, ways),
            page_map=jnp.full((num_logical_pages,), -1, dtype=jnp.int32),
        )

    def _from_wide(_t):
        raise NotImplementedError(
            "paged tables restore page-by-page (write_page), not from one "
            "flat wide image — see DeviceEngine.restore's paged path"
        )

    return PagedKernels(
        layout=layout,
        create=_create,
        decide=lambda t, b, now, ways_=ways, with_store=False: _decide(
            t, b, now
        ),
        decide_scan=lambda t, bs, ns, ways_=ways, with_store=False: (
            _decide_scan(t, bs, ns)
        ),
        inject=lambda t, i, now, ways_=ways: _inject(t, i, now),
        probe_exists=lambda t, hi, lo, g, now, ways_=ways: _probe_exists(
            t, hi, lo, g, now
        ),
        gather_rows=lambda t, slots: base.gather_rows(t.data, slots),
        to_wide=lambda t: base.to_wide(t.data),
        from_wide=_from_wide,
        bytes_per_slot=BYTES_PER_SLOT[layout],
        bind_page=_bind_page,
        unbind_page=_unbind_page,
        extract_page=_extract_page,
        write_page=_write_page,
        ways=ways,
        groups_per_page=gpp,
        page_slots=page_slots,
        num_phys_pages=num_phys_pages,
        num_logical_pages=num_logical_pages,
        num_logical_groups=num_groups,
    )
