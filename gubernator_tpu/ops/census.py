"""Table census: layout-generic residency/age/churn scan of the slot table.

The paged-table roadmap (ROADMAP item 1: host-DRAM cold tier) needs
evidence about WHICH slots are cold, how much HBM is wasted on
expired-but-resident entries, and how group fill pressure is
distributed — none of which `occupancy_stats()`'s two scalars can say.
This module is that observation layer: ONE jitted, non-donating
program per table layout that scans the resident table and returns
O(buckets) device scalars (never O(slots) host transfer):

- log2 histograms of slot AGE (now - stamp: time since the counter
  window was created/updated) and IDLE time (now - lru: time since the
  slot last served a request), over used slots;
- a fixed-width per-group-region occupancy heatmap — the future "page"
  axis: region r aggregates a contiguous run of groups, exactly the
  granularity a demotion policy would page at;
- expired-but-still-resident waste (used slots whose remaining window
  has fully elapsed: expire_at <= now);
- probe pressure: the per-group used-way fill histogram plus the
  longest run of completely full groups (full groups force unexpired
  evictions on insert);
- a cold-set summary: used-slot counts whose idle time exceeds
  k x the slot's own duration, for a static tuple of multipliers
  (1x/4x/16x by default) — `count * bytes_per_slot` is the HBM a cold
  tier would reclaim at that aggressiveness.

Conventions shared with the numpy oracle (bit-exactness is pinned by
tests/test_table_census.py):

- ages/idles clamp negative deltas (wraparound or future stamps from
  injected state) to 0 — they land in bucket 0, never underflow;
- histogram bin 0 counts deltas < 1 ms; bin i counts [2^(i-1), 2^i) ms;
  the last bin absorbs everything >= 2^(n_buckets-2) ms (np.searchsorted
  semantics on the shared power-of-two boundary vector);
- the heatmap pads the group axis up to heatmap_width * ceil(G/R)
  with empty groups, so trailing regions may aggregate fewer groups.

The program is built from the layout's traceable `to_wide` (the same
converter the ici sync tick uses), so one implementation covers
wide/packed/fused/narrow and both ici tiers; the replica tier passes
`stacked=True` and the program scans replica 0's table (replicas
mirror each other post-sync).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.kernels import get_raw_kernels

I64 = jnp.int64

# Shared defaults (EngineConfig / IciEngineConfig mirror these; the
# metrics exposition derives its `le` bounds from N_BUCKETS, so the
# catalog stays in lockstep without importing jax).
CENSUS_BUCKETS = 32  # log2 ms bins: bin 31 is >= ~12.4 days
DEFAULT_HEATMAP_WIDTH = 64
DEFAULT_THRESHOLDS = (1, 4, 16)  # cold = idle > k x slot duration


class CensusOutput(NamedTuple):
    """O(buckets) device arrays from one census scan."""

    live: jnp.ndarray  # () int64 used slots
    full_groups: jnp.ndarray  # () int64 groups with all ways used
    waste: jnp.ndarray  # () int64 used & expire_at <= now
    age_hist: jnp.ndarray  # (n_buckets,) int64 log2 ms bins of now-stamp
    age_sum: jnp.ndarray  # () int64 total clamped age ms over used slots
    idle_hist: jnp.ndarray  # (n_buckets,) int64 log2 ms bins of now-lru
    idle_sum: jnp.ndarray  # () int64 total clamped idle ms over used slots
    heatmap: jnp.ndarray  # (heatmap_width,) int64 used slots per region
    fill_hist: jnp.ndarray  # (ways+1,) int64 groups by used-way count
    max_full_run: jnp.ndarray  # () int64 longest run of full groups
    cold: jnp.ndarray  # (len(thresholds),) int64 used & idle > k*duration
    # Per-region count of cold slots (idle > thresholds[0] x duration),
    # same region axis/padding as `heatmap` — the demotion policy's
    # victim signal (runtime/pager.py demote_victims): a region full of
    # USED slots may still be all-cold, and the pager's LRU touch ticks
    # cannot see that (one probe re-warms a whole page).
    cold_heatmap: jnp.ndarray  # (heatmap_width,) int64 cold slots per region


def _log2_bins(values: jnp.ndarray, used: jnp.ndarray, n_buckets: int):
    """(counts, sum) of `values` over used lanes in log2-ms bins."""
    v = jnp.where(used, jnp.maximum(values, jnp.int64(0)), jnp.int64(0))
    bounds = jnp.int64(2) ** jnp.arange(n_buckets - 1, dtype=I64)
    idx = jnp.searchsorted(bounds, v, side="right")
    ones = jnp.where(used, jnp.int64(1), jnp.int64(0))
    counts = jnp.zeros((n_buckets,), dtype=I64).at[idx].add(ones)
    return counts, jnp.sum(v, dtype=I64)


def _census_wide(
    wide, now, *, ways: int, heatmap_width: int, thresholds, n_buckets: int
) -> CensusOutput:
    used = wide.used
    n = used.shape[0]
    groups = n // ways
    age = now - wide.stamp
    idle = now - wide.lru

    age_hist, age_sum = _log2_bins(age, used, n_buckets)
    idle_hist, idle_sum = _log2_bins(idle, used, n_buckets)

    live = jnp.sum(used, dtype=I64)
    waste = jnp.sum(used & (wide.expire_at <= now), dtype=I64)

    g_used = jnp.sum(
        used.reshape(groups, ways), axis=1, dtype=I64
    )
    full = g_used == ways
    full_groups = jnp.sum(full, dtype=I64)
    fill_hist = (
        jnp.zeros((ways + 1,), dtype=I64)
        .at[g_used]
        .add(jnp.ones((groups,), dtype=I64))
    )
    # Longest run of consecutive full groups: distance to the most
    # recent non-full group (cummax of its index), 0 outside runs.
    g_idx = jnp.arange(groups, dtype=I64)
    last_unfull = jax.lax.cummax(jnp.where(~full, g_idx, jnp.int64(-1)))
    max_full_run = jnp.max(
        jnp.where(full, g_idx - last_unfull, jnp.int64(0))
    )

    per_region = -(-groups // heatmap_width)  # ceil
    padded = (
        jnp.zeros((heatmap_width * per_region,), dtype=I64)
        .at[:groups]
        .set(g_used)
    )
    heatmap = jnp.sum(
        padded.reshape(heatmap_width, per_region), axis=1, dtype=I64
    )

    idle_c = jnp.maximum(idle, jnp.int64(0))
    cold = jnp.stack(
        [
            jnp.sum(
                used & (idle_c > jnp.int64(k) * wide.duration), dtype=I64
            )
            for k in thresholds
        ]
    )

    cold0 = used & (idle_c > jnp.int64(thresholds[0]) * wide.duration)
    g_cold = jnp.sum(cold0.reshape(groups, ways), axis=1, dtype=I64)
    cold_padded = (
        jnp.zeros((heatmap_width * per_region,), dtype=I64)
        .at[:groups]
        .set(g_cold)
    )
    cold_heatmap = jnp.sum(
        cold_padded.reshape(heatmap_width, per_region), axis=1, dtype=I64
    )

    return CensusOutput(
        live=live,
        full_groups=full_groups,
        waste=waste,
        age_hist=age_hist,
        age_sum=age_sum,
        idle_hist=idle_hist,
        idle_sum=idle_sum,
        heatmap=heatmap,
        fill_hist=fill_hist,
        max_full_run=max_full_run,
        cold=cold,
        cold_heatmap=cold_heatmap,
    )


@functools.lru_cache(maxsize=None)
def make_census(
    layout: str,
    ways: int,
    heatmap_width: int = DEFAULT_HEATMAP_WIDTH,
    thresholds: tuple = DEFAULT_THRESHOLDS,
    n_buckets: int = CENSUS_BUCKETS,
    stacked: bool = False,
):
    """One jitted census program: (table, now) -> CensusOutput.

    NON-donating by construction (plain jax.jit, no donate_argnums):
    the engine dispatches it on the live table reference between
    flushes, and the table must survive. `stacked=True` builds the
    replica-tier variant whose input leaves carry a leading device
    axis; it scans replica 0 (post-sync replicas are mirrors)."""
    RK = get_raw_kernels(layout)

    def impl(table, now):
        if stacked:
            table = jax.tree.map(lambda x: x[0], table)
        wide = RK.to_wide(table)
        return _census_wide(
            wide,
            now,
            ways=ways,
            heatmap_width=heatmap_width,
            thresholds=tuple(thresholds),
            n_buckets=n_buckets,
        )

    return jax.jit(impl)


# ---------------------------------------------------------------------------
# Pure-numpy oracle (tests/test_table_census.py pins bit-exactness)


def census_oracle(
    wide,
    now: int,
    *,
    ways: int,
    heatmap_width: int = DEFAULT_HEATMAP_WIDTH,
    thresholds: tuple = DEFAULT_THRESHOLDS,
    n_buckets: int = CENSUS_BUCKETS,
) -> dict:
    """Reference census over a WIDE table of host numpy arrays; mirrors
    _census_wide decision-for-decision (same clamps, same searchsorted
    boundaries, same heatmap padding)."""
    def h(col, dt):
        return np.asarray(col, dtype=dt)  # guberlint: allow-host-sync -- pure-numpy oracle over host reference arrays (test differential target, never serving)

    used = h(wide.used, bool)
    stamp = h(wide.stamp, np.int64)
    lru = h(wide.lru, np.int64)
    expire_at = h(wide.expire_at, np.int64)
    duration = h(wide.duration, np.int64)
    n = used.shape[0]
    groups = n // ways
    bounds = np.int64(2) ** np.arange(n_buckets - 1, dtype=np.int64)

    def bins(deltas):
        v = np.where(used, np.maximum(deltas, 0), 0).astype(np.int64)
        idx = np.searchsorted(bounds, v, side="right")
        counts = np.bincount(
            idx[used], minlength=n_buckets
        ).astype(np.int64)
        return counts, np.int64(v.sum())

    age_hist, age_sum = bins(np.int64(now) - stamp)
    idle = np.int64(now) - lru
    idle_hist, idle_sum = bins(idle)

    g_used = used.reshape(groups, ways).sum(axis=1).astype(np.int64)
    full = g_used == ways
    g_idx = np.arange(groups, dtype=np.int64)
    last_unfull = np.maximum.accumulate(np.where(~full, g_idx, -1))
    max_full_run = int(np.where(full, g_idx - last_unfull, 0).max())

    per_region = -(-groups // heatmap_width)
    padded = np.zeros(heatmap_width * per_region, dtype=np.int64)
    padded[:groups] = g_used
    heatmap = padded.reshape(heatmap_width, per_region).sum(axis=1)

    idle_c = np.maximum(idle, 0)
    cold = np.array(
        [
            int((used & (idle_c > np.int64(k) * duration)).sum())
            for k in thresholds
        ],
        dtype=np.int64,
    )

    cold0 = used & (idle_c > np.int64(thresholds[0]) * duration)
    g_cold = cold0.reshape(groups, ways).sum(axis=1).astype(np.int64)
    cold_padded = np.zeros(heatmap_width * per_region, dtype=np.int64)
    cold_padded[:groups] = g_cold
    cold_heatmap = cold_padded.reshape(heatmap_width, per_region).sum(axis=1)

    return {
        "live": int(used.sum()),
        "full_groups": int(full.sum()),
        "waste": int((used & (expire_at <= np.int64(now))).sum()),
        "age_hist": age_hist,
        "age_sum": int(age_sum),
        "idle_hist": idle_hist,
        "idle_sum": int(idle_sum),
        "heatmap": heatmap.astype(np.int64),
        "fill_hist": np.bincount(
            g_used, minlength=ways + 1
        ).astype(np.int64),
        "max_full_run": max_full_run,
        "cold": cold,
        "cold_heatmap": cold_heatmap.astype(np.int64),
    }
