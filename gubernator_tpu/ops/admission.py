"""Admission accounting: ground-truth admitted-vs-limit scan of the table.

After PR 13 a single check can be answered by five different paths with
different staleness (owner engine, GLOBAL replica, degraded-local,
lease-local debit, columnar fastpath) — yet nothing measured whether
the fleet actually ENFORCES the configured limits. This module is the
ground-truth half of the admission observatory (docs/monitoring.md
"Admission"): ONE jitted, non-donating program per table layout that
scans the resident table and reduces per-key admitted-this-window
vs. configured limit to O(buckets) device scalars (never O(slots) host
transfer):

- admitted-this-window per key: `limit - tokens_remaining`, where
  whole tokens remaining is the raw `remaining` column for token
  buckets and `remaining >> FIXED_SHIFT` (arithmetic shift, the
  reference's int64 truncation) for leaky buckets' Q44.20 level;
  clamped at 0 — a bursted slot (remaining > limit) has admitted 0,
  not a negative count;
- per-key EXCESS: `max(0, admitted - limit)` — hits the table itself
  admitted beyond the configured limit (non-zero only when `remaining`
  went negative, e.g. injected or reconciled state);
- sums of admitted/limit over active keys (the over-admission SLI
  numerator/denominator: `excess_sum / limit_sum`), excess key count,
  max per-key excess, OVER_LIMIT key count, and a log2 histogram of
  per-key excess (same searchsorted boundary conventions as
  ops/census.py, pinned bit-exact by the shared oracle tests).

"Active" means: used, limit > 0, and the window has not fully elapsed
(`expire_at > now`) — an expired-but-resident slot's counters describe
a PAST window and must not feed the current-window SLI.

The device scan is owner-LOCAL truth. The fleet-wide SLI reconciles it
with the lease ledger (carved-but-unreconciled slice hits) and GLOBAL
in-flight replica admissions in the engine/auditor layers — see
runtime/engine.py admission_snapshot and parallel/auditor.py.

The program is built from the layout's traceable `to_wide` (same as the
census), so one implementation covers wide/packed/fused/narrow, both
ici tiers (`stacked=True` scans replica 0), and the paged table's
physical frames; the host-DRAM cold tier is scanned by the numpy
oracle below (runtime/engine.py, same pattern as the census host tier).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.kernels import get_raw_kernels

I64 = jnp.int64

# Leaky buckets store their level in Q44.20 fixed point
# (models/bucket.py FIXED_SHIFT); whole tokens = remaining >> 20.
# Mirrored literal so the metrics catalog can size its `le` bounds
# without importing jax (same convention as census.CENSUS_BUCKETS).
ADMISSION_SHIFT = 20
ADMISSION_BUCKETS = 32  # log2 hit bins: bin 31 is >= 2^30 excess hits

_OVER_LIMIT = 1  # api.types.Status.OVER_LIMIT (int8 column value)


class AdmissionOutput(NamedTuple):
    """O(buckets) device arrays from one admission scan."""

    keys: jnp.ndarray  # () int64 active keys (used, limit>0, unexpired)
    admitted_sum: jnp.ndarray  # () int64 Σ clamp(limit - tokens, >= 0)
    limit_sum: jnp.ndarray  # () int64 Σ limit over active keys
    excess_sum: jnp.ndarray  # () int64 Σ max(0, admitted - limit)
    excess_keys: jnp.ndarray  # () int64 active keys with excess > 0
    max_excess: jnp.ndarray  # () int64 worst single-key excess
    over_limit_keys: jnp.ndarray  # () int64 active keys at OVER_LIMIT
    excess_hist: jnp.ndarray  # (n_buckets,) int64 log2 bins of excess


def _admission_wide(wide, now, *, n_buckets: int) -> AdmissionOutput:
    active = wide.used & (wide.limit > 0) & (wide.expire_at > now)
    # Whole tokens remaining: raw column for token buckets, Q44.20
    # arithmetic shift for leaky (floors toward -inf, matching the
    # reference's truncation of non-negative levels and keeping debt
    # monotone for negative ones).
    tokens = jnp.where(
        wide.algo == jnp.int8(1),
        wide.remaining >> ADMISSION_SHIFT,
        wide.remaining,
    )
    admitted = jnp.where(
        active, jnp.maximum(wide.limit - tokens, jnp.int64(0)), jnp.int64(0)
    )
    excess = jnp.maximum(admitted - wide.limit, jnp.int64(0))

    keys = jnp.sum(active, dtype=I64)
    admitted_sum = jnp.sum(admitted, dtype=I64)
    limit_sum = jnp.sum(jnp.where(active, wide.limit, jnp.int64(0)), dtype=I64)
    excess_sum = jnp.sum(excess, dtype=I64)
    excess_mask = active & (excess > 0)
    excess_keys = jnp.sum(excess_mask, dtype=I64)
    max_excess = jnp.max(excess)
    over_limit_keys = jnp.sum(
        active & (wide.status == jnp.int8(_OVER_LIMIT)), dtype=I64
    )

    # Histogram of per-key excess over keys WITH excess (bin 0 would
    # otherwise just mirror `keys`); same boundary vector semantics as
    # census._log2_bins: bin 0 is < 1 hit (empty by construction here),
    # bin i is [2^(i-1), 2^i), the last bin absorbs the tail.
    bounds = jnp.int64(2) ** jnp.arange(n_buckets - 1, dtype=I64)
    idx = jnp.searchsorted(bounds, jnp.where(excess_mask, excess, 0), "right")
    ones = jnp.where(excess_mask, jnp.int64(1), jnp.int64(0))
    excess_hist = jnp.zeros((n_buckets,), dtype=I64).at[idx].add(ones)

    return AdmissionOutput(
        keys=keys,
        admitted_sum=admitted_sum,
        limit_sum=limit_sum,
        excess_sum=excess_sum,
        excess_keys=excess_keys,
        max_excess=max_excess,
        over_limit_keys=over_limit_keys,
        excess_hist=excess_hist,
    )


@functools.lru_cache(maxsize=None)
def make_admission(
    layout: str,
    ways: int,
    n_buckets: int = ADMISSION_BUCKETS,
    stacked: bool = False,
):
    """One jitted admission program: (table, now) -> AdmissionOutput.

    NON-donating by construction (plain jax.jit, no donate_argnums):
    the engine dispatches it on the live table reference between
    flushes, and the table must survive. `stacked=True` builds the
    replica-tier variant whose input leaves carry a leading device
    axis; it scans replica 0 (post-sync replicas are mirrors)."""
    RK = get_raw_kernels(layout)

    def impl(table, now):
        if stacked:
            table = jax.tree.map(lambda x: x[0], table)
        wide = RK.to_wide(table)
        return _admission_wide(wide, now, n_buckets=n_buckets)

    return jax.jit(impl)


# ---------------------------------------------------------------------------
# Pure-numpy oracle (tests/test_admission.py + the kernel-fuzz section
# pin bit-exactness; runtime/engine.py runs it over the paged host tier)


def admission_oracle(
    wide, now: int, *, n_buckets: int = ADMISSION_BUCKETS
) -> dict:
    """Reference admission accounting over a WIDE table of host numpy
    arrays; mirrors _admission_wide decision-for-decision (same clamps,
    same arithmetic shift, same searchsorted boundaries)."""
    def h(col, dt):
        return np.asarray(col, dtype=dt)  # guberlint: allow-host-sync -- pure-numpy oracle over host reference arrays (differential target + paged host tier, never a device readback)

    used = h(wide.used, bool)
    algo = h(wide.algo, np.int8)
    status = h(wide.status, np.int8)
    limit = h(wide.limit, np.int64)
    remaining = h(wide.remaining, np.int64)
    expire_at = h(wide.expire_at, np.int64)

    active = used & (limit > 0) & (expire_at > np.int64(now))
    tokens = np.where(algo == 1, remaining >> ADMISSION_SHIFT, remaining)
    admitted = np.where(active, np.maximum(limit - tokens, 0), 0).astype(
        np.int64
    )
    excess = np.maximum(admitted - limit, 0).astype(np.int64)
    excess_mask = active & (excess > 0)

    bounds = np.int64(2) ** np.arange(n_buckets - 1, dtype=np.int64)
    idx = np.searchsorted(bounds, np.where(excess_mask, excess, 0), "right")
    excess_hist = np.bincount(
        idx[excess_mask], minlength=n_buckets
    ).astype(np.int64)

    return {
        "keys": int(active.sum()),
        "admitted_sum": int(admitted.sum()),
        "limit_sum": int(np.where(active, limit, 0).sum()),
        "excess_sum": int(excess.sum()),
        "excess_keys": int(excess_mask.sum()),
        "max_excess": int(excess.max(initial=0)),
        "over_limit_keys": int((active & (status == _OVER_LIMIT)).sum()),
        "excess_hist": excess_hist,
    }
