"""Layout-agnostic kernel facade.

The engine selects a table layout by name (EngineConfig.layout):

- "wide": one int64 column per field (ops/layout.py + ops/decide.py) —
  the reference-shaped baseline.
- "packed": narrowed/packed columns with a 3-gather probe (ops/packed.py).
- "fused": ONE (N, C) tensor, one gather + one scatter (ops/fused.py) —
  the fastest at scale (the SoA layouts hit XLA defensive whole-table
  copies; see ops/fused.py's module docstring) and the flagship default.
- "narrow": fused v2 — a split-word (N, 9) tensor (ops/narrow.py)
  ordered so way selection reads only a 5-column row PREFIX (40 B/way,
  half of fused's probe DMA) and the int32-clamped counters bit-pack
  into one word; still exactly one gather + one scatter.

All are bit-exact against the oracle (tests/test_kernel_fuzz.py runs the
whole differential suite per layout). Snapshots are ALWAYS exchanged in
the wide format (to_wide/from_wide), so Loader files are portable across
layouts.
"""

from __future__ import annotations

from typing import NamedTuple

# The registry every layout-selection surface validates against
# (EngineConfig.layout, GUBER_TABLE_LAYOUT / GUBER_ICI_LAYOUT, bench.py
# --layout, the kernel fuzz suite).
LAYOUTS = ("wide", "packed", "fused", "narrow")

# Resident bytes per table slot, by layout (engine table-size gates,
# e.g. the bucket-warmer's scratch-copy budget; see each layout module
# for the field-by-field accounting).
BYTES_PER_SLOT = {"wide": 83, "packed": 72, "fused": 80, "narrow": 72}

import os

from gubernator_tpu.ops.decide import (
    decide as _wd,
    decide_scan as _wds,
    gather_rows as _wgr,
    probe_exists as _wpe,
)
from gubernator_tpu.ops.inject import inject as _wi
from gubernator_tpu.ops.layout import SlotTable

# Decide-program backends (GUBER_KERNEL). "xla" is the grown fleet of
# per-layout XLA programs; "pallas" routes the narrow/fused decide hot
# path through the hand-written one-HBM-pass kernel
# (ops/pallas_decide.py) with the XLA path kept as the fallback and the
# bit-exactness oracle. Layouts pallas does not lower (wide/packed — the
# diagnostic layouts) and all non-decide entry points stay on XLA.
KERNEL_BACKENDS = ("xla", "pallas")


def kernel_backend() -> str:
    """Decide-program backend, read from GUBER_KERNEL at registry-build
    time (engine/topology startup — NOT per decide call), so a built
    `Kernels` facade is pinned to one backend and the warmed programs
    are exactly the served programs."""
    v = os.environ.get("GUBER_KERNEL", "xla").strip().lower() or "xla"
    if v not in KERNEL_BACKENDS:
        raise ValueError(
            f"GUBER_KERNEL={v!r}: expected one of {KERNEL_BACKENDS}"
        )
    return v


class Kernels(NamedTuple):
    layout: str
    create: object  # (num_groups, ways) -> table
    decide: object  # (table, batch, now, ways, with_store) -> (table, out)
    decide_scan: object  # (table, batches, nows, ways, with_store)
    inject: object  # (table, items, now, ways) -> (table, ehi, elo)
    probe_exists: object  # (table, hi, lo, group, now, ways) -> bool[B]
    gather_rows: object  # (table, slots) -> SlotTable rows (wide view)
    to_wide: object  # table -> SlotTable
    from_wide: object  # SlotTable -> table
    bytes_per_slot: int = 83  # resident table bytes per slot


def _wide_decide(table, batch, now, ways, with_store=False):
    return _wd(table, batch, now, ways=ways)


def _wide_scan(table, batches, nows, ways, with_store=False):
    return _wds(table, batches, nows, ways=ways)


_WIDE = Kernels(
    layout="wide",
    create=SlotTable.create,
    decide=_wide_decide,
    decide_scan=_wide_scan,
    inject=lambda table, items, now, ways: _wi(table, items, now, ways=ways),
    probe_exists=lambda table, hi, lo, group, now, ways: _wpe(
        table, hi, lo, group, now, ways=ways
    ),
    gather_rows=_wgr,
    to_wide=lambda t: t,
    from_wide=lambda t: t,
    bytes_per_slot=BYTES_PER_SLOT["wide"],
)


def _packed():
    from gubernator_tpu.ops import packed as _p

    return Kernels(
        layout="packed",
        create=_p.PackedTable.create,
        decide=lambda table, batch, now, ways, with_store=False: _p.decide_packed(
            table, batch, now, ways=ways
        ),
        decide_scan=lambda table, batches, nows, ways, with_store=False: (
            _p.decide_scan_packed(table, batches, nows, ways=ways)
        ),
        inject=lambda table, items, now, ways: _p.inject_packed(
            table, items, now, ways=ways
        ),
        probe_exists=lambda table, hi, lo, group, now, ways: (
            _p.probe_exists_packed(table, hi, lo, group, now, ways=ways)
        ),
        gather_rows=_p.gather_rows_packed,
        to_wide=_p.unpack_table,
        from_wide=_p.pack_table,
        bytes_per_slot=BYTES_PER_SLOT["packed"],
    )


def _fused():
    from gubernator_tpu.ops import fused as _f

    return Kernels(
        layout="fused",
        create=_f.FusedTable.create,
        decide=lambda table, batch, now, ways, with_store=False: _f.decide_fused(
            table, batch, now, ways=ways
        ),
        decide_scan=lambda table, batches, nows, ways, with_store=False: (
            _f.decide_scan_fused(table, batches, nows, ways=ways)
        ),
        inject=lambda table, items, now, ways: _f.inject_fused(
            table, items, now, ways=ways
        ),
        probe_exists=lambda table, hi, lo, group, now, ways: (
            _f.probe_exists_fused(table, hi, lo, group, now, ways=ways)
        ),
        gather_rows=_f.gather_rows_fused,
        to_wide=_f.unpack_table,
        from_wide=_f.pack_table,
        bytes_per_slot=BYTES_PER_SLOT["fused"],
    )


def _narrow():
    from gubernator_tpu.ops import narrow as _n

    return Kernels(
        layout="narrow",
        create=_n.NarrowTable.create,
        decide=lambda table, batch, now, ways, with_store=False: _n.decide_narrow(
            table, batch, now, ways=ways
        ),
        decide_scan=lambda table, batches, nows, ways, with_store=False: (
            _n.decide_scan_narrow(table, batches, nows, ways=ways)
        ),
        inject=lambda table, items, now, ways: _n.inject_narrow(
            table, items, now, ways=ways
        ),
        probe_exists=lambda table, hi, lo, group, now, ways: (
            _n.probe_exists_narrow(table, hi, lo, group, now, ways=ways)
        ),
        gather_rows=_n.gather_rows_narrow,
        to_wide=_n.unpack_table,
        from_wide=_n.pack_table,
        bytes_per_slot=BYTES_PER_SLOT["narrow"],
    )


def _pallas(layout: str, base: Kernels) -> Kernels:
    """Reroute the decide hot path of `base` through the fused Pallas
    program; every other entry point (inject, probes, snapshots) keeps
    the XLA impls — they are not wave-rate paths."""
    from gubernator_tpu.ops import pallas_decide as _pd

    return base._replace(
        decide=lambda table, batch, now, ways, with_store=False: (
            _pd.decide_flat(table, batch, now, layout=layout, ways=ways)
        ),
        decide_scan=lambda table, batches, nows, ways, with_store=False: (
            _pd.decide_scan_flat(
                table, batches, nows, layout=layout, ways=ways
            )
        ),
    )


def get_kernels(layout: str) -> Kernels:
    if layout == "wide":
        return _WIDE
    if layout == "packed":
        return _packed()
    if layout == "fused":
        base = _fused()
    elif layout == "narrow":
        base = _narrow()
    else:
        raise ValueError(f"unknown table layout: {layout!r}")
    if kernel_backend() == "pallas":
        return _pallas(layout, base)
    return base


class RawKernels(NamedTuple):
    """UNJITTED impls for composition inside shard_map/pjit (the
    multi-device tier, parallel/mesh.py + parallel/ici.py). The jitted
    `Kernels` wrappers donate buffers and can't be nested inside a
    shard_map body; these are the raw traceable functions.

    `to_wide`/`from_wide` are traceable table<->SlotTable converters the
    sync tick uses so its merge logic stays layout-agnostic while decide
    runs layout-native (VERDICT r4 item 2: the hot path must be fused on
    the multi-device tier too — wide measured 137x slower on TPU)."""

    layout: str
    create: object  # (num_groups, ways) -> table
    decide: object  # (table, batch, now, ways) -> (table, DecideOutput)
    inject: object  # (table, items, now, ways) -> (table, ehi, elo)
    to_wide: object  # table -> SlotTable (traceable)
    from_wide: object  # SlotTable -> table (traceable)


def get_census(layout: str, ways: int, **kwargs):
    """Census program for `layout` (ops/census.py): one jitted,
    NON-donating scan per (layout, geometry) returning O(buckets)
    device scalars — the table-observatory entry point, registered
    here alongside the kernel registry so every layout-selection
    surface resolves both from one place. Lazy import: census is a
    scrape-cadence diagnostic, not a serving dependency."""
    if layout not in LAYOUTS:
        raise ValueError(f"unknown table layout: {layout!r}")
    from gubernator_tpu.ops.census import make_census

    return make_census(layout, ways, **kwargs)


def get_admission(layout: str, ways: int, **kwargs):
    """Admission-accounting program for `layout` (ops/admission.py):
    one jitted, NON-donating scan per (layout, geometry) reducing
    per-key admitted-this-window vs. configured limit to O(buckets)
    device scalars — the enforcement-error SLI's ground truth,
    registered here alongside the kernel registry so every
    layout-selection surface resolves both from one place. Lazy
    import: admission accounting is a scrape-cadence diagnostic, not
    a serving dependency."""
    if layout not in LAYOUTS:
        raise ValueError(f"unknown table layout: {layout!r}")
    from gubernator_tpu.ops.admission import make_admission

    return make_admission(layout, ways, **kwargs)


def get_paged_kernels(
    layout: str,
    num_groups: int,
    ways: int,
    groups_per_page: int,
    num_phys_pages: int,
):
    """Paged addressing layer over `layout` (ops/paged.py): the physical
    table shrinks to a resident-page budget and every kernel consults a
    device page map (one extra gather) to translate logical groups.
    Registered here so layout selection and paging compose at the same
    seam the engine already resolves kernels from. Lazy import: flat
    tables never pay for the paged module."""
    if layout not in LAYOUTS:
        raise ValueError(f"unknown table layout: {layout!r}")
    from gubernator_tpu.ops.paged import make_paged_kernels

    return make_paged_kernels(
        layout, num_groups, ways, groups_per_page, num_phys_pages
    )


def get_raw_kernels(layout: str) -> RawKernels:
    if layout == "wide":
        from gubernator_tpu.ops.decide import _decide_impl
        from gubernator_tpu.ops.inject import _inject_impl

        return RawKernels(
            layout="wide",
            create=SlotTable.create,
            decide=lambda t, b, now, ways: _decide_impl(t, b, now, ways=ways),
            inject=lambda t, i, now, ways: _inject_impl(t, i, now, ways=ways),
            to_wide=lambda t: t,
            from_wide=lambda t: t,
        )
    if layout == "packed":
        from gubernator_tpu.ops import packed as _p

        return RawKernels(
            layout="packed",
            create=_p.PackedTable.create,
            decide=lambda t, b, now, ways: _p._decide_packed_impl(
                t, b, now, ways=ways
            ),
            inject=lambda t, i, now, ways: _p._inject_packed_impl(
                t, i, now, ways
            ),
            to_wide=_p.unpack_table,
            from_wide=_p.pack_table,
        )
    if layout == "fused":
        from gubernator_tpu.ops import fused as _f

        raw = RawKernels(
            layout="fused",
            create=_f.FusedTable.create,
            decide=lambda t, b, now, ways: _f._decide_fused_impl(
                t, b, now, ways=ways
            ),
            inject=lambda t, i, now, ways: _f._inject_fused_impl(
                t, i, now, ways
            ),
            to_wide=_f.unpack_table,
            from_wide=_f.pack_table,
        )
    elif layout == "narrow":
        from gubernator_tpu.ops import narrow as _n

        raw = RawKernels(
            layout="narrow",
            create=_n.NarrowTable.create,
            decide=lambda t, b, now, ways: _n._decide_narrow_impl(
                t, b, now, ways=ways
            ),
            inject=lambda t, i, now, ways: _n._inject_narrow_impl(
                t, i, now, ways
            ),
            to_wide=_n.unpack_table,
            from_wide=_n.pack_table,
        )
    else:
        raise ValueError(f"unknown table layout: {layout!r}")
    if kernel_backend() == "pallas":
        # The mesh tier composes RawKernels.decide inside shard_map
        # (parallel/mesh.py local_decide), so routing the raw decide here
        # is what makes IciMeshTopology dispatch the Pallas program PER
        # SHARD: each shard's slice traces its own pallas_call.
        from gubernator_tpu.ops import pallas_decide as _pd

        raw = raw._replace(
            decide=lambda t, b, now, ways: _pd.raw_decide_flat(
                t, b, now, layout=layout, ways=ways
            )
        )
    return raw
