"""Host-side encoding of validated requests into device batch operands.

Resolves everything the kernel must not do itself: string hashing, group
addressing, Gregorian calendar math (SURVEY.md §7 hard part (e)), leaky
burst defaulting, and domain clamping for the int64-exact leak math.

The caller (assembler) guarantees all active lanes in one batch have
distinct groups; this module just encodes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from gubernator_tpu.api.keys import group_of, key_hash128
from gubernator_tpu.api.types import Algorithm, Behavior, RateLimitReq
from gubernator_tpu.models.bucket import MAX_COUNT, MAX_DURATION_MS
from gubernator_tpu.ops.layout import RequestBatch
from gubernator_tpu.utils import gregorian as greg


class EncodeError(ValueError):
    """Per-request encoding failure (e.g. invalid Gregorian interval)."""


def encode_one(
    batch: RequestBatch,
    lane: int,
    r: RateLimitReq,
    now_ms: int,
    num_groups: int,
    key: Optional[tuple] = None,
) -> None:
    """Encode one request into `lane` of a host-side RequestBatch.

    `key` optionally carries a precomputed (key_hi, key_lo) pair.
    Raises EncodeError for invalid Gregorian durations; the caller turns
    that into a per-item error response (the reference propagates the
    error from GregorianExpiration the same way, algorithms.go:128-131).
    """
    hi, lo = key if key is not None else key_hash128(r.hash_key())
    is_greg = bool(r.behavior & Behavior.DURATION_IS_GREGORIAN)

    duration = min(max(int(r.duration), 0), MAX_DURATION_MS) if not is_greg else int(r.duration)
    if is_greg:
        # Host resolves the calendar; kernel sees only epoch-ms operands.
        try:
            rate_num = greg.gregorian_duration(now_ms, r.duration)
            greg_expire = greg.gregorian_expiration(now_ms, r.duration)
        except greg.GregorianError as e:
            raise EncodeError(str(e)) from e
        eff_duration = greg_expire - now_ms
    else:
        rate_num = duration
        greg_expire = 0
        eff_duration = duration

    limit = min(max(int(r.limit), -MAX_COUNT), MAX_COUNT)
    hits = min(max(int(r.hits), -MAX_COUNT), MAX_COUNT)
    burst = min(max(int(r.burst), 0), MAX_COUNT)
    if r.algorithm == Algorithm.LEAKY_BUCKET and burst == 0:
        burst = limit  # reference algorithms.go:264-266

    batch.key_hi[lane] = hi
    batch.key_lo[lane] = lo
    batch.group[lane] = group_of(lo, num_groups)
    batch.algo[lane] = int(r.algorithm)
    batch.behavior[lane] = int(r.behavior)
    batch.hits[lane] = hits
    batch.limit[lane] = limit
    batch.duration[lane] = duration
    batch.rate_num[lane] = rate_num
    batch.eff_duration[lane] = eff_duration
    batch.greg_expire[lane] = greg_expire
    batch.burst[lane] = burst
    batch.created_at[lane] = (
        int(r.created_at) if r.created_at is not None else int(now_ms)
    )
    batch.active[lane] = True


def encode_batch(
    reqs: Sequence[RateLimitReq], now_ms: int, num_groups: int, batch_size: int
) -> RequestBatch:
    """Encode up to batch_size requests (caller ensures distinct groups)."""
    assert len(reqs) <= batch_size
    b = RequestBatch.zeros(batch_size)
    for i, r in enumerate(reqs):
        encode_one(b, i, r, now_ms, num_groups)
    return b


_GREG = int(Behavior.DURATION_IS_GREGORIAN)
_LEAKY = int(Algorithm.LEAKY_BUCKET)


def encode_rows(
    wb: RequestBatch,
    lanes,
    rows,  # list of (req, hi, lo, grp)
    now_ms: int,
) -> None:
    """Vectorized twin of encode_one for a whole wave: one attribute pass
    into Python lists, then column-wise numpy assignment. Semantics are
    identical (equivalence fuzz-tested in tests/test_encode_rows.py);
    Gregorian items raise EncodeError before any column is written, so
    the caller can drop them from the wave first (encode_one remains the
    per-item path for flagged requests)."""
    n = len(rows)
    hits = [0] * n
    limit = [0] * n
    duration = [0] * n
    burst = [0] * n
    algo = [0] * n
    behavior = [0] * n
    created = [0] * n
    key_hi = [0] * n
    key_lo = [0] * n
    group = [0] * n

    for j, (r, hi, lo, grp) in enumerate(rows):
        if r.behavior & _GREG:
            raise EncodeError("encode_rows cannot take Gregorian items")
        hits[j] = r.hits
        limit[j] = r.limit
        duration[j] = r.duration
        burst[j] = r.burst
        algo[j] = int(r.algorithm)
        behavior[j] = int(r.behavior)
        created[j] = int(r.created_at) if r.created_at is not None else now_ms
        key_hi[j] = hi
        key_lo[j] = lo
        group[j] = grp

    def clamped(vals, lo_b, hi_b):
        # Vectorized clamp (the per-item min/max pairs dominated this
        # function's profile). Values beyond int64 make the conversion
        # raise and would poison the whole flush — clamp those on
        # Python ints, but only on that rare path.
        try:
            a = np.array(vals, dtype=np.int64)
        except OverflowError:
            a = np.array(
                [min(max(int(v), lo_b), hi_b) for v in vals],
                dtype=np.int64,
            )
        return np.clip(a, lo_b, hi_b)

    hits = clamped(hits, -MAX_COUNT, MAX_COUNT)
    limit = clamped(limit, -MAX_COUNT, MAX_COUNT)
    burst = clamped(burst, 0, MAX_COUNT)
    # leaky items with burst 0 default to their limit (encode_one parity)
    is_leaky = np.array(algo, dtype=np.int8) == _LEAKY
    burst = np.where(is_leaky & (burst == 0), limit, burst)

    lanes = np.asarray(lanes, dtype=np.int64)
    dur = clamped(duration, 0, MAX_DURATION_MS)
    wb.key_hi[lanes] = key_hi
    wb.key_lo[lanes] = key_lo
    wb.group[lanes] = np.array(group, dtype=np.int32)
    wb.algo[lanes] = np.array(algo, dtype=np.int8)
    wb.behavior[lanes] = np.array(behavior, dtype=np.int32)
    wb.hits[lanes] = hits
    wb.limit[lanes] = limit
    wb.duration[lanes] = dur
    wb.rate_num[lanes] = dur
    wb.eff_duration[lanes] = dur
    wb.greg_expire[lanes] = 0
    wb.burst[lanes] = burst
    wb.created_at[lanes] = created
    wb.active[lanes] = True
