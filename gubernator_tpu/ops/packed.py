"""Packed slot-table layout: fewer, narrower columns on the probe path.

The wide layout (ops/layout.py) probes 6 int64-ish columns per way
(key_hi, key_lo, used, expire_at, invalid_at, lru — ~41 bytes x W ways
per lane); at 16M slots the gathers are memory-bound and dominate the
kernel (the round-2 10M-key collapse). This layout cuts the probe to 3
gathers x 24 bytes per way:

- `key_lo` (int64): the 64-bit probe identity. The full 128-bit compare
  is completed by verifying `key_hi` at the matched way only (one
  per-lane gather). Distinct keys therefore NEVER merge counters; the
  residual risk is two live keys in one group sharing all 64 key_lo
  bits (expected colliding pairs at 10M keys: ~3e-6), which degrades to
  re-insertion (a fresh bucket), the same failure class as LRU eviction.
- `meta` (int64): lru_stamp_ms << 4 | status << 2 | algo << 1 | used.
  One gather yields the used bit and the LRU ordering; algo/status ride
  free for the state phase.
- `expire_at` (int64): full epoch-ms expiry — no epoch-rebase machinery,
  no precision loss for Gregorian-year windows.
- `invalid_at` (the store's re-fetch hint, reference cache.go:35-40) is
  always consulted and maintained, exactly like the wide and fused
  kernels — a snapshot taken on a store-attached daemon must decide
  identically on every layout.

Cold (per-lane, not per-way) columns: limit/burst narrow to int32 (the
2^31-1 count clamp is already the documented encode contract,
models/bucket.py MAX_COUNT); remaining stays int64 (leaky Q44.20 needs
51 bits, and the reference lets negative hits push token remaining past
the limit, algorithms.go:196); duration/stamp stay int64 (Gregorian-year
durations exceed int32 ms).

Per-slot bytes: 64 (vs 83 wide). Probe bytes per way: 24 (vs 41).

Branch semantics are IDENTICAL to the wide kernel: this module reuses
_token_paths/_leaky_paths from ops/decide.py verbatim and is fuzz-pinned
against the same oracle (tests/test_kernel_fuzz.py runs both layouts).
Bucket field contract: reference store.go:29-43.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.api.types import Algorithm, Behavior, Status
from gubernator_tpu.ops.decide import _leaky_paths, _token_paths
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch, SlotTable

I64 = jnp.int64

META_USED = 1
META_ALGO_SHIFT = 1
META_STATUS_SHIFT = 2
META_LRU_SHIFT = 4


class PackedTable(NamedTuple):
    """Packed struct-of-arrays counter table; a JAX pytree."""

    key_hi: jnp.ndarray  # (N,) int64
    key_lo: jnp.ndarray  # (N,) int64
    meta: jnp.ndarray  # (N,) int64: lru<<4 | status<<2 | algo<<1 | used
    expire_at: jnp.ndarray  # (N,) int64 epoch ms
    limit: jnp.ndarray  # (N,) int32
    duration: jnp.ndarray  # (N,) int64
    remaining: jnp.ndarray  # (N,) int64 (token: tokens; leaky: Q44.20)
    stamp: jnp.ndarray  # (N,) int64
    burst: jnp.ndarray  # (N,) int32
    invalid_at: jnp.ndarray  # (N,) int64, 0 = unset (store hint)

    @property
    def num_slots(self) -> int:
        return self.key_hi.shape[0]

    # Wide-compatible views (host introspection: live_count, key pruning)
    @property
    def used(self) -> jnp.ndarray:
        return (self.meta & META_USED) != 0

    @property
    def algo(self) -> jnp.ndarray:
        return ((self.meta >> META_ALGO_SHIFT) & 1).astype(jnp.int8)

    @property
    def status(self) -> jnp.ndarray:
        return ((self.meta >> META_STATUS_SHIFT) & 3).astype(jnp.int8)

    @property
    def lru(self) -> jnp.ndarray:
        return self.meta >> META_LRU_SHIFT

    @staticmethod
    def create(num_groups: int, ways: int = 8) -> "PackedTable":
        n = num_groups * ways
        i64 = lambda: jnp.zeros((n,), dtype=jnp.int64)  # noqa: E731
        i32 = lambda: jnp.zeros((n,), dtype=jnp.int32)  # noqa: E731
        return PackedTable(
            key_hi=i64(), key_lo=i64(), meta=i64(), expire_at=i64(),
            limit=i32(), duration=i64(), remaining=i64(), stamp=i64(),
            burst=i32(), invalid_at=i64(),
        )


def _pack_meta(used, algo, status, lru):
    return (
        (lru.astype(I64) << META_LRU_SHIFT)
        | (status.astype(I64) & 3) << META_STATUS_SHIFT
        | (algo.astype(I64) & 1) << META_ALGO_SHIFT
        | used.astype(I64)
    )


@jax.jit
def pack_table(wide: SlotTable) -> PackedTable:
    """Wide -> packed conversion (snapshot interop; counts clamp to the
    int32 contract MAX_COUNT already enforced at encode time)."""
    return PackedTable(
        key_hi=wide.key_hi,
        key_lo=wide.key_lo,
        meta=_pack_meta(wide.used, wide.algo, wide.status, wide.lru),
        expire_at=wide.expire_at,
        limit=wide.limit.astype(jnp.int32),
        duration=wide.duration,
        remaining=wide.remaining,
        stamp=wide.stamp,
        burst=wide.burst.astype(jnp.int32),
        invalid_at=wide.invalid_at,
    )


@jax.jit
def unpack_table(packed: PackedTable) -> SlotTable:
    """Packed -> wide conversion (canonical Loader snapshot format)."""
    return SlotTable(
        key_hi=packed.key_hi,
        key_lo=packed.key_lo,
        used=packed.used,
        algo=packed.algo,
        status=packed.status,
        limit=packed.limit.astype(I64),
        duration=packed.duration,
        remaining=packed.remaining,
        stamp=packed.stamp,
        expire_at=packed.expire_at,
        invalid_at=packed.invalid_at,
        burst=packed.burst.astype(I64),
        lru=packed.lru,
    )


def _choose_slot_packed(table: PackedTable, batch: RequestBatch, now, ways: int):
    """4-gather probe: key_lo + meta + expire_at + invalid_at per way;
    key_hi verified at the chosen way only. Same insertion priority as the
    wide kernel: matched-expired > empty > expired > LRU."""
    grp_base = batch.group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]  # (B, W)

    w_key_lo = table.key_lo[way_ix]
    w_meta = table.meta[way_ix]
    w_expire = table.expire_at[way_ix]
    w_used = (w_meta & META_USED) != 0
    w_lru = w_meta >> META_LRU_SHIFT

    w_invalid = table.invalid_at[way_ix]
    w_expired = w_used & (
        (w_expire < now) | ((w_invalid != 0) & (w_invalid < now))
    )

    lo_match = w_used & (w_key_lo == batch.key_lo[:, None])
    live_lo = lo_match & ~w_expired
    lo_exists = jnp.any(live_lo, axis=1)
    matched_way = jnp.argmax(live_lo, axis=1)

    cat = jnp.where(
        lo_match & w_expired,
        0,
        jnp.where(~w_used, 1, jnp.where(w_expired, 2, 3)),
    ).astype(I64)
    tie = jnp.where(
        cat == 3, jnp.clip(w_lru, 0, (1 << 44) - 1), way_ix - grp_base[:, None]
    )
    score = (cat << 44) + tie
    insert_way = jnp.argmin(score, axis=1)

    # Complete the 128-bit identity check on the matched way only.
    hi_at_match = table.key_hi[grp_base + matched_way]
    exists = lo_exists & (hi_at_match == batch.key_hi)

    way = jnp.where(exists, matched_way, insert_way)
    slot = grp_base + way
    pick = jax.vmap(lambda r, w: r[w])
    sel = pick(cat, insert_way)
    evicts_live = (~exists) & (sel == 3) & batch.active

    # Displaced occupant's key: hi needs one more per-lane gather (only
    # the insert way's occupant can be displaced).
    old_hi = jnp.where(exists, hi_at_match, table.key_hi[grp_base + insert_way])
    old_lo = pick(w_key_lo, way)
    old_used = pick(w_used, way)
    displaced = (
        batch.active
        & ~exists
        & old_used
        & ((old_hi != batch.key_hi) | (old_lo != batch.key_lo))
    )
    evicted_hi = jnp.where(displaced, old_hi, 0)
    evicted_lo = jnp.where(displaced, old_lo, 0)
    w_state = dict(meta=pick(w_meta, way), expire=pick(w_expire, way))
    return slot, exists, evicts_live, evicted_hi, evicted_lo, w_state


def _decide_packed_impl(table: PackedTable, batch: RequestBatch, now, *, ways: int):
    now = jnp.asarray(now, dtype=I64)
    slot, exists, evicts_live, evicted_hi, evicted_lo, w_state = (
        _choose_slot_packed(table, batch, now, ways)
    )

    # State phase: per-lane gathers of the cold columns; algo/status come
    # from the already-gathered meta word.
    meta_sel = w_state["meta"]
    st = dict(
        algo=((meta_sel >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta_sel >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=table.limit[slot].astype(I64),
        duration=table.duration[slot],
        remaining=table.remaining[slot],
        stamp=table.stamp[slot],
        expire_at=w_state["expire"],
        burst=table.burst[slot].astype(I64),
        invalid_at=table.invalid_at[slot],
    )
    for k in st:
        st[k] = jnp.where(exists, st[k], jnp.zeros_like(st[k]))

    bhv = batch.behavior
    b_greg = (bhv & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    b_reset = (bhv & int(Behavior.RESET_REMAINING)) != 0
    b_drain = (bhv & int(Behavior.DRAIN_OVER_LIMIT)) != 0

    tok_state, tok_resp = _token_paths(batch, st, b_greg, b_reset, b_drain, exists, now)
    lky_state, lky_resp = _leaky_paths(batch, st, b_greg, b_reset, b_drain, exists, now)

    is_leaky = batch.algo == jnp.int8(Algorithm.LEAKY_BUCKET)

    def pick(t, l):
        return jnp.where(is_leaky, l, t)

    new_state = {k: pick(tok_state[k], lky_state[k]) for k in tok_state}
    resp = {k: pick(tok_resp[k], lky_resp[k]) for k in tok_resp}

    n = table.num_slots
    idx = jnp.where(batch.active, slot, n)
    freed = ~new_state["used"]

    def upd(arr, val):
        return arr.at[idx].set(val, mode="drop")

    meta_new = jnp.where(
        freed,
        0,
        _pack_meta(
            jnp.ones_like(freed),
            batch.algo,
            new_state["status"],
            jnp.broadcast_to(now, idx.shape),
        ),
    )
    kwargs = dict(
        key_hi=upd(table.key_hi, jnp.where(freed, 0, batch.key_hi)),
        key_lo=upd(table.key_lo, jnp.where(freed, 0, batch.key_lo)),
        meta=upd(table.meta, meta_new),
        expire_at=upd(table.expire_at, new_state["expire_at"]),
        limit=upd(table.limit, new_state["limit"].astype(jnp.int32)),
        duration=upd(table.duration, new_state["duration"]),
        remaining=upd(table.remaining, new_state["remaining"]),
        stamp=upd(table.stamp, new_state["stamp"]),
        burst=upd(table.burst, new_state["burst"].astype(jnp.int32)),
    )
    kwargs["invalid_at"] = upd(
        table.invalid_at,
        jnp.where(
            exists & ~freed, st["invalid_at"], jnp.zeros_like(batch.key_hi)
        ),
    )
    new_table = PackedTable(**kwargs)

    act = batch.active
    out = DecideOutput(
        status=jnp.where(act, resp["status"], jnp.int8(0)),
        limit=jnp.where(act, batch.limit, 0),
        remaining=jnp.where(act, resp["remaining"], 0),
        reset_time=jnp.where(act, resp["reset_time"], 0),
        slot=idx,
        evicted_hi=evicted_hi,
        evicted_lo=evicted_lo,
        freed=act & freed,
        hits=jnp.sum(act & exists),
        misses=jnp.sum(act & ~exists),
        unexpired_evictions=jnp.sum(evicts_live),
        over_limit=jnp.sum(act & resp["over"]),
    )
    return new_table, out


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_packed(table: PackedTable, batch: RequestBatch, now, ways: int = 8):
    """Jitted packed-layout decide step with donated table buffers."""
    return _decide_packed_impl(table, batch, now, ways=ways)


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_scan_packed(table: PackedTable, batches: RequestBatch, nows, ways: int = 8):
    """Scan twin of ops.decide.decide_scan for the packed layout."""

    def step(tbl, xs):
        b, now = xs
        tbl, out = _decide_packed_impl(tbl, b, now, ways=ways)
        return tbl, out

    return jax.lax.scan(step, table, (batches, nows))


@functools.partial(jax.jit, static_argnames=("ways",))
def probe_exists_packed(table: PackedTable, key_hi, key_lo, group, now, ways: int = 8):
    """Residency probe (store read-through seam), packed layout. Always
    consults invalid_at — this path only runs with a store attached."""
    now = jnp.asarray(now, dtype=I64)
    grp_base = group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]
    w_meta = table.meta[way_ix]
    w_used = (w_meta & META_USED) != 0
    w_invalid = table.invalid_at[way_ix]
    w_expired = w_used & (
        (table.expire_at[way_ix] < now) | ((w_invalid != 0) & (w_invalid < now))
    )
    live = (
        w_used
        & ~w_expired
        & (table.key_lo[way_ix] == key_lo[:, None])
        & (table.key_hi[way_ix] == key_hi[:, None])
    )
    return jnp.any(live, axis=1)


@jax.jit
def gather_rows_packed(table: PackedTable, slots) -> SlotTable:
    """Post-decide row readback, expanded to the wide row struct so the
    engine's store write-behind code is layout-agnostic."""
    n = table.num_slots
    safe = jnp.clip(slots, 0, n - 1)
    valid = slots < n

    def g(arr):
        v = arr[safe]
        return jnp.where(valid, v, jnp.zeros_like(v))

    meta = g(table.meta)
    return SlotTable(
        key_hi=g(table.key_hi),
        key_lo=g(table.key_lo),
        used=(meta & META_USED) != 0,
        algo=((meta >> META_ALGO_SHIFT) & 1).astype(jnp.int8),
        status=((meta >> META_STATUS_SHIFT) & 3).astype(jnp.int8),
        limit=g(table.limit).astype(I64),
        duration=g(table.duration),
        remaining=g(table.remaining),
        stamp=g(table.stamp),
        expire_at=g(table.expire_at),
        invalid_at=g(table.invalid_at),
        burst=g(table.burst).astype(I64),
        lru=meta >> META_LRU_SHIFT,
    )


def _inject_packed_impl(table: PackedTable, items, now, ways: int):
    """Packed twin of ops.inject._inject_impl: overwrite rows with
    authoritative state (Loader restore, Store read-through, GLOBAL
    UpdatePeerGlobals landing)."""
    now = jnp.asarray(now, dtype=I64)
    # Reuse the packed probe to find each item's slot (match or insert).
    batch_like = RequestBatch.zeros(items.key_hi.shape[0])._replace(
        key_hi=items.key_hi,
        key_lo=items.key_lo,
        group=items.group,
        active=items.active,
    )
    slot, exists, _ev, evicted_hi, evicted_lo, _w = _choose_slot_packed(
        table, batch_like, now, ways
    )
    n = table.num_slots
    idx = jnp.where(items.active, slot, n)

    def upd(arr, val):
        return arr.at[idx].set(val, mode="drop")

    new_table = PackedTable(
        key_hi=upd(table.key_hi, items.key_hi),
        key_lo=upd(table.key_lo, items.key_lo),
        meta=upd(
            table.meta,
            _pack_meta(
                jnp.ones_like(items.active),
                items.algo,
                items.status,
                jnp.broadcast_to(now, idx.shape),
            ),
        ),
        expire_at=upd(table.expire_at, items.expire_at),
        limit=upd(table.limit, items.limit.astype(jnp.int32)),
        duration=upd(table.duration, items.duration),
        remaining=upd(table.remaining, items.remaining),
        stamp=upd(table.stamp, items.stamp),
        burst=upd(table.burst, items.burst.astype(jnp.int32)),
        invalid_at=upd(table.invalid_at, items.invalid_at),
    )
    # evicted_hi/lo are already masked to displaced lanes by the probe —
    # same contract as ops.inject.inject.
    return new_table, evicted_hi, evicted_lo


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def inject_packed(table: PackedTable, items, now, ways: int = 8):
    return _inject_packed_impl(table, items, now, ways)
