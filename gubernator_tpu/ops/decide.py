"""The decide kernel: one vectorized step replacing the reference hot loop.

decide(table, batch, now) -> (table', DecideOutput)

This single jitted function subsumes the reference's entire L3 execution
engine — WorkerPool dispatch (reference workers.go:261-324), LRU cache
get/add/evict (reference lrucache.go:88-161), and every branch of
tokenBucket/leakyBucket (reference algorithms.go:37-493) — as masked int64
vector ops over a W-way set-associative HBM slot table. The table buffers
are donated, so the update is in-place on device.

Branch semantics are bit-for-bit identical to models/oracle.py (the spec),
which is fuzz-verified in tests/test_kernel_fuzz.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from gubernator_tpu.api.types import Algorithm, Behavior, Status
from gubernator_tpu.models.bucket import FIXED_SHIFT, MAX_ELAPSED_MS
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch, SlotTable

I64 = jnp.int64


def _leak_fixed(elapsed, limit, rate_num, burst):
    """Vectorized twin of models.bucket.leak_fixed (same int64 ops)."""
    limit_g = jnp.maximum(limit, 1)
    rn = jnp.maximum(rate_num, 1)
    cap_t = burst + 1
    e_c = jnp.clip(elapsed, 0, MAX_ELAPSED_MS)
    a = e_c // rn
    e = e_c % rn
    a_lim = cap_t // limit_g + 1
    a_c = jnp.minimum(a, a_lim)
    whole = a_c * limit
    saturated = (a > a_lim) | (whole >= cap_t)
    hi = limit >> 16
    lo = limit & 0xFFFF
    p1 = e * hi
    q1 = p1 // rn
    r1 = p1 % rn
    q2 = (r1 << 16) // rn
    r2 = (r1 << 16) % rn
    p2 = e * lo
    q3 = (r2 + p2) // rn
    r3 = (r2 + p2) % rn
    tok = (q1 << 16) + q2 + q3
    frac_s = (r3 << FIXED_SHIFT) // rn
    cap_s = cap_t << FIXED_SHIFT
    leak = jnp.minimum(((whole + tok) << FIXED_SHIFT) + frac_s, cap_s)
    leak = jnp.where(saturated, cap_s, leak)
    return jnp.where(elapsed <= 0, jnp.zeros_like(leak), leak)


def _choose_slot(table: SlotTable, batch: RequestBatch, now, ways: int):
    """Probe each request's W-way group: find the live matching way, or the
    way to insert into (matched-expired > empty > expired > LRU)."""
    grp_base = batch.group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]  # (B, W)

    w_key_hi = table.key_hi[way_ix]
    w_key_lo = table.key_lo[way_ix]
    w_used = table.used[way_ix]
    w_expire = table.expire_at[way_ix]
    w_invalid = table.invalid_at[way_ix]
    w_lru = table.lru[way_ix]

    # Lazy expiry on read (reference cache.go:43-57, lrucache.go:115-118)
    w_expired = w_used & (
        (w_expire < now) | ((w_invalid != 0) & (w_invalid < now))
    )
    w_match = (
        w_used
        & (w_key_hi == batch.key_hi[:, None])
        & (w_key_lo == batch.key_lo[:, None])
    )

    live_match = w_match & ~w_expired
    exists = jnp.any(live_match, axis=1)
    matched_way = jnp.argmax(live_match, axis=1)

    # Insertion priority: matched-but-expired way (must reuse to avoid
    # duplicate keys) > empty > any expired > least-recently-used.
    cat = jnp.where(
        w_match & w_expired,
        0,
        jnp.where(~w_used, 1, jnp.where(w_expired, 2, 3)),
    ).astype(I64)
    # Composite score: category dominates; among live ways, oldest lru wins;
    # otherwise lowest way index (deterministic).
    tie = jnp.where(cat == 3, jnp.clip(w_lru, 0, (1 << 44) - 1), way_ix - grp_base[:, None])
    score = (cat << 44) + tie
    insert_way = jnp.argmin(score, axis=1)

    way = jnp.where(exists, matched_way, insert_way)
    slot = grp_base + way
    pick = jax.vmap(lambda r, w: r[w])  # row-wise way selection
    # Eviction metric: inserting over a live (used, unexpired) slot
    sel = pick(cat, insert_way)
    evicts_live = (~exists) & (sel == 3) & batch.active
    # Displaced occupant key, recovered from the ALREADY-GATHERED way
    # arrays (re-gathering from the table costs ~1.7x kernel throughput
    # on CPU): the chosen way's current occupant, when it holds a
    # DIFFERENT live key than the request.
    old_hi = pick(w_key_hi, way)
    old_lo = pick(w_key_lo, way)
    old_used = pick(w_used, way)
    displaced = (
        batch.active
        & ~exists
        & old_used
        & ((old_hi != batch.key_hi) | (old_lo != batch.key_lo))
    )
    evicted_hi = jnp.where(displaced, old_hi, 0)
    evicted_lo = jnp.where(displaced, old_lo, 0)
    return slot, exists, evicts_live, evicted_hi, evicted_lo


def _token_paths(batch: RequestBatch, st, b_greg, b_reset, b_drain, exists_any, now):
    """All token-bucket branches (reference algorithms.go:37-257) as masks.

    Returns (state_update, resp) where state fields are full-lane values to
    scatter for lanes whose algo==TOKEN_BUCKET.
    """
    r_hits, r_limit = batch.hits, batch.limit
    created = batch.created_at

    # --- existing-item path (state algo == TOKEN and live) ---
    # limit hot-change (algorithms.go:105-113)
    limit_changed = st["limit"] != r_limit
    rem0 = jnp.where(
        limit_changed,
        jnp.maximum(st["remaining"] + (r_limit - st["limit"]), 0),
        st["remaining"],
    )
    # duration hot-change, possibly renewing (algorithms.go:122-147)
    dur_changed = st["duration"] != batch.duration
    expire1 = jnp.where(b_greg, batch.greg_expire, st["stamp"] + batch.duration)
    renew = dur_changed & (expire1 <= created)
    expire2 = jnp.where(renew, created + batch.duration, expire1)
    stamp1 = jnp.where(renew, created, st["stamp"])
    rem1 = jnp.where(renew, r_limit, rem0)
    new_expire = jnp.where(dur_changed, expire2, st["expire_at"])
    rl_reset = jnp.where(dur_changed, expire2, st["expire_at"])

    # branch masks in reference order (hits==0 -> at-limit -> exact -> over)
    m_hits0 = r_hits == 0
    m_atlim = ~m_hits0 & (rem0 == 0) & (r_hits > 0)  # STALE pre-renewal rem
    m_exact = ~m_hits0 & ~m_atlim & (rem1 == r_hits)
    m_over = ~m_hits0 & ~m_atlim & ~m_exact & (r_hits > rem1)
    m_cons = ~m_hits0 & ~m_atlim & ~m_exact & ~m_over

    rem_state = jnp.where(
        m_exact,
        0,
        jnp.where(
            m_over,
            jnp.where(b_drain, 0, rem1),
            jnp.where(m_cons, rem1 - r_hits, rem1),
        ),
    )
    sticky = st["status"].astype(jnp.int8)
    status_state = jnp.where(m_atlim, jnp.int8(Status.OVER_LIMIT), sticky)
    resp_status = jnp.where(
        m_atlim | m_over, jnp.int8(Status.OVER_LIMIT), sticky
    )
    resp_rem = jnp.where(
        m_exact,
        0,
        jnp.where(
            m_over,
            jnp.where(b_drain, 0, rem0),
            jnp.where(m_cons, rem1 - r_hits, rem0),
        ),
    )

    # --- new-item path (algorithms.go:206-257) ---
    expire_new = jnp.where(b_greg, batch.greg_expire, created + batch.duration)
    over_new = r_hits > r_limit
    rem_new = jnp.where(over_new, r_limit, r_limit - r_hits)
    resp_status_new = jnp.where(
        over_new, jnp.int8(Status.OVER_LIMIT), jnp.int8(Status.UNDER_LIMIT)
    )

    # --- RESET_REMAINING on an existing item (algorithms.go:78-90): free
    # the slot, fixed response. Applies whatever the stored algorithm is.
    m_reset = exists_any & b_reset

    fresh = ~exists_any | (st["algo"] != jnp.int8(Algorithm.TOKEN_BUCKET))
    use_new = ~m_reset & fresh

    state = dict(
        used=~m_reset,
        limit=r_limit,
        duration=batch.duration,
        remaining=jnp.where(use_new, rem_new, rem_state),
        stamp=jnp.where(use_new, created, stamp1),
        expire_at=jnp.where(use_new, expire_new, new_expire),
        status=jnp.where(
            use_new, jnp.int8(Status.UNDER_LIMIT), status_state
        ),
        burst=jnp.zeros_like(r_limit),
    )
    resp = dict(
        status=jnp.where(
            m_reset,
            jnp.int8(Status.UNDER_LIMIT),
            jnp.where(use_new, resp_status_new, resp_status),
        ),
        remaining=jnp.where(
            m_reset,
            r_limit,
            jnp.where(
                use_new, jnp.where(over_new, r_limit, r_limit - r_hits), resp_rem
            ),
        ),
        reset_time=jnp.where(
            m_reset, 0, jnp.where(use_new, expire_new, rl_reset)
        ),
        over=~m_reset & jnp.where(use_new, over_new, m_atlim | m_over),
    )
    return state, resp


def _leaky_paths(batch: RequestBatch, st, b_greg, b_reset, b_drain, exists_any, now):
    """All leaky-bucket branches (reference algorithms.go:260-493)."""
    r_hits, r_limit, r_burst = batch.hits, batch.limit, batch.burst
    created = batch.created_at
    S = FIXED_SHIFT

    # --- existing-item path ---
    rem_s0 = jnp.where(b_reset, r_burst << S, st["remaining"])
    burst_changed = st["burst"] != r_burst
    rem_s1 = jnp.where(
        burst_changed & (r_burst > (rem_s0 >> S)), r_burst << S, rem_s0
    )
    # expiry refresh when hits != 0 (algorithms.go:356-358)
    expire_upd = jnp.where(
        r_hits != 0, created + batch.eff_duration, st["expire_at"]
    )
    # leak accrual (algorithms.go:360-367); burst already updated to r_burst
    elapsed = created - st["stamp"]
    leak_s = _leak_fixed(elapsed, r_limit, batch.rate_num, r_burst)
    leaked = (leak_s >> S) > 0
    rem_s2 = jnp.where(leaked, rem_s1 + leak_s, rem_s1)
    stamp1 = jnp.where(leaked, created, st["stamp"])
    # unconditional burst clamp (algorithms.go:369-371)
    rem_s3 = jnp.where((rem_s2 >> S) > r_burst, r_burst << S, rem_s2)

    ri = batch.rate_num // jnp.maximum(r_limit, 1)
    rem_int = rem_s3 >> S

    # branch masks in reference order (at-limit -> exact -> over -> hits==0)
    m_atlim = (rem_int == 0) & (r_hits > 0)
    m_exact = ~m_atlim & (rem_int == r_hits)
    m_over = ~m_atlim & ~m_exact & (r_hits > rem_int)
    m_hits0 = ~m_atlim & ~m_exact & ~m_over & (r_hits == 0)
    m_cons = ~m_atlim & ~m_exact & ~m_over & ~m_hits0

    rem_s_final = jnp.where(
        m_exact,
        0,
        jnp.where(
            m_over,
            jnp.where(b_drain, 0, rem_s3),
            jnp.where(m_cons, rem_s3 - (r_hits << S), rem_s3),
        ),
    )
    resp_rem = jnp.where(
        m_exact,
        0,
        jnp.where(
            m_over,
            jnp.where(b_drain, 0, rem_int),
            jnp.where(m_cons, rem_s_final >> S, rem_int),
        ),
    )
    resp_status = jnp.where(
        m_atlim | m_over, jnp.int8(Status.OVER_LIMIT), jnp.int8(Status.UNDER_LIMIT)
    )
    base_reset = created + (r_limit - rem_int) * ri
    resp_reset = jnp.where(
        m_exact,
        created + r_limit * ri,
        jnp.where(m_cons, created + (r_limit - (rem_s_final >> S)) * ri, base_reset),
    )

    # --- new-item path (algorithms.go:437-493); rate from the RAW duration
    # field (pre-Gregorian-override quirk) ---
    ri_new = batch.duration // jnp.maximum(r_limit, 1)
    over_new = r_hits > r_burst
    rem_new = r_burst - r_hits
    rem_s_new = jnp.where(over_new, 0, rem_new << S)
    resp_rem_new = jnp.where(over_new, 0, rem_new)
    resp_reset_new = created + (r_limit - resp_rem_new) * ri_new
    expire_new = created + batch.eff_duration

    fresh = ~exists_any | (st["algo"] != jnp.int8(Algorithm.LEAKY_BUCKET))
    use_new = fresh

    state = dict(
        used=jnp.ones_like(fresh),
        limit=r_limit,
        # Found path stores the RAW duration (algorithms.go:333); new items
        # store the effective duration (algorithms.go:455-456).
        duration=jnp.where(use_new, batch.eff_duration, batch.duration),
        remaining=jnp.where(use_new, rem_s_new, rem_s_final),
        stamp=jnp.where(use_new, created, stamp1),
        expire_at=jnp.where(use_new, expire_new, expire_upd),
        status=jnp.zeros_like(st["status"]),  # leaky has no stored status
        burst=r_burst,
    )
    resp = dict(
        status=jnp.where(
            use_new,
            jnp.where(over_new, jnp.int8(Status.OVER_LIMIT), jnp.int8(Status.UNDER_LIMIT)),
            resp_status,
        ),
        remaining=jnp.where(use_new, resp_rem_new, resp_rem),
        reset_time=jnp.where(use_new, resp_reset_new, resp_reset),
        over=jnp.where(use_new, over_new, m_atlim | m_over),
    )
    return state, resp


def _decide_impl(table: SlotTable, batch: RequestBatch, now, *, ways: int):
    now = jnp.asarray(now, dtype=I64)
    slot, exists, evicts_live, evicted_hi, evicted_lo = _choose_slot(
        table, batch, now, ways
    )

    # Gather the chosen slot's state (garbage for fresh lanes; masked off).
    st = dict(
        algo=table.algo[slot],
        status=table.status[slot],
        limit=table.limit[slot],
        duration=table.duration[slot],
        remaining=table.remaining[slot],
        stamp=table.stamp[slot],
        expire_at=table.expire_at[slot],
        burst=table.burst[slot],
        invalid_at=table.invalid_at[slot],
    )
    # Fresh lanes must not see stale values in arithmetic that could
    # overflow; zero them out (semantically they're ignored anyway).
    for k in st:
        if k in ("algo", "status"):
            st[k] = jnp.where(exists, st[k], jnp.zeros_like(st[k]))
        else:
            st[k] = jnp.where(exists, st[k], jnp.zeros_like(st[k]))

    bhv = batch.behavior
    b_greg = (bhv & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    b_reset = (bhv & int(Behavior.RESET_REMAINING)) != 0
    b_drain = (bhv & int(Behavior.DRAIN_OVER_LIMIT)) != 0

    tok_state, tok_resp = _token_paths(batch, st, b_greg, b_reset, b_drain, exists, now)
    lky_state, lky_resp = _leaky_paths(batch, st, b_greg, b_reset, b_drain, exists, now)

    is_leaky = batch.algo == jnp.int8(Algorithm.LEAKY_BUCKET)

    def pick(t, l):
        return jnp.where(is_leaky, l, t)

    new_state = {k: pick(tok_state[k], lky_state[k]) for k in tok_state}
    resp = {k: pick(tok_resp[k], lky_resp[k]) for k in tok_resp}

    # Scatter back. Inactive (padding) lanes target index N -> dropped.
    n = table.num_slots
    idx = jnp.where(batch.active, slot, n)
    freed = ~new_state["used"]  # token RESET_REMAINING frees the slot

    def upd(arr, val):
        return arr.at[idx].set(val, mode="drop")

    new_table = SlotTable(
        key_hi=upd(table.key_hi, jnp.where(freed, 0, batch.key_hi)),
        key_lo=upd(table.key_lo, jnp.where(freed, 0, batch.key_lo)),
        used=upd(table.used, new_state["used"]),
        algo=upd(table.algo, batch.algo),
        status=upd(table.status, new_state["status"]),
        limit=upd(table.limit, new_state["limit"]),
        duration=upd(table.duration, new_state["duration"]),
        remaining=upd(table.remaining, new_state["remaining"]),
        stamp=upd(table.stamp, new_state["stamp"]),
        expire_at=upd(table.expire_at, new_state["expire_at"]),
        # The store's invalidation mark survives updates on a live entry
        # (reference: algorithms never touch CacheItem.InvalidAt); fresh
        # inserts and freed slots clear it.
        invalid_at=upd(
            table.invalid_at,
            jnp.where(exists & ~freed, st["invalid_at"], jnp.zeros_like(batch.key_hi)),
        ),
        burst=upd(table.burst, new_state["burst"]),
        lru=upd(table.lru, jnp.broadcast_to(now, idx.shape)),
    )

    act = batch.active
    out = DecideOutput(
        status=jnp.where(act, resp["status"], jnp.int8(0)),
        limit=jnp.where(act, batch.limit, 0),
        remaining=jnp.where(act, resp["remaining"], 0),
        reset_time=jnp.where(act, resp["reset_time"], 0),
        slot=idx,
        evicted_hi=evicted_hi,
        evicted_lo=evicted_lo,
        freed=act & freed,
        hits=jnp.sum(act & exists),
        misses=jnp.sum(act & ~exists),
        unexpired_evictions=jnp.sum(evicts_live),
        over_limit=jnp.sum(act & resp["over"]),
    )
    return new_table, out


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide(table: SlotTable, batch: RequestBatch, now, ways: int = 8):
    """Jitted decide step with donated table buffers (in-place on device)."""
    return _decide_impl(table, batch, now, ways=ways)


def make_decide(ways: int = 8):
    """Returns a decide fn closed over `ways` (for engines/benchmarks)."""
    return functools.partial(decide, ways=ways)


@functools.partial(jax.jit, static_argnames=("ways",))
def probe_exists(table: SlotTable, key_hi, key_lo, group, now, ways: int = 8):
    """Ground-truth residency probe: True per lane iff the key has a LIVE
    entry in its group (same lazy-expiry + invalidation semantics as the
    decide kernel's match). The engine uses this right before each wave to
    drive store read-through on actual table misses — the reference
    consults the store on every cache miss (algorithms.go:45-51), and the
    table, not host bookkeeping, is what defines a miss."""
    now = jnp.asarray(now, dtype=I64)
    grp_base = group.astype(I64) * ways
    way_ix = grp_base[:, None] + jnp.arange(ways, dtype=I64)[None, :]
    w_used = table.used[way_ix]
    w_invalid = table.invalid_at[way_ix]
    w_expired = w_used & (
        (table.expire_at[way_ix] < now) | ((w_invalid != 0) & (w_invalid < now))
    )
    live = (
        w_used
        & ~w_expired
        & (table.key_hi[way_ix] == key_hi[:, None])
        & (table.key_lo[way_ix] == key_lo[:, None])
    )
    return jnp.any(live, axis=1)


@jax.jit
def gather_rows(table: SlotTable, slots):
    """Post-decide row readback for the Store write-behind seam: returns
    each slot's full state (padding slots index N -> zeros via clip+mask)."""
    n = table.num_slots
    safe = jnp.clip(slots, 0, n - 1)
    valid = slots < n

    def g(arr):
        v = arr[safe]
        return jnp.where(valid, v, jnp.zeros_like(v))

    return SlotTable(*[g(getattr(table, f)) for f in SlotTable._fields])


@functools.partial(jax.jit, static_argnames=("ways",), donate_argnums=(0,))
def decide_scan(table: SlotTable, batches: RequestBatch, nows, ways: int = 8):
    """Run a time-sequence of batches through decide in ONE dispatch.

    `batches` fields are stacked (T, B); `nows` is (T,). Used by tests (to
    fuzz long sequences without per-step dispatch overhead) and by the
    benchmark's steady-state loop. Compiler-friendly sequential control
    flow via lax.scan — no Python loop under jit.
    """

    def step(tbl, xs):
        b, now = xs
        tbl, out = _decide_impl(tbl, b, now, ways=ways)
        return tbl, out

    return jax.lax.scan(step, table, (batches, nows))
