"""Owner-sharded decide over a device mesh.

The TPU-native replacement for peer forwarding (SURVEY.md §2.3 row 1):
instead of hashing keys to *hosts* and relaying batches over gRPC, the
slot table is sharded across the devices of a jax.sharding.Mesh — each
device owns a contiguous range of slot groups — and ONE jitted SPMD call
decides the whole batch: every device masks the batch lanes whose group
falls in its shard, runs the same decide kernel on its local table shard,
and lane results are combined with a psum over the mesh axis (each lane
is answered by exactly one owner device, so the sum is the answer).

"Forwarding" therefore costs one replicated batch broadcast plus one
(B,)-sized psum over ICI — no per-peer RPCs, no retries, no batching
timers — while ownership semantics (exactly one authoritative counter
per key) are identical to the reference's hash ring.
"""

from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.kernels import get_raw_kernels
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch, SlotTable
from gubernator_tpu.utils import transfer
from gubernator_tpu.utils.jaxcompat import shard_map

AXIS = "owners"

# The multi-device tier defaults to the fused layout like the single-chip
# engine (VERDICT r4 item 2: one hot path everywhere — wide measured 137x
# slower on TPU at 1M keys).
DEFAULT_LAYOUT = "fused"


def make_mesh(devices=None, axis: str = AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices).reshape(-1), (axis,))


def create_sharded_table(
    mesh: Mesh, num_groups: int, ways: int = 8, layout: str = DEFAULT_LAYOUT,
    metrics=None,
):
    """Layout-native table sharded along the slot axis; contiguous groups
    per device (num_groups must divide evenly by mesh size). The shard
    placement rides the accounted transfer wrapper (utils/transfer.py,
    GL010): one h2d "warmup" ledger entry for the whole table."""
    n_dev = mesh.devices.size
    assert num_groups % n_dev == 0, "num_groups must be divisible by mesh size"
    sharding = NamedSharding(mesh, P(AXIS))
    table = get_raw_kernels(layout).create(num_groups, ways)
    return transfer.put_tree(table, sharding, metrics=metrics)


def make_sharded_decide(
    mesh: Mesh, num_groups: int, ways: int = 8, layout: str = DEFAULT_LAYOUT
):
    """Builds decide(table, batch, now) -> (table', DecideOutput) where the
    table is sharded over `mesh` and the batch is replicated."""
    n_dev = mesh.devices.size
    groups_per = num_groups // n_dev
    RK = get_raw_kernels(layout)

    def local_decide(table, batch: RequestBatch, now):
        dev = jax.lax.axis_index(AXIS)
        g0 = dev.astype(jnp.int64) * groups_per
        local_grp = batch.group.astype(jnp.int64) - g0
        mine = (local_grp >= 0) & (local_grp < groups_per) & batch.active
        local_batch = batch._replace(
            group=jnp.where(mine, local_grp, 0).astype(batch.group.dtype),
            active=mine,
        )
        table, out = RK.decide(table, local_batch, now, ways)
        # Inactive lanes produce zeros, so a psum over owners yields each
        # lane's single authoritative answer; scalar metrics sum naturally.
        out = jax.tree.map(lambda x: jax.lax.psum(x, AXIS), out)
        return table, out

    sharded = shard_map(
        local_decide,
        mesh=mesh,
        in_specs=(P(AXIS), P(), P()),
        out_specs=(P(AXIS), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def decide_fn(table, batch: RequestBatch, now):
        now = jnp.asarray(now, dtype=jnp.int64)
        return sharded(table, batch, now)

    return decide_fn
