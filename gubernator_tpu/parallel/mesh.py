"""Owner-sharded decide over a device mesh.

The TPU-native replacement for peer forwarding (SURVEY.md §2.3 row 1):
instead of hashing keys to *hosts* and relaying batches over gRPC, the
slot table is sharded across the devices of a jax.sharding.Mesh — each
device owns a contiguous range of slot groups — and ONE jitted SPMD call
decides the whole batch: every device masks the batch lanes whose group
falls in its shard, runs the same decide kernel on its local table shard,
and lane results are combined with a psum over the mesh axis (each lane
is answered by exactly one owner device, so the sum is the answer).

"Forwarding" therefore costs one replicated batch broadcast plus one
(B,)-sized psum over ICI — no per-peer RPCs, no retries, no batching
timers — while ownership semantics (exactly one authoritative counter
per key) are identical to the reference's hash ring.
"""

from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.kernels import (
    BYTES_PER_SLOT,
    Kernels,
    get_kernels,
    get_raw_kernels,
)
from gubernator_tpu.ops.layout import DecideOutput, RequestBatch, SlotTable
from gubernator_tpu.utils import lockorder, transfer
from gubernator_tpu.utils.jaxcompat import shard_map

AXIS = "owners"

# Process-wide multi-device ENQUEUE guard. Two engines in one process
# (two pods in a test, serving + background demoter, a sync tick racing
# a warmup) each dispatch multi-device programs onto the SAME devices;
# nothing orders the per-device enqueues of two concurrent dispatches
# against each other, so device 0 can start program A while device 1
# starts program B — both collectives then wait on the other's
# rendezvous forever (the test_two_tier_global ~25% hang). Holding this
# lock across the *dispatch call* (not the async execution) makes the
# enqueue order identical on every device; each device then drains its
# queue in order and no cross-program rendezvous can interleave.
# Reentrant: composite operations (snapshot -> extract_page per page)
# may take it around an outer section and again around inner dispatches.
_COLLECTIVES = lockorder.make_rlock("mesh.collectives")


def collective_guard():
    """The process-wide mesh dispatch lock (see _COLLECTIVES). Engines
    acquire it INSIDE their own table lock (consistent order:
    engine.table -> mesh.collectives), or alone during init/warmup."""
    return _COLLECTIVES


def _mask_to_local(groups_per: int, batch):
    """Shared ownership discipline for every sharded kernel: deactivate
    lanes whose group falls outside this shard's contiguous range
    [dev*groups_per, (dev+1)*groups_per), rebase the rest to shard-local
    group indices. Inactive lanes produce zeros in every layout kernel
    (drop-scatter + masked outputs), so a psum over the mesh axis
    recovers each lane's single authoritative answer."""
    dev = jax.lax.axis_index(AXIS)
    g0 = dev.astype(jnp.int64) * groups_per
    local_grp = batch.group.astype(jnp.int64) - g0
    mine = (local_grp >= 0) & (local_grp < groups_per) & batch.active
    return batch._replace(
        group=jnp.where(mine, local_grp, 0).astype(batch.group.dtype),
        active=mine,
    )

# The multi-device tier defaults to the fused layout like the single-chip
# engine (VERDICT r4 item 2: one hot path everywhere — wide measured 137x
# slower on TPU at 1M keys).
DEFAULT_LAYOUT = "fused"


def make_mesh(devices=None, axis: str = AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices).reshape(-1), (axis,))


def create_sharded_table(
    mesh: Mesh, num_groups: int, ways: int = 8, layout: str = DEFAULT_LAYOUT,
    metrics=None,
):
    """Layout-native table sharded along the slot axis; contiguous groups
    per device (num_groups must divide evenly by mesh size). The shard
    placement rides the accounted transfer wrapper (utils/transfer.py,
    GL010): one h2d "warmup" ledger entry for the whole table."""
    n_dev = mesh.devices.size
    assert num_groups % n_dev == 0, "num_groups must be divisible by mesh size"
    sharding = NamedSharding(mesh, P(AXIS))
    table = get_raw_kernels(layout).create(num_groups, ways)
    return transfer.put_tree(table, sharding, metrics=metrics)


def make_sharded_decide(
    mesh: Mesh, num_groups: int, ways: int = 8, layout: str = DEFAULT_LAYOUT
):
    """Builds decide(table, batch, now) -> (table', DecideOutput) where the
    table is sharded over `mesh` and the batch is replicated."""
    n_dev = mesh.devices.size
    groups_per = num_groups // n_dev
    RK = get_raw_kernels(layout)

    def local_decide(table, batch: RequestBatch, now):
        local_batch = _mask_to_local(groups_per, batch)
        table, out = RK.decide(table, local_batch, now, ways)
        # Inactive lanes produce zeros, so a psum over owners yields each
        # lane's single authoritative answer; scalar metrics sum naturally.
        out = jax.tree.map(lambda x: jax.lax.psum(x, AXIS), out)
        return table, out

    sharded = shard_map(
        local_decide,
        mesh=mesh,
        in_specs=(P(AXIS), P(), P()),
        out_specs=(P(AXIS), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def decide_fn(table, batch: RequestBatch, now):
        now = jnp.asarray(now, dtype=jnp.int64)
        return sharded(table, batch, now)

    return decide_fn


def make_sharded_inject(
    mesh: Mesh, num_groups: int, ways: int = 8, layout: str = DEFAULT_LAYOUT
):
    """Builds inject(table, items, now) -> (table', evicted_hi, evicted_lo)
    over a sharded table: the decide ownership mask applied to the inject
    batch. Displaced-occupant key columns are psum-merged exactly like
    DecideOutput (a lane lands on exactly one owner; inactive lanes
    scatter nothing and report (0, 0))."""
    n_dev = mesh.devices.size
    groups_per = num_groups // n_dev
    RK = get_raw_kernels(layout)

    def local_inject(table, items, now):
        table, ehi, elo = RK.inject(
            table, _mask_to_local(groups_per, items), now, ways
        )
        return table, jax.lax.psum(ehi, AXIS), jax.lax.psum(elo, AXIS)

    sharded = shard_map(
        local_inject,
        mesh=mesh,
        in_specs=(P(AXIS), P(), P()),
        out_specs=(P(AXIS), P(), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def inject_fn(table, items, now):
        now = jnp.asarray(now, dtype=jnp.int64)
        return sharded(table, items, now)

    return inject_fn


def _no_scan(*_a, **_k):
    raise NotImplementedError(
        "the mesh tier serves wave-at-a-time SPMD programs; there is no "
        "decide_scan path (bench the single-chip engine for scan shapes)"
    )


def make_mesh_kernels(
    mesh: Mesh,
    layout: str,
    num_groups: int,
    ways: int = 8,
    *,
    page_groups: int = 0,
    page_budget: int = 0,
    metrics=None,
):
    """Kernels-compatible facade over a mesh-sharded table, so the engine
    core binds one kernel set and never learns the topology.

    Flat (page_groups == 0): returns an ops.kernels.Kernels whose
    decide/inject are the shard_map ownership programs above and whose
    read-side ops (probe_exists, gather_rows, to_wide, census input) are
    the plain layout jits — GSPMD partitions them over the sharded table
    automatically.

    Paged (page_groups > 0): returns an ops.paged.PagedKernels-shaped
    facade where the PHYSICAL table is sharded along the slot axis and
    the page map is replicated: translation (logical -> physical group)
    runs replicated *before* the shard_map, then the ownership mask
    applies in PHYSICAL group space with groups_per = num_phys_groups /
    n_dev. Sentinel (non-resident) lanes rebase out of every shard's
    range, go inactive everywhere, and psum to zeros — same degrade-to-
    dropped-write guarantee as the single-chip paged table. Page frames
    are placed by the MeshPager (runtime/pager.py) so each shard keeps
    its own frame pool and host-DRAM cold tier."""
    n_dev = mesh.devices.size
    if num_groups % n_dev:
        raise ValueError(
            f"num_groups {num_groups} must divide by mesh size {n_dev}"
        )
    if page_groups <= 0:
        base = get_kernels(layout)
        raw = get_raw_kernels(layout)
        decide_fn = make_sharded_decide(mesh, num_groups, ways, layout)
        inject_fn = make_sharded_inject(mesh, num_groups, ways, layout)
        sharding = NamedSharding(mesh, P(AXIS))

        def _create(*_a, **_k):
            return create_sharded_table(
                mesh, num_groups, ways, layout, metrics=metrics
            )

        def _from_wide(wide):
            return jax.device_put(raw.from_wide(wide), sharding)  # guberlint: allow-unaccounted-transfer -- restore path: the engine's snapshot/restore tx accounts the upload around this call

        return Kernels(
            layout=layout,
            create=_create,
            decide=lambda t, b, now, ways_=ways, with_store=False: decide_fn(
                t, b, now
            ),
            decide_scan=_no_scan,
            inject=lambda t, i, now, ways_=ways: inject_fn(t, i, now),
            probe_exists=base.probe_exists,
            gather_rows=base.gather_rows,
            to_wide=base.to_wide,
            from_wide=_from_wide,
            bytes_per_slot=BYTES_PER_SLOT[layout],
        )
    return _make_mesh_paged_kernels(
        mesh, layout, num_groups, ways, page_groups, page_budget, metrics
    )


def _make_mesh_paged_kernels(
    mesh: Mesh,
    layout: str,
    num_groups: int,
    ways: int,
    groups_per_page: int,
    num_phys_pages: int,
    metrics=None,
):
    # Lazy import mirrors ops/kernels.get_paged_kernels: flat mesh tables
    # never pay for the paged module.
    from gubernator_tpu.ops.paged import PagedKernels, PagedTable

    n_dev = mesh.devices.size
    if groups_per_page <= 0:
        raise ValueError(f"groups_per_page must be > 0: {groups_per_page}")
    if num_phys_pages <= 0 or num_phys_pages % n_dev:
        raise ValueError(
            f"page budget {num_phys_pages} must be a positive multiple of "
            f"mesh size {n_dev} (each shard owns an equal frame pool)"
        )
    gpp = groups_per_page
    page_slots = gpp * ways
    num_logical_pages = -(-num_groups // gpp)  # ceil
    num_phys_groups = num_phys_pages * gpp
    groups_per = num_phys_groups // n_dev
    base = get_kernels(layout)
    raw = get_raw_kernels(layout)
    sentinel = jnp.int32(num_phys_groups)
    data_sharding = NamedSharding(mesh, P(AXIS))
    repl = NamedSharding(mesh, P())
    pt_sharding = PagedTable(data=data_sharding, page_map=repl)

    def _xlate(page_map, group):
        """Logical -> PHYSICAL group, replicated (the page map is small
        and replicated; one gather before the shard_map)."""
        g = group.astype(jnp.int32)
        pp = page_map[g // gpp]
        phys = jnp.where(pp >= 0, pp * gpp + g % gpp, sentinel)
        return phys.astype(group.dtype)

    def _local_decide(data, batch, now):
        data, out = raw.decide(
            data, _mask_to_local(groups_per, batch), now, ways
        )
        return data, jax.tree.map(lambda x: jax.lax.psum(x, AXIS), out)

    _sharded_decide = shard_map(
        _local_decide,
        mesh=mesh,
        in_specs=(P(AXIS), P(), P()),
        out_specs=(P(AXIS), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _decide(pt, batch, now):
        now = jnp.asarray(now, dtype=jnp.int64)
        b = batch._replace(group=_xlate(pt.page_map, batch.group))
        data, out = _sharded_decide(pt.data, b, now)
        return PagedTable(data, pt.page_map), out

    def _local_inject(data, items, now):
        data, ehi, elo = raw.inject(
            data, _mask_to_local(groups_per, items), now, ways
        )
        return data, jax.lax.psum(ehi, AXIS), jax.lax.psum(elo, AXIS)

    _sharded_inject = shard_map(
        _local_inject,
        mesh=mesh,
        in_specs=(P(AXIS), P(), P()),
        out_specs=(P(AXIS), P(), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _inject(pt, items, now):
        now = jnp.asarray(now, dtype=jnp.int64)
        i = items._replace(group=_xlate(pt.page_map, items.group))
        data, ehi, elo = _sharded_inject(pt.data, i, now)
        return PagedTable(data, pt.page_map), ehi, elo

    @jax.jit
    def _probe_exists(pt, hi, lo, group, now):
        g = _xlate(pt.page_map, group)
        return base.probe_exists(pt.data, hi, lo, g, now, ways)

    def _starts(start, ndim):
        z = jnp.asarray(0, dtype=jnp.int32)
        return (jnp.asarray(start, dtype=jnp.int32),) + (z,) * (ndim - 1)

    def _zero_region(data, start):
        def z(leaf):
            blk = jnp.zeros((page_slots,) + leaf.shape[1:], dtype=leaf.dtype)
            return jax.lax.dynamic_update_slice(
                leaf, blk, _starts(start, leaf.ndim)
            )

        return jax.tree.map(z, data)

    # Page moves are the single-chip programs with output shardings
    # pinned: the physical table stays sharded along the slot axis and
    # the page map stays replicated, regardless of what GSPMD would
    # infer from the replicated update operands.
    @functools.partial(
        jax.jit, donate_argnums=(0,), out_shardings=pt_sharding
    )
    def _bind_page(pt, lp, pp):
        data = _zero_region(pt.data, pp * page_slots)
        return PagedTable(data, pt.page_map.at[lp].set(pp))

    @functools.partial(
        jax.jit, donate_argnums=(0,), out_shardings=pt_sharding
    )
    def _unbind_page(pt, lp, pp):
        # Zero the evacuated frame: census and key-string pruning scan
        # the PHYSICAL table and must not see ghost rows.
        data = _zero_region(pt.data, pp * page_slots)
        return PagedTable(data, pt.page_map.at[lp].set(jnp.int32(-1)))

    @functools.partial(jax.jit, out_shardings=repl)
    def _extract_page(pt, pp):
        slots = pp * page_slots + jnp.arange(page_slots, dtype=jnp.int64)
        return base.gather_rows(pt.data, slots)

    @functools.partial(
        jax.jit, donate_argnums=(0,), out_shardings=pt_sharding
    )
    def _write_page(pt, lp, pp, rows_wide):
        rows = raw.from_wide(SlotTable(*rows_wide))
        start = pp * page_slots

        def upd(leaf, r):
            return jax.lax.dynamic_update_slice(
                leaf, r.astype(leaf.dtype), _starts(start, leaf.ndim)
            )

        data = jax.tree.map(upd, pt.data, rows)
        return PagedTable(data, pt.page_map.at[lp].set(pp))

    def _create(*_a, **_k):
        data = create_sharded_table(
            mesh, num_phys_groups, ways, layout, metrics=metrics
        )
        page_map = jax.device_put(  # guberlint: allow-unaccounted-transfer -- one-time empty-map constant at table creation, not a serving-path upload
            jnp.full((num_logical_pages,), -1, dtype=jnp.int32), repl
        )
        return PagedTable(data=data, page_map=page_map)

    def _from_wide(_t):
        raise NotImplementedError(
            "paged tables restore page-by-page (write_page), not from one "
            "flat wide image — see the engine's paged restore path"
        )

    return PagedKernels(
        layout=layout,
        create=_create,
        decide=lambda t, b, now, ways_=ways, with_store=False: _decide(
            t, b, now
        ),
        decide_scan=_no_scan,
        inject=lambda t, i, now, ways_=ways: _inject(t, i, now),
        probe_exists=lambda t, hi, lo, g, now, ways_=ways: _probe_exists(
            t, hi, lo, g, now
        ),
        gather_rows=lambda t, slots: base.gather_rows(t.data, slots),
        to_wide=lambda t: base.to_wide(t.data),
        from_wide=_from_wide,
        bytes_per_slot=BYTES_PER_SLOT[layout],
        bind_page=_bind_page,
        unbind_page=_unbind_page,
        extract_page=_extract_page,
        write_page=_write_page,
        ways=ways,
        groups_per_page=gpp,
        page_slots=page_slots,
        num_phys_pages=num_phys_pages,
        num_logical_pages=num_logical_pages,
        num_logical_groups=num_groups,
    )
