"""Multi-region peer picking (reference region_picker.go:19-103).

Peers whose data_center differs from the local node's are routed into
per-region rings. MULTI_REGION replication across those rings — a
declared-but-unimplemented behavior in the reference (its multi-region
test is an empty TODO, functional_test.go:1578-1586) — IS implemented
here: see parallel/region_sync.py (rendezvous-hashed home region,
async DCN hit-delta + authoritative broadcast legs). The routing is
pinned by tests/test_multiregion.py's RegionPicker unit suite — the
tests the reference never wrote.
"""

from __future__ import annotations

from typing import Dict, List

from gubernator_tpu.parallel.hash_ring import ReplicatedConsistentHash


class RegionPicker:
    def __init__(self, local_picker: ReplicatedConsistentHash = None):
        self.local_picker = local_picker or ReplicatedConsistentHash()
        self.regions: Dict[str, ReplicatedConsistentHash] = {}

    def new(self) -> "RegionPicker":
        return RegionPicker(self.local_picker.new())

    def add(self, peer) -> None:
        dc = peer.info.data_center
        ring = self.regions.get(dc)
        if ring is None:
            ring = self.local_picker.new()
            self.regions[dc] = ring
        ring.add(peer)

    def pickers(self) -> Dict[str, ReplicatedConsistentHash]:
        return self.regions

    def peers(self) -> List[object]:
        out = []
        for ring in self.regions.values():
            out.extend(ring.peers())
        return out

    def get_by_region(self, region: str, key: str):
        ring = self.regions.get(region)
        return ring.get(key) if ring is not None else None
