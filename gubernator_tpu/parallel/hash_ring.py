"""Consistent-hash peer ownership (reference replicated_hash.go:29-119).

Same scheme as the reference so key->owner assignment can be drop-in
compatible: 512 virtual replicas per peer, replica hash =
hash(str(i) + md5hex(grpc_address)), key hash = hash(hash_key), owner =
first replica clockwise (binary search, wraparound). The hash function
is pluggable (fnv1 / fnv1a / fnv1a-mix, reference config.go:421-443).

The DEFAULT hash is fnv1a-mix (fnv1a + the murmur3 fmix64 finalizer):
neither bare FNV variant avalanches its trailing bytes, so sequential
keys ("acct:1".."acct:999") — the shape real rate-limit keys take —
span only ~2^53 of the 64-bit space and land in a narrow band of the
ring (measured worst-host skew on 3 hosts x 512 vnodes over 10k
sequential keys: fnv1 +65%, fnv1a +31%, fnv1a-mix +4%; the reference's
own distribution test tolerates ~±10%). Pass hash_fn=fnv1_64 (config
peer_picker_hash="fnv1") ONLY when drop-in key->owner parity with a
live reference cluster is required (mixed-fleet migration).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Callable, Dict, List, Optional, Sequence

DEFAULT_REPLICAS = 512

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_M64 = (1 << 64) - 1


def fnv1_64(data: str) -> int:
    h = _FNV_OFFSET
    for b in data.encode("utf-8"):
        h = ((h * _FNV_PRIME) & _M64) ^ b
    return h


def fnv1a_64(data: str) -> int:
    h = _FNV_OFFSET
    for b in data.encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _M64
    return h


def fmix64(h: int) -> int:
    """MurmurHash3 64-bit finalizer (public-domain constants): full
    avalanche over all input bits, fixing FNV's weak trailing-byte
    diffusion."""
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _M64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _M64
    h ^= h >> 33
    return h


def fnv1a_mix_64(data: str) -> int:
    return fmix64(fnv1a_64(data))


HASHES: Dict[str, Callable[[str], int]] = {
    "fnv1": fnv1_64,
    "fnv1a": fnv1a_64,
    "fnv1a-mix": fnv1a_mix_64,
}


class ReplicatedConsistentHash:
    """Maps rate-limit keys to owning peers. Peers are any objects with a
    `.info.grpc_address` attribute (runtime Peer handles)."""

    def __init__(
        self,
        hash_fn: Callable[[str], int] = fnv1a_mix_64,
        replicas: int = DEFAULT_REPLICAS,
    ):
        self.hash_fn = hash_fn
        self.replicas = replicas
        self._peers: Dict[str, object] = {}
        self._ring_hashes: List[int] = []
        self._ring_peers: List[object] = []
        self._mask_cache = None  # (ring uint64 array, is_owner bool array)

    def new(self) -> "ReplicatedConsistentHash":
        return ReplicatedConsistentHash(self.hash_fn, self.replicas)

    def add(self, peer) -> None:
        addr = peer.info.grpc_address
        self._peers[addr] = peer
        key = hashlib.md5(addr.encode("utf-8")).hexdigest()
        entries = [(self.hash_fn(str(i) + key), peer) for i in range(self.replicas)]
        merged = sorted(
            list(zip(self._ring_hashes, self._ring_peers)) + entries,
            key=lambda e: e[0],
        )
        self._ring_hashes = [h for h, _ in merged]
        self._ring_peers = [p for _, p in merged]
        self._mask_cache = None

    def size(self) -> int:
        return len(self._peers)

    def peers(self) -> List[object]:
        return list(self._peers.values())

    def get_by_address(self, grpc_address: str):
        return self._peers.get(grpc_address)

    def get(self, key: str):
        """Owning peer for a hash-key; raises if the pool is empty."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        h = self.hash_fn(key)
        idx = bisect.bisect_left(self._ring_hashes, h)
        if idx == len(self._ring_hashes):
            idx = 0
        return self._ring_peers[idx]

    def successors(self, key: str, n: int = 1) -> List[object]:
        """Up to `n` DISTINCT peers clockwise past the key's owner — the
        peers that would own this key if the owner (and then each
        successor in turn) left the ring. This is the standby placement
        rule (parallel/standby.py): shadowing a key at its successors
        means a promoted standby already owns exactly the rows it
        inherits under the post-death ring. Raises if the pool is empty;
        returns fewer than `n` when the pool is small."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        h = self.hash_fn(key)
        idx = bisect.bisect_left(self._ring_hashes, h)
        if idx == len(self._ring_hashes):
            idx = 0
        ring_n = len(self._ring_peers)
        owner = self._ring_peers[idx]
        seen = {owner.info.grpc_address}
        out: List[object] = []
        for step in range(1, ring_n):
            p = self._ring_peers[(idx + step) % ring_n]
            addr = p.info.grpc_address
            if addr in seen:
                continue
            seen.add(addr)
            out.append(p)
            if len(out) >= n:
                break
        return out

    def _ring_arrays(self):
        """Cached (hashes, is_owner, addr_padded, addr_lens) ring arrays
        for the vectorized edge (invalidated by add() — rebuilding
        replicas*peers entries per call would dominate the edge's
        per-call budget). addr_padded/addr_lens support fully-vectorized
        ragged packing of per-item owner bytes (owner_spans)."""
        import numpy as np

        cache = self._mask_cache
        if cache is None:
            addrs = [
                p.info.grpc_address.encode() for p in self._ring_peers
            ]
            maxlen = max((len(a) for a in addrs), default=1)
            padded = np.zeros((max(len(addrs), 1), maxlen), dtype=np.uint8)
            for i, a in enumerate(addrs):
                padded[i, : len(a)] = np.frombuffer(a, np.uint8)
            cache = (
                np.asarray(self._ring_hashes, dtype=np.uint64),
                np.asarray(
                    [bool(p.info.is_owner) for p in self._ring_peers],
                    dtype=bool,
                ),
                padded,
                np.asarray([len(a) for a in addrs], dtype=np.int64),
            )
            self._mask_cache = cache
        return cache

    def _ring_idx(self, key_hashes):
        """Identical placement to get(): bisect_left on the sorted ring
        with wraparound. `key_hashes` are uint64 values of the SAME hash
        function as hash_fn (the native batch)."""
        import numpy as np

        ring = self._ring_arrays()[0]
        idx = np.searchsorted(ring, key_hashes, side="left")
        return np.where(idx == len(ring), 0, idx)

    def local_mask(self, key_hashes) -> "object":
        """Vectorized ownership check for the columnar edge: True per key
        iff this node owns it."""
        return self._ring_arrays()[1][self._ring_idx(key_hashes)]

    def owner_spans(self, key_hashes, need) -> tuple:
        """(owner_data uint8, owner_offsets int64) — per-item owner
        address bytes where `need` is True, empty spans elsewhere; the
        exact shape wire.build_responses_md consumes. Fully vectorized
        ragged packing (no per-item Python)."""
        import numpy as np

        _, _, padded, alens = self._ring_arrays()
        idx = self._ring_idx(key_hashes)
        need = np.asarray(need, dtype=bool)
        lens = np.where(need, alens[idx], 0)
        offsets = np.zeros(len(idx) + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        rows = padded[idx[need]]
        mask = (
            np.arange(padded.shape[1])[None, :] < alens[idx[need]][:, None]
        )
        return rows[mask], offsets
