"""MULTI_REGION behavior: DCN-tier async replication across regions.

The reference declares the MULTI_REGION behavior bit and ships the
RegionPicker plumbing, but the replication itself is unimplemented (its
multi-region test is an empty TODO — reference region_picker.go:19-103,
functional_test.go:1578-1586, gubernator.proto:124-127). This module
implements it, composing with the existing two-tier GLOBAL design:

- **Home region** per key: rendezvous hashing (highest-random-weight via
  fnv1a over "region|key") across the region set — stable under region
  add/remove, no coordination needed.
- **In-region serving is unchanged**: a MULTI_REGION request is answered
  by the key's in-region owner at in-region latency (local ring routing,
  forwarding, batching all as today). Cross-region traffic never sits on
  the serving path.
- **Hit-delta leg** (the reference globalManager's runAsyncHits shape,
  global.go:91-187, lifted to region granularity): a non-home-region
  owner aggregates MULTI_REGION hits per key and pushes them on the
  global cadence to the key's owner peer IN THE HOME REGION over DCN
  gRPC (GetPeerRateLimits with DRAIN_OVER_LIMIT forced, like relayed
  GLOBAL hits, gubernator.go:510-512).
- **Broadcast leg** (runBroadcasts shape, global.go:193-283): the
  home-region owner re-reads each updated key with hits=0 and pushes the
  authoritative state to the key's owner peer in EVERY OTHER region
  (UpdatePeerGlobals); receivers inject it over their local counter.
  Non-home regions therefore serve provisional local counts between
  syncs and converge to the authoritative value each cadence — the same
  consistency contract GLOBAL replicas have, one level up.

Delta-then-overwrite is double-count-free: a region's local hits are
provisional until the home region's broadcast (which already includes
the pushed deltas) overwrites them.

tests/test_multiregion.py pins both layers — cross-DC convergence e2e,
plus unit coverage of the queue/flush internals (noop gating, hit
aggregation, DRAIN forcing + strip-on-retry, requeue on unreachable
home, home-churn delta→broadcast conversion, the hits=0 authoritative
re-read) — the tests the reference's empty TODO never wrote.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.api.types import (
    Behavior,
    RateLimitReq,
    UpdatePeerGlobal,
    has_behavior,
)
from gubernator_tpu.parallel.global_sync import ORIGIN_MD_KEY, BatchQueue
from gubernator_tpu.utils import clock as _clock
from gubernator_tpu.parallel.hash_ring import fnv1a_64
from gubernator_tpu.service.config import BehaviorConfig

log = logging.getLogger("gubernator_tpu.multiregion")


_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer: fnv1a alone has weak avalanche, which skews
    rendezvous scores for similar region names."""
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _M64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _M64
    return x ^ (x >> 31)


def home_region(regions: List[str], key: str) -> Optional[str]:
    """Rendezvous (HRW) hash: the region with the highest mixed fnv1a
    score owns the key. Deterministic on every node given the same region
    set; adding/removing a region only remaps keys homed there."""
    best, best_score = None, -1
    for r in regions:
        score = _mix64(fnv1a_64(f"{r}|{key}"))
        if score > best_score or (score == best_score and (best is None or r < best)):
            best, best_score = r, score
    return best


class RegionManager:
    """Async cross-region reconciliation loops (one per daemon).

    Mirrors GlobalManager's queue/flush structure (global.go:43-291) but
    routes across the RegionPicker's per-region rings instead of the
    local ring."""

    def __init__(self, svc, behaviors: BehaviorConfig):
        self.svc = svc
        self.b = behaviors
        # Constructed on the daemon's event loop; queue state and asyncio
        # events are loop-affine — off-loop producers (the columnar
        # serving executor) must enter via observe_from_thread.
        self._loop = asyncio.get_running_loop()
        # Consistency observatory: monotonic enqueue stamps for the DCN
        # tier's queue-wait / fan-out legs (same side-dict design as
        # GlobalManager — queued items stay metadata-free).
        self._hit_enq: Dict[str, float] = {}
        self._upd_enq: Dict[str, float] = {}

        def hits_error(take, e):
            log.exception("MULTI_REGION hit-delta flush failed")
            self.svc.metrics.region_send_errors.inc()
            self._requeue(take)

        def upd_error(take, e):
            log.exception("MULTI_REGION broadcast flush failed")
            self.svc.metrics.region_broadcast_errors.inc()

        self._hits_q = BatchQueue(
            behaviors.global_sync_wait_s, behaviors.global_batch_limit,
            self._send_hits, hits_error,
        )
        self._upd_q = BatchQueue(
            behaviors.global_sync_wait_s, behaviors.global_batch_limit,
            self._broadcast, upd_error,
        )

    @property
    def hits(self) -> Dict[str, RateLimitReq]:
        return self._hits_q.items

    @property
    def updates(self) -> Dict[str, RateLimitReq]:
        return self._upd_q.items

    def _requeue(self, take: Dict[str, RateLimitReq]) -> None:
        """Failed deltas are re-aggregated, not dropped: unlike GLOBAL
        (where the owner's own cache still holds the hits), a lost
        cross-region delta permanently undercounts the home region AND
        gets erased from this region by the next authoritative broadcast.
        At most one aggregated entry per key, so the queue stays bounded
        by key cardinality during a home-region outage."""
        for r in take.values():
            self.queue_hit(r)

    # -- region topology -----------------------------------------------------

    def _local_region(self) -> str:
        return self.svc.local_info.data_center or ""

    def _all_regions(self) -> List[str]:
        regions = {self._local_region()}
        picker = self.svc.picker
        rp = getattr(picker, "region_picker", None)
        if rp is not None:
            regions.update(rp.pickers().keys())
        return sorted(regions)

    def home_of(self, key: str) -> str:
        return home_region(self._all_regions(), key) or self._local_region()

    def is_home(self, key: str) -> bool:
        return self.home_of(key) == self._local_region()

    # -- queueing (called by the serving path on the IN-REGION owner) --------

    def observe(self, req: RateLimitReq) -> None:
        """Called after the in-region owner applied a MULTI_REGION item:
        home-region owners queue an authoritative broadcast; other
        regions queue a hit-delta toward the home region."""
        regions = self._all_regions()
        if len(regions) < 2:
            return  # single-region deployment: nothing to reconcile
        local = self._local_region()
        if (home_region(regions, req.hash_key()) or local) == local:
            self.queue_update(req)
        else:
            self.queue_hit(req)

    def observe_from_thread(self, reqs) -> None:
        """Thread-safe batch observe from the columnar serving executor:
        one call_soon_threadsafe hop runs every queue mutation on the
        manager's loop (same hazard as GlobalManager.queue_from_thread)."""

        def apply():
            for req in reqs:
                self.observe(req)

        self._loop.call_soon_threadsafe(apply)

    @staticmethod
    def _is_noop(r: RateLimitReq) -> bool:
        # hits=0 reads queue nothing — EXCEPT a RESET_REMAINING, which
        # mutates state and must reach the home region or the next
        # authoritative broadcast would silently undo it.
        return r.hits == 0 and not has_behavior(
            r.behavior, Behavior.RESET_REMAINING
        )

    def queue_hit(self, r: RateLimitReq) -> None:
        if self._is_noop(r):
            return
        key = r.hash_key()
        self._hit_enq.setdefault(key, time.perf_counter())
        existing = self._hits_q.items.get(key)
        if existing is not None:
            if has_behavior(r.behavior, Behavior.RESET_REMAINING):
                existing.behavior |= Behavior.RESET_REMAINING
            existing.hits += r.hits
        else:
            self._hits_q.items[key] = dataclasses.replace(
                r, metadata=dict(r.metadata)
            )
        self._hits_q.notify()

    def queue_update(self, r: RateLimitReq) -> None:
        if self._is_noop(r):
            return
        key = r.hash_key()
        self._upd_enq.setdefault(key, time.perf_counter())
        md = dict(r.metadata)
        # Origin-if-absent (GlobalManager.queue_update): the home-region
        # broadcast carries the stamp so receiving regions feed the same
        # propagation-lag histogram.
        md.setdefault(ORIGIN_MD_KEY, str(_clock.now_ms()))
        self._upd_q.items[key] = dataclasses.replace(r, metadata=md)
        self._upd_q.notify()

    # -- hit-delta leg (global.go:144-187 shape, DCN targets) ----------------

    def _region_peer(self, region: str, key: str):
        rp = getattr(self.svc.picker, "region_picker", None)
        if rp is None:
            return None
        return rp.get_by_region(region, key)

    async def _send_hits(self, hits: Dict[str, RateLimitReq]) -> None:
        t0 = time.perf_counter()
        wait_leg = self.svc.metrics.global_sync_leg_duration.labels(
            "hit_queue_wait"
        )
        for key in hits:
            t_enq = self._hit_enq.pop(key, None)
            if t_enq is not None:
                wait_leg.observe(t0 - t_enq)
        try:
            by_peer: Dict[str, Tuple[object, List[RateLimitReq]]] = {}
            regions = self._all_regions()
            local = self._local_region()
            for key, r in hits.items():
                home = home_region(regions, key) or local
                if home == local:
                    # Region set changed since queueing: we're home now.
                    self.queue_update(r)
                    continue
                try:
                    peer = self._region_peer(home, key)
                # guberlint: allow-swallow -- pick failure is counted via region_send_errors and the hit requeued just below
                except Exception:
                    peer = None
                if peer is None:
                    # Home region unreachable (membership churn):
                    # requeue — see _requeue for why dropping is unsafe.
                    self.svc.metrics.region_send_errors.inc()
                    self.queue_hit(r)
                    continue
                # Relayed cross-region deltas drain at the home region
                # (the GLOBAL relay rule, gubernator.go:510-512); the
                # receiver must not re-forward them in-region.
                r2 = dataclasses.replace(r, metadata=dict(r.metadata))
                r2.behavior |= Behavior.DRAIN_OVER_LIMIT
                addr = peer.info.grpc_address
                if addr in by_peer:
                    by_peer[addr][1].append(r2)
                else:
                    by_peer[addr] = (peer, [r2])

            sem = asyncio.Semaphore(self.b.global_peer_requests_concurrency)

            async def send(peer, reqs):
                async with sem:
                    try:
                        await peer.get_peer_rate_limits(
                            reqs, timeout=self.b.global_timeout_s
                        )
                    except Exception as e:
                        log.warning(
                            "MULTI_REGION hit-delta to %s failed: %s",
                            peer.info.grpc_address, e,
                        )
                        self.svc.metrics.region_send_errors.inc()
                        # DRAIN was forced for the relay; strip it before
                        # re-aggregating so retries carry the original
                        # behavior bits.
                        for r in reqs:
                            r.behavior &= ~Behavior.DRAIN_OVER_LIMIT
                            self.queue_hit(r)

            await asyncio.gather(*(send(p, rs) for p, rs in by_peer.values()))
        finally:
            self.svc.metrics.region_send_duration.observe(
                time.perf_counter() - t0
            )

    # -- broadcast leg (global.go:234-283 shape, one peer per region) --------

    async def _broadcast(self, updates: Dict[str, RateLimitReq]) -> None:
        enq_stamps = {k: self._upd_enq.pop(k, None) for k in updates}
        other_regions = [
            r for r in self._all_regions() if r != self._local_region()
        ]
        if not other_regions:
            return
        t0 = time.perf_counter()
        try:
            # Pure status read of the CURRENT authoritative state: hits=0
            # and no mutating behavior bits. A queued RESET_REMAINING was
            # already applied when the request was served; re-applying it
            # here would wipe any hits counted since (the reset's effect
            # still propagates — the re-read sees the post-reset value).
            futs = [
                asyncio.wrap_future(
                    self.svc.engine.check_async(
                        dataclasses.replace(
                            upd,
                            hits=0,
                            behavior=upd.behavior
                            & ~int(Behavior.RESET_REMAINING),
                            metadata=dict(upd.metadata),
                        )
                    )
                )
                for upd in updates.values()
            ]
            statuses = await asyncio.gather(*futs)
            globals_ = []
            for (key, upd), status in zip(updates.items(), statuses):
                origin = upd.metadata.get(ORIGIN_MD_KEY)
                if origin is not None:
                    md = dict(status.metadata or {})
                    md[ORIGIN_MD_KEY] = origin
                    status = dataclasses.replace(status, metadata=md)
                globals_.append(
                    UpdatePeerGlobal(
                        key=key,
                        status=status,
                        algorithm=upd.algorithm,
                        duration=upd.duration,
                        created_at=upd.created_at or 0,
                    )
                )

            # Group by (region, target peer): the key's in-region owner
            # receives the authoritative state for its region.
            by_peer: Dict[Tuple[str, str], Tuple[object, List[UpdatePeerGlobal]]] = {}
            for g in globals_:
                for region in other_regions:
                    try:
                        peer = self._region_peer(region, g.key)
                    # guberlint: allow-swallow -- pick failure is counted via region_broadcast_errors just below
                    except Exception:
                        peer = None
                    if peer is None:
                        self.svc.metrics.region_broadcast_errors.inc()
                        continue
                    k = (region, peer.info.grpc_address)
                    if k in by_peer:
                        by_peer[k][1].append(g)
                    else:
                        by_peer[k] = (peer, [g])

            sem = asyncio.Semaphore(self.b.global_peer_requests_concurrency)

            async def push(peer, gs):
                async with sem:
                    try:
                        await peer.update_peer_globals(
                            gs, timeout=self.b.global_timeout_s
                        )
                    except Exception as e:
                        log.warning(
                            "MULTI_REGION broadcast to %s failed: %s",
                            peer.info.grpc_address, e,
                        )
                        self.svc.metrics.region_broadcast_errors.inc()

            await asyncio.gather(*(push(p, gs) for p, gs in by_peer.values()))
            t_done = time.perf_counter()
            fan_leg = self.svc.metrics.global_sync_leg_duration.labels(
                "broadcast_fanout"
            )
            for t_enq in enq_stamps.values():
                if t_enq is not None:
                    fan_leg.observe(t_done - t_enq)
            self.svc.metrics.region_broadcast_counter.inc()
        finally:
            self.svc.metrics.region_broadcast_duration.observe(
                time.perf_counter() - t0
            )

    async def drain(self) -> None:
        """Final flush of both cross-region legs before shutdown
        (graceful-drain path, docs/robustness.md): a lost delta would
        permanently undercount the home region, so it ships now rather
        than dying with the loop."""
        await self._hits_q.drain()
        await self._upd_q.drain()

    async def close(self) -> None:
        await self._hits_q.close()
        await self._upd_q.close()
